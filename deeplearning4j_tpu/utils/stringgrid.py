"""String grid / fingerprint clustering / dedup utilities.

Capability match of the reference's data-cleaning trio:
``util/StringGrid.java`` (a grid of string cells with column ops and
cluster-based dedup), ``util/StringCluster.java`` (groups strings whose
*fingerprint* matches — "Two words", "TWO words", "WORDS TWO" cluster
together), ``util/FingerPrintKeyer.java`` (the OpenRefine-style fingerprint:
case-fold, strip punctuation/accents, unique-sort tokens).
"""

from __future__ import annotations

import re
import unicodedata
from collections import Counter
from pathlib import Path
from typing import Iterable, Sequence

__all__ = ["fingerprint", "ngram_fingerprint", "StringCluster", "StringGrid"]

_PUNCT = re.compile(r"[^\w\s]", re.UNICODE)


def _ascii_fold(s: str) -> str:
    return (unicodedata.normalize("NFKD", s)
            .encode("ascii", "ignore").decode("ascii"))


def fingerprint(s: str) -> str:
    """``FingerPrintKeyer.key``: trim, case-fold, strip punctuation and
    accents, then join the UNIQUE tokens in sorted order."""
    s = _ascii_fold(s.strip().lower())
    s = _PUNCT.sub("", s)
    return " ".join(sorted(set(s.split())))


def ngram_fingerprint(s: str, n: int = 2) -> str:
    """``FingerPrintKeyer`` n-gram variant: unique sorted character n-grams
    of the de-punctuated, de-spaced string."""
    s = _PUNCT.sub("", _ascii_fold(s.strip().lower())).replace(" ", "")
    grams = {s[i:i + n] for i in range(max(0, len(s) - n + 1))}
    return "".join(sorted(grams))


class StringCluster(dict):
    """``StringCluster.java``: fingerprint -> {variant: count}."""

    def __init__(self, strings: Iterable[str]):
        super().__init__()
        for s in strings:
            self.setdefault(fingerprint(s), Counter())[s] += 1

    def clusters(self) -> list[Counter]:
        """Clusters sorted by distinct-variant count desc, then total
        occurrences desc (``SizeComparator``)."""
        return sorted(self.values(),
                      key=lambda m: (-len(m), -sum(m.values())))


class StringGrid(list):
    """``StringGrid.java``: a list of string rows with column operations and
    fingerprint-cluster dedup.  Rows are lists of cells, right-padded with
    ``NONE`` to equal width."""

    NONE = "NONE"

    def __init__(self, sep: str = ",", rows: Iterable[Sequence[str]] = ()):
        super().__init__([list(r) for r in rows])
        self.sep = sep
        self._fill_out()

    # -- construction ---------------------------------------------------
    @classmethod
    def from_lines(cls, lines: Iterable[str], sep: str = ",") -> "StringGrid":
        rows = [cls._split_quoted(ln, sep) for ln in lines if ln.strip()]
        return cls(sep, rows)

    @classmethod
    def from_file(cls, path: str | Path, sep: str = ",") -> "StringGrid":
        return cls.from_lines(Path(path).read_text().splitlines(), sep)

    @staticmethod
    def _split_quoted(line: str, sep: str) -> list[str]:
        """Split on ``sep`` honoring double-quoting and backslash escapes
        (``StringUtils.splitOnCharWithQuoting`` behavior)."""
        out, cur, in_q, esc = [], [], False, False
        for ch in line:
            if esc:
                cur.append(ch)
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_q = not in_q
            elif ch == sep and not in_q:
                out.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
        out.append("".join(cur))
        return out

    def _fill_out(self) -> None:
        width = self.num_columns()
        for row in self:
            row.extend([self.NONE] * (width - len(row)))

    # -- shape / access -------------------------------------------------
    def num_columns(self) -> int:
        return max((len(r) for r in self), default=0)

    def get_column(self, column: int) -> list[str]:
        return [row[column] for row in self]

    def head(self, num: int) -> "StringGrid":
        return StringGrid(self.sep, self[:num])

    # -- filtering ------------------------------------------------------
    def remove_rows_with_empty_column(self, column: int,
                                      missing_value: str = "") -> None:
        self[:] = [r for r in self if r[column] != missing_value]

    def remove_columns(self, *columns: int) -> None:
        drop = set(c % self.num_columns() for c in columns)
        self[:] = [[c for j, c in enumerate(r) if j not in drop] for r in self]

    def rows_with_column_values(self, values: Iterable[str],
                                column: int) -> list[list[str]]:
        vals = set(values)
        return [r for r in self if r[column] in vals]

    def filter_rows_by_column(self, column: int,
                              values: Iterable[str]) -> list[int]:
        vals = set(values)
        return [i for i, r in enumerate(self) if r[column] in vals]

    # -- clustering / dedup --------------------------------------------
    def cluster_column(self, column: int) -> StringCluster:
        return StringCluster(self.get_column(column))

    def dedupe_by_cluster(self, column: int) -> None:
        """Canonicalize each cluster of near-duplicate cell values to its
        most frequent variant (``dedupeByCluster``)."""
        cluster = self.cluster_column(column)
        canonical = {}
        for variants in cluster.values():
            best = variants.most_common(1)[0][0]
            for v in variants:
                canonical[v] = best
        for row in self:
            row[column] = canonical.get(row[column], row[column])

    def dedupe_by_cluster_all(self) -> None:
        for c in range(self.num_columns()):
            self.dedupe_by_cluster(c)

    def unique_rows(self) -> "StringGrid":
        seen, out = set(), []
        for r in self:
            key = tuple(r)
            if key not in seen:
                seen.add(key)
                out.append(r)
        return StringGrid(self.sep, out)

    # -- likelihood sort (sortColumnsByWordLikelihoodIncluded) -----------
    def sort_by_word_likelihood(self, column: int) -> None:
        """Sort rows by the mean corpus frequency of the words in the given
        column (most-typical rows first), the reference's word-likelihood
        column sort."""
        counts = Counter()
        for cell in self.get_column(column):
            counts.update(cell.lower().split())
        total = sum(counts.values()) or 1

        def score(row):
            words = row[column].lower().split()
            if not words:
                return 0.0
            return sum(counts[w] / total for w in words) / len(words)

        self.sort(key=score, reverse=True)

    # -- output ---------------------------------------------------------
    def to_lines(self) -> list[str]:
        return [self.sep.join(r) for r in self]

    def write_file(self, path: str | Path) -> None:
        Path(path).write_text("\n".join(self.to_lines()) + "\n")
