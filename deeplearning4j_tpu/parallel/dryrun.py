"""Multi-chip sharding dryrun: one full explicit-SPMD train step on tiny
shapes over an n-device mesh (dp/pp/tp/sp — GPipe micro-batch pipeline over
pp, Megatron-style tp, ring attention over sp, dp gradient pmean).

Package home of the logic behind the repo-root ``__graft_entry__.py``
driver hook and the ``python -m deeplearning4j_tpu dryrun`` CLI: both
import from here, so the check works from an installed package too.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mesh_spec_for(n_devices: int):
    """Factor n into (dp, pp, tp, sp): peel 2s round-robin so every
    parallelism kind is exercised when n allows (8 -> dp2·pp2·tp2,
    16 -> + sp2).  ep is exercised by the sharded-embedding path
    (tests/test_sharded_embedding.py) rather than the flagship step."""
    from .mesh import DP, PP, SP, TP, MeshSpec
    dims = {DP: 1, PP: 1, TP: 1, SP: 1}
    order = [DP, PP, TP, SP]
    n, i = n_devices, 0
    while n % 2 == 0 and n > 1:
        dims[order[i % 4]] *= 2
        n //= 2
        i += 1
    dims[DP] *= n  # odd residue onto dp
    return MeshSpec(dp=dims[DP], sp=dims[SP], tp=dims[TP],
                    pp=dims[PP], ep=1)


def dryrun_multichip(n_devices: int) -> None:
    """One full sharded train step on tiny shapes over n virtual devices.

    Forces the CPU platform in-process: the environment's boot-time TPU
    registration overrides JAX_PLATFORMS env vars, and this check must run
    on the virtual CPU device pool.
    """
    jax.config.update("jax_platforms", "cpu")
    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} devices, have {len(jax.devices())} "
        "(set XLA_FLAGS=--xla_force_host_platform_device_count)")

    from ..models.transformer import TransformerConfig, TransformerLM
    from ..optimize import transforms as T
    from .mesh import DP, PP, SP, TP, make_mesh

    spec = mesh_spec_for(n_devices)
    mesh = make_mesh(spec, devices=jax.devices()[:n_devices])

    sizes = spec.resolve(n_devices)
    n_heads = max(4, sizes[TP] * 2)
    seq = 8 * sizes[SP]
    n_micro = 2 * sizes[PP]
    batch = sizes[DP] * n_micro      # local batch per dp shard == n_micro
    cfg = TransformerConfig(
        vocab_size=128, d_model=8 * n_heads, n_heads=n_heads,
        n_layers=2 * sizes[PP], d_ff=64, max_len=seq, causal=True,
        dtype=jnp.float32, remat=True,
    )

    if sizes[PP] > 1:
        from ..models.pipeline import PipelinedTransformerLM
        model = PipelinedTransformerLM(cfg, mesh, n_micro=n_micro)
    else:
        model = TransformerLM(cfg, mesh=mesh)
    tx = T.adamw(T.warmup_cosine(1e-2, 2, 100), weight_decay=0.01)
    params = model.place(model.init(jax.random.key(0)))
    opt = model.init_opt(params, tx)
    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    step = model.build_train_step(tx)
    params, _, loss = step(params, opt, tokens, targets)
    loss = float(loss)
    assert jnp.isfinite(loss), f"non-finite loss {loss}"

    # second leg: ZeRO-1 weight-update sharding ON the same mesh — when pp
    # is live this exercises the pipelined ZeRO-1 path (dp-sharded state
    # with a pp row dimension on stage-sharded leaves).
    z1 = ""
    if sizes[DP] > 1:
        p1 = model.place(model.init(jax.random.key(0)))  # step donated params
        o1 = model.init_opt_zero1(p1, tx)
        z1_step = model.build_train_step(tx, zero1=True)
        _, _, z1_loss = z1_step(p1, o1, tokens, targets)
        z1_loss = float(z1_loss)
        assert jnp.isfinite(z1_loss), f"non-finite zero1 loss {z1_loss}"
        kind = "pp-pipelined" if sizes[PP] > 1 else "plain"
        z1 = f" zero1[{kind},dp{sizes[DP]}]_loss={z1_loss:.4f}"

    # third leg: cross-device ring attention.  The round-robin factoring
    # gives sp=1 at n=8 (dp2·pp2·tp2), so ring attention's ppermute path
    # would only ever run over sp>1 at n>=16.  Fold pp into sp (same device
    # count) so the driver-recorded dryrun exercises the ring at n=8 too.
    sp = ""
    if sizes[SP] == 1 and sizes[PP] > 1:
        from .mesh import MeshSpec
        sp_spec = MeshSpec(dp=sizes[DP], sp=sizes[PP] * sizes[SP],
                           tp=sizes[TP], pp=1, ep=1)
        sp_mesh = make_mesh(sp_spec, devices=jax.devices()[:n_devices])
        sp_seq = 8 * sp_spec.sp
        sp_cfg = TransformerConfig(
            vocab_size=128, d_model=8 * n_heads, n_heads=n_heads,
            n_layers=2, d_ff=64, max_len=sp_seq, causal=True,
            dtype=jnp.float32, remat=True,
        )
        sp_model = TransformerLM(sp_cfg, mesh=sp_mesh)
        p2 = sp_model.place(sp_model.init(jax.random.key(0)))
        o2 = sp_model.init_opt(p2, tx)
        sp_tokens = jax.random.randint(
            jax.random.key(2), (sizes[DP] * 2, sp_seq), 0, sp_cfg.vocab_size)
        sp_step = sp_model.build_train_step(tx)
        _, _, sp_loss = sp_step(p2, o2, sp_tokens, jnp.roll(sp_tokens, -1, axis=1))
        sp_loss = float(sp_loss)
        assert jnp.isfinite(sp_loss), f"non-finite sp loss {sp_loss}"
        sp = f" ring[dp{sp_spec.dp}·tp{sp_spec.tp}·sp{sp_spec.sp}]_loss={sp_loss:.4f}"

    print(f"dryrun_multichip OK: mesh={dict(sizes)} devices={n_devices} "
          f"batch={batch} seq={seq} n_micro={n_micro if sizes[PP] > 1 else 0} "
          f"loss={loss:.4f}{z1}{sp}")
