"""Guards on bench.py's evidence-based config pickers: a 0.0-throughput
row is EVIDENCE of a broken config (not missing data), and a winner must
clear a >2% margin so one noisy TUNE row can't flip the headline config
on measurement jitter."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench  # noqa: E402

OK_CHECK = {"max_err": 0.001}


def _att_rows(ring, flash, check=OK_CHECK):
    rows = []
    if check is not None:
        rows.append({"flash_check": check})
    if ring is not None:
        rows.append({"attention": "ring", "batch": 64, "tokens_per_sec": ring})
    if flash is not None:
        rows.append({"attention": "flash", "batch": 64,
                     "tokens_per_sec": flash})
    return rows


def test_pick_attention_needs_margin_not_just_a_win():
    choice, reason = bench._pick_attention(_att_rows(100.0, 101.0))
    assert choice == "ring"                      # 1% is inside jitter
    choice, reason = bench._pick_attention(_att_rows(100.0, 103.0))
    assert choice == "flash" and "TUNE" in reason


def test_pick_attention_treats_zero_throughput_as_evidence():
    # flash measured at 0.0 tok/s: a broken config, not a missing row —
    # it must participate in the comparison and lose, not be skipped
    assert bench._pick_attention(_att_rows(100.0, 0.0))[0] == "ring"
    # no ring evidence at all -> conservative default, never flash-by-void
    assert bench._pick_attention(_att_rows(None, 103.0))[0] == "ring"
    # correctness battery failed -> speed win is irrelevant
    bad = {"max_err": 0.2}
    assert bench._pick_attention(_att_rows(100.0, 103.0, bad))[0] == "ring"


def _bn_rows(off, on):
    rows = []
    if off is not None:
        rows.append({"bn_fold": False, "batch": 256, "mfu": off})
    if on is not None:
        rows.append({"bn_fold": True, "batch": 256, "mfu": on})
    return rows


def test_pick_bn_fold_margin_and_missing_evidence():
    assert bench._pick_bn_fold(_bn_rows(0.30, 0.303))[0] is False  # ~1%
    on, reason = bench._pick_bn_fold(_bn_rows(0.30, 0.31))
    assert on is True and "TUNE" in reason
    assert bench._pick_bn_fold(_bn_rows(None, 0.31))[0] is False
    assert bench._pick_bn_fold(_bn_rows(0.30, None))[0] is False
    assert bench._pick_bn_fold(_bn_rows(0.30, 0.0))[0] is False
