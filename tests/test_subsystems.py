"""Subsystem parity tests: RNTN, trees, inverted index, windows, sentiment,
record readers, observability, storage/config registry."""

import json
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.models.rntn import RNTN, linearize
from deeplearning4j_tpu.text.tree import Tree, binarize, parse_sexpr, right_branching
from deeplearning4j_tpu.text.index import InvertedIndex
from deeplearning4j_tpu.text.windows import PAD, Window, window_matrix, windows
from deeplearning4j_tpu.text.sentiwordnet import SentiWordNet
from deeplearning4j_tpu.datasets.records import (
    CollectionRecordReader,
    CSVRecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.parallel.observe import MetricsRegistry, StatusServer, StepTimer
from deeplearning4j_tpu.parallel.scaleout import StateTracker
from deeplearning4j_tpu.parallel.storage import (
    ConfigRegistry,
    LocalArtifactStore,
    StoreModelSaver,
)


# --------------------------------------------------------------------------- trees

def test_sexpr_parse_roundtrip():
    t = parse_sexpr("(3 (2 nice) (1 (0 not) (2 movie)))")
    assert t.gold_label == 3
    assert t.words() == ["nice", "not", "movie"]
    assert t.depth() >= 3
    assert "(3" in t.to_sexpr()


def test_binarize_and_right_branching():
    t = parse_sexpr("(1 (0 a) (0 b) (0 c) (0 d))")
    b = binarize(t)
    for node in b.subtrees():
        # pre-terminals (tag -> word) stay unary, as in treebank convention
        assert node.is_leaf() or node.is_pre_terminal() or len(node.children) == 2
    rb = right_branching(["x", "y", "z"], label=1)
    assert rb.words() == ["x", "y", "z"]
    assert rb.gold_label == 1


def test_rntn_learns_toy_sentiment():
    """Positive trees contain 'good', negative contain 'bad' — root
    classification should become near-perfect."""
    pos = [right_branching(f"this movie is good {w}".split(), label=1)
           for w in ["really", "very", "so", "quite"]]
    neg = [right_branching(f"this movie is bad {w}".split(), label=0)
           for w in ["really", "very", "so", "quite"]]
    trees = pos + neg
    model = RNTN(layer_size=12, n_classes=2, max_nodes=16, lr=0.1, seed=1)
    losses = model.fit(trees, epochs=60, batch_size=8)
    assert losses[-1] < losses[0]
    assert model.accuracy(trees) >= 0.9
    preds = model.predict_tree(trees[0])
    assert preds.shape[0] == int(np.sum(
        linearize(trees[0], model.vocab, 16).mask))


def test_inverted_index():
    ix = InvertedIndex()
    ix.add_all(["the cat sat", "the dog ran", "a cat and a dog"])
    assert ix.num_documents() == 3
    assert ix.documents_for("cat") == [0, 2]
    assert ix.doc_frequency("dog") == 2
    hits = ix.search("cat")
    assert hits and hits[0][0] in (0, 2)
    batches = list(ix.batch_iter(2))
    assert len(batches) == 2 and len(batches[0]) == 2


def test_windows():
    ws = windows(["a", "b", "c"], window_size=3, labels=["x", "y", "z"])
    assert len(ws) == 3
    assert ws[0].words == [PAD, "a", "b"] and ws[0].focus == "a"
    assert ws[2].label == "z"
    m = window_matrix(ws[0], lambda w: np.ones(2) if w == "a" else None, 2)
    assert m.shape == (6,)
    assert m[2:4].tolist() == [1.0, 1.0]


def test_sentiwordnet_seed_and_file(tmp_path):
    swn = SentiWordNet()
    assert swn.classify("this is a good great movie".split()) in (
        "positive", "strong_positive")
    assert swn.classify("terrible awful hate".split()) == "strong_negative"
    p = tmp_path / "swn.txt"
    p.write_text("# comment\na\t1\t0.75\t0.0\tsplendid#1\tgloss\n")
    swn2 = SentiWordNet(p)
    assert swn2.score("splendid") == pytest.approx(0.75)


def test_record_readers(tmp_path):
    p = tmp_path / "r.csv"
    p.write_text("1.0,2.0,cat\n3.0,4.0,dog\n5.0,6.0,cat\n")
    rr = CSVRecordReader(p)
    it = RecordReaderDataSetIterator(rr, batch=2, label_index=2)
    ds = it.next()
    assert ds.features.shape == (2, 2)
    assert it.total_outcomes() == 2
    rr2 = CollectionRecordReader([[0.1, 0.2], [0.3, 0.4]])
    unsup = RecordReaderDataSetIterator(rr2, batch=2, label_index=None)
    b = unsup.next()
    np.testing.assert_array_equal(b.features, b.labels)


def test_metrics_registry_and_step_timer():
    reg = MetricsRegistry()
    reg.increment("x")
    reg.increment("x", 2)
    reg.gauge("g", 3.5)
    with reg.time("op"):
        pass
    snap = reg.snapshot()
    assert snap["counters"]["x"] == 3
    assert snap["gauges"]["g"] == 3.5
    assert snap["timers"]["op"]["count"] == 1

    class FakeModel:
        def score(self):
            return 1.25

    timer = StepTimer(reg, "step")
    timer.iteration_done(FakeModel(), 1)
    timer.iteration_done(FakeModel(), 2)
    assert reg.snapshot()["counters"]["step.iterations"] == 2
    assert reg.snapshot()["gauges"]["step.score"] == 1.25


def test_status_server_endpoints():
    tracker = StateTracker()
    tracker.add_worker("w0")
    tracker.increment("jobs", 4)
    reg = MetricsRegistry()
    reg.increment("steps", 7)
    srv = StatusServer(tracker, reg).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        health = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert health == {"ok": True}
        status = json.loads(urllib.request.urlopen(base + "/status").read())
        assert status["workers"] == ["w0"]
        assert status["counters"]["jobs"] == 4
        metrics = json.loads(urllib.request.urlopen(base + "/metrics").read())
        assert metrics["counters"]["steps"] == 7
    finally:
        srv.stop()


def test_local_store_and_registry(tmp_path):
    store = LocalArtifactStore(tmp_path)
    store.put_bytes("a/b.bin", b"hello")
    assert store.exists("a/b.bin")
    assert store.get_bytes("a/b.bin") == b"hello"
    assert store.list() == ["a/b.bin"]
    with pytest.raises(ValueError):
        store.put_bytes("../escape", b"x")

    saver = StoreModelSaver(store, "m.pkl")
    saver.save({"w": [1, 2]})
    assert saver.load() == {"w": [1, 2]}

    reg = ConfigRegistry(store)
    reg.register("host1", "training", {"lr": 0.1})
    assert reg.exists("host1", "training")
    assert reg.retrieve("host1", "training") == {"lr": 0.1}
    assert reg.hosts() == ["host1"]
    reg.unregister("host1", "training")
    assert not reg.exists("host1", "training")
