"""Self-healing training supervisor (DESIGN.md §12).

Wraps a :class:`~..parallel.trainer.DataParallelTrainer` fit (and, via
:meth:`TrainingSupervisor.supervise`, any run-shaped callable such as
``Driver.run`` or ``DistributedRunner.run``) with the recovery policy the
scaleout layer's heartbeat eviction stops short of:

- **bounded retry** with exponential backoff + jitter
  (:class:`RetryPolicy`) — a transient step failure, a dying data
  pipeline, or a crashed attempt resumes from the newest *valid*
  checkpoint (params + transform state + RNG key + data cursor), so a
  retried run re-joins the uninterrupted trajectory bitwise;
- **NaN/Inf divergence guard**: the trainer detects a non-finite loss at
  the async resolution point and raises
  :class:`~.faults.DivergenceError`; the supervisor rolls back to the
  last checkpoint and (optionally) skips the offending batch window
  instead of silently training on garbage;
- **preemption handling**: SIGTERM/SIGINT set a flag the fit loop polls
  between steps; the trainer drains its pending ring, writes an
  emergency checkpoint, and returns — the supervisor then either resumes
  (simulated/injected preemption) or raises
  :class:`~.faults.TrainingPreempted` so the process can exit having
  lost nothing.

Every recovery event is counted in the metrics registry
(``resilience.retries``, ``resilience.rollbacks``,
``resilience.preemptions``, ``resilience.emergency_checkpoints``,
``resilience.gave_up``) and summarized in :class:`SupervisorReport`.

This module deliberately imports nothing from ``parallel/`` — the trainer
and checkpoint manager arrive as arguments — so the dependency arrow runs
one way: the training stack calls INTO resilience, never the reverse.
"""

from __future__ import annotations

import dataclasses
import random
import signal
import threading
import time
from typing import Any, Callable

from ..observability import FLIGHTREC, METRICS, trace
from ..observability import enabled as _obs_enabled
from ..observability.goodput import GoodputTracker
from .faults import FAULTS, DeviceLossError, DivergenceError, TrainingPreempted


def _device_ids(devices) -> list:
    """JSON-safe device labels for resize bundles."""
    return [getattr(d, "id", str(d)) for d in devices]


def _loss_tail(by_step: dict, n: int = 32) -> dict:
    """The last ``n`` step-keyed losses (JSON-safe) for a flight bundle."""
    return {int(s): float(by_step[s]) for s in sorted(by_step)[-n:]}


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retry with exponential backoff + jitter.

    ``max_attempts`` bounds a *failure streak*: a successful attempt (or a
    divergence rollback, which has its own ``max_rollbacks`` budget)
    resets the streak.  ``retry_on`` is the exception tuple that counts
    as retryable — everything else propagates immediately.
    """

    max_attempts: int = 3
    backoff_base_s: float = 0.05
    backoff_factor: float = 2.0
    backoff_max_s: float = 5.0
    jitter: float = 0.1
    retry_on: tuple = (Exception,)

    def backoff(self, attempt: int, rng: random.Random) -> float:
        base = min(self.backoff_max_s,
                   self.backoff_base_s * self.backoff_factor ** max(0, attempt - 1))
        return base * (1.0 + self.jitter * rng.random())


@dataclasses.dataclass
class SupervisorReport:
    """What happened across one supervised run (also mirrored to METRICS)."""

    attempts: int = 0              # fit attempts started
    retries: int = 0               # attempts that ended in a retryable failure
    rollbacks: int = 0             # divergence rollbacks
    preemptions: int = 0           # injected/simulated preemptions resumed
    emergency_checkpoints: int = 0
    skipped_steps: int = 0         # batch-window steps skipped after rollback
    resumed_from: list = dataclasses.field(default_factory=list)
    resizes: int = 0               # topology changes (shrink + grow)
    mesh_sizes: list = dataclasses.field(default_factory=list)  # after each resize
    # step -> loss for every step a successful attempt resolved; steps
    # whose attempt aborted mid-window are absent (their losses died with
    # the pending ring), so consumers must align by step, not position
    losses_by_step: dict = dataclasses.field(default_factory=dict)
    # GoodputTracker.report() of the run (None when observability is off):
    # wall-clock classified into productive/checkpoint/restore/rollback/
    # stall/drain, summing to wall-clock by construction
    goodput: dict | None = None


class TrainingSupervisor:
    """Retry / rollback / preemption supervisor around a trainer fit.

    ``checkpoint_manager`` is required for :meth:`fit` (resume is the
    whole recovery mechanism) and unused by :meth:`supervise`.
    ``install_signal_handlers`` hooks SIGTERM/SIGINT for
    emergency-checkpoint-then-exit; it is skipped automatically off the
    main thread (the ``signal`` module's constraint).
    """

    def __init__(self, checkpoint_manager=None, policy: RetryPolicy | None = None,
                 *, nan_guard: bool = True, skip_window_on_divergence: bool = True,
                 max_rollbacks: int = 3, install_signal_handlers: bool = True,
                 seed: int = 0):
        self.manager = checkpoint_manager
        self.policy = policy or RetryPolicy()
        self.nan_guard = nan_guard
        self.skip_window_on_divergence = skip_window_on_divergence
        self.max_rollbacks = max_rollbacks
        self.install_signal_handlers = install_signal_handlers
        self.report = SupervisorReport()
        self._rng = random.Random(seed)
        self._preempt_requested = False
        self._injected_preempt = False
        self._grow_requested = False
        self._lost_devices: list = []  # quarantined chips awaiting re-admission
        self.trainer = None  # the live trainer (rebuilt on every resize)
        self.goodput: GoodputTracker | None = None  # set per fit() run
        self._old_handlers: dict[int, Any] = {}

    # ------------------------------------------------------------- signals
    def _handle_signal(self, signum, frame) -> None:
        self._preempt_requested = True
        METRICS.increment("resilience.signals")

    def _install_signals(self) -> None:
        if (not self.install_signal_handlers
                or threading.current_thread() is not threading.main_thread()):
            return
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._old_handlers[sig] = signal.signal(sig, self._handle_signal)

    def _restore_signals(self) -> None:
        for sig, old in self._old_handlers.items():
            signal.signal(sig, old)
        self._old_handlers.clear()

    def _should_stop(self, step: int) -> bool:
        """The fit loop's per-step preemption poll: real signals, the
        injected ``preempt`` fault site, and the ``mesh.grow`` re-admission
        signal all land here — each drains the run through the trainer's
        emergency-checkpoint path before the supervisor acts."""
        if self._preempt_requested:
            return True
        if FAULTS.check("preempt", step) is not None:
            self._injected_preempt = True
            return True
        if FAULTS.check("mesh.grow", step) is not None:
            # graceful half of elasticity: a quarantined worker re-registered.
            # Drain (the trainer writes the emergency checkpoint), then the
            # fit loop rebuilds the mesh LARGER and resumes from it.
            self._grow_requested = True
            return True
        return False

    # ------------------------------------------------------------- elastic
    def _resize(self, factory, old_devices, new_devices, step, direction):
        """Rebuild the trainer over ``new_devices`` (detect -> drain ->
        reshard -> resume, DESIGN.md §21).  The rebuild is timed here; the
        exact state re-split lands in ``elastic.reshard_seconds`` when the
        next attempt's restore crosses widths."""
        t0 = time.monotonic()
        trainer = factory(list(new_devices))
        dt = time.monotonic() - t0
        self.report.resizes += 1
        self.report.mesh_sizes.append(len(new_devices))
        METRICS.increment("elastic.mesh_resizes")
        METRICS.gauge("elastic.mesh_size", len(new_devices))
        METRICS.gauge("elastic.resizes_total", self.report.resizes)
        FLIGHTREC.dump("mesh_resize", extra={
            "direction": direction,
            "step": int(step) if step is not None else None,
            "old_devices": _device_ids(old_devices),
            "new_devices": _device_ids(new_devices),
            "rebuild_seconds": dt,
        })
        return trainer

    # ------------------------------------------------------------- generic
    def supervise(self, fn: Callable, *args, **kwargs):
        """Bounded-retry wrapper for run-shaped callables that own their
        own resume semantics (``Driver.run``, ``DistributedRunner.run``)."""
        attempt = 0
        while True:
            self.report.attempts += 1
            try:
                return fn(*args, **kwargs)
            except self.policy.retry_on:
                attempt += 1
                self.report.retries += 1
                METRICS.increment("resilience.retries")
                if attempt >= self.policy.max_attempts:
                    METRICS.increment("resilience.gave_up")
                    raise
                time.sleep(self.policy.backoff(attempt, self._rng))

    # ------------------------------------------------------------- fit
    def fit(self, trainer, params, data, *, epochs: int = 1,
            checkpoint_every: int = 1, key=None,
            **fit_kwargs) -> tuple[Any, list[float]]:
        """Supervised ``trainer.fit``: run to completion through faults.

        ``data`` must be re-iterable, or a zero-arg callable returning a
        fresh iterable per attempt (one-shot generators cannot be
        replayed after a mid-stream failure).  Returns the final state
        and the per-step losses keyed by step (each step's loss appears
        once even when a window was re-run after a rollback).

        ``trainer`` may also be a FACTORY ``callable(devices) -> trainer``
        (anything without a ``.fit`` attribute): the supervisor then owns
        elasticity.  ``factory(None)`` builds the initial trainer over its
        default devices; on :class:`DeviceLossError` the supervisor drops
        the dead chips, calls ``factory(survivors)``, and resumes from the
        newest valid checkpoint with a resharding restore; on a
        ``mesh.grow`` signal it drains, re-admits the quarantined chips,
        and rebuilds larger.  Without a factory a device loss propagates —
        retrying onto a mesh that still names dead hardware helps nobody.
        """
        if self.manager is None:
            raise ValueError("TrainingSupervisor.fit requires a checkpoint_manager")
        factory = None
        if callable(trainer) and not hasattr(trainer, "fit"):
            factory = trainer
            trainer = factory(None)
        self.trainer = trainer
        METRICS.gauge("elastic.mesh_size",
                      int(trainer.mesh.devices.size))
        data_factory = data if callable(data) else (lambda: data)
        by_step: dict[int, float] = {}
        streak = 0
        rollbacks = 0
        extra_skip = 0
        self._preempt_requested = False
        self._grow_requested = False
        # Goodput accounting (DESIGN.md §22): the supervisor owns the
        # tracker, the trainer marks restore/checkpoint/stall/drain, the
        # exception arms below mark rollback/restore.  None when
        # observability is off — the fit loop then does zero extra work.
        gp = fit_kwargs.pop("goodput", None)
        if gp is None and _obs_enabled():
            gp = GoodputTracker()
        self.goodput = gp
        self._install_signals()
        try:
            with trace.span("resilience.supervised_fit", epochs=epochs):
                while True:
                    self._injected_preempt = False
                    self.report.attempts += 1
                    resumed = self.manager.latest_valid_step()
                    if resumed is not None:
                        self.report.resumed_from.append(resumed)
                    template = trainer.init_state(params, key=key)
                    try:
                        state, losses = trainer.fit(
                            template, data_factory(), epochs=epochs,
                            checkpoint_manager=self.manager,
                            checkpoint_every=checkpoint_every, resume=True,
                            nan_guard=self.nan_guard,
                            should_stop=self._should_stop,
                            extra_skip=extra_skip, goodput=gp,
                            **fit_kwargs)
                    except DivergenceError as e:
                        if gp is not None:
                            gp.transition("rollback")
                        trainer.abort()
                        rollbacks += 1
                        self.report.rollbacks += 1
                        METRICS.increment("resilience.rollbacks")
                        # flight bundle BEFORE the rollback decision: the
                        # rings still hold the spans/chaos fires leading up
                        # to the NaN, and the loss tail is step-keyed
                        FLIGHTREC.dump("divergence", extra={
                            "step": int(e.step),
                            "value": repr(getattr(e, "value", None)),
                            "rollbacks": rollbacks,
                            "losses_tail": _loss_tail(by_step)})
                        if rollbacks > self.max_rollbacks:
                            METRICS.increment("resilience.gave_up")
                            raise
                        if self.skip_window_on_divergence:
                            # skip the batch window (target, e.step]: the
                            # restore covers steps <= target, extra_skip
                            # drops the batches that produced the NaN
                            target = self.manager.latest_valid_step() or 0
                            window = max(1, e.step - target)
                            extra_skip += window
                            self.report.skipped_steps += window
                        continue
                    except DeviceLossError as e:
                        # abrupt half of elasticity: chips died mid-step.
                        # The in-flight window is gone with them — drop it,
                        # rebuild from the survivors, reshard-resume.
                        if gp is not None:
                            gp.transition("restore")  # rebuild + reshard
                        trainer.abort()
                        METRICS.increment("resilience.device_losses")
                        if factory is None:
                            raise
                        old = list(trainer.mesh.devices.flat)
                        dead = set(id(d) for d in e.devices)
                        survivors = [d for d in old if id(d) not in dead]
                        if not survivors:
                            METRICS.increment("resilience.gave_up")
                            raise
                        self._lost_devices.extend(e.devices)
                        trainer = self._resize(factory, old, survivors,
                                               e.step, "shrink")
                        self.trainer = trainer
                        continue
                    except self.policy.retry_on as e:
                        if gp is not None:
                            gp.transition("rollback")  # incl. backoff sleep
                        trainer.abort()
                        streak += 1
                        self.report.retries += 1
                        METRICS.increment("resilience.retries")
                        FLIGHTREC.dump("supervisor_retry", extra={
                            "error": repr(e),
                            "streak": streak,
                            "losses_tail": _loss_tail(by_step)})
                        if streak >= self.policy.max_attempts:
                            METRICS.increment("resilience.gave_up")
                            raise
                        time.sleep(self.policy.backoff(streak, self._rng))
                        continue
                    streak = 0
                    for i, loss in enumerate(losses):
                        by_step[state.step - len(losses) + 1 + i] = loss
                    self.report.losses_by_step = dict(by_step)
                    if self._grow_requested:
                        self._grow_requested = False
                        self.report.emergency_checkpoints += 1
                        if factory is not None and self._lost_devices:
                            old = list(trainer.mesh.devices.flat)
                            have = {id(d) for d in old}
                            regained = [d for d in self._lost_devices
                                        if id(d) not in have]
                            self._lost_devices = []
                            if regained:
                                if gp is not None:
                                    gp.transition("restore")
                                trainer = self._resize(
                                    factory, old, old + regained,
                                    state.step, "grow")
                                self.trainer = trainer
                        continue  # resume from the drain checkpoint
                    if self._injected_preempt:
                        self.report.preemptions += 1
                        self.report.emergency_checkpoints += 1
                        METRICS.increment("resilience.preemptions")
                        continue  # resume from the emergency checkpoint
                    if self._preempt_requested:
                        METRICS.increment("resilience.preemptions")
                        exc = TrainingPreempted(state.step)
                        exc.state = state
                        raise exc
                    return state, [by_step[s] for s in sorted(by_step)]
        finally:
            if gp is not None:
                self.report.goodput = gp.finish()
            self._restore_signals()
