"""Host-side scaleout control plane: jobs, performers, state tracking,
routing, and an in-process master/worker runtime.

Capability parity with the reference's L5/L6 (SURVEY.md §2.3-2.4):

- ``Job``/``JobIterator`` (``scaleout/job/*.java``) — serializable work units
- ``WorkerPerformer`` SPI (``scaleout/perform/WorkerPerformer.java``)
- ``JobAggregator`` (``scaleout/aggregator/JobAggregator.java``) with the
  running-average ``ArrayAggregator`` (≡ ``INDArrayAggregator``)
- ``StateTracker`` (``scaleout/api/statetracker/StateTracker.java`` ~40-method
  blackboard): workers, heartbeats, jobs, updates, counters, current-model
  replication — an in-process, thread-safe dict replacing Hazelcast
- ``WorkRouter`` policies: ``IterativeReduceWorkRouter`` (dispatch only when
  all workers reported) vs ``HogWildWorkRouter`` (always dispatch)
- ``DistributedRunner`` (``DeepLearning4jDistributed.java``): master loop +
  worker threads with 1 s heartbeats, 120 s stale eviction
  (``MasterActor.java:123-153``), job re-routing, ``ModelSaver`` hooks.

Why threads, not actors: on TPU pods the *data plane* is SPMD collectives
(``trainer.py``); what remains for a control plane is exactly what fits in
one coordinator process (JAX single-controller model).  The SPI surface is
kept so orchestration-level workloads (sharded embedding training, grid
jobs) and the reference's test pattern ("distributed-without-a-cluster",
``BaseTestDistributed``) port over directly.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Iterator, Protocol, Sequence

import numpy as np

from ..observability import FLIGHTREC, METRICS, trace
from ..resilience.faults import FAULTS, WorkerKilled


class ScaleoutTimeout(RuntimeError):
    """``DistributedRunner.run`` hit ``max_wall_s`` with work outstanding.

    ``partial`` carries the tracker's current model at expiry so a caller
    that wants best-effort results can still read them — but the default
    is to RAISE: a run that silently returns half-trained state on
    deadline is indistinguishable from success (DESIGN.md §12).
    """

    def __init__(self, max_wall_s: float, partial: Any = None):
        super().__init__(
            f"scaleout run exceeded max_wall_s={max_wall_s:g}s with jobs "
            "outstanding (partial result attached as .partial)")
        self.partial = partial


# --------------------------------------------------------------------------- jobs

@dataclass
class Job:
    """Serializable work unit (``scaleout/job/Job.java``).

    ``attempts`` counts failed executions (incremented by the master on
    each requeue); at ``max_job_attempts`` the job is quarantined instead
    of re-routed — a poison job cannot take the whole run down with it.
    """

    work: Any
    worker_id: str = ""
    result: Any = None
    job_id: str = ""
    attempts: int = 0
    last_error: str = ""
    # distributed-trace identity: stamped by the master at dispatch, bound
    # on the worker around perform() — one run renders as one flame
    trace_id: str = ""
    parent_span_id: str = ""


class JobIterator(Protocol):
    """``scaleout/job/JobIterator.java``."""

    def next(self, worker_id: str = "") -> Job: ...
    def has_next(self) -> bool: ...
    def reset(self) -> None: ...


class CollectionJobIterator:
    """``scaleout/job/collection/CollectionJobIterator.java``."""

    def __init__(self, items: Sequence[Any]):
        self.items = list(items)
        self._i = 0

    def next(self, worker_id: str = "") -> Job:
        job = Job(work=self.items[self._i], worker_id=worker_id)
        self._i += 1
        return job

    def has_next(self) -> bool:
        return self._i < len(self.items)

    def reset(self) -> None:
        self._i = 0


class DataSetJobIterator:
    """Wrap a DataSetIterator as a job stream (``JobIterator`` over batches)."""

    def __init__(self, it):
        self._it = it

    def next(self, worker_id: str = "") -> Job:
        return Job(work=self._it.next(), worker_id=worker_id)

    def has_next(self) -> bool:
        return self._it.has_next()

    def reset(self) -> None:
        self._it.reset()


# --------------------------------------------------------------------------- SPI

class WorkerPerformer(Protocol):
    """``scaleout/perform/WorkerPerformer.java``: do the work, then push
    updates through ``update``."""

    def perform(self, job: Job) -> None: ...
    def update(self, *args: Any) -> None: ...


class JobAggregator(Protocol):
    """``scaleout/aggregator/JobAggregator.java``."""

    def accumulate(self, job: Job) -> None: ...
    def aggregate(self) -> Any: ...


class ArrayAggregator:
    """Running average of pytree/array results (``INDArrayAggregator``:
    accumulate sum, divide by count on aggregate)."""

    def __init__(self):
        self._sum = None
        self._count = 0

    def accumulate(self, job: Job) -> None:
        import jax
        if job.result is None:
            return
        if self._sum is None:
            self._sum = jax.tree_util.tree_map(np.asarray, job.result)
        else:
            self._sum = jax.tree_util.tree_map(
                lambda a, b: a + np.asarray(b), self._sum, job.result)
        self._count += 1

    def aggregate(self) -> Any:
        import jax
        if self._sum is None:
            return None
        return jax.tree_util.tree_map(lambda a: a / self._count, self._sum)


# --------------------------------------------------------------------------- state tracker

class StateTracker:
    """In-process cluster blackboard (Hazelcast ``BaseHazelCastStateTracker``
    capability: workers/jobs/updates/heartbeats/counters/current-model).
    Thread-safe; all mutation under one lock (operations are tiny)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._workers: set[str] = set()
        self._enabled: dict[str, bool] = {}
        self._heartbeats: dict[str, float] = {}
        self._jobs: dict[str, Job] = {}          # worker -> current job
        self._updates: dict[str, Any] = {}       # worker -> latest update
        self._counters: dict[str, float] = defaultdict(float)
        self._current: Any = None                # current global model/params
        self._needs_replicate: set[str] = set()
        self._done = False
        self._saved_workers: dict[str, Job] = {} # job persistence for re-retrieval
        self._failed: list[tuple[str, Job, str]] = []   # prompt failure reports
        self._quarantined: list[Job] = []               # poison jobs, retired
        self.update_listeners: list[Callable[[Any], None]] = []

    # -- workers --------------------------------------------------------
    def add_worker(self, worker_id: str) -> None:
        with self._lock:
            self._workers.add(worker_id)
            self._enabled[worker_id] = True
            self._heartbeats[worker_id] = time.time()

    def remove_worker(self, worker_id: str) -> None:
        with self._lock:
            self._workers.discard(worker_id)
            self._enabled.pop(worker_id, None)
            self._heartbeats.pop(worker_id, None)
            self._jobs.pop(worker_id, None)

    def workers(self) -> list[str]:
        with self._lock:
            return sorted(self._workers)

    def enable_worker(self, worker_id: str) -> None:
        with self._lock:
            self._enabled[worker_id] = True

    def disable_worker(self, worker_id: str) -> None:
        with self._lock:
            self._enabled[worker_id] = False

    def is_enabled(self, worker_id: str) -> bool:
        with self._lock:
            return self._enabled.get(worker_id, False)

    # -- heartbeats / failure detection ---------------------------------
    def heartbeat(self, worker_id: str) -> None:
        with self._lock:
            self._heartbeats[worker_id] = time.time()

    def last_heartbeat(self, worker_id: str) -> float:
        with self._lock:
            return self._heartbeats.get(worker_id, 0.0)

    def evict_stale(self, timeout_s: float = 120.0) -> tuple[list[str], list["Job"]]:
        """Master-side eviction sweep (``MasterActor.java:123-153``).

        Returns (evicted worker ids, their orphaned in-flight jobs) so the
        master can re-route the work (``StateTracker.loadForWorker`` parity).
        """
        now = time.time()
        evicted, orphans = [], []
        with self._lock:
            for w in list(self._workers):
                if now - self._heartbeats.get(w, 0) > timeout_s:
                    evicted.append(w)
                    job = self._jobs.get(w)
                    if job is not None:
                        orphans.append(job)
                    self.remove_worker(w)
        return evicted, orphans

    # -- jobs -----------------------------------------------------------
    def add_job(self, job: Job) -> None:
        with self._lock:
            self._jobs[job.worker_id] = job
            self._saved_workers[job.worker_id] = job

    def job_for(self, worker_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(worker_id)

    def clear_job(self, worker_id: str) -> None:
        with self._lock:
            self._jobs.pop(worker_id, None)

    def current_jobs(self) -> list[Job]:
        with self._lock:
            return list(self._jobs.values())

    def load_for_worker(self, worker_id: str) -> Job | None:
        """Job re-retrieval after worker restart (``WorkRetriever``)."""
        with self._lock:
            return self._saved_workers.get(worker_id)

    # -- failures / quarantine ------------------------------------------
    def record_failure(self, worker_id: str, job: Job, error: str = "") -> None:
        """Prompt failure report from a dying worker: atomically moves the
        job from in-flight to the failed queue, so the master re-routes it
        on the next poll instead of waiting out the heartbeat timeout."""
        with self._lock:
            job.last_error = error
            self._jobs.pop(worker_id, None)
            self._failed.append((worker_id, job, error))

    def take_failed(self) -> list[tuple[str, Job, str]]:
        with self._lock:
            out, self._failed = self._failed, []
            return out

    def has_failures(self) -> bool:
        with self._lock:
            return bool(self._failed)

    def quarantine(self, job: Job) -> None:
        with self._lock:
            self._quarantined.append(job)

    def quarantined(self) -> list[Job]:
        with self._lock:
            return list(self._quarantined)

    # -- updates --------------------------------------------------------
    def add_update(self, worker_id: str, update: Any) -> None:
        with self._lock:
            self._updates[worker_id] = update
            listeners = list(self.update_listeners)
        METRICS.increment("scaleout.updates")
        for l in listeners:
            l(update)

    def updates(self) -> dict[str, Any]:
        with self._lock:
            return dict(self._updates)

    def clear_updates(self) -> None:
        with self._lock:
            self._updates.clear()

    # -- counters (distributed words-seen etc.) -------------------------
    def increment(self, key: str, by: float = 1.0) -> None:
        with self._lock:
            self._counters[key] += by

    def count(self, key: str) -> float:
        with self._lock:
            return self._counters[key]

    # -- current model / replication ------------------------------------
    def set_current(self, value: Any) -> None:
        with self._lock:
            self._current = value
            self._needs_replicate = set(self._workers)

    def get_current(self) -> Any:
        with self._lock:
            return self._current

    def add_replicate(self, worker_id: str) -> None:
        with self._lock:
            self._needs_replicate.add(worker_id)

    def needs_replicate(self, worker_id: str) -> bool:
        with self._lock:
            return worker_id in self._needs_replicate

    def done_replicating(self, worker_id: str) -> None:
        with self._lock:
            self._needs_replicate.discard(worker_id)

    # -- lifecycle ------------------------------------------------------
    def finish(self) -> None:
        with self._lock:
            self._done = True

    def reset_done(self) -> None:
        """Clear a leftover finish flag (a master starting a new run on a
        reused tracker/state dir must not no-op on the previous run's
        DONE)."""
        with self._lock:
            self._done = False

    def is_done(self) -> bool:
        with self._lock:
            return self._done


# --------------------------------------------------------------------------- routers

class WorkRouter:
    """Dispatch/merge policy (``api/workrouter/WorkRouter.java`` +
    ``BaseWorkRouter.java``)."""

    def __init__(self, tracker: StateTracker, aggregator_factory=ArrayAggregator):
        self.tracker = tracker
        self.aggregator_factory = aggregator_factory

    def send_work(self) -> bool:
        raise NotImplementedError

    def update(self) -> None:
        """Aggregate worker updates into the new current model
        (``BaseWorkRouter.update`` → ``IterateAndUpdateImpl``)."""
        updates = self.tracker.updates()
        if not updates:
            return
        agg = self.aggregator_factory()
        for wid, upd in updates.items():
            agg.accumulate(Job(work=None, worker_id=wid, result=upd))
        merged = agg.aggregate()
        if merged is not None:
            self.tracker.set_current(merged)
        self.tracker.clear_updates()


class IterativeReduceWorkRouter(WorkRouter):
    """Synchronous parameter averaging: only dispatch the next wave after
    every live worker has reported (``IterativeReduceWorkRouter.java:30``)."""

    def send_work(self) -> bool:
        n_workers = len(self.tracker.workers())
        return n_workers > 0 and len(self.tracker.updates()) >= n_workers


class HogWildWorkRouter(WorkRouter):
    """Asynchronous: always dispatch (``HogWildWorkRouter.java``)."""

    def send_work(self) -> bool:
        return True


# --------------------------------------------------------------------------- model saving

class ModelSaver(Protocol):
    """``actor/core/ModelSaver.java``."""

    def save(self, model: Any) -> None: ...
    def load(self) -> Any: ...


class FileModelSaver:
    """``DefaultModelSaver`` — pickle to a file, atomic replace.

    Each save writes a UNIQUE temp file in the target directory (two
    concurrent savers on the same path previously raced on one shared
    ``.tmp`` name — a torn mix of both pickles could be published) and
    fsyncs before the rename, so the published file is always one
    complete, durable pickle.
    """

    def __init__(self, path: str | Path):
        self.path = Path(path)

    def save(self, model: Any) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fd = tempfile.NamedTemporaryFile(
            dir=self.path.parent, prefix=self.path.name + ".",
            suffix=".tmp", delete=False)
        try:
            with fd as f:
                pickle.dump(model, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(fd.name, self.path)
        except Exception:
            Path(fd.name).unlink(missing_ok=True)
            raise

    def load(self) -> Any:
        with open(self.path, "rb") as f:
            return pickle.load(f)


# --------------------------------------------------------------------------- runner

class DistributedRunner:
    """In-process master/worker runtime (``DeepLearning4jDistributed`` +
    ``MasterActor``/``WorkerActor`` loops).

    Workers = threads pulling jobs via the StateTracker, running the
    WorkerPerformer, heartbeating every ``heartbeat_s``; the master loop
    polls, applies the WorkRouter policy, re-routes orphaned jobs, and
    evicts stale workers.  Mirrors the reference's test pattern: the REAL
    orchestration stack in one process.
    """

    def __init__(self, job_iterator, performer_factory: Callable[[StateTracker], WorkerPerformer],
                 n_workers: int = 2, router_cls=IterativeReduceWorkRouter,
                 tracker: StateTracker | None = None,
                 model_saver: ModelSaver | None = None,
                 heartbeat_s: float = 0.05, poll_s: float = 0.02,
                 eviction_timeout_s: float = 120.0,
                 max_job_attempts: int = 3, job_timeout_s: float = 0.0,
                 max_respawns: int = 0, on_timeout: str = "raise"):
        if on_timeout not in ("raise", "return"):
            raise ValueError(f"on_timeout must be 'raise' or 'return', got {on_timeout!r}")
        self.job_iterator = job_iterator
        self.performer_factory = performer_factory
        self.n_workers = n_workers
        self.tracker = tracker or StateTracker()
        self.router = router_cls(self.tracker)
        self.model_saver = model_saver
        self.heartbeat_s = heartbeat_s
        self.poll_s = poll_s
        self.eviction_timeout_s = eviction_timeout_s
        # resilience knobs (DESIGN.md §12): per-job retry budget before
        # quarantine, optional per-job execution deadline (0 = disabled;
        # enabling it trades exactly-once for at-most-``max_job_attempts``
        # execution — a timed-out worker may still finish concurrently),
        # and a replacement-worker budget (0 = no respawn: the pool only
        # shrinks on failure, which keeps iterative-reduce wave averages
        # comparable across a death; raise it when capacity matters more
        # than wave composition, or when every worker can crash)
        self.max_job_attempts = max(1, max_job_attempts)
        self.job_timeout_s = job_timeout_s
        self.max_respawns = max_respawns
        self.on_timeout = on_timeout
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._dispatched_at: dict[str, float] = {}  # worker -> dispatch time
        self._worker_seq = 0
        self._respawned = 0

    # -- worker loop ----------------------------------------------------
    def _worker_loop(self, worker_id: str):
        performer = self.performer_factory(self.tracker)
        while not self._stop.is_set() and not self.tracker.is_done():
            self.tracker.heartbeat(worker_id)
            if not self.tracker.is_enabled(worker_id):
                time.sleep(self.heartbeat_s)
                continue
            if self.tracker.needs_replicate(worker_id):
                current = self.tracker.get_current()
                if current is not None:
                    performer.update(current)
                self.tracker.done_replicating(worker_id)
            job = self.tracker.job_for(worker_id)
            if job is None:
                time.sleep(self.poll_s)
                continue
            # chaos seams: silent death (thread exits, job still assigned,
            # heartbeats stop — the eviction path must recover) and the
            # straggler simulation (injected sleep before performing)
            FAULTS.maybe_fire("scaleout.worker")
            slow = FAULTS.check("scaleout.worker.slow")
            if slow is not None:
                time.sleep(slow.delay_s)
            try:
                with METRICS.time("scaleout.job"), \
                        trace.bind(job.trace_id, job.parent_span_id), \
                        trace.span("scaleout.perform", worker=worker_id,
                                   attempts=job.attempts):
                    FAULTS.maybe_fire("scaleout.perform")
                    performer.perform(job)
            except WorkerKilled:
                raise            # injected silent death: no failure report
            except Exception as e:
                # prompt failure report: the master re-routes the job on
                # its next poll instead of waiting out the heartbeat
                # timeout; the worker thread still dies (its performer
                # state is suspect) and a replacement is spawned
                self.tracker.record_failure(worker_id, job, repr(e))
                METRICS.increment("scaleout.job_failures")
                raise
            if job.result is not None:
                self.tracker.add_update(worker_id, job.result)
            self.tracker.clear_job(worker_id)
            METRICS.increment("scaleout.jobs_completed")

    # -- worker lifecycle (subclass seam: ProcessDistributedRunner spawns
    #    OS processes here instead of threads) ---------------------------
    def _spawn_one(self, wid: str) -> None:
        self.tracker.add_worker(wid)
        t = threading.Thread(target=self._worker_loop, args=(wid,), daemon=True)
        self._threads.append(t)
        t.start()

    def _spawn_workers(self) -> None:
        for _ in range(self.n_workers):
            wid = f"worker-{self._worker_seq}"
            self._worker_seq += 1
            self._spawn_one(wid)

    def _maybe_respawn(self) -> None:
        """Top the pool back up to ``n_workers`` after deaths/evictions,
        bounded by ``max_respawns`` (a deterministic crash loop must run
        out of budget, not respawn forever).  Once the budget is exhausted
        the wave SHRINKS to the live worker count instead of running with
        a hole: ``IterativeReduceWorkRouter`` and ``ArrayAggregator``
        already key off the live worker set, so superstep averages are
        weighted by the surviving wave, not a fixed composition."""
        live = len(self.tracker.workers())
        while live < self.n_workers and self._respawned < self.max_respawns:
            wid = f"worker-{self._worker_seq}"
            self._worker_seq += 1
            self._respawned += 1
            self._spawn_one(wid)
            METRICS.increment("scaleout.workers_respawned")
            live += 1
        if 0 < live < self.n_workers:
            # a worker stayed dead past its respawn budget — accept the
            # smaller wave (elastic shrink) rather than waiting on a
            # phantom.  (live == 0 is left to the run deadline: there is
            # no wave to shrink to.)
            old = self.n_workers
            self.n_workers = live
            METRICS.increment("scaleout.wave_shrinks")
            METRICS.gauge("elastic.wave_size", live)
            FLIGHTREC.dump("mesh_resize", extra={
                "kind": "scaleout_wave", "direction": "shrink",
                "old_wave": old, "new_wave": live,
                "workers": self.tracker.workers()})

    def register_worker(self, worker_id: str | None = None) -> str:
        """Grow the wave: admit a new (or re-registering) worker into a
        live run.  Raises the target ``n_workers`` so the master expects
        the larger wave, spawns the worker, and notes the resize — the
        inverse of the shrink in :meth:`_maybe_respawn`."""
        wid = worker_id or f"worker-{self._worker_seq}"
        if worker_id is None:
            self._worker_seq += 1
        old = self.n_workers
        self.n_workers += 1
        self._spawn_one(wid)
        METRICS.increment("scaleout.wave_grows")
        METRICS.gauge("elastic.wave_size", len(self.tracker.workers()))
        FLIGHTREC.dump("mesh_resize", extra={
            "kind": "scaleout_wave", "direction": "grow",
            "old_wave": old, "new_wave": self.n_workers, "worker": wid})
        return wid

    def retire_worker(self, worker_id: str | None = None) -> str | None:
        """Shrink the wave by ONE idle worker — the graceful inverse of
        :meth:`register_worker` (the autoscaler's scale-in seam).

        Only a worker with NO in-flight job is eligible (drain, never
        abandon: a mid-job worker finishes and becomes eligible next
        window), and the last enabled worker is never retired.  The
        target ``n_workers`` drops FIRST so the respawn sweep cannot
        refill the hole, then the worker is disabled — it parks on
        heartbeats and takes no further jobs.  Returns the retired id,
        or ``None`` when nothing is eligible right now (the caller
        retries at its next control window)."""
        live = [w for w in self.tracker.workers()
                if self.tracker.is_enabled(w)]
        if len(live) <= 1:
            return None
        if worker_id is None:
            idle = [w for w in reversed(live)
                    if self.tracker.job_for(w) is None]
            worker_id = idle[0] if idle else None
        elif (worker_id not in live
              or self.tracker.job_for(worker_id) is not None):
            worker_id = None
        if worker_id is None:
            return None
        old = self.n_workers
        self.n_workers = max(1, self.n_workers - 1)
        self.tracker.disable_worker(worker_id)
        METRICS.increment("scaleout.wave_shrinks")
        METRICS.gauge("elastic.wave_size",
                      len([w for w in self.tracker.workers()
                           if self.tracker.is_enabled(w)]))
        FLIGHTREC.dump("mesh_resize", extra={
            "kind": "scaleout_wave", "direction": "shrink",
            "old_wave": old, "new_wave": self.n_workers,
            "worker": worker_id})
        return worker_id

    def _shutdown_workers(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)

    def _observe_heartbeats(self) -> None:
        """Gauge per-worker heartbeat age (how stale each worker looks to
        the master) — the signal the eviction sweep thresholds on."""
        now = time.time()
        for w in self.tracker.workers():
            METRICS.gauge("scaleout.heartbeat_age_s." + w,
                          round(now - self.tracker.last_heartbeat(w), 3))

    # -- master loop ----------------------------------------------------
    def run(self, max_wall_s: float = 300.0) -> Any:
        with trace.span("scaleout.run", n_workers=self.n_workers):
            return self._run(max_wall_s)

    def _requeue_or_quarantine(self, job: Job, requeue: list[Job]) -> None:
        """One more failed attempt for ``job``: re-route it while it has
        retry budget, quarantine it when it runs out (a poison job must
        not wedge the run)."""
        job.attempts += 1
        if job.attempts >= self.max_job_attempts:
            self.tracker.quarantine(job)
            METRICS.increment("scaleout.jobs_quarantined")
        else:
            requeue.append(job)
            METRICS.increment("scaleout.jobs_requeued")

    def _run(self, max_wall_s: float) -> Any:
        self.tracker.reset_done()    # a prior run's DONE must not no-op us
        METRICS.increment("scaleout.runs")
        self._spawn_workers()
        METRICS.gauge("elastic.wave_size", self.n_workers)
        deadline = time.time() + max_wall_s
        last_evict = time.time()
        requeue: list[Job] = []  # orphaned/failed jobs awaiting re-dispatch
        completed = False
        try:
            while time.time() < deadline:
                if self.tracker.is_done():
                    completed = True
                    break            # external kill (Kill CLI / finish flag)
                # prompt failure reports: the reporting worker's thread (or
                # process) is gone — drop it now and re-route its job,
                # without waiting out the heartbeat timeout
                for wid, job, _err in self.tracker.take_failed():
                    self.tracker.remove_worker(wid)
                    self._dispatched_at.pop(wid, None)
                    self._requeue_or_quarantine(job, requeue)
                # per-job execution deadline (opt-in): a wedged worker is
                # treated like a dead one — removed and its job re-routed
                if self.job_timeout_s > 0:
                    now = time.time()
                    for wid, t0 in list(self._dispatched_at.items()):
                        if now - t0 <= self.job_timeout_s:
                            continue
                        job = self.tracker.job_for(wid)
                        self._dispatched_at.pop(wid, None)
                        if job is None:
                            continue  # finished right at the deadline
                        self.tracker.remove_worker(wid)
                        METRICS.increment("scaleout.job_timeouts")
                        self._requeue_or_quarantine(job, requeue)
                # eviction sweep (reference: every 60 s; scaled to poll rate);
                # orphaned in-flight jobs are re-routed to live workers
                if time.time() - last_evict > max(1.0, self.eviction_timeout_s / 2):
                    self._observe_heartbeats()
                    evicted, orphans = self.tracker.evict_stale(self.eviction_timeout_s)
                    if evicted:
                        METRICS.increment("scaleout.workers_evicted", len(evicted))
                        for wid in evicted:
                            self._dispatched_at.pop(wid, None)
                    for job in orphans:
                        self._requeue_or_quarantine(job, requeue)
                    last_evict = time.time()
                # top the pool back up after deaths/evictions (bounded)
                self._maybe_respawn()
                if self.router.send_work():
                    self.router.update()
                    if self.model_saver is not None:
                        current = self.tracker.get_current()
                        if current is not None:
                            self.model_saver.save(current)
                # dispatch to idle workers — but never hand a worker its next
                # job while its previous update awaits aggregation (the
                # updates dict is keyed by worker: a second result would
                # overwrite the first, silently breaking the synchronous
                # superstep average)
                dispatched = False
                pending_updates = self.tracker.updates()
                for wid in self.tracker.workers():
                    if self.tracker.job_for(wid) is not None or wid in pending_updates:
                        continue
                    if requeue:
                        job = requeue.pop(0)
                    elif self.job_iterator.has_next():
                        job = self.job_iterator.next(wid)
                    else:
                        continue
                    job.worker_id = wid
                    if not job.trace_id:
                        # the scaleout.run span is open on this thread, so
                        # dispatched jobs inherit the run's trace identity
                        ctx = trace.current_trace_context()
                        if ctx is not None:
                            job.trace_id, job.parent_span_id = ctx
                    self.tracker.add_job(job)
                    self._dispatched_at[wid] = time.time()
                    METRICS.increment("scaleout.jobs_dispatched")
                    dispatched = True
                if (not self.job_iterator.has_next()
                        and not requeue
                        and not self.tracker.current_jobs()
                        and not self.tracker.has_failures()
                        and not dispatched):
                    # drain final updates
                    if self.tracker.updates():
                        self.router.update()
                        if self.model_saver is not None:
                            current = self.tracker.get_current()
                            if current is not None:
                                self.model_saver.save(current)
                    self.tracker.finish()
                    completed = True
                    break
                time.sleep(self.poll_s)
        finally:
            self._shutdown_workers()
        if not completed:
            # the old behavior — returning half-finished state on deadline
            # as if nothing happened — was indistinguishable from success
            METRICS.increment("scaleout.run_timeouts")
            if self.on_timeout == "raise":
                raise ScaleoutTimeout(max_wall_s, partial=self.tracker.get_current())
        return self.tracker.get_current()
