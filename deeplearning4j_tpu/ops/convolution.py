"""Convolution and pooling primitives.

TPU-native equivalent of ND4J ``Convolution.conv2d`` and
``Transforms.maxPool`` as consumed by the reference's
``nn/layers/convolution/ConvolutionDownSampleLayer.java:40-53``.  Built on
``lax.conv_general_dilated`` / ``lax.reduce_window`` so XLA tiles them onto
the MXU / VPU; layout is NCHW to match the reference's
(examples, channels, rows, cols) convention, and both ops are fully
differentiable (the reference's conv backward is a stub —
``ConvolutionDownSampleLayer.java:105-112`` — ours is real autodiff).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

_DN = ("NCHW", "OIHW", "NCHW")


def conv2d(x: jnp.ndarray, w: jnp.ndarray, stride: Sequence[int] = (1, 1),
           padding: str = "VALID", precision=None) -> jnp.ndarray:
    """2-D convolution. x: (N,C,H,W); w: (O,I,kH,kW) -> (N,O,H',W').

    Note: like XLA (and unlike the reference's FFT-based ``conv2d`` full-mode),
    this is cross-correlation with VALID/SAME padding — the deep-learning
    convention the reference's layer actually relies on.  ``precision=None``
    uses the backend default (fast MXU path on TPU); pass
    ``lax.Precision.HIGHEST`` for full-f32 accumulation.
    """
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(stride), padding=padding,
        dimension_numbers=_DN, precision=precision,
        preferred_element_type=jnp.float32)


def max_pool(x: jnp.ndarray, window: Sequence[int], stride: Sequence[int] | None = None,
             padding: str = "VALID") -> jnp.ndarray:
    """Max pooling over the trailing two (spatial) dims of an NCHW tensor."""
    stride = tuple(stride) if stride is not None else tuple(window)
    dims = (1, 1) + tuple(window)
    strides = (1, 1) + stride
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, padding)


def avg_pool(x: jnp.ndarray, window: Sequence[int], stride: Sequence[int] | None = None,
             padding: str = "VALID") -> jnp.ndarray:
    stride = tuple(stride) if stride is not None else tuple(window)
    dims = (1, 1) + tuple(window)
    strides = (1, 1) + stride
    summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, padding)
    if padding == "VALID":
        return summed / (window[0] * window[1])
    counts = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strides, padding)
    return summed / counts


def im2col(x: jnp.ndarray, kh: int, kw: int, stride: Sequence[int] = (1, 1),
           padding: str = "VALID") -> jnp.ndarray:
    """Extract sliding patches: (N,C,H,W) -> (N, C*kh*kw, L) with L output
    positions.  Parity helper for the reference's im2col-based kernels; on TPU
    prefer conv2d directly (XLA already lowers to MXU-tiled convolution)."""
    patches = lax.conv_general_dilated_patches(
        x, (kh, kw), tuple(stride), padding, dimension_numbers=_DN)
    n, ckk, h, w = patches.shape
    return patches.reshape(n, ckk, h * w)
