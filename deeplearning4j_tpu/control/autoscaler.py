"""The SLO-driven autoscaler: a supervised control loop that fails safe.

Signals in (read-only): ``slo.burn_rate.*`` (worst full-window burn),
live ``RequestQueue`` depth (post-expiry-sweep, so dead requests never
inflate it), and the forecaster's ``time_to_breach`` horizon.  Actions
out (through existing seams ONLY — graftlint CT01): grow via
``PrefixRouter.scale_up`` (the replica warms BEFORE ring admission),
shrink via ``scale_down`` (quarantine-path drain; on timeout the
replica is reactivated, never half-drained), or resize a training wave
via ``register_worker``/``retire_worker``.  The :class:`Autoscaler`
itself never touches a pool or ring: it is wired with four callables
(``read_signals``/``scale_up``/``scale_down``/``pool_size``), which is
also what makes the decision logic unit-testable against a scripted
metric feed.

Decisions are hysteresis-damped three ways: a cooldown after ANY
attempt (a failed scale-up burns the window too — retry storms against
a broken actuator are worse than waiting), min/max pool bounds, and
scale-IN only after ``down_consecutive`` consecutive quiet windows
(one quiet sample after a spike must not shed the capacity the spike
just proved necessary).  At most one action per evaluation window.

Failure mode, by construction: the ``control.autoscaler`` fault site
kills the loop permanently — the pool freezes at its current size
(static capacity), routing and drain state are untouched, and the
``control.autoscaler_alive`` gauge drops to 0 so the outage is visible.
An autoscaler that can crash into a HALF-ACTION is the bug this design
refuses: every actuator it calls is itself all-or-nothing.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..observability import FLIGHTREC, METRICS, core, trace
from ..resilience.faults import FAULTS


@dataclass(frozen=True)
class AutoscalerConfig:
    """Control-loop knobs.  Defaults suit the smoke tools' time scale;
    production tunes ``interval_s``/``cooldown_s`` up together."""

    interval_s: float = 1.0        # evaluation window
    min_replicas: int = 1
    max_replicas: int = 4
    cooldown_s: float = 10.0       # after any attempted action
    burn_up: float = 1.0           # burn >= this -> scale up
    burn_down: float = 0.25        # burn <= this counts toward quiet
    queue_high: int = 32           # queue depth -> scale up
    queue_low: int = 2             # queue depth <= this counts toward quiet
    ttb_horizon_s: float = 120.0   # forecast breach inside this -> scale up
    down_consecutive: int = 3      # quiet windows before scale-in
    warm_timeout_s: float = 120.0  # passed through to scale_up actuators
    drain_timeout_s: float = 30.0  # passed through to scale_down actuators


@dataclass
class ControlSignals:
    """One window's worth of inputs to :meth:`Autoscaler.evaluate`."""

    burn: float | None = None        # worst full-window SLO burn rate
    queue_depth: int = 0             # live queued requests (swept)
    ttb_s: float | None = None       # forecast seconds to SLO breach


class Autoscaler:
    """Supervised scale controller over injected signal/actuator seams.

    ``read_signals()`` returns a :class:`ControlSignals`; ``scale_up()``
    and ``scale_down()`` perform one all-or-nothing resize (raising on
    failure); ``pool_size()`` returns current capacity.  ``clock`` is
    injectable so the hysteresis logic is testable without sleeping.
    Lifecycle follows the ``FleetScraper`` daemon idiom: ``start()`` is
    a no-op while alive, the loop swallows everything except the kill
    fault, ``stop()`` joins.
    """

    def __init__(self, read_signals, scale_up, scale_down, pool_size,
                 cfg: AutoscalerConfig = AutoscalerConfig(),
                 clock=time.monotonic):
        self.read_signals = read_signals
        self.scale_up = scale_up
        self.scale_down = scale_down
        self.pool_size = pool_size
        self.cfg = cfg
        self._clock = clock
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._dead = False             # killed by chaos — static capacity
        self._last_action_t: float | None = None
        self._quiet_windows = 0
        self._actions = 0

    # ------------------------------------------------------------ lifecycle
    def start(self) -> bool:
        if not core.enabled():
            return False
        if self._dead:
            return False
        if self._thread is not None and self._thread.is_alive():
            return False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="dl4j-tpu-autoscaler", daemon=True)
        self._thread.start()
        METRICS.gauge("control.autoscaler_alive", 1.0)
        return True

    def stop(self, timeout_s: float = 5.0) -> None:
        t = self._thread
        self._thread = None
        if t is None:
            return
        self._stop.set()
        t.join(timeout=timeout_s)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    @property
    def dead(self) -> bool:
        return self._dead

    def _run(self) -> None:
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.step()
            except Exception:
                # the control loop must never take the process down —
                # a bad window is skipped, the next one reads fresh
                METRICS.increment("control.errors")
            if self._dead:
                break   # chaos kill: freeze at current (static) capacity

    # ------------------------------------------------------------ one window
    def step(self) -> str | None:
        """One control window: chaos check, read, decide, act (at most
        once).  Returns the action taken (``"up"``/``"down"``) or None."""
        if self._dead:
            return None
        if FAULTS.check("control.autoscaler") is not None:
            self._kill()
            return None
        sig = self.read_signals()
        decision = self.evaluate(sig, self._clock())
        if decision is not None:
            self._act(decision, sig)
        return decision

    def _kill(self) -> None:
        """Chaos took the controller: degrade to static capacity.  No
        actuator runs after this point — the pool keeps whatever size
        and routing state it had, which is a correct (if unelastic)
        configuration by construction."""
        self._dead = True
        METRICS.increment("control.autoscaler_killed")
        METRICS.gauge("control.autoscaler_alive", 0.0)
        FLIGHTREC.dump("control_autoscaler_killed", extra={
            "pool_size": self._safe_pool_size(),
            "actions_taken": self._actions})

    # ------------------------------------------------------------ decision
    def evaluate(self, sig: ControlSignals, now: float) -> str | None:
        """Pure-ish decision (mutates only hysteresis counters): returns
        ``"up"``, ``"down"`` or ``None`` for this window's signals."""
        cfg = self.cfg
        pressure = (
            (sig.burn is not None and sig.burn >= cfg.burn_up)
            or sig.queue_depth >= cfg.queue_high
            or (sig.ttb_s is not None and sig.ttb_s <= cfg.ttb_horizon_s))
        quiet = (
            (sig.burn is None or sig.burn <= cfg.burn_down)
            and sig.queue_depth <= cfg.queue_low
            and (sig.ttb_s is None or sig.ttb_s > cfg.ttb_horizon_s))
        # the quiet streak advances regardless of cooldown — a long calm
        # spell during cooldown still counts toward the scale-in vote
        self._quiet_windows = self._quiet_windows + 1 if quiet else 0
        if pressure:
            # any pressure window resets the scale-in vote even when the
            # cooldown blocks acting on it (hysteresis against flapping)
            self._quiet_windows = 0
        if self._last_action_t is not None \
                and now - self._last_action_t < cfg.cooldown_s:
            return None
        size = self._safe_pool_size()
        if pressure and size < cfg.max_replicas:
            return "up"
        if not pressure and self._quiet_windows >= cfg.down_consecutive \
                and size > cfg.min_replicas:
            return "down"
        return None

    # ------------------------------------------------------------ actuation
    def _act(self, direction: str, sig: ControlSignals) -> None:
        self._last_action_t = self._clock()   # a FAILED try burns it too
        self._quiet_windows = 0
        with trace.span("control.scale", direction=direction,
                        pool_size=self._safe_pool_size()):
            try:
                if direction == "up":
                    self.scale_up()
                else:
                    self.scale_down()
            except Exception as e:
                METRICS.increment("control.scale_errors")
                FLIGHTREC.dump("control_scale", extra={
                    "direction": direction, "ok": False, "error": str(e),
                    "burn": sig.burn, "queue_depth": sig.queue_depth,
                    "ttb_s": sig.ttb_s})
                return
        self._actions += 1
        if direction == "up":
            METRICS.increment("control.scale_up")
        else:
            METRICS.increment("control.scale_down")
        size = self._safe_pool_size()
        METRICS.gauge("control.pool_size", float(size))
        FLIGHTREC.dump("control_scale", extra={
            "direction": direction, "ok": True, "pool_size": size,
            "burn": sig.burn, "queue_depth": sig.queue_depth,
            "ttb_s": sig.ttb_s})

    def _safe_pool_size(self) -> int:
        try:
            return int(self.pool_size())
        except Exception:
            return 0


# ------------------------------------------------------------ wiring helpers
def router_signals(slo_evaluator, queue, objective: str,
                   forecast=None, forecast_objective: str | None = None):
    """Build a ``read_signals`` callable from the standard serving
    stack: an ``SLOEvaluator`` (worst full-window burn for
    ``objective``), a ``RequestQueue`` (live depth), and optionally a
    ``ForecastEvaluator`` (+ its objective name) for time-to-breach."""
    def read() -> ControlSignals:
        ttb = None
        if forecast is not None:
            ttb = forecast.ttb_seconds(forecast_objective or objective)
        return ControlSignals(
            burn=slo_evaluator.burn_rate(objective),
            queue_depth=queue.depth(),
            ttb_s=ttb)
    return read


def router_actuators(router, replica_factory,
                     cfg: AutoscalerConfig = AutoscalerConfig()):
    """Build ``(scale_up, scale_down, pool_size)`` over a
    :class:`~..serving.router.router.PrefixRouter`.  ``replica_factory``
    returns a fresh started-but-unadmitted ``Replica`` (an
    ``EngineReplica`` or a spawned ``ProcessReplica``); admission waits
    for its warmed flag inside ``router.scale_up``.  Scale-in drains the
    ring-order LAST replica (newest vnode owner) and closes it only
    after a clean detach."""
    def up() -> None:
        router.scale_up(replica_factory(),
                        warm_timeout_s=cfg.warm_timeout_s)

    def down() -> None:
        names = router.pool.names()
        victim = names[-1]
        rep = router.scale_down(victim,
                                drain_timeout_s=cfg.drain_timeout_s)
        rep.close()

    def size() -> int:
        return len(router.pool.names())

    return up, down, size


def wave_actuators(runner):
    """Build ``(scale_up, scale_down, pool_size)`` over an elastic
    training runner: grow with ``register_worker``, shrink with the
    idle-only ``retire_worker`` (a no-eligible-worker window raises so
    the attempt is visible in ``control.scale_errors`` and retried
    after cooldown)."""
    def up() -> None:
        runner.register_worker()

    def down() -> None:
        if runner.retire_worker() is None:
            raise RuntimeError("no idle worker eligible to retire")

    def size() -> int:
        return len([w for w in runner.tracker.workers()
                    if runner.tracker.is_enabled(w)])

    return up, down, size
