"""Driver: the single-controller training entry point.

Names and owns the role the reference sketches twice — the Spark prototype
(``spark/spark-cdh5/.../multilayer/PrototypeSparkJob.java``: the driver
program holds the model, farms batches out, folds results back) and the
YARN ``ComputableMaster`` superstep — and that the TPU-native design
collapses into one process: a **single controller** that owns the mesh,
the jitted SPMD step (collectives are the data plane; no per-batch
shipping), multi-host bootstrap, checkpointing, and observability.  One
object, one ``run()``:

    driver = Driver(loss_fn, T.chain(T.momentum(0.9), T.sgd_lr(1e-2)),
                    mesh_spec=MeshSpec(dp=8),
                    checkpoint_dir="/tmp/ckpt")
    state, losses = driver.run(params, batches, epochs=2)

Equivalent reference call stacks: SURVEY.md §3.3 (Akka master loop) and
§3.5 (YARN superstep) — here both are the same jitted step under `pmean`.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable

import jax

from ..observability import METRICS, StatusServer, sample_device_memory, trace
from ..resilience import RetryPolicy, TrainingSupervisor
from .checkpoint import CheckpointManager
from .mesh import MeshSpec, initialize_multihost, make_mesh
from .trainer import DataParallelTrainer, TrainState


class Driver:
    """Single-controller driver over a device mesh.

    ``mesh_spec=None`` uses all local devices as pure data parallelism;
    pass ``multihost=True`` to join a ``jax.distributed`` cluster first
    (env-var contract, see ``initialize_multihost``) so the same driver
    program runs on every host of a pod slice.

    ``retry_policy`` (requires ``checkpoint_dir``) routes ``run`` through
    a :class:`~..resilience.TrainingSupervisor`: bounded retry with
    backoff, resume from the newest valid checkpoint, NaN/Inf rollback,
    and SIGTERM/SIGINT emergency checkpointing (DESIGN.md §12).
    """

    def __init__(self, loss_fn, transform, *, mesh_spec: MeshSpec | None = None,
                 multihost: bool = False, router: str = "iterative_reduce",
                 checkpoint_dir: str | Path | None = None,
                 checkpoint_every: int = 0, status_port: int | None = None,
                 retry_policy: RetryPolicy | None = None):
        if multihost:
            initialize_multihost()
        if mesh_spec is None:
            mesh_spec = MeshSpec(dp=len(jax.devices()))
        # resolve wildcard (-1) axes against the device pool before sizing
        sizes = mesh_spec.resolve(len(jax.devices()))
        n = 1
        for v in sizes.values():
            n *= v
        self.mesh = make_mesh(mesh_spec, devices=jax.devices()[:n])
        self.trainer = DataParallelTrainer(loss_fn, transform, mesh=self.mesh,
                                           router=router)
        self.checkpoint_manager = (CheckpointManager(checkpoint_dir)
                                   if checkpoint_dir else None)
        self.checkpoint_every = checkpoint_every
        if retry_policy is not None and self.checkpoint_manager is None:
            raise ValueError(
                "retry_policy requires checkpoint_dir — supervised recovery "
                "resumes from checkpoints")
        self.retry_policy = retry_policy
        self.status_server = None
        if status_port is not None:
            self.status_server = StatusServer(port=status_port).start()

    def run(self, params, batches: Iterable, *, epochs: int = 1,
            resume: bool = True, key=None) -> tuple[TrainState, list[float]]:
        """Fit to completion (with auto-resume when a checkpoint manager is
        configured); returns the final state and per-step losses.

        With a ``retry_policy``, runs under the self-healing supervisor —
        ``batches`` must then be re-iterable (a retried attempt replays
        the stream from the checkpoint's data cursor)."""
        with trace.span("driver.run", epochs=epochs):
            if self.retry_policy is not None:
                supervisor = TrainingSupervisor(
                    self.checkpoint_manager, self.retry_policy)
                state, losses = supervisor.fit(
                    self.trainer, params, batches, epochs=epochs,
                    checkpoint_every=max(1, self.checkpoint_every), key=key)
            else:
                state = self.trainer.init_state(params, key=key)
                # fit streams any iterable — no list() materialization;
                # one-shot generators make a single pass (multi-epoch
                # needs re-iterables)
                state, losses = self.trainer.fit(
                    state, batches, epochs=epochs,
                    checkpoint_manager=self.checkpoint_manager,
                    checkpoint_every=self.checkpoint_every, resume=resume)
        METRICS.increment("driver.steps", len(losses))
        if losses:
            METRICS.gauge("driver.loss", losses[-1])
        sample_device_memory()
        return state, losses

    def final_params(self, state: TrainState):
        return self.trainer.final_params(state)

    def close(self) -> None:
        if self.status_server is not None:
            self.status_server.stop()
