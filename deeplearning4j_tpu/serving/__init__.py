"""Model serving: continuous-batching inference over trained checkpoints.

The inference half of the north star ("serves heavy traffic from millions
of users", ROADMAP.md): :class:`InferenceEngine` keeps a slot-pool KV
cache full of concurrently-decoding sequences, :class:`BatchScorer`
coalesces forward/score calls for ``MultiLayerNetwork``/zoo models,
:class:`RequestQueue` applies deadline-aware admission control with
bounded-queue backpressure, and :class:`ModelServer` exposes the whole
thing over stdlib HTTP with Prometheus metrics.  See DESIGN.md §13.
"""

from .batcher import (Completion, DeadlineExceeded, GenerateRequest,
                      PagePoolExhausted, PendingResult, QueueFull,
                      RequestQueue, ScoreRequest, ServingRejected)
from .client import ServingClient, ServingError
from .engine import BatchScorer, InferenceEngine, ServingConfig
from .paging import PagePool, prefix_chain_keys
from .engine import MigrationRejected, MigrationTicket, PrefillRecord
from .router import (AllReplicasUnavailable, EngineReplica, HashRing,
                     PrefixRouter, ProcessReplica, ReplicaPool,
                     ReplicaUnavailable, RouterConfig, RouterServer)
from .server import ModelServer
from .disagg import DisaggScheduler, KVMigrator, TransferPlan

__all__ = [
    "AllReplicasUnavailable",
    "BatchScorer",
    "Completion",
    "DeadlineExceeded",
    "DisaggScheduler",
    "EngineReplica",
    "KVMigrator",
    "MigrationRejected",
    "MigrationTicket",
    "PrefillRecord",
    "TransferPlan",
    "GenerateRequest",
    "HashRing",
    "InferenceEngine",
    "ModelServer",
    "PagePool",
    "PagePoolExhausted",
    "PendingResult",
    "PrefixRouter",
    "ProcessReplica",
    "QueueFull",
    "ReplicaPool",
    "ReplicaUnavailable",
    "RequestQueue",
    "RouterConfig",
    "RouterServer",
    "ScoreRequest",
    "ServingClient",
    "ServingConfig",
    "ServingError",
    "ServingRejected",
    "prefix_chain_keys",
]
