"""t-SNE: exact (TPU pairwise kernels) and Barnes-Hut variants.

Capability match of ``plot/Tsne.java:42`` (exact t-SNE with adaptive-
perplexity binary search ``hBeta``/``computeGaussianPerplexity`` at
``:143,164,261-428``) and ``plot/BarnesHutTsne.java:36`` (theta-approximated
gradient with the quad tree).  TPU-first split: the exact variant's O(n^2)
pairwise affinity and gradient math runs as jitted dense kernels (MXU
distance matrices); Barnes-Hut stays host-side (pointer-chasing tree walk)
for large n.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..clustering.quadtree import QuadTree
from ..clustering.vptree import VPTree


# --------------------------------------------------------------------------- shared

def _hbeta(d_row: np.ndarray, beta: float):
    """Entropy + probabilities for one row at precision beta
    (``Tsne.java hBeta:143``)."""
    p = np.exp(-d_row * beta)
    sum_p = max(p.sum(), 1e-12)
    h = np.log(sum_p) + beta * float(d_row @ p) / sum_p
    return h, p / sum_p


def _search_beta(d_row: np.ndarray, target: float, tol: float = 1e-5,
                 max_tries: int = 50) -> np.ndarray:
    """Bisect the precision beta until the row's entropy hits ``target``;
    returns the row's conditional probabilities (shared by exact and BH)."""
    beta, beta_min, beta_max = 1.0, -np.inf, np.inf
    h, p = _hbeta(d_row, beta)
    for _ in range(max_tries):
        if abs(h - target) < tol:
            break
        if h > target:
            beta_min = beta
            beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
        else:
            beta_max = beta
            beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
        h, p = _hbeta(d_row, beta)
    return p


def binary_search_perplexity(d2: np.ndarray, perplexity: float,
                             tol: float = 1e-5, max_tries: int = 50) -> np.ndarray:
    """Per-row beta search to hit log(perplexity) entropy
    (``computeGaussianPerplexity``, ``Tsne.java:261-428``)."""
    n = d2.shape[0]
    target = np.log(perplexity)
    P = np.zeros_like(d2)
    for i in range(n):
        row = np.delete(d2[i], i)
        P[i, np.arange(n) != i] = _search_beta(row, target, tol, max_tries)
    return P


@jax.jit
def _pairwise_sq_dists(x):
    s = jnp.sum(x * x, axis=1)
    return jnp.maximum(s[:, None] - 2.0 * x @ x.T + s[None, :], 0.0)


@jax.jit
def _tsne_grad(y, P):
    """Exact t-SNE gradient: 4 * sum_j (p_ij - q_ij) q*_ij (y_i - y_j)."""
    d2 = _pairwise_sq_dists(y)
    num = 1.0 / (1.0 + d2)
    num = num * (1.0 - jnp.eye(y.shape[0]))
    Q = jnp.maximum(num / jnp.sum(num), 1e-12)
    PQ = (P - Q) * num
    grad = 4.0 * ((jnp.diag(PQ.sum(axis=1)) - PQ) @ y)
    kl = jnp.sum(P * jnp.log(jnp.maximum(P, 1e-12) / Q))
    return grad, kl


class Tsne:
    """Exact t-SNE with momentum + per-element adaptive gains
    (``Tsne.java`` step scheme)."""

    def __init__(self, n_components: int = 2, perplexity: float = 30.0,
                 learning_rate: float = 200.0, n_iter: int = 500,
                 early_exaggeration: float = 4.0, exaggeration_iters: int = 100,
                 seed: int = 0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.early_exaggeration = early_exaggeration
        self.exaggeration_iters = exaggeration_iters
        self.seed = seed
        self.kl_: float = float("nan")

    def _input_probs(self, x: np.ndarray) -> np.ndarray:
        # one-shot preprocessing (called once per fit, not per iteration);
        # the perplexity search below is host code and needs the matrix
        # graftlint: disable=HS01
        d2 = np.asarray(_pairwise_sq_dists(jnp.asarray(x, jnp.float32)))
        P = binary_search_perplexity(d2, self.perplexity)
        P = (P + P.T) / (2.0 * P.shape[0])
        return np.maximum(P, 1e-12)

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        P = self._input_probs(x)
        rng = np.random.default_rng(self.seed)
        y = jnp.asarray(rng.normal(0, 1e-4, (n, self.n_components)), jnp.float32)
        vel = jnp.zeros_like(y)
        gains = jnp.ones_like(y)
        Pj = jnp.asarray(P * self.early_exaggeration, jnp.float32)
        for it in range(self.n_iter):
            if it == self.exaggeration_iters:
                Pj = Pj / self.early_exaggeration
            grad, kl = _tsne_grad(y, Pj)
            momentum = 0.5 if it < 250 else 0.8
            gains = jnp.where(jnp.sign(grad) != jnp.sign(vel),
                              gains + 0.2, gains * 0.8)
            gains = jnp.maximum(gains, 0.01)
            vel = momentum * vel - self.learning_rate * gains * grad
            y = y + vel
            y = y - jnp.mean(y, axis=0)
        self.kl_ = float(kl)
        return np.asarray(y)


class BarnesHutTsne(Tsne):
    """theta-approximate t-SNE (``BarnesHutTsne.java:36``): sparse input
    affinities from VP-tree kNN; repulsive forces via the quad tree."""

    def __init__(self, theta: float = 0.5, **kw):
        super().__init__(**kw)
        self.theta = theta

    def _sparse_input_probs(self, x: np.ndarray):
        n = x.shape[0]
        k = min(n - 1, int(3 * self.perplexity))
        tree = VPTree(x)
        rows, cols, vals = [], [], []
        for i in range(n):
            nbrs = [t for t in tree.knn(x[i], k + 1) if t[0] != i][:k]
            idx = np.array([t[0] for t in nbrs])
            d2 = np.array([t[1] for t in nbrs]) ** 2
            p = _search_beta(d2, np.log(self.perplexity))
            rows.extend([i] * len(idx))
            cols.extend(idx.tolist())
            vals.extend(p.tolist())
        P = {}
        for r, c, v in zip(rows, cols, vals):
            P[(r, c)] = P.get((r, c), 0.0) + v / (2.0 * n)
            P[(c, r)] = P.get((c, r), 0.0) + v / (2.0 * n)
        return P

    def fit_transform(self, x) -> np.ndarray:
        x = np.asarray(x, np.float64)
        n = x.shape[0]
        P = self._sparse_input_probs(x)
        rng = np.random.default_rng(self.seed)
        y = rng.normal(0, 1e-4, (n, 2))
        vel = np.zeros_like(y)
        gains = np.ones_like(y)
        exagg = self.early_exaggeration
        for it in range(self.n_iter):
            if it == self.exaggeration_iters:
                exagg = 1.0
            tree = QuadTree.build(y)
            rep = np.zeros_like(y)
            sum_q = 0.0
            for i in range(n):
                f, sq = tree.compute_non_edge_forces(y[i], self.theta, i)
                rep[i] = f
                sum_q += sq
            sum_q = max(sum_q, 1e-12)
            attr = np.zeros_like(y)
            for (i, j), p in P.items():
                diff = y[i] - y[j]
                q = 1.0 / (1.0 + diff @ diff)
                attr[i] += exagg * p * q * diff
            grad = 4.0 * (attr - rep / sum_q)
            momentum = 0.5 if it < 250 else 0.8
            gains = np.where(np.sign(grad) != np.sign(vel), gains + 0.2, gains * 0.8)
            gains = np.maximum(gains, 0.01)
            vel = momentum * vel - self.learning_rate * gains * grad
            y = y + vel
            y -= y.mean(axis=0)
        return y
