"""Static concurrency model for the LK01/LK02/LK03/TH01 rules.

The serving/training stack is threaded (serve loop, HTTP handler pool,
prefetch worker, scaleout heartbeats), and the classic failure modes —
an unlocked write racing the serve thread, two locks taken in opposite
orders, a device fence held under a lock — are exactly the bugs a test
suite only catches once in a thousand runs.  This module builds the
per-module facts those rules consume:

- **per-class field-access pass**: every write to ``self.<attr>`` in
  every method, annotated with the set of locks statically held at the
  write site (``with self._lock:`` scoping);
- **lock inventory**: attributes assigned from ``threading.Lock`` /
  ``RLock`` / ``Condition`` / ``Semaphore`` constructors, plus
  module-level / function-local lock names;
- **guarded-by annotations**: an explicit contract comment on any
  assignment line — ``self._slots = {}  # guarded-by: self._lock`` —
  declares that *every* non-``__init__`` write must hold that lock;
- **thread-entry reachability**: methods used as ``Thread(target=...)``,
  ``run`` on ``Thread`` subclasses, and ``do_GET``-style HTTP handler
  methods are entry roots; the per-class call graph (``self.m()`` edges)
  gives each method its set of executing *contexts* (which entry threads
  can reach it, and whether external callers can);
- **lock-order graph**: nested ``with`` acquisitions and one level of
  ``self.m()`` call propagation produce ``held -> acquired`` edges;
  cycles (including a non-reentrant lock re-acquired through a helper)
  are deadlock schedules.

Everything is best-effort and single-module, like the rest of graftlint:
reads are not tracked (only writes race destructively enough to flag),
``lock.acquire()`` / ``release()`` call pairs are not modelled (use
``with``), nested ``def`` bodies execute later so they are skipped, and
cross-module lock cycles are out of scope.  ``__init__``/``__new__``/
``__del__`` writes are exempt — construction happens-before publication.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterator

from .core import assigned_names, dotted_name, last_segment

#: threading constructors whose result we treat as a lock object, mapped
#: to whether acquisition is reentrant (a Condition wraps an RLock by
#: default, so we treat it as reentrant)
_LOCK_CTORS = {
    "threading.Lock": False,
    "threading.RLock": True,
    "threading.Condition": True,
    "threading.Semaphore": False,
    "threading.BoundedSemaphore": False,
}

#: container-mutating method names: ``self.x.append(...)`` is a write to x
_MUTATORS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse",
}

#: methods whose writes are construction, not sharing
_INIT_METHODS = {"__init__", "__new__", "__del__", "__post_init__"}

#: the external (caller-thread) context label
EXTERNAL = "external"

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*self\.(\w+)")


def _self_attr(node: ast.AST) -> str | None:
    """``attr`` when ``node`` is exactly ``self.attr``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


@dataclasses.dataclass
class FieldWrite:
    """One store/mutation of ``self.<attr>`` at one source location."""

    attr: str
    method: str
    node: ast.AST
    held: frozenset[str]      # lock attrs of the class held at the write


@dataclasses.dataclass
class LockAcquire:
    """One static ``with <lock>`` acquisition site."""

    lock_id: str              # "Class.attr" or "global:name"
    node: ast.AST
    func: str                 # qualified function/method name


@dataclasses.dataclass
class OrderEdge:
    """``held`` was locked when ``acquired`` was taken at ``node``."""

    held: str
    acquired: str
    node: ast.AST
    func: str


class ClassConcurrency:
    """The field/lock/thread facts for one class definition."""

    def __init__(self, module, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.name = node.name
        self.methods: dict[str, ast.FunctionDef] = {
            s.name: s for s in node.body
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_attrs: dict[str, bool] = {}      # attr -> reentrant?
        self.guarded_by: dict[str, str] = {}       # attr -> lock attr
        self.writes: dict[str, list[FieldWrite]] = {}
        self.calls: dict[str, set[str]] = {m: set() for m in self.methods}
        #: (caller, callee, locks statically held at the call site)
        self.call_sites: list[tuple[str, str, frozenset[str]]] = []
        self.acquired_in: dict[str, set[str]] = {m: set() for m in self.methods}
        self.entry_methods: set[str] = set()
        self.spawns_threads = False
        self._collect()

    # ---------------------------------------------------------------- facts
    def _collect(self) -> None:
        subclasses_thread = any(
            (dotted_name(b) or "").endswith("Thread") for b in self.node.bases)
        for name, fn in self.methods.items():
            if name.startswith("do_") and name[3:].isupper():
                self.entry_methods.add(name)        # BaseHTTPRequestHandler
            if name == "run" and subclasses_thread:
                self.entry_methods.add(name)
            self._scan_method(fn)
        # transitive lock acquisition closure over self.m() calls
        changed = True
        while changed:
            changed = False
            for m, callees in self.calls.items():
                for c in callees:
                    extra = self.acquired_in.get(c, set()) - self.acquired_in[m]
                    if extra:
                        self.acquired_in[m] |= extra
                        changed = True
        self._apply_held_floors()

    def _apply_held_floors(self) -> None:
        """Interprocedural lock context: a private helper whose every
        in-class call site holds lock L runs with L held (the
        ``_helper_locked`` convention) — fold that floor into its
        writes.  Entry methods and public methods get no floor: the
        thread runtime / external callers invoke them bare."""
        floors: dict[str, frozenset[str]] = {}
        for _ in range(len(self.methods)):
            changed = False
            for m in self.methods:
                if m in self.entry_methods or not m.startswith("_") \
                        or m in _INIT_METHODS:
                    continue
                sites = [held | floors.get(caller, frozenset())
                         for caller, callee, held in self.call_sites
                         if callee == m]
                if not sites:
                    continue
                floor = frozenset.intersection(*sites)
                if floor and floors.get(m) != floor:
                    floors[m] = floor
                    changed = True
            if not changed:
                break
        for ws in self.writes.values():
            for i, w in enumerate(ws):
                floor = floors.get(w.method)
                if floor:
                    ws[i] = dataclasses.replace(w, held=w.held | floor)

    def _scan_method(self, fn: ast.FunctionDef) -> None:
        self._walk(fn.body, fn.name, frozenset())

    def _walk(self, stmts, method: str, held: frozenset[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue                     # executes later / elsewhere
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                newly = set()
                for item in stmt.items:
                    attr = _self_attr(item.context_expr)
                    if attr is None:
                        continue
                    line = self.module.line(item.context_expr.lineno)
                    # a with on a known lock attr, or one that *looks*
                    # like a lock contract even before we saw the ctor
                    if attr in self.lock_attrs or self._lockish(attr):
                        newly.add(attr)
                self._scan_stmt_exprs(stmt, method, held, header_only=True)
                self._walk(stmt.body, method, held | frozenset(newly))
                continue
            self._scan_stmt_exprs(stmt, method, held)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._walk(sub, method, held)
            for handler in getattr(stmt, "handlers", []) or []:
                self._walk(handler.body, method, held)

    def _lockish(self, attr: str) -> bool:
        """Is ``self.<attr>`` plausibly a lock even if its constructor was
        not seen yet (methods are scanned before __init__ sometimes)?"""
        init = self.methods.get("__init__")
        if init is None:
            return False
        for node in ast.walk(init):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                canon = self.module.canonical(node.value.func) or ""
                if canon in _LOCK_CTORS:
                    for t in node.targets:
                        if _self_attr(t) == attr:
                            return True
        return False

    def _scan_stmt_exprs(self, stmt: ast.stmt, method: str,
                         held: frozenset[str], header_only: bool = False) -> None:
        """Record writes, lock ctors, guarded-by annotations, and self-call
        edges found in one statement (its own expressions only — compound
        bodies are walked separately so ``held`` stays accurate)."""
        for node in self._own_nodes(stmt, header_only):
            # lock constructor: self._lock = threading.Lock()
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                canon = self.module.canonical(node.value.func) or ""
                if canon in _LOCK_CTORS:
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is not None:
                            self.lock_attrs[attr] = _LOCK_CTORS[canon]
            # guarded-by annotation on any line assigning self.attr
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                m = _GUARDED_BY_RE.search(self.module.line(node.lineno))
                if m:
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        attr = _self_attr(t) or _self_attr(
                            t.value if isinstance(t, ast.Subscript) else t)
                        if attr is not None:
                            self.guarded_by[attr] = m.group(1)
            # attribute writes
            if isinstance(node, ast.Attribute) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node)
                if attr is not None:
                    self._record_write(attr, method, node, held)
            elif isinstance(node, ast.Subscript) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                attr = _self_attr(node.value)
                if attr is not None:
                    self._record_write(attr, method, node, held)
            elif isinstance(node, ast.Call):
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _MUTATORS):
                    attr = _self_attr(node.func.value)
                    if attr is not None:
                        self._record_write(attr, method, node, held)
                # self.m() call edge
                callee = _self_attr(node.func)
                if callee is not None and callee in self.methods:
                    self.calls[method].add(callee)
                    self.call_sites.append((method, callee, held))
                # thread spawn + target entry method
                canon = self.module.canonical(node.func) or ""
                if canon == "threading.Thread" or canon.endswith(".Thread"):
                    self.spawns_threads = True
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = _self_attr(kw.value)
                            if target is not None and target in self.methods:
                                self.entry_methods.add(target)
            # with self._lock: acquisition inventory (for LK02 closure)
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None and (attr in self.lock_attrs
                                             or self._lockish(attr)):
                        self.acquired_in[method].add(attr)

    def _own_nodes(self, stmt: ast.stmt, header_only: bool) -> Iterator[ast.AST]:
        """``stmt`` and its expression children, not descending into the
        bodies of compound statements or nested defs."""
        yield stmt
        blocked = {"body", "orelse", "finalbody", "handlers"}
        stack = [c for f, c in ast.iter_fields(stmt)
                 if f not in blocked for c in (c if isinstance(c, list) else [c])
                 if isinstance(c, ast.AST)]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            yield n
            stack.extend(c for c in ast.iter_child_nodes(n))

    def _record_write(self, attr: str, method: str, node: ast.AST,
                      held: frozenset[str]) -> None:
        if attr in self.lock_attrs:
            return
        self.writes.setdefault(attr, []).append(
            FieldWrite(attr=attr, method=method, node=node, held=held))

    # ---------------------------------------------------------------- queries
    @property
    def threaded(self) -> bool:
        """Does this class run code on more than the caller's thread?"""
        return self.spawns_threads or bool(self.entry_methods)

    def entry_reachable(self) -> dict[str, set[str]]:
        """method -> set of entry roots whose thread can execute it."""
        reach: dict[str, set[str]] = {}
        for root in self.entry_methods:
            seen = {root}
            stack = [root]
            while stack:
                for callee in self.calls.get(stack.pop(), ()):
                    if callee not in seen:
                        seen.add(callee)
                        stack.append(callee)
            for m in seen:
                reach.setdefault(m, set()).add(root)
        return reach

    def contexts(self, method: str) -> frozenset[str]:
        """The set of thread contexts that can execute ``method``: one
        label per reaching entry root, plus ``external`` when a method
        outside every entry closure can call it (or it IS one)."""
        reach = self.entry_reachable()
        ctx = set(reach.get(method, ()))
        if method not in reach:
            ctx.add(EXTERNAL)
        else:
            for caller, callees in self.calls.items():
                if method in callees and caller not in reach:
                    ctx.add(EXTERNAL)
                    break
        return frozenset(ctx)


class ModuleConcurrency:
    """All classes of a module, plus the module-wide lock-order graph."""

    def __init__(self, module):
        self.module = module
        self.classes = [ClassConcurrency(module, n)
                        for n in ast.walk(module.tree)
                        if isinstance(n, ast.ClassDef)]
        self._global_locks = self._collect_global_locks()
        self.edges: list[OrderEdge] = []
        self.blocking: list[tuple[ast.Call, str, str]] = []  # node, why, func
        self._lock_kinds: dict[str, bool] = {}     # lock id -> reentrant?
        self._build_order_and_blocking()

    # ------------------------------------------------------------- inventory
    def _collect_global_locks(self) -> dict[str, bool]:
        locks: dict[str, bool] = {}
        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                canon = self.module.canonical(node.value.func) or ""
                if canon in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            locks[t.id] = _LOCK_CTORS[canon]
        return locks

    def _cls_for(self, fn: ast.FunctionDef) -> ClassConcurrency | None:
        for cls in self.classes:
            if fn.name in cls.methods and cls.methods[fn.name] is fn:
                return cls
        return None

    def _lock_id(self, expr: ast.AST, cls: ClassConcurrency | None) -> str | None:
        attr = _self_attr(expr)
        if attr is not None and cls is not None and (
                attr in cls.lock_attrs or cls._lockish(attr)):
            return f"{cls.name}.{attr}"
        if isinstance(expr, ast.Name) and expr.id in self._global_locks:
            return f"global:{expr.id}"
        return None

    def _reentrant(self, lock_id: str, cls: ClassConcurrency | None) -> bool:
        if lock_id.startswith("global:"):
            return self._global_locks.get(lock_id[7:], False)
        if cls is not None and "." in lock_id:
            return cls.lock_attrs.get(lock_id.split(".", 1)[1], True)
        return True

    # ------------------------------------------------------- order + blocking
    def _build_order_and_blocking(self) -> None:
        for node in ast.walk(self.module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                cls = self._cls_for(node)
                qual = f"{cls.name}.{node.name}" if cls else node.name
                self._walk_fn(node.body, cls, qual, [])

    def _walk_fn(self, stmts, cls, qual: str,
                 held: list[tuple[str, str]]) -> None:
        """held: list of (lock_id, receiver source text) in acquisition
        order for the current static scope."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                entered = list(held)
                for item in stmt.items:
                    lock_id = self._lock_id(item.context_expr, cls)
                    if lock_id is None:
                        continue
                    for h, _ in entered:
                        if h == lock_id and self._reentrant(lock_id, cls):
                            continue
                        self.edges.append(OrderEdge(
                            held=h, acquired=lock_id,
                            node=item.context_expr, func=qual))
                    entered.append(
                        (lock_id, dotted_name(item.context_expr) or lock_id))
                if len(entered) > len(held):
                    self._scan_calls(stmt, cls, qual, held, header_only=True)
                    self._walk_fn(stmt.body, cls, qual, entered)
                    continue
            if held:
                self._scan_calls(stmt, cls, qual, held)
            else:
                # still record self.m() edges for transitive acquisition
                pass
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._walk_fn(sub, cls, qual, held)
            for handler in getattr(stmt, "handlers", []) or []:
                self._walk_fn(handler.body, cls, qual, held)

    def _scan_calls(self, stmt: ast.stmt, cls, qual: str,
                    held: list[tuple[str, str]],
                    header_only: bool = False) -> None:
        """Under ``held`` locks: record blocking calls (LK03) and edges
        from held locks to every lock a called sibling method acquires."""
        if not held:
            return
        blocked = {"body", "orelse", "finalbody", "handlers"}
        stack = [c for f, c in ast.iter_fields(stmt)
                 if f not in blocked for c in (c if isinstance(c, list) else [c])
                 if isinstance(c, ast.AST)]
        if isinstance(stmt, ast.expr):
            stack = [stmt]
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(n, ast.Call):
                why = self._blocking_reason(n, held)
                if why:
                    self.blocking.append((n, why, qual))
                callee = _self_attr(n.func)
                if (callee is not None and cls is not None
                        and callee in cls.methods):
                    for inner in cls.acquired_in.get(callee, ()):
                        inner_id = f"{cls.name}.{inner}"
                        for h, _ in held:
                            if h == inner_id and not self._reentrant(
                                    inner_id, cls):
                                # non-reentrant lock re-acquired via helper:
                                # a guaranteed self-deadlock schedule
                                self.edges.append(OrderEdge(
                                    held=h, acquired=inner_id,
                                    node=n, func=qual))
                            elif h != inner_id:
                                self.edges.append(OrderEdge(
                                    held=h, acquired=inner_id,
                                    node=n, func=qual))
            stack.extend(ast.iter_child_nodes(n))

    def _blocking_reason(self, call: ast.Call,
                         held: list[tuple[str, str]]) -> str | None:
        """Why ``call`` can block indefinitely (None when it cannot)."""
        canon = self.module.canonical(call.func) or ""
        base = last_segment(canon) if canon else ""
        has_args = bool(call.args or call.keywords)
        kwnames = {kw.arg for kw in call.keywords}
        if isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            recv = dotted_name(call.func.value)
            if attr == "block_until_ready":
                return "device fence (block_until_ready)"
            if attr == "wait" and not has_args:
                if any(recv == text for _, text in held):
                    return None        # Condition.wait releases its own lock
                return "untimed .wait()"
            if attr == "join" and not has_args:
                return "untimed .join()"
            if attr in ("get", "result") and not call.args \
                    and "timeout" not in kwnames:
                if attr == "get" and has_args:
                    return None        # dict.get(key[, default]) style
                if not has_args:
                    return f"untimed .{attr}()"
            if attr in ("recv", "accept", "makefile", "getresponse"):
                return f"socket I/O (.{attr})"
        if canon == "jax.device_get":
            return "device fence (jax.device_get)"
        if canon == "time.sleep":
            return "time.sleep under lock (convoy)"
        if canon.startswith("urllib.request.") or base == "urlopen":
            return "HTTP I/O (urlopen)"
        return None


def module_concurrency(module) -> ModuleConcurrency:
    """Build (and memoize on the ModuleInfo) the concurrency model."""
    cached = getattr(module, "_concurrency", None)
    if cached is None:
        cached = ModuleConcurrency(module)
        module._concurrency = cached
    return cached


def find_cycles(edges: list[OrderEdge]) -> list[list[OrderEdge]]:
    """Every elementary cycle in the lock-order graph, as edge lists.
    Self-edges (non-reentrant re-acquisition) are length-1 cycles."""
    by_src: dict[str, list[OrderEdge]] = {}
    for e in edges:
        by_src.setdefault(e.held, []).append(e)
    cycles: list[list[OrderEdge]] = []
    seen_keys: set[frozenset[tuple[str, str]]] = set()

    for start in sorted(by_src):
        def dfs(node: str, path: list[OrderEdge], visited: set[str]) -> None:
            for e in by_src.get(node, ()):
                if e.acquired == start:
                    cyc = path + [e]
                    key = frozenset((c.held, c.acquired) for c in cyc)
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(cyc)
                elif e.acquired not in visited and e.acquired > start:
                    dfs(e.acquired, path + [e], visited | {e.acquired})

        dfs(start, [], {start})
    return cycles
