"""Persistent XLA compilation cache wiring.

Large jitted programs (the sharded BERT step, the bucketed trainer steps)
pay tens of seconds of trace+lower+compile on first call.  JAX ships a
persistent on-disk compilation cache that skips that cost across process
restarts; this module is the single place the repo turns it on, so the
trainer, ``MultiLayerNetwork``, and ``bench.py`` all share one policy.

Opt-in by design: the cache writes files, and a library must not scribble
on disk because it was imported.  The directory comes from (highest wins)

1. an explicit ``cache_dir`` argument (``bench.py`` passes a repo-local
   ``.cache/xla``),
2. the ``DL4J_TPU_COMPILE_CACHE_DIR`` environment variable,

and when neither is set — or ``DL4J_TPU_COMPILE_CACHE=0`` — setup is a
no-op.  Configuration is idempotent and process-global (first directory
wins, matching jax's own semantics: the config is global state).
"""

from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_configured_dir: str | None = None

ENV_DIR = "DL4J_TPU_COMPILE_CACHE_DIR"
ENV_ENABLE = "DL4J_TPU_COMPILE_CACHE"


def setup_compile_cache(cache_dir: str | None = None, *,
                        min_compile_time_s: float = 0.0) -> str | None:
    """Point jax's persistent compilation cache at ``cache_dir``.

    Returns the configured directory, or ``None`` when disabled/unset.
    Safe to call from every trainer/network constructor: after the first
    successful configuration, later calls return the configured directory
    without touching jax config again (even if they pass a different dir —
    the jax cache is process-global, so repointing it mid-process would
    only split the cache).

    ``min_compile_time_s`` keeps trivial programs out of the cache; 0
    caches everything (jax's own min-entry-size floor still applies).
    """
    global _configured_dir
    if os.environ.get(ENV_ENABLE, "1") == "0":
        return None
    with _lock:
        if _configured_dir is not None:
            return _configured_dir
        target = cache_dir or os.environ.get(ENV_DIR)
        if not target:
            return None
        import jax

        os.makedirs(target, exist_ok=True)
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", target)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_time_s))
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        try:
            # jax initializes the persistent cache lazily on the FIRST
            # compile and latches the result: if anything compiled before
            # this call (warmup jits, another library), the new dir would
            # silently never take effect — reset so the next compile
            # re-initializes against the directory we just configured.
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # pragma: no cover - internal API drift
            pass
        _configured_dir = target
        return _configured_dir


def configured_dir() -> str | None:
    """The directory the process-global cache points at (None if unset)."""
    return _configured_dir


def _reset_for_tests() -> None:
    """Forget the process-global configuration (jax config is untouched)."""
    global _configured_dir
    with _lock:
        _configured_dir = None
