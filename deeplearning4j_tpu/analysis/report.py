"""graftlint reporting: human text, machine JSON, and metrics gauges.

The metrics side closes the loop with the PR 1 observability layer: every
analyzer run publishes one ``graftlint.violations.<RULE>`` gauge per rule
(count of ACTIVE findings — suppressed/baselined ones are counted
separately in the JSON report) plus a ``graftlint.runs`` counter, so a CI
scrape of ``/metrics.prom`` can alert on lint regressions the same way it
alerts on step-time regressions.
"""

from __future__ import annotations

from collections import Counter
from typing import Iterable

from .core import ACTIVE, BASELINED, SUPPRESSED, Finding, all_rules


def summarize(findings: Iterable[Finding]) -> dict:
    findings = list(findings)
    by_status = Counter(f.status for f in findings)
    by_rule: dict[str, dict[str, int]] = {}
    for rule_id in sorted(all_rules()):
        per = Counter(f.status for f in findings if f.rule == rule_id)
        by_rule[rule_id] = {"active": per.get(ACTIVE, 0),
                            "suppressed": per.get(SUPPRESSED, 0),
                            "baselined": per.get(BASELINED, 0)}
    return {
        "total": len(findings),
        "active": by_status.get(ACTIVE, 0),
        "suppressed": by_status.get(SUPPRESSED, 0),
        "baselined": by_status.get(BASELINED, 0),
        "by_rule": by_rule,
    }


def to_json(findings: Iterable[Finding], errors: list[str] | None = None) -> dict:
    """Machine-readable report (the ``--json`` CLI payload, shaped for CI
    annotation: one record per finding with file/line/rule/message)."""
    findings = list(findings)
    return {
        "tool": "graftlint",
        "summary": summarize(findings),
        "findings": [{
            "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
            "status": f.status, "message": f.message, "code": f.code,
        } for f in findings],
        **({"errors": errors} if errors else {}),
    }


def to_text(findings: Iterable[Finding], show_all: bool = False) -> str:
    """Compiler-style lines for active findings (all statuses with
    ``show_all``)."""
    out = []
    for f in findings:
        if f.status != ACTIVE and not show_all:
            continue
        tag = "" if f.status == ACTIVE else f" [{f.status}]"
        out.append(f"{f.path}:{f.line}:{f.col}: {f.rule}{tag} {f.message}")
    return "\n".join(out)


def emit_metrics(findings: Iterable[Finding], registry=None,
                 skipped: int = 0) -> None:
    """Publish per-rule gauges through the observability layer.  Imported
    lazily so the analyzer stays usable without jax/observability on the
    path (e.g. a bare CI box running only the linter).  ``skipped`` is the
    analyzer's unreadable/unparseable file count — published as the
    ``graftlint.skipped_files`` gauge so hostile inputs degrade visibly
    instead of silently shrinking coverage."""
    if registry is None:
        try:
            from ..observability import METRICS as registry
        except Exception:
            return
    findings = list(findings)
    registry.increment("graftlint.runs")
    for rule_id in sorted(all_rules()):
        n = sum(1 for f in findings
                if f.rule == rule_id and f.status == ACTIVE)
        registry.gauge(f"graftlint.violations.{rule_id}", n)
    registry.gauge("graftlint.violations.total",
                   sum(1 for f in findings if f.status == ACTIVE))
    registry.gauge("graftlint.skipped_files", skipped)
