"""Corpus -> LM batch pipeline (text/lm_dataset.py): packing round-trip,
target-shift property, stateless-shuffle resumability, and end-to-end
training of the flagship on real tokenized text."""

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.text import LMCorpus, LMTokenBatchIterator

SENTS = [
    "the quick brown fox jumps over the lazy dog",
    "the lazy dog sleeps while the quick fox runs",
    "a quick fox and a lazy dog meet the brown fox",
] * 8


def test_corpus_packs_and_decodes():
    corpus = LMCorpus(SENTS)
    # every sentence ends with <eos>; the id stream decodes back to the
    # original token stream
    toks = corpus.decode(corpus.ids)
    assert toks.count("<eos>") == len(SENTS)
    first = toks[:toks.index("<eos>")]
    assert first == SENTS[0].split()
    # frequency-sorted convention: "the" (most frequent) gets index 0
    assert corpus.vocab.word_at(0) == "the"
    assert corpus.vocab_size == len(corpus.vocab) + 2


def test_unk_and_min_frequency():
    corpus = LMCorpus(SENTS, min_word_frequency=9)  # drops words seen 8x
    kept = set(corpus.vocab.words())
    assert "the" in kept and "sleeps" not in kept
    ids = [corpus.vocab.index_of(w) for w in ("sleeps",)]
    assert ids == [-1]
    # dropped words encode as <unk>, not as errors
    assert (corpus.ids == corpus.unk_id).sum() > 0


def test_batches_shift_property_and_epochs():
    corpus = LMCorpus(SENTS)
    it = LMTokenBatchIterator(corpus, batch=4, seq=8, seed=7)
    tokens, targets = it.next()
    assert tokens.shape == (4, 8) and targets.shape == (4, 8)
    np.testing.assert_array_equal(tokens[:, 1:], targets[:, :-1])

    # one epoch covers each block at most once, reshuffled next epoch
    it2 = LMTokenBatchIterator(corpus, batch=4, seq=8, seed=7)
    e0 = [it2.next()[0] for _ in range(it2.batches_per_epoch)]
    e1 = [it2.next()[0] for _ in range(it2.batches_per_epoch)]
    assert not all(np.array_equal(a, b) for a, b in zip(e0, e1))


def test_cursor_resume_is_deterministic():
    corpus = LMCorpus(SENTS)
    it = LMTokenBatchIterator(corpus, batch=2, seq=8, seed=3)
    seq = [it.next() for _ in range(5)]
    it2 = LMTokenBatchIterator(corpus, batch=2, seq=8, seed=3)
    it2.set_cursor(3)
    a, b = it2.next()
    np.testing.assert_array_equal(a, seq[3][0])
    np.testing.assert_array_equal(b, seq[3][1])
    assert it.cursor == 5


def test_too_small_corpus_rejected():
    import pytest
    with pytest.raises(ValueError, match="fewer than one batch"):
        LMTokenBatchIterator(LMCorpus(SENTS[:1]), batch=64, seq=128)


def test_flagship_trains_on_packed_text():
    """End to end: tokenize -> pack -> batches -> TransformerLM train steps
    reduce loss on a repetitive corpus (the full L8 -> flagship path)."""
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.optimize import transforms as T

    corpus = LMCorpus(SENTS)
    it = LMTokenBatchIterator(corpus, batch=4, seq=8, seed=0)
    cfg = TransformerConfig(
        vocab_size=corpus.vocab_size, d_model=32, n_heads=4, n_layers=2,
        d_ff=64, max_len=8, causal=True, dtype=jnp.float32, remat=False)
    model = TransformerLM(cfg)
    tx = T.adamw(0.01)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    losses = []
    for tokens, targets in it.epoch_batches():
        for _ in range(6):
            params, opt, loss = step(params, opt, jnp.asarray(tokens),
                                     jnp.asarray(targets))
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])
