"""Runtime race detector (analysis/lockguard.py) unit + integration tests.

The detector tests drive a private ``LockGuard`` instance (install/
uninstall scoped per test) so deliberate violations never leak into the
session singleton the ``lockguard`` marker asserts on.  The integration
half runs real serving traffic with the engine object under Eraser watch
and pins the concurrency regressions fixed alongside this tier: the
prefetch worker-error handoff and the scorer shape race.
"""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.analysis.lockguard import (
    LOCKGUARD,
    LockGuard,
    enabled_from_env,
    lockguard_active,
)


@pytest.fixture
def guard():
    g = LockGuard()
    g.install()
    yield g
    g.uninstall()


def _run(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(5.0)
    assert not t.is_alive()


# ------------------------------------------------------------ lock order

def test_lock_order_inversion_detected_without_deadlocking(guard):
    """Thread 1 takes A then B; thread 2 (run strictly AFTER thread 1
    finished, so nothing can actually wedge) takes B then A — the cycle
    in the order graph is reported even though this run never blocked."""
    a, b = threading.Lock(), threading.Lock()

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    _run(ab)
    _run(ba)
    kinds = [v.kind for v in guard.violations()]
    assert kinds == ["lock-order"]
    assert "inversion" in str(guard.violations()[0])


def test_consistent_order_is_clean(guard):
    a, b = threading.Lock(), threading.Lock()

    def ab():
        with a:
            with b:
                pass

    _run(ab)
    _run(ab)
    assert guard.violations() == []


def test_cycle_reported_once_not_per_occurrence(guard):
    a, b = threading.Lock(), threading.Lock()

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for _ in range(3):
        _run(ab)
        _run(ba)
    assert len(guard.violations()) == 1


def test_rlock_reentry_is_not_a_self_cycle(guard):
    r = threading.RLock()
    with r:
        with r:
            pass
    assert guard.violations() == []


def test_condition_wait_keeps_hold_tracking_truthful(guard):
    """Condition.wait fully releases its (R)Lock; after the wait the
    re-acquire must not create phantom order edges or leak held state."""
    cv = threading.Condition()
    done = threading.Event()

    def waiter():
        with cv:
            cv.wait(0.2)
        done.set()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(5.0)
    assert done.is_set()
    assert guard.violations() == []


# ---------------------------------------------------------------- eraser

def test_unguarded_shared_write_detected(guard):
    class Box:
        def __init__(self):
            self.x = 0

    b = Box()
    guard.watch(b)
    b.x = 1                      # owner (this thread)
    _run(lambda: setattr(b, "x", 2))   # second thread, no lock held
    kinds = [v.kind for v in guard.violations()]
    assert kinds == ["unguarded-write"]
    assert guard.violations()[0].details == ("Box", "x")


def test_consistently_locked_write_is_clean(guard):
    class Box:
        def __init__(self):
            self.lock = threading.Lock()
            self.x = 0

    b = Box()
    guard.watch(b)
    with b.lock:
        b.x = 1

    def locked_write():
        with b.lock:
            b.x = 2

    _run(locked_write)
    assert guard.violations() == []


def test_exclusive_single_thread_writes_are_clean(guard):
    class Box:
        def __init__(self):
            self.x = 0

    b = Box()
    guard.watch(b)
    for i in range(10):          # one thread, no lock — fine forever
        b.x = i
    assert guard.violations() == []


def test_violation_reported_once_per_field(guard):
    class Box:
        def __init__(self):
            self.x = 0

    b = Box()
    guard.watch(b)
    b.x = 1
    for _ in range(3):
        _run(lambda: setattr(b, "x", 2))
    assert len(guard.violations()) == 1


def test_unwatch_stops_tracking(guard):
    class Box:
        def __init__(self):
            self.x = 0

    b = Box()
    guard.watch(b)
    guard.unwatch(b)
    b.x = 1
    _run(lambda: setattr(b, "x", 2))
    assert guard.violations() == []


# ------------------------------------------------------------- lifecycle

def test_install_is_scoped_and_reversible():
    real = threading.Lock
    with lockguard_active(LockGuard()) as g:
        assert threading.Lock is not real
        assert g.installed
    assert threading.Lock is real


def test_env_switch_parses():
    import os

    old = os.environ.get("DL4J_TPU_LOCKGUARD")
    try:
        os.environ["DL4J_TPU_LOCKGUARD"] = "1"
        assert enabled_from_env()
        os.environ["DL4J_TPU_LOCKGUARD"] = "0"
        assert not enabled_from_env()
        os.environ.pop("DL4J_TPU_LOCKGUARD")
        assert not enabled_from_env()
    finally:
        if old is not None:
            os.environ["DL4J_TPU_LOCKGUARD"] = old


def test_report_and_metrics_emission(guard):
    from deeplearning4j_tpu.observability import METRICS

    a, b = threading.Lock(), threading.Lock()

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    _run(ab)
    _run(ba)
    assert "lock-order" in guard.report()
    guard.emit_metrics()
    gauges = METRICS.snapshot()["gauges"]
    assert gauges["lockguard.violations.lock_order"] == 1
    assert gauges["lockguard.violations.unguarded_write"] == 0


# ------------------------------------------- integration: serving stack

def _tiny_lm():
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)

    cfg = TransformerConfig(vocab_size=31, d_model=16, n_heads=2,
                            n_layers=1, d_ff=32, max_len=64,
                            dtype=jnp.float32)
    model = TransformerLM(cfg)
    return model, model.init(jax.random.key(7))


@pytest.mark.lockguard
def test_engine_traffic_clean_under_watch():
    """Serving traffic with the engine object under Eraser watch AND the
    session lockguard marker: every rebindable shared field the engine
    mutates mid-flight must show a consistent lockset (watch is applied
    after ``start()`` — the warmup handoff is a happens-before edge the
    lockset algorithm cannot see, see lockguard module docstring)."""
    from deeplearning4j_tpu.serving import InferenceEngine, ServingConfig

    model, params = _tiny_lm()
    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2))
    engine.start()
    LOCKGUARD.watch(engine)
    try:
        outs = [engine.submit([1, 2, 3], 3, seed=i) for i in range(4)]
        got = [h.result(60.0) for h in outs]
        assert all(len(o.tokens) == 3 for o in got)
        assert engine.stats()["completed"] == 4
    finally:
        engine.stop()
        LOCKGUARD.unwatch(engine)
    # the marker's teardown asserts LOCKGUARD.violations() == []


@pytest.mark.lockguard
def test_scorer_concurrent_first_submits_clean_under_watch():
    """Regression for the BatchScorer shape race: concurrent FIRST
    submits from many threads race the ``_row_shape`` check-then-set;
    it is now atomic under ``_shape_lock``, so the watched scorer stays
    violation-free and every row scores against one agreed shape."""
    from deeplearning4j_tpu.serving import BatchScorer

    scorer = BatchScorer(lambda xs: xs * 2.0, max_batch=8)
    with scorer:
        LOCKGUARD.watch(scorer)
        results = []
        res_lock = threading.Lock()

        def first_submit(i):
            out = scorer.score(np.full((4,), float(i)), timeout=30.0)
            with res_lock:
                results.append(out)

        ts = [threading.Thread(target=first_submit, args=(i,))
              for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30.0)
        LOCKGUARD.unwatch(scorer)
    assert len(results) == 6
    assert all(r.shape == (4,) for r in results)


@pytest.mark.lockguard
def test_threaded_prefetch_worker_error_handoff():
    """Regression for the ``_ThreadedPrefetch._error`` race: the worker
    publishes its exception under ``_err_lock`` and the consumer claims
    it with an atomic swap, so exactly one claimant re-raises — run
    under the lockguard marker to keep the queue/lock traffic honest."""
    from deeplearning4j_tpu.datasets.iterator import prefetch_to_device

    def exploding_source():
        yield np.zeros((2, 2), np.float32)
        raise RuntimeError("worker boom")

    it = prefetch_to_device(exploding_source(), size=2, host_thread=True)
    batches = []
    with pytest.raises(RuntimeError, match="worker boom"):
        for b in it:
            batches.append(b)
    # the error may win the race against the first staged batch — the
    # contract is "raised exactly once, worker shut down", not ordering
    assert len(batches) <= 1
    assert not it.thread.is_alive()
