"""Device-memory gauges.

Samples ``jax.local_devices()[*].memory_stats()`` into the metrics
registry.  TPU/GPU backends report ``bytes_in_use`` / ``peak_bytes_in_use``
/ ``bytes_limit``; the CPU backend returns ``None`` — sampling is then a
no-op, so instrumented paths can call this unconditionally.

``sample_state_bytes`` sits next to the HBM gauges and makes the ZeRO
memory win a scraped number instead of a claim: per-device bytes actually
held by the param and optimizer-state trees, computed from shard METADATA
(``addressable_shards`` shapes — no device sync, no transfer), published
at trainer init and after every restore.
"""

from __future__ import annotations

import numpy as np

from . import core
from .metrics import METRICS, MetricsRegistry

_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def sample_device_memory(registry: MetricsRegistry = METRICS) -> int:
    """Gauge per-device memory stats; returns how many devices reported.

    On backends without memory stats (CPU) this degrades to a no-op gauge:
    ``device.memory_stats_supported`` is published as 0.0 and no exception
    ever escapes, so instrumented paths call this unconditionally."""
    if not core.enabled():
        return 0
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return 0
    reported = 0
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        try:
            prefix = f"device.{d.id}."
            for k in _KEYS:
                if k in stats:
                    registry.gauge(prefix + k, float(stats[k]))
        except Exception:
            continue
        reported += 1
    registry.gauge("device.memory_stats_supported", float(reported))
    return reported


def _bytes_by_device(tree) -> dict[int, int]:
    """Per-device bytes held by a pytree's placed arrays, from shard
    metadata only.  Replicated leaves charge every device the full leaf;
    dp-sharded leaves charge each device its chunk — exactly the
    accounting that shows the 1/ndp ZeRO shrink."""
    import jax

    out: dict[int, int] = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array):
            continue
        try:
            shards = leaf.addressable_shards
            itemsize = np.dtype(leaf.dtype).itemsize
        except Exception:
            continue
        for sh in shards:
            n = int(np.prod(sh.data.shape, dtype=np.int64)) \
                if sh.data.shape else 1
            out[sh.device.id] = out.get(sh.device.id, 0) + n * itemsize
    return out


def sample_state_bytes(params, tstate,
                       registry: MetricsRegistry = METRICS) -> int:
    """Gauge ``train.params_bytes.device.{id}`` and
    ``train.opt_state_bytes.device.{id}``; returns devices reported."""
    if not core.enabled():
        return 0
    seen: set[int] = set()
    for name, tree in (("train.params_bytes", params),
                       ("train.opt_state_bytes", tstate)):
        for dev_id, nbytes in _bytes_by_device(tree).items():
            registry.gauge(f"{name}.device.{dev_id}", float(nbytes))
            seen.add(dev_id)
    return len(seen)
