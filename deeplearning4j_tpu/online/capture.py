"""Durable capture of served traffic (DESIGN.md §23).

Append-only, fsync'd, segment-rotated JSONL — the serving half of the
online loop's dataflow.  Every record is framed as
``{"sha": sha256(rec)[:16], "rec": {...}}`` on its own line, so replay
can verify each record independently and a damaged byte range costs
exactly the records it covers, never the store.  The durability contract
is the log-structured one (and deliberately NOT the tempfile+rename
idiom graftlint OL01 enforces for *rewrites*): records are only ever
APPENDED to the active segment and fsync'd before ``append`` returns, so
a crash can tear at most the final line — replay tolerates a torn tail
(and any ``corrupt_file`` chaos damage) by skipping records whose
checksum no longer matches, counting them in
``capture.corrupt_records``.

Chaos seams: ``capture.write`` damages the active segment *after* a
durable append (bad medium under the checksums — the same shape as
``checkpoint.write``); ``capture.replay`` raises
:class:`~..resilience.faults.CaptureReplayFault` at replay start (a
retryable round-level failure).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from pathlib import Path
from typing import Any, Iterator

from ..observability import METRICS
from ..resilience.faults import FAULTS, corrupt_file

_SEGMENT_FMT = "capture-%06d.jsonl"


def _frame(rec: dict) -> str:
    """One self-verifying JSONL line: canonical-JSON body + short sha."""
    body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
    sha = hashlib.sha256(body.encode()).hexdigest()[:16]
    return json.dumps({"sha": sha, "rec": json.loads(body)},
                      sort_keys=True, separators=(",", ":"))


class CaptureStore:
    """Append-only segment-rotated JSONL store of served requests.

    ``append`` is thread-safe (HTTP handler threads feed it) and durable
    on return: write → flush → ``os.fsync``.  ``replay`` yields every
    verifiable record across all segments in append order; readers and
    the writer never coordinate — replay opens its own handles and the
    writer only ever appends.
    """

    def __init__(self, directory: str | Path, segment_bytes: int = 1 << 20):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.segment_bytes = int(segment_bytes)
        self._lock = threading.Lock()
        existing = self.segments()
        self._seg_index = (int(existing[-1].stem.split("-")[1])
                           if existing else 0)
        # boot-time tail seal: a torn final line (crash mid-append, or
        # truncation damage) must not swallow the NEXT append by
        # concatenating onto the half-line — seal the damaged segment and
        # start a fresh one.  Append-only discipline: damaged media is
        # never rewritten, only retired.
        if existing:
            tail = existing[-1].read_bytes()
            if tail and not tail.endswith(b"\n"):
                self._seg_index += 1
                METRICS.increment("capture.sealed_segments")
        # appended records are durable before append() returns; "a" mode
        # means a crash (or injected damage) can only cost tail records
        self._fh = open(self._active_path(), "a", encoding="utf-8")
        self._publish_gauges()

    # ---------------------------------------------------------------- paths
    def _active_path(self) -> Path:
        return self.directory / (_SEGMENT_FMT % self._seg_index)

    def segments(self) -> list[Path]:
        """All segment files, oldest first."""
        return sorted(self.directory.glob("capture-*.jsonl"))

    # --------------------------------------------------------------- writes
    def append(self, rec: dict) -> None:
        """Durably append one record (fsync'd before returning)."""
        line = _frame(dict(rec))
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
            METRICS.increment("online.captured_records")
            # chaos: damage the segment AFTER the durable append — a bad
            # medium under the per-record checksums, which replay must
            # absorb record-by-record (never losing the whole store)
            spec = FAULTS.check("capture.write")
            if spec is not None:
                self._fh.close()
                corrupt_file(self._active_path(), spec.kind)
                self._fh = open(self._active_path(), "a", encoding="utf-8")
            if self._fh.tell() >= self.segment_bytes:
                self._rotate_locked()
            self._publish_gauges_locked()

    def _rotate_locked(self) -> None:
        self._fh.close()
        self._seg_index += 1
        self._fh = open(self._active_path(), "a", encoding="utf-8")

    # ---------------------------------------------------------------- reads
    def replay(self) -> Iterator[dict]:
        """Yield every verifiable record, oldest first.

        Torn-tail tolerant: a line that does not parse, is mis-framed,
        or fails its checksum is SKIPPED (counted in
        ``capture.corrupt_records``) — replay never raises on damage,
        only on the injected ``capture.replay`` round fault.
        """
        FAULTS.maybe_fire("capture.replay")
        for seg in self.segments():
            try:
                text = seg.read_text(encoding="utf-8", errors="replace")
            except OSError:
                continue
            for line in text.splitlines():
                rec = self._verify_line(line)
                if rec is not None:
                    yield rec

    def _verify_line(self, line: str) -> dict | None:
        line = line.strip()
        if not line:
            return None
        try:
            framed = json.loads(line)
            sha, rec = framed["sha"], framed["rec"]
            body = json.dumps(rec, sort_keys=True, separators=(",", ":"))
            if hashlib.sha256(body.encode()).hexdigest()[:16] != sha:
                raise ValueError("checksum mismatch")
            return rec
        except (ValueError, KeyError, TypeError):
            METRICS.increment("capture.corrupt_records")
            return None

    def replay_tenant(self, tenant: str | None) -> Iterator[dict]:
        """Replay filtered to one tenant's traffic (``None`` matches
        records served without a tenant).  The server stamps the RAW
        tenant id on every capture record (the bounded ``__other__``
        fold applies to metric names only), so per-tenant fine-tuning
        (ROADMAP: per-tenant LoRA) slices here losslessly."""
        for rec in self.replay():
            if rec.get("tenant") == tenant:
                yield rec

    def records(self) -> list[dict]:
        return list(self.replay())

    # -------------------------------------------------------------- gauges
    def _publish_gauges(self) -> None:
        with self._lock:
            self._publish_gauges_locked()

    def _publish_gauges_locked(self) -> None:
        total = sum(p.stat().st_size for p in self.segments())
        METRICS.gauge("capture.bytes", total)
        METRICS.gauge("capture.segments", self._seg_index + 1)

    # ----------------------------------------------------------- lifecycle
    def close(self) -> None:
        with self._lock:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "CaptureStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __len__(self) -> int:
        return sum(1 for _ in self.replay())
