"""Perf smoke: bounded-recompile guard for the async trainer hot loop.

Runs a 30-step CPU fit whose batch sizes are deliberately ragged and
asserts the steady-state number of XLA compilations equals the number of
padding *buckets* actually used (`train_step.recompile` counter) — the
regression this guards against is the pre-bucketing behavior where every
distinct ragged shape silently compiled a fresh step program.

The expected bucket set is an INDEPENDENT reimplementation of the
trainer's ladder (powers of two rounded up to the dp width, capped at the
nominal batch): if someone changes the trainer's bucketing they must
consciously change this file too, not just watch a counter follow along.

Wired as a fast tier-1 test (`tests/test_perf_smoke.py`); also runnable
standalone: `python tools/perf_smoke.py` prints one JSON line.
`--shardguard` runs both legs with runtime sharding-drift detection
(analysis/shardguard.py) and fails on any implicit resharding.
"""

from __future__ import annotations

import json
import math
import sys

# the ragged pattern: first size fixes the nominal bucket cap
RAGGED_SIZES = [32, 31, 17, 9, 23, 13, 32, 5, 29, 11]


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def expected_buckets(sizes, n_dp: int) -> set[int]:
    """Reference bucket ladder (kept independent of the trainer's code)."""
    nominal = _round_up(sizes[0], n_dp)
    out = set()
    for n in sizes:
        if n >= nominal:
            out.add(_round_up(n, n_dp))
        else:
            out.add(min(_round_up(1 << math.ceil(math.log2(n)), n_dp), nominal))
    return out


def run(steps: int = 30) -> dict:
    import numpy as np

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.analysis.runtime import guard_mode
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer

    observability.enable()
    METRICS.reset()

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(6, 1))

    def batches():
        for k in range(steps):
            n = RAGGED_SIZES[k % len(RAGGED_SIZES)]
            x = rng.normal(size=(n, 6)).astype(np.float32)
            y = (x @ w_true).astype(np.float32)
            yield DataSet(x, y)

    def loss_fn(p, x, y, key=None):
        return ((x @ p["w"] - y) ** 2).mean()

    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    params = {"w": np.zeros((6, 1), np.float32)}
    state, losses = trainer.fit(trainer.init_state(params), batches())

    snap = METRICS.snapshot()["counters"]
    recompiles = int(snap.get("train_step.recompile", 0))
    n_buckets = len(expected_buckets(
        [RAGGED_SIZES[k % len(RAGGED_SIZES)] for k in range(steps)],
        trainer.n_dp))
    result = {
        "steps": int(snap.get("train_step.iterations", 0)),
        "recompiles": recompiles,
        "expected_buckets": n_buckets,
        "n_dp": trainer.n_dp,
        # fit's steady state ran under jax.transfer_guard(<mode>): any
        # implicit host<->device transfer would have failed the run
        "transfer_guard": guard_mode() or "off",
        "losses_finite": all(math.isfinite(l) for l in losses),
        "final_loss": losses[-1] if losses else None,
    }
    assert result["steps"] == steps, f"ran {result['steps']}/{steps} steps"
    assert result["losses_finite"], "non-finite loss in smoke run"
    assert recompiles == n_buckets, (
        f"{recompiles} recompiles != {n_buckets} buckets — "
        "per-shape recompilation is back (or the ladder changed; "
        "update expected_buckets deliberately)")
    return result


def run_zero(steps: int = 30) -> dict:
    """ZeRO leg: a zero_stage=2 fit must keep the bounded-recompile
    invariant AND actually shrink optimizer-state bytes/device to
    ~replicated/ndp (within flatten-padding tolerance) — the memory win
    is asserted from the ``train.opt_state_bytes`` gauges, not inferred."""
    import numpy as np

    import jax
    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer

    observability.enable()

    d = 64  # big enough that per-leaf pad rows are noise vs the total
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(d, 1))

    def loss_fn(p, x, y, key=None):
        return ((x @ p["w"] - y) ** 2).mean()

    def batches():
        for k in range(steps):
            n = RAGGED_SIZES[k % len(RAGGED_SIZES)]
            x = rng.normal(size=(n, d)).astype(np.float32)
            yield DataSet(x, (x @ w_true).astype(np.float32))

    def opt_bytes_per_device():
        gauges = METRICS.snapshot()["gauges"]
        vals = [v for k, v in gauges.items()
                if k.startswith("train.opt_state_bytes.device.")]
        assert vals, "train.opt_state_bytes gauges missing"
        return max(vals)

    def one(stage):
        METRICS.reset()
        # momentum: a stateful transform, so there are bytes to shard
        tr = DataParallelTrainer(loss_fn, T.chain(T.momentum(0.9),
                                                  T.sgd_lr(0.05)),
                                 zero_stage=stage)
        params = {"w": np.zeros((d, 1), np.float32)}
        state, losses = tr.fit(tr.init_state(params), batches())
        snap = METRICS.snapshot()["counters"]
        return tr, {
            "recompiles": int(snap.get("train_step.recompile", 0)),
            "opt_state_bytes_per_device": opt_bytes_per_device(),
            "final_loss": losses[-1],
            "losses_finite": all(math.isfinite(l) for l in losses),
        }

    tr0, r0 = one(0)
    tr2, r2 = one(2)
    n_dp = tr2.n_dp
    n_buckets = len(expected_buckets(
        [RAGGED_SIZES[k % len(RAGGED_SIZES)] for k in range(steps)], n_dp))
    z = tr2._zero
    # each leaf pads by < n_dp elements; the per-device share of all pad
    # is at most one element per leaf (times itemsize)
    pad_slack = sum(
        np.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(z.natural_tstate))
    result = {
        "n_dp": n_dp,
        "zero_stage": 2,
        "recompiles": r2["recompiles"],
        "expected_buckets": n_buckets,
        "opt_bytes_replicated": r0["opt_state_bytes_per_device"],
        "opt_bytes_zero2": r2["opt_state_bytes_per_device"],
        "pad_slack_bytes": pad_slack,
        "losses_finite": r0["losses_finite"] and r2["losses_finite"],
    }
    assert result["losses_finite"], "non-finite loss in zero smoke run"
    assert r2["recompiles"] == n_buckets, (
        f"zero_stage=2: {r2['recompiles']} recompiles != {n_buckets} "
        "buckets — the sharded step broke the bucket ladder")
    rep, shard = result["opt_bytes_replicated"], result["opt_bytes_zero2"]
    assert rep / n_dp <= shard <= rep / n_dp + pad_slack, (
        f"opt-state bytes/device {shard} outside "
        f"[{rep / n_dp}, {rep / n_dp + pad_slack}] — the 1/ndp ZeRO "
        "shrink regressed")
    return result


def main(argv: list[str] | None = None) -> int:
    argv = argv or []
    shardguard = None
    if "--shardguard" in argv:
        # run both legs with runtime sharding-drift detection: the ragged
        # bucket ladder re-dispatches the same step at many shapes, which
        # is where a drifted device_put would silently reshard per step
        from deeplearning4j_tpu.analysis.shardguard import SHARDGUARD \
            as shardguard
        shardguard.reset()
        shardguard.enable()
    try:
        print(json.dumps(run()))
        print(json.dumps(run_zero()))
        if shardguard is not None:
            print(json.dumps(
                {"shardguard_violations": len(shardguard.violations())}))
            assert not shardguard.violations(), shardguard.report()
    finally:
        if shardguard is not None:
            shardguard.disable()
    return 0


if __name__ == "__main__":
    import os
    import pathlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main(sys.argv[1:]))
