"""Disaggregated prefill/decode serving (DESIGN.md §27).

Prefill is compute-bound and bursty; decode is memory-bound and steady.
Colocated, each ruins the other's tail: a long prompt landing
mid-decode-segment spikes inter-token p99, and a deep decode batch
queues prompts behind it.  This package splits them into TIERS —
prefill-role engines that fill KV pages and never decode, a
:class:`KVMigrator` that moves a request's pages to a decode engine
(content-addressed, so resident pages transfer as a hash-only claim),
and a :class:`DisaggScheduler` that drives the pipeline and requeues
(never corrupts) when chaos kills a prefill worker.

Page-accounting discipline: every pool acquire/release and block-table
write in this package lives inside the KVMigrator's export/import seams
— graftlint DG01 fails anything else.
"""

from .migrate import (KVMigrator, PageTransfer, TransferPlan,
                      export_payload)
from .scheduler import DisaggScheduler

__all__ = [
    "DisaggScheduler",
    "KVMigrator",
    "PageTransfer",
    "TransferPlan",
    "export_payload",
]
