"""Clustering + visualization tests (mirror of the reference's TsneTest and
clustering/{kdtree,quadtree,vptree} tests)."""

import numpy as np
import pytest

from deeplearning4j_tpu.clustering import KDTree, KMeansClustering, QuadTree, VPTree
from deeplearning4j_tpu.plot import BarnesHutTsne, Tsne


def three_blobs(n=30, seed=0):
    rng = np.random.default_rng(seed)
    centers = np.array([[0, 0], [10, 10], [-10, 10]], np.float64)
    pts = np.concatenate([rng.normal(c, 0.5, (n, 2)) for c in centers])
    labels = np.repeat([0, 1, 2], n)
    return pts, labels


def test_kmeans_recovers_blobs():
    pts, labels = three_blobs()
    km = KMeansClustering(k=3, seed=1).fit(pts)
    assign = km.labels()
    # each true cluster maps to exactly one k-means cluster
    for c in range(3):
        vals, counts = np.unique(assign[labels == c], return_counts=True)
        assert counts.max() / counts.sum() > 0.95
    assert km.predict(pts[:5]).shape == (5,)


def test_kdtree_nearest_matches_bruteforce():
    rng = np.random.default_rng(3)
    pts = rng.random((200, 4))
    tree = KDTree(pts)
    for _ in range(20):
        q = rng.random(4)
        idx, dist = tree.nearest(q)
        brute = np.linalg.norm(pts - q, axis=1)
        assert idx == int(brute.argmin())
        assert dist == pytest.approx(float(brute.min()))
    knn = tree.knn(pts[0], 5)
    brute_order = np.argsort(np.linalg.norm(pts - pts[0], axis=1))[:5]
    assert {i for i, _ in knn} == set(brute_order.tolist())


def test_kdtree_range_search():
    pts = np.array([[0, 0], [1, 1], [2, 2], [5, 5]], np.float64)
    tree = KDTree(pts)
    assert tree.range_search([0.5, 0.5], [2.5, 2.5]) == [1, 2]


def test_vptree_knn_matches_bruteforce():
    rng = np.random.default_rng(4)
    pts = rng.random((150, 6))
    tree = VPTree(pts)
    for _ in range(10):
        q = rng.random(6)
        got = {i for i, _ in tree.knn(q, 7)}
        brute = set(np.argsort(np.linalg.norm(pts - q, axis=1))[:7].tolist())
        assert got == brute


def test_quadtree_structure_and_com():
    pts, _ = three_blobs(n=20)
    tree = QuadTree.build(pts)
    assert tree.size == pts.shape[0]
    np.testing.assert_allclose(tree.com, pts.mean(axis=0), atol=1e-9)
    assert tree.depth() >= 2
    f, sq = tree.compute_non_edge_forces(pts[0], theta=0.5, index=0)
    assert np.all(np.isfinite(f)) and sq > 0


def test_exact_tsne_separates_blobs():
    pts, labels = three_blobs(n=20, seed=5)
    emb = Tsne(perplexity=10, n_iter=250, seed=0).fit_transform(pts)
    assert emb.shape == (60, 2)
    # clusters should be separated: within-cluster dist << between-cluster
    within = np.mean([np.linalg.norm(emb[labels == c] - emb[labels == c].mean(0), axis=1).mean()
                      for c in range(3)])
    centers = np.stack([emb[labels == c].mean(0) for c in range(3)])
    between = np.mean([np.linalg.norm(centers[i] - centers[j])
                       for i in range(3) for j in range(i + 1, 3)])
    assert between > 3 * within


def test_barnes_hut_tsne_runs():
    pts, labels = three_blobs(n=12, seed=6)
    emb = BarnesHutTsne(theta=0.5, perplexity=8, n_iter=120, seed=0).fit_transform(pts)
    assert emb.shape == (36, 2)
    assert np.all(np.isfinite(emb))


def test_renderers(tmp_path):
    from deeplearning4j_tpu.plot import FilterRenderer, NeuralNetPlotter, draw_mnist_grid
    rng = np.random.default_rng(0)
    params = [{"W": rng.random((16, 4)), "b": rng.random(4)}]
    grads = [{"W": rng.random((16, 4)), "b": rng.random(4)}]
    files = NeuralNetPlotter().plot_network_gradient(params, grads, tmp_path)
    assert files and files[0].exists()
    p = FilterRenderer().render_filters(rng.random((16, 9)), tmp_path / "f.png")
    assert p.exists()
    p2 = draw_mnist_grid(rng.random((12, 64)), tmp_path / "g.png")
    assert p2.exists()


# --------------------------------------------------------------------------
# Interactive embedding render app (RenderApplication.java parity)
# --------------------------------------------------------------------------

def test_embedding_render_server_serves_page_and_coords():
    import json
    import urllib.request

    from deeplearning4j_tpu.plot import EmbeddingRenderServer

    words = ["alpha", "beta", "gamma"]
    coords = np.array([[0.0, 0.0], [1.0, 2.0], [-1.0, 0.5]])
    srv = EmbeddingRenderServer(words, coords).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        page = urllib.request.urlopen(base + "/", timeout=10).read().decode()
        assert "canvas" in page and "api/coords" in page
        data = json.loads(urllib.request.urlopen(
            base + "/api/coords", timeout=10).read())
        assert [d["word"] for d in data] == words
        assert data[1] == {"word": "beta", "x": 1.0, "y": 2.0}
        # live update republished on next poll
        srv.update(words, coords + 1.0)
        data2 = json.loads(urllib.request.urlopen(
            base + "/api/coords", timeout=10).read())
        assert data2[0]["x"] == 1.0
        # bad shape rejected
        with pytest.raises(ValueError):
            srv.update(words, np.zeros((2, 2)))
    finally:
        srv.stop()


def test_render_word_vectors_from_word2vec():
    import json
    import urllib.request

    from deeplearning4j_tpu.plot.render_app import render_word_vectors
    from deeplearning4j_tpu.text.word2vec import Word2Vec

    corpus = ["the cat sat on the mat", "the dog sat on the rug",
              "cats and dogs play"] * 10
    w2v = Word2Vec(corpus, layer_size=16, min_word_frequency=1, iterations=2, seed=0)
    w2v.fit()
    srv = render_word_vectors(w2v, max_words=10, n_iter=50)
    try:
        data = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/api/coords", timeout=10).read())
        assert 1 < len(data) <= 10
        assert all(np.isfinite([d["x"], d["y"]]).all() for d in data)
    finally:
        srv.stop()
