"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §4 implication (c)): the
collectives layer is exercised on one host with
``--xla_force_host_platform_device_count=8``, mirroring the reference's
"distributed-without-a-cluster" pattern (``BaseTestDistributed``).

IMPORTANT environment quirk: the driver boots every interpreter through a
``sitecustomize`` that imports jax and registers the tunneled real-TPU
platform ("axon") with ``JAX_PLATFORMS=axon`` already set.  Tests must NOT
ride the tunnel (per-op dispatch round-trips make eager paths orders of
magnitude slower, and a held grant can hang ``jax.devices()`` outright), so
we both set the env vars (for subprocesses) and call
``jax.config.update("jax_platforms", "cpu")`` (effective post-import).
bench.py is the only place that uses the real chip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# NOTE: do NOT point the persistent XLA compile cache (compile_cache.py)
# at the suite — with this jaxlib (0.4.36) caching the 8-device sharded
# trainer step segfaults the process (reproducer:
# test_online_loop.py::test_round_trains_publishes_and_hot_reloads with
# DL4J_TPU_COMPILE_CACHE_DIR set, even at min_compile_time_s=1.0).

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 `-m 'not slow'` sweep — chaos "
        "replays and other multi-fit end-to-end runs that earn their "
        "keep in the composed smoke tools, not on every commit")
    config.addinivalue_line(
        "markers",
        "no_implicit_transfers: run the test under "
        "jax.transfer_guard('disallow') — any implicit host<->device "
        "transfer inside the test body fails it (hot-loop contract; see "
        "deeplearning4j_tpu/analysis/runtime.py)")
    config.addinivalue_line(
        "markers",
        "lockguard: run the test with instrumented threading locks — "
        "lock-order inversions and Eraser-style unguarded shared writes "
        "observed during the test fail it (see "
        "deeplearning4j_tpu/analysis/lockguard.py); DL4J_TPU_LOCKGUARD=1 "
        "applies the same check to every test in the session")
    config.addinivalue_line(
        "markers",
        "shardguard: run the test with runtime sharding-drift detection — "
        "any wrapped step dispatch whose array shardings differ from the "
        "placed NamedShardings (implicit resharding) fails the test (see "
        "deeplearning4j_tpu/analysis/shardguard.py); DL4J_TPU_SHARDGUARD=1 "
        "applies the same check to every test in the session")
    config.addinivalue_line(
        "markers",
        "strict_dtypes: run the test under "
        "jax.numpy_dtype_promotion('strict') — any implicit dtype "
        "promotion (e.g. a python float silently widening bf16 to fp32) "
        "inside the test body fails it (parity tests must pin dtypes "
        "explicitly, not inherit them from promotion rules)")


@pytest.fixture(autouse=True)
def _transfer_guard_marker(request):
    """Enforce the ``no_implicit_transfers`` marker: the whole test body
    runs inside ``jax.transfer_guard("disallow")``, so hot-loop tests
    assert zero implicit transfers in addition to their own checks.  On
    the CPU backend this catches implicit host->device crossings (D2H is
    free there — full enforcement happens on real devices)."""
    if request.node.get_closest_marker("no_implicit_transfers") is None:
        yield
        return
    with jax.transfer_guard("disallow"):
        yield


@pytest.fixture(autouse=True)
def _lockguard_marker(request):
    """Enforce the ``lockguard`` marker (or ``DL4J_TPU_LOCKGUARD=1``
    session-wide): threading locks created during the test are
    instrumented, and any lock-order inversion or unguarded shared write
    the detector observes fails the test at teardown.  Tests that
    deliberately provoke violations drive their own ``LockGuard``
    instance instead of the marker."""
    from deeplearning4j_tpu.analysis import lockguard as lg

    if request.node.get_closest_marker("lockguard") is None \
            and not lg.enabled_from_env():
        yield
        return
    lg.LOCKGUARD.reset()
    lg.LOCKGUARD.install()
    try:
        yield
        violations = lg.LOCKGUARD.violations()
        assert not violations, lg.LOCKGUARD.report()
    finally:
        lg.LOCKGUARD.uninstall()
        lg.LOCKGUARD.reset()


@pytest.fixture(autouse=True)
def _shardguard_marker(request):
    """Enforce the ``shardguard`` marker (or ``DL4J_TPU_SHARDGUARD=1``
    session-wide): step dispatches through ``ShardGuard.wrap`` sites
    (trainer sync/ZeRO steps, serving decode) are diffed against their
    placed shardings, and any implicit resharding observed fails the
    test at teardown.  Tests that deliberately provoke violations drive
    their own ``ShardGuard`` instance instead of the marker."""
    from deeplearning4j_tpu.analysis import shardguard as sg

    if request.node.get_closest_marker("shardguard") is None \
            and not sg.enabled_from_env():
        yield
        return
    sg.SHARDGUARD.reset()
    sg.SHARDGUARD.enable()
    try:
        yield
        violations = sg.SHARDGUARD.violations()
        assert not violations, sg.SHARDGUARD.report()
    finally:
        sg.SHARDGUARD.disable()
        sg.SHARDGUARD.reset()


@pytest.fixture(autouse=True)
def _strict_dtypes_marker(request):
    """Enforce the ``strict_dtypes`` marker: the whole test body runs
    under ``jax.numpy_dtype_promotion("strict")``, so mixed-dtype ops
    raise instead of silently widening (the bf16-kernel parity tests
    must measure the kernel's arithmetic, not an accidental fp32
    upcast)."""
    if request.node.get_closest_marker("strict_dtypes") is None:
        yield
        return
    with jax.numpy_dtype_promotion("strict"):
        yield


@pytest.fixture
def rng_np():
    return np.random.default_rng(42)


@pytest.fixture(autouse=True)
def _reset_observability(tmp_path):
    """The metrics registry, tracer and flight recorder are process-global
    singletons — wipe them (and restore the enable flag) after every test
    so counters recorded by one test can't satisfy another's assertions.
    The flight recorder's dump directory is pointed at the test's tmp dir
    for the DURATION of the test, so supervisor/serving tests that trip a
    dump never litter the repo working tree.  The cost-model cache is
    deliberately NOT cleared: signature hits persist across tests exactly
    as they do across steps in one process."""
    from deeplearning4j_tpu import observability as obs

    old_dump_dir = obs.FLIGHTREC.dump_dir
    obs.FLIGHTREC.dump_dir = tmp_path / "flightrec"
    yield
    obs.enable()
    obs.METRICS.reset()
    obs.TRACER.clear()
    obs.TRACER.stop_stream()
    obs.FLIGHTREC.clear()
    obs.FLIGHTREC.dump_dir = old_dump_dir
