"""Fused flash attention as a Pallas TPU kernel.

Perf groundwork for the flagship transformer (BASELINE.md: >=35% MFU on
BERT-base): the XLA path materializes per-layer (B, H, T, T) score tensors
in HBM; this kernel keeps the running-softmax state in VMEM and streams
K/V blocks through the MXU, so attention becomes HBM-bandwidth-light and
O(T) in memory.  Single-(shard-)chip op: under sequence parallelism the
ring layer (``models/transformer.ring_attention``) still rotates K/V
between chips and can call any per-block attention underneath.

Design (standard flash attention v2 schedule):
- grid = (batch*heads, T/BQ); each program owns one query block and loops
  over key blocks with a ``fori_loop``, carrying (acc, m, l) in registers.
- causal masking compares block-level iota offsets, so fully-masked key
  blocks still stream but contribute zeros (simple, branch-free).
- the kernel also emits the row log-sum-exp, and a ``jax.custom_vjp``
  backward recomputes per-block probabilities from (q, k, v, lse) under a
  ``lax.scan`` over key blocks — O(T) memory in the backward too, no
  hand-written backward kernel to maintain.

The op runs in Pallas interpret mode automatically on CPU (tests), and as
a compiled Mosaic kernel on TPU.  It is OPT-IN via
``TransformerConfig(attention="flash")`` until a real-chip benchmark
validates it end-to-end.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # TPU memory spaces are unavailable on CPU-only jaxlibs
    from jax.experimental.pallas import tpu as pltpu
    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                block_k: int, seq_len: int, scale: float):
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    bq = q.shape[0]
    qi = pl.program_id(1)
    q_pos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    n_k = seq_len // block_k

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (BQ, BK)
        if causal:
            k_pos = (j * block_k
                     + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = lax.fori_loop(0, n_k, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe))[:, None]


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    """q/k/v: (BH, T, D) -> (out (BH, T, D), lse (BH, T))."""
    bh, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
    scale = d ** -0.5

    kernel = functools.partial(_fwd_kernel, causal=causal, block_k=block_k,
                               seq_len=t, scale=scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0), **mem),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0), **mem),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0), **mem),
            # lse carries a trailing singleton: Mosaic requires the last two
            # block dims divisible by (8, 128) or equal to the array dims, so
            # a (1, block_q) block is unlowerable while (1, block_q, 1) is.
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _blockwise_bwd(q, k, v, out, lse, do, causal, block_k):
    """O(T)-memory backward: rebuild P per key block from (q, lse) under a
    scan, accumulate dq and emit per-block dk/dv (flash attention v2
    backward math, plain JAX so autodiff/XLA handle fusion)."""
    bh, t, d = q.shape
    block_k = min(block_k, t)
    n_k = t // block_k
    scale = d ** -0.5
    qf = q.astype(jnp.float32) * scale
    dof = do.astype(jnp.float32)
    # D_i = rowsum(dO * O) (the lse-gradient shortcut)
    delta = (dof * out.astype(jnp.float32)).sum(-1)            # (BH, T)
    q_pos = jnp.arange(t)[:, None]

    kb = k.reshape(bh, n_k, block_k, d).swapaxes(0, 1).astype(jnp.float32)
    vb = v.reshape(bh, n_k, block_k, d).swapaxes(0, 1).astype(jnp.float32)

    def body(dq_acc, blk):
        j, k_j, v_j = blk                                       # (BH, BK, D)
        s = jnp.einsum("btd,bkd->btk", qf, k_j)                 # (BH, T, BK)
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])                         # (BH, T, BK)
        dv_j = jnp.einsum("btk,btd->bkd", p, dof)
        dp = jnp.einsum("btd,bkd->btk", dof, v_j)
        ds = p * (dp - delta[..., None])
        dq_acc = dq_acc + jnp.einsum("btk,bkd->btd", ds, k_j) * scale
        dk_j = jnp.einsum("btk,btd->bkd", ds, qf)
        return dq_acc, (dk_j, dv_j)

    dq0 = jnp.zeros((bh, t, d), jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        body, dq0, (jnp.arange(n_k), kb, vb))
    dk = dk_blocks.swapaxes(0, 1).reshape(bh, t, d)
    dv = dv_blocks.swapaxes(0, 1).reshape(bh, t, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_bhtd(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_bhtd_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bhtd_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _blockwise_bwd(q, k, v, out, lse, do, causal, block_k)


_flash_bhtd.defvjp(_flash_bhtd_fwd, _flash_bhtd_bwd)


def flash_attention(q, k, v, *, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Fused attention for (B, T, H, D) tensors (the transformer's layout).

    ``interpret=None`` auto-selects Pallas interpret mode off-TPU so the
    same call works in CPU tests and compiles to Mosaic on the chip.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t, h, d = q.shape

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = _flash_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v),
                      causal, block_q, block_k, interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


# --------------------------------------------------------------- registration

def _naive_reference(q, k, v, *, causal: bool = True):
    # call-time import: ops.pallas.attention imports _blockwise_bwd from
    # this module, so a top-level import here would be circular
    from .pallas.attention import reference_attention
    return reference_attention(q, k, v, causal=causal)


# flash predates the ops/pallas tier but competes through the SAME
# candidate registry (one registration API — DESIGN.md §14); the public
# flash_attention signature above is unchanged.
from .pallas import registry as _kernel_registry  # noqa: E402

_kernel_registry.register(_kernel_registry.KernelCandidate(
    kind="attention", name="flash", fn=flash_attention,
    reference=_naive_reference,
    blocks=({"block_q": 128, "block_k": 128},
            {"block_q": 256, "block_k": 128}),
    # the on-chip battery's flash_check gate, unchanged: fwd/bwd max abs
    # error vs naive attention must stay under 0.05
    tolerances={"max_err": 0.05},
))
