"""Solvers: the optimization loops.

Capability match of ``optimize/Solver.java`` + ``optimize/solvers/*``:
``BaseOptimizer.optimize()`` shared loop (``BaseOptimizer.java:126-211``),
``GradientAscent``/``IterationGradientDescent`` first-order loops,
``ConjugateGradient`` (Polak-Ribière, ``ConjugateGradient.java:45``),
``LBFGS.java:21`` two-loop recursion, ``BackTrackLineSearch.java:52,112``
(Armijo backtracking), and ``StochasticHessianFree.java:27`` (CG on
curvature-vector products).

Design deviation (documented): the reference *maximizes* probability-style
scores; every solver here *minimizes* a loss.  An ``Objective`` is a pure
``value_and_grad(params, key) -> (loss, grads_pytree)``; solvers are host
loops around jitted evaluations — per-step hot paths in real training use the
jitted train step in ``nn.multilayer`` instead.  Curvature products use
``jax.jvp`` over ``jax.grad`` (R-operator; replaces the hand-written
``MultiLayerNetwork.computeDeltasR/feedForwardR:1415-1487``).

Mini-batch mode: pass ``batches=[(x, y), ...]`` and an objective of
signature ``(params, key, x, y) -> (loss, grads)``.  Each outer iteration
cycles to the next batch — line-search probes and curvature products
within one iteration all use THAT iteration's batch (the stochastic-HF
contract, Martens §4; the reference's ``StochasticHessianFree`` name says
the same), so no merged whole-dataset array ever exists and memory is
bounded by one batch.  The batch arrays are jit arguments: uniform batch
shapes compile once.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..nn.conf import NeuralNetConfiguration, OptimizationAlgorithm
from ..utils import tree_math as tm
from . import transforms as tfm
from .api import EpsTermination, IterationListener, TerminationCondition

log = logging.getLogger(__name__)

# Objective: (params, key) -> (loss, grads)
Objective = Callable[[Any, Any], tuple[jnp.ndarray, Any]]


@dataclass
class OptimizeResult:
    params: Any
    score: float
    iterations: int
    converged: bool
    history: list[float] = field(default_factory=list)


# --------------------------------------------------------------------------- line search

class BackTrackLineSearch:
    """Armijo backtracking (``BackTrackLineSearch.java:112``): shrink step
    until f(p + step*d) <= f(p) + c1*step*g·d, with relTol shrinkage and
    max-step clamping as in the reference."""

    def __init__(self, value_fn: Callable[[Any], jnp.ndarray], max_iterations: int = 5,
                 c1: float = 1e-4, rel_tol: float = 0.5, step_max: float = 100.0):
        self.value_fn = value_fn
        self.max_iterations = max_iterations
        self.c1 = c1
        self.rel_tol = rel_tol
        self.step_max = step_max

    def optimize(self, params, direction, grads, initial_step: float = 1.0) -> float:
        """Returns a step size along `direction` (descent direction)."""
        f0 = float(self.value_fn(params))
        slope = float(tm.dot(grads, direction))
        if slope >= 0:
            # not a descent direction; fall back to tiny step
            return 0.0
        dnorm = float(tm.norm2(direction))
        step = min(initial_step, self.step_max / max(dnorm, 1e-12))
        for _ in range(self.max_iterations):
            trial = tm.axpy(step, direction, params)
            f1 = float(self.value_fn(trial))
            if f1 <= f0 + self.c1 * step * slope:
                return step
            step *= self.rel_tol
        return step


# --------------------------------------------------------------------------- base loop

class BaseOptimizer:
    """Shared iteration loop (``BaseOptimizer.java:126-211``): evaluate
    loss+grad, post-process gradient through the conf's transform chain,
    line-search along the step, apply, check terminations, fire listeners."""

    name = "base"
    use_line_search = False

    def __init__(self, conf: NeuralNetConfiguration, objective: Objective,
                 listeners: Sequence[IterationListener] = (),
                 terminations: Sequence[TerminationCondition] = (),
                 transform: tfm.GradientTransform | None = None,
                 training_evaluator=None, batches=None):
        self.conf = conf
        self.objective = objective
        self.listeners = list(listeners)
        self.terminations = list(terminations) or [EpsTermination()]
        self.transform = transform if transform is not None else tfm.from_conf(conf)
        self.training_evaluator = training_evaluator
        self.batches = list(batches) if batches is not None else None
        self._score = float("inf")
        self._jit_obj = jax.jit(objective)
        # Value-only objective for line-search probes (no wasted backward
        # pass).  When the conf regularizes, the probed VALUE must include
        # the same L2 term the transform chain folds into the direction, or
        # Armijo measures a different objective than the one descended.
        if conf.use_regularization and conf.l2 > 0:
            l2 = conf.l2
            self._jit_val = jax.jit(
                lambda p, k, *b: objective(p, k, *b)[0] + tfm.l2_penalty(l2, p))
        else:
            self._jit_val = jax.jit(lambda p, k, *b: objective(p, k, *b)[0])

    def _batch(self, it: int) -> tuple:
        """The extra jit arguments for iteration ``it``: the next mini-batch
        in the cycle, or () in whole-objective mode."""
        if not self.batches:
            return ()
        return tuple(self.batches[it % len(self.batches)])

    def score(self) -> float:
        return self._score

    # direction selection hook — first-order: post-processed negative gradient
    def setup(self, params):
        return {"tstate": self.transform.init(params)}

    def direction(self, params, grads, state: dict):
        updates, state["tstate"] = self.transform.update(
            grads, state["tstate"], params, state.get("iteration", 0))
        return tm.neg(updates), state  # descent direction

    def optimize(self, params, key=None) -> OptimizeResult:
        key = key if key is not None else jax.random.key(self.conf.seed)
        state = self.setup(params)
        old_score = float("inf")
        history: list[float] = []
        converged = False
        it = 0
        for it in range(self.conf.num_iterations):
            state["iteration"] = it
            key, sub = jax.random.split(key)
            b = self._batch(it)
            loss, grads = self._jit_obj(params, sub, *b)
            self._score = float(loss)
            history.append(self._score)
            direction, state = self.direction(params, grads, state)
            if self.use_line_search:
                ls = BackTrackLineSearch(
                    lambda p, s=sub, b=b: self._jit_val(p, s, *b))
                # slope must be d(probed objective)·direction: include the L2
                # term the probe value carries
                probe_grads = grads
                if self.conf.use_regularization and self.conf.l2 > 0:
                    probe_grads = tfm.l2_grad(self.conf.l2, grads, params)
                step = ls.optimize(params, direction, probe_grads, initial_step=1.0)
                params = tm.axpy(step, direction, params)
            else:
                params = tm.add(params, direction)
            for l in self.listeners:
                l.iteration_done(self, it)
            if self.training_evaluator is not None and self.training_evaluator.should_stop(it):
                converged = True
                break
            if it > 0 and any(t.terminate(self._score, old_score, (grads,))
                              for t in self.terminations):
                converged = True
                break
            old_score = self._score
        return OptimizeResult(params, self._score, it + 1, converged, history)


class IterationGradientDescent(BaseOptimizer):
    """``IterationGradientDescent.java:18`` — plain per-iteration GD with the
    transform chain, no line search."""

    name = "iteration_gradient_descent"


class GradientAscent(BaseOptimizer):
    """``GradientAscent.java:20`` — line-searched steepest descent (reference
    ascends score; here descends loss, same trajectory on negated objective)."""

    name = "gradient_descent"
    use_line_search = True


class ConjugateGradient(BaseOptimizer):
    """Polak-Ribière nonlinear CG (``ConjugateGradient.java:45``) with Armijo
    line search and automatic restart on non-descent directions."""

    name = "conjugate_gradient"
    use_line_search = True

    def setup(self, params):
        s = super().setup(params)
        s["prev_grad"] = None
        s["prev_dir"] = None
        return s

    def direction(self, params, grads, state):
        g = grads
        if state["prev_grad"] is None:
            d = tm.neg(g)
        else:
            gg_prev = tm.dot(state["prev_grad"], state["prev_grad"])
            beta = tm.dot(g, tm.sub(g, state["prev_grad"])) / (gg_prev + 1e-30)
            beta = jnp.maximum(beta, 0.0)  # PR+ restart
            d = tm.axpy(beta, state["prev_dir"], tm.neg(g))
            if float(tm.dot(g, d)) >= 0:  # not descent → restart
                d = tm.neg(g)
        state["prev_grad"], state["prev_dir"] = g, d
        return d, state


class LBFGS(BaseOptimizer):
    """Limited-memory BFGS two-loop recursion (``LBFGS.java:21``), memory m=10."""

    name = "lbfgs"
    use_line_search = True
    m = 10

    def setup(self, params):
        s = super().setup(params)
        s["s_hist"], s["y_hist"] = [], []
        s["prev_params"], s["prev_grad"] = None, None
        return s

    def direction(self, params, grads, state):
        if state["prev_params"] is not None:
            sk = tm.sub(params, state["prev_params"])
            yk = tm.sub(grads, state["prev_grad"])
            if float(tm.dot(sk, yk)) > 1e-10:
                state["s_hist"].append(sk)
                state["y_hist"].append(yk)
                if len(state["s_hist"]) > self.m:
                    state["s_hist"].pop(0)
                    state["y_hist"].pop(0)
        state["prev_params"], state["prev_grad"] = params, grads

        q = grads
        alphas = []
        for sk, yk in zip(reversed(state["s_hist"]), reversed(state["y_hist"])):
            rho = 1.0 / float(tm.dot(yk, sk))
            alpha = rho * float(tm.dot(sk, q))
            q = tm.axpy(-alpha, yk, q)
            alphas.append((alpha, rho, sk, yk))
        if state["s_hist"]:
            sk, yk = state["s_hist"][-1], state["y_hist"][-1]
            gamma = float(tm.dot(sk, yk)) / (float(tm.dot(yk, yk)) + 1e-30)
            q = tm.scale(gamma, q)
        for alpha, rho, sk, yk in reversed(alphas):
            beta = rho * float(tm.dot(yk, q))
            q = tm.axpy(alpha - beta, sk, q)
        return tm.neg(q), state


class StochasticHessianFree(BaseOptimizer):
    """Hessian-free (truncated-Newton) optimization.

    Capability match of ``StochasticHessianFree.java:27`` +
    ``MultiLayerNetwork``'s R-operator machinery (``:1415-1487``): solve
    (G + λI) d = -g by truncated CG, where G is the **Gauss-Newton** matrix
    when the objective is supplied split as ``gauss_newton=(predict,
    loss_out)`` — ``predict(params, key) -> z`` (the network up to the final
    pre-activation) and ``loss_out(z) -> scalar`` (convex in z).  GN is PSD
    on non-convex nets, which is exactly why the reference CGs on GN
    products rather than the (indefinite) full Hessian; without the split we
    fall back to true Hessian-vector products (jvp-over-grad), safe only
    for convex-ish objectives.

    The CG loop runs under ``lax.while_loop`` in ONE jitted program — no
    per-iteration device->host sync (the r3 version ``float()``'d every CG
    step).  Levenberg-Marquardt damping adaptation via the reduction ratio
    (``dampingUpdate/reductionRatio``), initial λ from
    ``MultiLayerConfiguration.damping_factor`` default 100.
    """

    name = "hessian_free"
    cg_iterations = 20

    def __init__(self, *args, damping: float = 100.0, gauss_newton=None, **kw):
        super().__init__(*args, **kw)
        self.damping = damping
        self.gauss_newton = gauss_newton
        self._jit_cg = None
        self._jit_model = None

    def _cvp(self, params, vec, key, b=()):
        """Curvature-vector product: Gauss-Newton J^T H_L J v when the
        split is available, else full Hessian-vector product.  ``b`` is the
        current mini-batch (empty in whole-objective mode) — grad and
        curvature share it within an iteration."""
        if self.gauss_newton is not None:
            predict, loss_out = self.gauss_newton
            z, jv = jax.jvp(lambda p: predict(p, key, *b), (params,), (vec,))
            _, hjv = jax.jvp(jax.grad(lambda zz: loss_out(zz, *b)), (z,), (jv,))
            _, vjp_fn = jax.vjp(lambda p: predict(p, key, *b), params)
            (gv,) = vjp_fn(hjv)
            return gv
        grad_fn = lambda p: self.objective(p, key, *b)[1]
        _, hv = jax.jvp(grad_fn, (params,), (vec,))
        return hv

    def _cg_solve(self, params, grads, key, damping, batch=()):
        """Truncated CG on (G + λI) x = -g, compiled once: the whole loop is
        a ``lax.while_loop`` with a pytree carry, so the only host sync is
        the caller's use of the result."""
        if self._jit_cg is None:
            n_iters = self.cg_iterations

            def cg(params, grads, key, lam, *bt):
                b = tm.neg(grads)

                def cond(carry):
                    i, x, r, p, rs_old, live = carry
                    return (i < n_iters) & live & (rs_old > 1e-10)

                def body(carry):
                    i, x, r, p, rs_old, live = carry
                    hp = tm.axpy(lam, p, self._cvp(params, p, key, bt))
                    denom = tm.dot(p, hp)
                    live = denom > 1e-20
                    alpha = jnp.where(live,
                                      rs_old / jnp.maximum(denom, 1e-20), 0.0)
                    x = tm.axpy(alpha, p, x)
                    r = tm.axpy(-alpha, hp, r)
                    rs_new = jnp.where(live, tm.dot(r, r), 0.0)
                    beta = rs_new / jnp.maximum(rs_old, 1e-30)
                    p = tm.axpy(beta, p, r)
                    return (i + 1, x, r, p, rs_new, live)

                rs0 = tm.dot(b, b)
                init = (jnp.zeros((), jnp.int32), tm.zeros_like(b), b, b,
                        rs0, jnp.asarray(True))
                _, x, _, _, _, _ = lax.while_loop(cond, body, init)
                return x

            self._jit_cg = jax.jit(cg)
        return self._jit_cg(params, grads, key,
                            jnp.asarray(damping, jnp.float32), *batch)

    def _model_quantities(self, params, d, grads, key, damping, batch=()):
        """One jitted eval of (new_loss, damped quadratic-model reduction)."""
        if self._jit_model is None:
            def model(params, d, grads, key, lam, *bt):
                new_loss = self.objective(tm.add(params, d), key, *bt)[0]
                gd = tm.dot(grads, d)
                dGd = tm.dot(d, tm.axpy(lam, d, self._cvp(params, d, key, bt)))
                return new_loss, gd + 0.5 * dGd
            self._jit_model = jax.jit(model)
        return self._jit_model(params, d, grads, key,
                               jnp.asarray(damping, jnp.float32), *batch)

    def optimize(self, params, key=None) -> OptimizeResult:
        key = key if key is not None else jax.random.key(self.conf.seed)
        history: list[float] = []
        converged = False
        old_score = float("inf")
        it = 0
        for it in range(self.conf.num_iterations):
            key, sub = jax.random.split(key)
            b = self._batch(it)
            loss, grads = self._jit_obj(params, sub, *b)
            self._score = float(loss)
            history.append(self._score)
            d = self._cg_solve(params, grads, sub, self.damping, b)
            # quadratic-model reduction ratio → damping update (Martens §4.4;
            # reference dampingUpdate/reductionRatio)
            new_loss_dev, quad_dev = self._model_quantities(
                params, d, grads, sub, self.damping, b)
            new_loss, quad = float(new_loss_dev), float(quad_dev)
            rho = (new_loss - self._score) / quad if quad != 0 else 0.0
            if rho > 0.75:
                self.damping *= 2.0 / 3.0
            elif rho < 0.25:
                self.damping *= 1.5
            if new_loss < self._score:
                params = tm.add(params, d)
            for l in self.listeners:
                l.iteration_done(self, it)
            if it > 0 and any(t.terminate(self._score, old_score, (grads,))
                              for t in self.terminations):
                converged = True
                break
            old_score = self._score
        return OptimizeResult(params, self._score, it + 1, converged, history)


# --------------------------------------------------------------------------- Solver facade

_ALGOS = {
    OptimizationAlgorithm.GRADIENT_DESCENT: GradientAscent,
    OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT: IterationGradientDescent,
    OptimizationAlgorithm.CONJUGATE_GRADIENT: ConjugateGradient,
    OptimizationAlgorithm.LBFGS: LBFGS,
    OptimizationAlgorithm.HESSIAN_FREE: StochasticHessianFree,
}


class Solver:
    """``optimize/Solver.java:14-45`` — dispatch conf.optimization_algo to an
    optimizer instance; builder-flavored for familiarity."""

    def __init__(self, conf: NeuralNetConfiguration, objective: Objective, **kw):
        self.conf = conf
        self.objective = objective
        self.kw = kw

    def build(self) -> BaseOptimizer:
        cls = _ALGOS[self.conf.optimization_algo]
        return cls(self.conf, self.objective, **self.kw)

    def optimize(self, params, key=None) -> OptimizeResult:
        return self.build().optimize(params, key)
