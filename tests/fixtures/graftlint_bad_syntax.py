# Deliberately unparsable: exercises the hostile-input path — the
# analyzer must count this file as skipped (graftlint.skipped_files)
# and keep linting the rest of the tree, never crash.
def broken(:
    return oops(
