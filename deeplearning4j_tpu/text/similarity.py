"""Shared cosine-similarity / nearest-neighbor helpers for embedding models
(one implementation for Word2Vec / Glove / ParagraphVectors query APIs)."""

from __future__ import annotations

import numpy as np

_EPS = 1e-12


def cosine(v1, v2) -> float:
    if v1 is None or v2 is None:
        return 0.0
    v1 = np.asarray(v1, np.float64)
    v2 = np.asarray(v2, np.float64)
    denom = np.linalg.norm(v1) * np.linalg.norm(v2)
    return float(v1 @ v2 / denom) if denom > _EPS else 0.0


def nearest(matrix, vec, names, n: int = 10, exclude=()) -> list:
    """Top-n names by cosine similarity of their matrix rows to vec."""
    matrix = np.asarray(matrix)
    vec = np.asarray(vec)
    sims = matrix @ vec / np.maximum(
        np.linalg.norm(matrix, axis=1) * np.linalg.norm(vec), _EPS)
    exclude = set(exclude)
    out = []
    for i in np.argsort(-sims):
        name = names(int(i)) if callable(names) else names[int(i)]
        if name not in exclude:
            out.append(name)
        if len(out) >= n:
            break
    return out
