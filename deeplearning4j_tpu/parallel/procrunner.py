"""Multi-PROCESS scaleout runtime: the master/worker loop of
``scaleout.DistributedRunner`` with workers as real OS processes.

This is the cluster-of-JVMs capability of the reference
(``DeepLearning4jDistributed.java:128-187`` — master + worker nodes joined
through Akka, shared state in Hazelcast, updates spilled to local files)
mapped to the single-host-many-process shape: worker subprocesses share a
:class:`~.procstate.FileStateTracker` directory, updates spill to disk
(``LocalFileUpdateSaver`` parity), and a SIGKILL'd worker *process* is
detected by heartbeat staleness, evicted, and its in-flight job re-routed —
the real recovery chain, not a thread simulation.

The performer travels as a ``"module:callable"`` spec string resolved by
import in the worker process — the same reflection pattern the reference
uses for ``WorkerPerformerFactory`` (``MasterActor.java:166-180``).
"""

from __future__ import annotations

import importlib
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Any

from ..resilience.faults import FAULTS, WorkerKilled
from .procstate import FileStateTracker
from .scaleout import DistributedRunner, IterativeReduceWorkRouter


def resolve_performer_factory(spec: str):
    """``"pkg.module:attr"`` -> the factory callable."""
    mod, _, attr = spec.partition(":")
    return getattr(importlib.import_module(mod), attr)


def worker_loop(state_dir: str, worker_id: str, performer_spec: str,
                heartbeat_s: float = 0.05, poll_s: float = 0.02) -> None:
    """Worker-process main loop (``WorkerActor.java:150-160`` semantics:
    heartbeat every tick, pull the assigned job, perform, push the update)."""
    tracker = FileStateTracker(state_dir)
    performer = resolve_performer_factory(performer_spec)(tracker)
    # boot marker: interpreter startup can take seconds (site hooks import
    # heavy deps), so the master must not start the eviction clock until
    # the worker is actually alive — it waits for this file
    (tracker.dir / "boot" / worker_id).touch()
    while not tracker.is_done():
        tracker.heartbeat(worker_id)
        if not tracker.is_enabled(worker_id):
            time.sleep(heartbeat_s)
            continue
        if tracker.needs_replicate(worker_id):
            current = tracker.get_current()
            if current is not None:
                performer.update(current)
            tracker.done_replicating(worker_id)
        job = tracker.job_for(worker_id)
        if job is None:
            time.sleep(poll_s)
            continue
        # chaos seams (armed via DL4J_TPU_FAULTS, inherited through the
        # spawn env): silent process death / straggler / transient failure
        FAULTS.maybe_fire("scaleout.worker")
        slow = FAULTS.check("scaleout.worker.slow")
        if slow is not None:
            time.sleep(slow.delay_s)
        try:
            FAULTS.maybe_fire("scaleout.perform")
            performer.perform(job)
        except WorkerKilled:
            raise                # injected silent death: no failure report
        except Exception as e:
            # prompt failure report — the master re-routes the job without
            # waiting out the heartbeat timeout; this process then exits
            tracker.record_failure(worker_id, job, repr(e))
            raise
        if job.result is not None:
            tracker.add_update(worker_id, job.result)
        tracker.clear_job(worker_id)


class ProcessDistributedRunner(DistributedRunner):
    """``DistributedRunner`` with OS-process workers over a shared
    :class:`FileStateTracker` directory.

    ``performer_spec`` replaces the in-process factory: a
    ``"module:callable"`` string importable in the worker interpreter.
    ``worker_env`` lets tests pin e.g. ``JAX_PLATFORMS=cpu``.
    """

    def __init__(self, job_iterator, performer_spec: str, state_dir: Path | str,
                 n_workers: int = 2, router_cls=IterativeReduceWorkRouter,
                 heartbeat_s: float = 0.05, poll_s: float = 0.02,
                 eviction_timeout_s: float = 2.0,
                 model_saver=None, worker_env: dict[str, str] | None = None,
                 max_job_attempts: int = 3, job_timeout_s: float = 0.0,
                 max_respawns: int = 0, on_timeout: str = "raise"):
        tracker = FileStateTracker(state_dir)
        super().__init__(job_iterator, performer_factory=None,
                         n_workers=n_workers, router_cls=router_cls,
                         tracker=tracker, model_saver=model_saver,
                         heartbeat_s=heartbeat_s, poll_s=poll_s,
                         eviction_timeout_s=eviction_timeout_s,
                         max_job_attempts=max_job_attempts,
                         job_timeout_s=job_timeout_s,
                         max_respawns=max_respawns, on_timeout=on_timeout)
        self.state_dir = str(state_dir)
        self.performer_spec = performer_spec
        self.worker_env = worker_env
        self._procs: list[subprocess.Popen] = []
        self._spawned_wids: list[str] = []

    def worker_processes(self) -> list[subprocess.Popen]:
        """Live Popen handles (tests use these to SIGKILL a worker)."""
        return list(self._procs)

    def _spawn_one(self, wid: str) -> None:
        env = dict(os.environ)
        if self.worker_env:
            env.update(self.worker_env)
        # make the package importable in the worker regardless of master cwd
        pkg_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        self.tracker.add_worker(wid)
        log = open(Path(self.state_dir) / f"{wid}.log", "wb")
        self._procs.append(subprocess.Popen(
            [sys.executable, "-m", "deeplearning4j_tpu.parallel.worker_main",
             self.state_dir, wid, self.performer_spec,
             str(self.heartbeat_s), str(self.poll_s)],
            env=env, stdout=log, stderr=subprocess.STDOUT))
        self._spawned_wids.append(wid)

    def _await_boot(self, wids: list[str], timeout_s: float) -> None:
        """Boot barrier: heartbeats (and thus eviction eligibility) only
        mean something once the worker process is actually up —
        interpreter startup can take seconds (site hooks import heavy
        deps), so the staleness clock restarts when boot completes."""
        deadline = time.time() + timeout_s
        boot = Path(self.state_dir) / "boot"
        while time.time() < deadline:
            if all((boot / w).exists() for w in wids):
                break
            time.sleep(0.05)
        for w in wids:
            self.tracker.heartbeat(w)   # restart staleness clock

    def _spawn_workers(self) -> None:
        super()._spawn_workers()        # parallel Popen via _spawn_one
        self._await_boot(list(self._spawned_wids), 120.0)

    def _maybe_respawn(self) -> None:
        before = set(self._spawned_wids)
        super()._maybe_respawn()
        new = [w for w in self._spawned_wids if w not in before]
        if new:
            # replacement processes boot serially with the master waiting —
            # bounded (respawn is rare and capped), and without the wait a
            # short eviction timeout would evict the replacement mid-boot
            self._await_boot(new, 30.0)

    def _shutdown_workers(self) -> None:
        self.tracker.finish()          # workers exit their loop on DONE
        deadline = time.time() + 10.0
        for p in self._procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()


def collect_result(state_dir: Path | str) -> Any:
    """The final aggregated model from a finished run's state directory."""
    return FileStateTracker(state_dir).get_current()
