"""Flash-attention Pallas kernel: forward/backward parity with the naive
attention math (interpret mode on CPU; the same code compiles to Mosaic on
TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops.flash_attention import flash_attention


def _naive(q, k, v, causal):
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _qkv(b=2, t=256, h=3, d=16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, t, h, d)
    return tuple(jax.random.normal(k, shape, jnp.float32) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_naive(causal):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal)
    want = _naive(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_naive(causal):
    q, k, v = _qkv(b=1, t=128, h=2, d=8, seed=1)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal)
        return jnp.sum(jnp.sin(o))            # non-trivial cotangent

    def loss_naive(q, k, v):
        return jnp.sum(jnp.sin(_naive(q, k, v, causal)))

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_naive, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_multiple_key_blocks_exercised():
    """t=512 with block 128 -> 4 key blocks per query block; parity must
    hold across block boundaries (running-softmax correctness)."""
    q, k, v = _qkv(b=1, t=512, h=1, d=8, seed=2)
    got = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    want = _naive(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_transformer_flash_config_matches_ring():
    """The flagship model with attention='flash' computes the same loss and
    gradients as the default path (single device, sp=1)."""
    import dataclasses

    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, lm_loss_local)

    cfg = TransformerConfig(vocab_size=128, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_len=128, causal=True,
                            dtype=jnp.float32, remat=False)
    from deeplearning4j_tpu.models.transformer import init_params
    params = init_params(jax.random.key(0), cfg)
    toks = jax.random.randint(jax.random.key(1), (2, 128), 0, 128)
    tgts = jnp.roll(toks, -1, axis=1)

    def loss_with(attn_impl):
        c = dataclasses.replace(cfg, attention=attn_impl)
        return jax.value_and_grad(
            lambda p: lm_loss_local(p, toks, tgts, c))(params)

    l_ring, g_ring = loss_with("ring")
    l_flash, g_flash = loss_with("flash")
    assert abs(float(l_ring) - float(l_flash)) < 1e-5
    for a, b in zip(jax.tree_util.tree_leaves(g_ring),
                    jax.tree_util.tree_leaves(g_flash)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-4)
