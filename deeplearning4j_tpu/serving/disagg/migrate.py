"""KV-page migration between serving tiers (DESIGN.md §27).

A migrated request is pages plus a block-table row — the paging layer
(§17) already made both portable.  What this module adds is the
PROTOCOL: an explicit per-request transfer schedule (which pages move,
which transfer as hash-only claims), atomic refcount handoff on the
decode side, and an exceptional-path discipline that releases every
acquired reference (graftlint PG01/DG01).

Content addressing does the heavy lifting.  The decode pool's prefix
cache keys chains of full token pages by chained blake2b — the SAME
keys the prefill side's pages carry — so a page that already exists on
the decode side transfers as ``(hash, claim)``: one incref, zero bytes
moved.  Only pages beyond the decode-resident prefix ship bytes, and
pages that exist purely as decode budget (no prefill content) ship
nothing at all — they are allocated empty on arrival.

int8/GQA transparency: :meth:`InferenceEngine.read_pages` exports
whatever the pool stores (``k``/``v`` plus ``k_scale``/``v_scale``
under kv_quant), and the import scatters those bytes verbatim — no
requantization, so a moved page is byte-identical to the page the
prefill wrote, and quantized prefix aliasing stays sound on the far
side (identical content ⇒ identical bytes, §20).

Failure contract: an unwind ANYWHERE releases the decode-side claims
via :meth:`PagePool.decref_quarantine` and hands the dead pages to the
decode engine's serve thread for wiping (wipe-before-reallocatable —
the migrator thread must never touch device state it does not own).
The prefill-side record stays with the caller until the moment its
bytes have been read, then is released; re-running the migration after
any abort is always safe.
"""

from __future__ import annotations

import base64
import dataclasses
import time

import numpy as np

from ...observability import METRICS
from ...resilience.faults import FAULTS
from ..batcher import GenerateRequest, PendingResult
from ..engine import MigrationRejected, MigrationTicket, PrefillRecord

__all__ = ["KVMigrator", "PageTransfer", "TransferPlan", "export_payload"]


@dataclasses.dataclass
class PageTransfer:
    """One block-table position in a migration schedule."""

    index: int            # position in the block-table row
    key: str | None       # chained content hash (None: no full-page key)
    action: str           # "claim" | "move" | "alloc"


@dataclasses.dataclass
class TransferPlan:
    """Per-request transfer schedule: for every page the request needs
    on the decode side, whether it transfers as a hash-only claim
    (resident — incref, zero bytes), a byte move (prefill content the
    decode side lacks), or a bare allocation (decode budget, no content
    to move).  Planned per request, in the spirit of an explicit
    collective redistribution schedule rather than ad-hoc copies."""

    entries: list[PageTransfer]

    def count(self, action: str) -> int:
        return sum(1 for e in self.entries if e.action == action)

    @property
    def pages_moved(self) -> int:
        return self.count("move")

    @property
    def pages_deduped(self) -> int:
        return self.count("claim")


def _page_counts(prompt_len: int, max_new: int, page_size: int):
    """(content pages, total pages) for a request: prefill writes K/V
    for positions ``[0, p-1)`` (the last token is the first decode
    query), so only ``ceil((p-1)/ps)`` pages carry bytes; the rest of
    the ``ceil((p+max_new)/ps)`` block-table row is decode budget."""
    n_content = -(-(prompt_len - 1) // page_size)
    n_total = -(-(prompt_len + max_new) // page_size)
    return n_content, n_total


class KVMigrator:
    """Moves prefilled requests into a decode engine's page pool.

    All page accounting for the disagg tier funnels through here: the
    export seam (:meth:`export_payload` — read bytes, release the
    prefill record) and the import seam (:meth:`migrate` /
    :meth:`import_payload` — claim, alloc, upload, hand off to
    :meth:`InferenceEngine.admit_from_pages`).  graftlint DG01 fails
    pool calls or block-table writes anywhere else in
    ``serving/disagg/``.
    """

    def __init__(self, decode_engine):
        if decode_engine.page_pool is None:
            raise ValueError("decode engine must be paged — the "
                             "migration unit is a KV page")
        if decode_engine.cfg.role == "prefill":
            raise ValueError("cannot migrate INTO a prefill-role engine")
        self.decode = decode_engine

    # ------------------------------------------------------------ planning
    def plan_transfer(self, prompt: list[int], max_new_tokens: int,
                      cached_len: int | None = None) -> TransferPlan:
        """Advisory schedule for migrating ``prompt``: which block-table
        positions claim, move, or allocate.  ``cached_len`` overrides
        the decode pool's :meth:`~..paging.PagePool.peek_prefix` answer
        (the HTTP probe passes its own).  Advisory because the import
        claim re-walks the chain atomically and may find more or fewer
        resident pages — the executed plan is returned by
        :meth:`migrate`."""
        pool = self.decode.page_pool
        ps = pool.page_size
        usable = len(prompt) - 1
        if cached_len is None:
            cached_len = pool.peek_prefix(prompt, usable)
        keys = pool.chain_keys(prompt, usable)
        n_claim = cached_len // ps
        n_content, n_total = _page_counts(len(prompt), max_new_tokens, ps)
        entries = []
        for i in range(n_total):
            key = keys[i] if i < len(keys) else None
            action = ("claim" if i < n_claim
                      else "move" if i < n_content else "alloc")
            entries.append(PageTransfer(index=i, key=key, action=action))
        return TransferPlan(entries)

    # ------------------------------------------------------------ live path
    def migrate(self, src, record: PrefillRecord,
                pending: PendingResult) -> tuple[MigrationTicket,
                                                 TransferPlan]:
        """Move ``record``'s KV from prefill engine ``src`` into the
        decode engine and queue ``pending`` for admission between
        decode segments.  Returns the admission ticket plus the plan as
        EXECUTED (claims reflect what the atomic lookup actually found).

        Ownership: the decode-side pages hand off atomically to
        :meth:`~..engine.InferenceEngine.admit_from_pages`; ``record``
        is consumed (its pages released on the prefill side) once its
        bytes are safely read.  On ANY unwind before that, the record
        is untouched — the caller releases or retries it — and every
        decode-side reference acquired here is quarantined back.
        """
        pool = self.decode.page_pool
        ps = pool.page_size
        prompt = record.prompt
        n_content, n_total = _page_counts(len(prompt),
                                          record.max_new_tokens, ps)
        with METRICS.time("disagg.migrate_seconds"):
            FAULTS.maybe_fire("disagg.migrate")
            claimed: list[int] = []
            fresh: list[int] = []
            try:
                # generation stamp BEFORE the claim: if a reload lands
                # between this read and admission, the drain fence
                # rejects the ticket and we re-plan — claimed pages
                # computed under superseded weights can never decode
                gen = int(self.decode.stats()["generation"])
                usable = len(prompt) - 1
                claimed, _cached = pool.lookup_prefix(prompt, usable)
                n_claim = len(claimed)
                fresh = pool.alloc(n_total - n_claim)
                # mid-migration kill point: references held on both
                # sides, nothing admitted — the unwind below must leave
                # refcounts exactly balanced (the chaos-leg assertion)
                FAULTS.maybe_fire("disagg.migrate")
                uploads = []
                if n_claim < n_content:
                    raw = src.read_pages(record.pages[n_claim:n_content])
                    for j in range(n_content - n_claim):
                        layers = [{name: arr[j]
                                   for name, arr in layer.items()}
                                  for layer in raw]
                        uploads.append((fresh[j], layers))
            except BaseException:
                dead = pool.decref_quarantine(claimed + fresh)
                self.decode.queue_wipe(dead)
                raise
            # bytes are read: the prefill side's copy is now redundant
            src.release_prefill(record)
            keys = pool.chain_keys(prompt, usable)
            plan = TransferPlan([
                PageTransfer(
                    index=i, key=keys[i] if i < len(keys) else None,
                    action=("claim" if i < n_claim
                            else "move" if i < n_content else "alloc"))
                for i in range(n_total)])
            METRICS.increment("disagg.migrations")
            METRICS.increment("disagg.pages_moved", len(uploads))
            METRICS.increment("disagg.pages_deduped", n_claim)
            try:
                ticket = self.decode.admit_from_pages(
                    pending, pages=claimed + fresh, uploads=uploads,
                    generation=gen)
            except BaseException:
                dead = pool.decref_quarantine(claimed + fresh)
                self.decode.queue_wipe(dead)
                raise
            return ticket, plan

    # ------------------------------------------------------------ wire form
    @staticmethod
    def export_payload(src, record: PrefillRecord,
                       cached_len: int = 0) -> dict:
        """Serialize ``record`` for a cross-process migration
        (``POST /v1/migrate``): the request, the per-page content
        hashes, and base64 page bytes for content pages beyond
        ``cached_len`` positions (a prior probe of the decode side —
        pass 0 to ship everything).  Consumes the record.

        Wire shape: ``pages[str(i)]`` is the block-row-position-``i``
        payload, a per-layer list of ``{name: {b64, dtype, shape}}`` —
        exactly what :meth:`InferenceEngine.read_pages` produced, int8
        scales riding beside their pages.
        """
        ps = src.cfg.page_size
        prompt = record.prompt
        n_content, n_total = _page_counts(len(prompt),
                                          record.max_new_tokens, ps)
        skip = min(cached_len // ps, n_content)
        keys = src.page_pool.chain_keys(prompt, len(prompt) - 1)
        pages: dict[str, list] = {}
        if skip < n_content:
            raw = src.read_pages(record.pages[skip:n_content])
            for j in range(n_content - skip):
                pages[str(skip + j)] = [
                    {name: _encode_arr(arr[j])
                     for name, arr in layer.items()}
                    for layer in raw]
        src.release_prefill(record)
        return {
            "request": {
                "prompt": list(prompt),
                "max_new_tokens": record.max_new_tokens,
                "temperature": record.temperature,
                "seed": record.seed,
                "eos_id": record.eos_id,
            },
            "page_size": ps,
            "hashes": keys,
            "pages": pages,
        }

    def import_payload(self, payload: dict) -> PendingResult:
        """Import a wire-form migration: plan against the local pool,
        claim what is resident, upload the provided bytes for the rest,
        and queue the request for admission.  Raises ``RuntimeError``
        (HTTP 409) when a needed content page has neither resident
        bytes nor wire bytes — the exporter probed a prefix that has
        since been evicted; it must re-export with full bytes.  Returns
        the pending handle (``result()`` blocks until decode
        completes)."""
        req_d = payload["request"]
        prompt = [int(t) for t in req_d["prompt"]]
        max_new = int(req_d["max_new_tokens"])
        ps = int(payload.get("page_size") or
                 self.decode.page_pool.page_size)
        if ps != self.decode.page_pool.page_size:
            raise ValueError(
                f"page_size mismatch: exporter {ps}, decode side "
                f"{self.decode.page_pool.page_size} — migration requires "
                "identical page geometry")
        pool = self.decode.page_pool
        n_content, n_total = _page_counts(len(prompt), max_new, ps)
        wire = payload.get("pages") or {}
        gen = int(self.decode.stats()["generation"])
        claimed: list[int] = []
        fresh: list[int] = []
        try:
            claimed, _cached = pool.lookup_prefix(prompt, len(prompt) - 1)
            n_claim = len(claimed)
            for i in range(n_claim, n_content):
                if str(i) not in wire:
                    raise RuntimeError(
                        f"migration payload missing bytes for content "
                        f"page {i} (claimed {n_claim} resident) — the "
                        "probed prefix was evicted; re-export with full "
                        "bytes")
            fresh = pool.alloc(n_total - n_claim)
            uploads = []
            for j, i in enumerate(range(n_claim, n_content)):
                layers = [{name: _decode_arr(enc)
                           for name, enc in layer.items()}
                          for layer in wire[str(i)]]
                uploads.append((fresh[j], layers))
        except BaseException:
            dead = pool.decref_quarantine(claimed + fresh)
            self.decode.queue_wipe(dead)
            raise
        req = GenerateRequest(
            prompt=prompt, max_new_tokens=max_new,
            temperature=float(req_d.get("temperature") or 0.0),
            seed=int(req_d.get("seed") or 0),
            eos_id=req_d.get("eos_id"))
        req.submitted_s = time.monotonic()
        pending = PendingResult(req)
        METRICS.increment("disagg.migrations")
        METRICS.increment("disagg.pages_moved", len(uploads))
        METRICS.increment("disagg.pages_deduped", n_claim)
        try:
            ticket = self.decode.admit_from_pages(
                pending, pages=claimed + fresh, uploads=uploads,
                generation=gen)
        except BaseException:
            dead = pool.decref_quarantine(claimed + fresh)
            self.decode.queue_wipe(dead)
            raise
        if not ticket.wait(timeout=60.0):
            # pages already released by the drain fence; the request
            # was never admitted — single-shot HTTP semantics say 409
            if not pending.done():
                pending._fail(MigrationRejected(ticket.reason or
                                                "migration rejected"))
            raise RuntimeError(
                f"migration rejected at admission: {ticket.reason} — "
                "safe to retry")
        return pending


def _encode_arr(arr) -> dict:
    a = np.ascontiguousarray(arr)
    return {"b64": base64.b64encode(a.tobytes()).decode("ascii"),
            "dtype": str(a.dtype), "shape": list(a.shape)}


def _decode_arr(enc: dict):
    raw = base64.b64decode(enc["b64"])
    return np.frombuffer(raw, dtype=np.dtype(enc["dtype"])).reshape(
        enc["shape"])


#: module-level alias — the server's export path reads better without
#: instantiating a migrator it has no decode engine for
export_payload = KVMigrator.export_payload
