"""L3 — data layer (reference: ``deeplearning4j-core/.../datasets`` + ``base``).

Host-side data pipeline: the ``DataSet`` container, the ``DataSetIterator``
protocol with fetcher-backed and wrapper implementations, and dataset
fetchers (Iris/MNIST/digits/CSV/LFW) with offline-first sourcing.
"""

from .dataset import DataSet
from .fetchers import (
    BaseDataFetcher,
    CSVDataFetcher,
    CurvesDataFetcher,
    DigitsDataFetcher,
    IrisDataFetcher,
    LFWDataFetcher,
    MnistDataFetcher,
)
from .svmlight import (
    SVMLightDataFetcher,
    SVMLightDataSetIterator,
    load_svmlight,
    parse_svmlight_line,
    save_svmlight,
)
from .iterator import (
    BaseDatasetIterator,
    CSVDataSetIterator,
    CurvesDataSetIterator,
    DataSetIterator,
    DigitsDataSetIterator,
    IrisDataSetIterator,
    ListDataSetIterator,
    MnistDataSetIterator,
    MovingWindowDataSetIterator,
    MultipleEpochsIterator,
    ReconstructionDataSetIterator,
    SamplingDataSetIterator,
    TestDataSetIterator,
)

__all__ = [
    "DataSet",
    "BaseDataFetcher", "CSVDataFetcher", "CurvesDataFetcher", "DigitsDataFetcher",
    "IrisDataFetcher", "LFWDataFetcher", "MnistDataFetcher",
    "BaseDatasetIterator", "CSVDataSetIterator", "CurvesDataSetIterator", "DataSetIterator",
    "DigitsDataSetIterator", "IrisDataSetIterator", "ListDataSetIterator",
    "MnistDataSetIterator", "MovingWindowDataSetIterator",
    "MultipleEpochsIterator", "ReconstructionDataSetIterator",
    "SamplingDataSetIterator", "TestDataSetIterator",
    "SVMLightDataFetcher", "SVMLightDataSetIterator", "load_svmlight",
    "parse_svmlight_line", "save_svmlight",
]
