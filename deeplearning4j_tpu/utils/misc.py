"""Host utility grab-bag.

Capability match of the reference's ``util/`` survivors that matter beyond
the JVM: ``MathUtils.java`` statistics/distances/entropy, ``SummaryStatistics``,
``DiskBasedQueue.java:22`` (file-backed FIFO for OOM-safe corpora),
``MovingWindowMatrix``, ``SerializationUtils``, ``ArchiveUtils``.
"""

from __future__ import annotations

import math
import pickle
import shutil
import tarfile
import tempfile
import uuid
import zipfile
from collections import deque
from pathlib import Path
from typing import Any, Iterable

import numpy as np


# --------------------------------------------------------------------------- math (MathUtils.java)

def entropy(probs) -> float:
    p = np.asarray(probs, np.float64)
    p = p[p > 0]
    return float(-(p * np.log(p)).sum())


def information_gain(parent_counts, split_counts) -> float:
    parent = np.asarray(parent_counts, np.float64)
    h_parent = entropy(parent / parent.sum())
    total = parent.sum()
    h_children = 0.0
    for counts in split_counts:
        c = np.asarray(counts, np.float64)
        if c.sum() > 0:
            h_children += (c.sum() / total) * entropy(c / c.sum())
    return h_parent - h_children


def euclidean_distance(a, b) -> float:
    return float(np.linalg.norm(np.asarray(a, np.float64) - np.asarray(b, np.float64)))


def manhattan_distance(a, b) -> float:
    return float(np.abs(np.asarray(a, np.float64) - np.asarray(b, np.float64)).sum())


def correlation(x, y) -> float:
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    if x.std() == 0 or y.std() == 0:
        return 0.0
    return float(np.corrcoef(x, y)[0, 1])


def sigmoid(x) -> float:
    return 1.0 / (1.0 + math.exp(-x))


def bernoulli_log_likelihood(labels, probs) -> float:
    y = np.asarray(labels, np.float64)
    p = np.clip(np.asarray(probs, np.float64), 1e-12, 1 - 1e-12)
    return float((y * np.log(p) + (1 - y) * np.log(1 - p)).sum())


def normalize_to_range(x, lo: float = 0.0, hi: float = 1.0) -> np.ndarray:
    x = np.asarray(x, np.float64)
    xmin, xmax = x.min(), x.max()
    if xmax == xmin:
        return np.full_like(x, lo)
    return lo + (x - xmin) / (xmax - xmin) * (hi - lo)


class SummaryStatistics:
    """Streaming mean/min/max/std (``SummaryStatistics``-style)."""

    def __init__(self):
        self.n = 0
        self._mean = 0.0
        self._m2 = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def add(self, value: float) -> None:
        self.n += 1
        delta = value - self._mean
        self._mean += delta / self.n
        self._m2 += delta * (value - self._mean)
        self.min = min(self.min, value)
        self.max = max(self.max, value)

    def add_all(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(v)

    @property
    def mean(self) -> float:
        return self._mean

    @property
    def variance(self) -> float:
        return self._m2 / (self.n - 1) if self.n > 1 else 0.0

    @property
    def std(self) -> float:
        return math.sqrt(self.variance)

    def __str__(self) -> str:
        return (f"n={self.n} mean={self.mean:.6g} std={self.std:.6g} "
                f"min={self.min:.6g} max={self.max:.6g}")


# --------------------------------------------------------------------------- disk queue

class DiskBasedQueue:
    """File-backed FIFO (``DiskBasedQueue.java:22``): keeps an in-memory
    window, spills the rest to per-item pickle files — OOM-safe corpus
    buffering."""

    def __init__(self, directory: str | Path | None = None,
                 memory_window: int = 1000):
        self.dir = Path(directory) if directory else Path(tempfile.mkdtemp(
            prefix="dl4jtpu_queue_"))
        self.dir.mkdir(parents=True, exist_ok=True)
        self.memory_window = memory_window
        self._mem: deque = deque()
        self._spilled: deque[Path] = deque()

    def add(self, item: Any) -> None:
        if len(self._mem) < self.memory_window and not self._spilled:
            self._mem.append(item)
            return
        path = self.dir / f"{uuid.uuid4().hex}.pkl"
        with open(path, "wb") as f:
            pickle.dump(item, f)
        self._spilled.append(path)

    def poll(self) -> Any:
        if self._mem:
            item = self._mem.popleft()
        elif self._spilled:
            path = self._spilled.popleft()
            with open(path, "rb") as f:
                item = pickle.load(f)
            path.unlink(missing_ok=True)
        else:
            raise IndexError("queue empty")
        # refill memory window from disk
        while self._spilled and len(self._mem) < self.memory_window - 1:
            p = self._spilled.popleft()
            with open(p, "rb") as f:
                self._mem.append(pickle.load(f))
            p.unlink(missing_ok=True)
        return item

    def __len__(self) -> int:
        return len(self._mem) + len(self._spilled)

    def is_empty(self) -> bool:
        return len(self) == 0

    def close(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)


# --------------------------------------------------------------------------- windows

def moving_window_matrix(matrix, window_rows: int, window_cols: int,
                         add_rotations: bool = False) -> np.ndarray:
    """Non-overlapping (rows, cols) windows of a 2-D matrix, flattened per
    window (``MovingWindowMatrix``); optional 90-degree rotations."""
    m = np.asarray(matrix)
    wins = []
    for r in range(0, m.shape[0] - window_rows + 1, window_rows):
        for c in range(0, m.shape[1] - window_cols + 1, window_cols):
            w = m[r:r + window_rows, c:c + window_cols]
            wins.append(w.reshape(-1))
            if add_rotations:
                for k in (1, 2, 3):
                    wins.append(np.rot90(w, k).reshape(-1))
    return np.stack(wins) if wins else np.zeros((0, window_rows * window_cols))


# --------------------------------------------------------------------------- serde / archives

def save_object(obj: Any, path: str | Path) -> None:
    """``SerializationUtils.saveObject`` (pickle, atomic)."""
    path = Path(path)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as f:
        pickle.dump(obj, f)
    tmp.replace(path)


def read_object(path: str | Path) -> Any:
    with open(path, "rb") as f:
        return pickle.load(f)


def unzip_file_to(archive: str | Path, dest: str | Path) -> None:
    """``ArchiveUtils`` — tar/tar.gz/zip extraction."""
    archive, dest = Path(archive), Path(dest)
    dest.mkdir(parents=True, exist_ok=True)
    name = archive.name
    if name.endswith(".zip"):
        with zipfile.ZipFile(archive) as z:
            z.extractall(dest)
    elif name.endswith((".tar.gz", ".tgz", ".tar")):
        mode = "r:gz" if name.endswith(("gz", "tgz")) else "r"
        with tarfile.open(archive, mode) as t:
            t.extractall(dest, filter="data")  # block tar-slip traversal
    else:
        raise ValueError(f"unknown archive format: {name}")
