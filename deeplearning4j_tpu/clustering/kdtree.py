"""KD-tree for nearest-neighbor queries.

Capability match of ``clustering/kdtree/KDTree.java`` (353 LoC): axis-cycling
median construction, nearest/knn/range queries.  Host-side numpy (tree
search is branchy host work; the TPU path for bulk neighbor queries is the
dense distance matrix in ``kmeans``/t-SNE).
"""

from __future__ import annotations

import numpy as np


class _Node:
    __slots__ = ("point", "index", "axis", "left", "right")

    def __init__(self, point, index, axis):
        self.point = point
        self.index = index
        self.axis = axis
        self.left = None
        self.right = None


class KDTree:
    def __init__(self, points):
        self.points = np.asarray(points, np.float64)
        self.n, self.d = self.points.shape
        idx = np.arange(self.n)
        self.root = self._build(idx, 0)

    def _build(self, idx, depth):
        if idx.size == 0:
            return None
        axis = depth % self.d
        order = idx[np.argsort(self.points[idx, axis], kind="stable")]
        mid = order.size // 2
        node = _Node(self.points[order[mid]], int(order[mid]), axis)
        node.left = self._build(order[:mid], depth + 1)
        node.right = self._build(order[mid + 1:], depth + 1)
        return node

    def nearest(self, query) -> tuple[int, float]:
        """(index, distance) of the closest stored point."""
        query = np.asarray(query, np.float64)
        best = [(-1, np.inf)]

        def visit(node):
            if node is None:
                return
            dist = float(np.linalg.norm(node.point - query))
            if dist < best[0][1]:
                best[0] = (node.index, dist)
            diff = query[node.axis] - node.point[node.axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if abs(diff) < best[0][1]:
                visit(far)

        visit(self.root)
        return best[0]

    def knn(self, query, k: int) -> list[tuple[int, float]]:
        query = np.asarray(query, np.float64)
        heap: list[tuple[float, int]] = []  # max-heap via negated dist

        import heapq

        def visit(node):
            if node is None:
                return
            dist = float(np.linalg.norm(node.point - query))
            if len(heap) < k:
                heapq.heappush(heap, (-dist, node.index))
            elif dist < -heap[0][0]:
                heapq.heapreplace(heap, (-dist, node.index))
            diff = query[node.axis] - node.point[node.axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far)

        visit(self.root)
        return sorted(((i, -nd) for nd, i in heap), key=lambda t: t[1])

    def range_search(self, lower, upper) -> list[int]:
        """Indices of points inside the axis-aligned box [lower, upper]."""
        lower = np.asarray(lower, np.float64)
        upper = np.asarray(upper, np.float64)
        out: list[int] = []

        def visit(node):
            if node is None:
                return
            if np.all(node.point >= lower) and np.all(node.point <= upper):
                out.append(node.index)
            if node.point[node.axis] >= lower[node.axis]:
                visit(node.left)
            if node.point[node.axis] <= upper[node.axis]:
                visit(node.right)

        visit(self.root)
        return sorted(out)
