"""Build the native host runtime: ``python -m deeplearning4j_tpu.native.build``.

g++ -O3 shared library; no external deps.  The library is optional — all
call sites fall back to pure Python when it is absent.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

HERE = Path(__file__).parent
SRC = HERE / "src" / "host_runtime.cpp"
LIB = HERE / "libdl4jtpu_host.so"


def build(verbose: bool = True) -> Path | None:
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-std=c++17",
           str(SRC), "-o", str(LIB)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (FileNotFoundError, subprocess.TimeoutExpired) as e:
        if verbose:
            print(f"native build unavailable: {e}", file=sys.stderr)
        return None
    if proc.returncode != 0:
        if verbose:
            print(f"native build failed:\n{proc.stderr}", file=sys.stderr)
        return None
    if verbose:
        print(f"built {LIB}")
    return LIB


if __name__ == "__main__":
    sys.exit(0 if build() else 1)
