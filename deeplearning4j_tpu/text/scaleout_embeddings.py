"""Distributed embedding training over the scaleout SPI — row shipping.

Host-level capability match of the reference's distributed Word2Vec/GloVe
(``scaleout/perform/models/word2vec/Word2VecWork.java``,
``Word2VecPerformer.java:72-137``, ``Word2VecJobIterator.java``, GloVe mirror
``GlovePerformer.java``/``GloveWork.java``):

- the master-side job iterator slices the corpus into sentence chunks and
  ships each worker a ``Word2VecWork`` carrying ONLY the table rows the
  chunk's words (and their Huffman paths / pre-drawn negatives) touch;
- the worker trains those rows with the same batched jitted kernels as the
  local model and returns per-row DELTAS;
- the aggregator sums deltas into the master tables, which the next wave of
  works is built from.

Learning-rate decay follows ``Word2VecPerformer.java:82``: linear in the
distributed words-processed counter (StateTracker ``increment``/``count``).

For the SPMD mesh equivalent of the same strategy (tables sharded over the
``ep`` axis, row shipping as psum), see ``text/sharded_embedding.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from ..parallel.scaleout import Job, StateTracker
from .word2vec import _hs_step, _ns_step, skipgram_pairs

WORDS_KEY = "w2v.words_processed"


@dataclasses.dataclass
class EmbeddingTables:
    """Master-side tables + Huffman/pair metadata (the
    ``InMemoryLookupTable`` role)."""

    syn0: np.ndarray                       # (n, d)
    syn1: np.ndarray                       # (n-1, d) HS inner nodes
    codes: np.ndarray                      # (n, L)
    points: np.ndarray                     # (n, L)
    lengths: np.ndarray                    # (n,)
    syn1neg: np.ndarray | None = None      # (n, d) when negative > 0
    unigram: np.ndarray | None = None      # 0.75-power unigram probs

    @classmethod
    def from_model(cls, w2v) -> "EmbeddingTables":
        """Build from a vocab-initialised (unfitted) Word2Vec."""
        if w2v.vocab is None:
            w2v.build_vocab()
        if w2v.syn0 is None:
            w2v.reset_weights()
        codes, points, lengths = w2v.huffman.code_arrays()
        neg = w2v.negative > 0
        unigram = None
        if neg:
            c = w2v.vocab.counts_array() ** 0.75
            unigram = (c / c.sum()).astype(np.float64)
        return cls(
            syn0=np.asarray(w2v.syn0).copy(),
            syn1=np.asarray(w2v.syn1).copy(),
            codes=codes.astype(np.float32), points=points, lengths=lengths,
            syn1neg=np.asarray(w2v.syn1neg).copy() if neg else None,
            unigram=unigram)


@dataclasses.dataclass
class Word2VecWork:
    """The shipped unit (``Word2VecWork.java``): sentence indices plus the
    exact rows they touch.  ``rows*`` map global row index → vector copy."""

    sentences: list[np.ndarray]
    rows0: dict[int, np.ndarray]
    rows1: dict[int, np.ndarray]
    rows1neg: dict[int, np.ndarray]
    negatives: np.ndarray | None           # (n_pairs_est, k) pre-drawn
    alpha: float


@dataclasses.dataclass
class RowDeltas:
    """Per-row deltas returned by a worker (reference: the updated rows in
    ``Word2VecWork.addDeltas``)."""

    d0: dict[int, np.ndarray]
    d1: dict[int, np.ndarray]
    d1neg: dict[int, np.ndarray]
    n_words: int


class Word2VecJobIterator:
    """Slices the (pre-tokenized) corpus and builds row-shipping works
    (``Word2VecJobIterator.java``)."""

    def __init__(self, sentences_idx: Sequence[np.ndarray],
                 tables: EmbeddingTables, *, window: int = 5,
                 chunk: int = 8, negative: int = 0, hs: bool | None = None,
                 alpha: float = 0.025, min_alpha: float = 1e-2,
                 iterations: int = 1, seed: int = 42,
                 tracker: StateTracker | None = None):
        self.sentences_idx = list(sentences_idx)
        self.tables = tables
        self.window = window
        self.chunk = chunk
        self.negative = negative
        self.hs = hs if hs is not None else negative == 0
        self.alpha = alpha
        self.min_alpha = min_alpha
        self.iterations = iterations
        self.rng = np.random.default_rng(seed)
        self.tracker = tracker
        self.total_words = max(
            1, sum(int(s.size) for s in self.sentences_idx) * iterations)
        self._cursor = 0
        self._epoch = 0

    def _chunks_left(self) -> bool:
        return (self._epoch < self.iterations - 1
                or self._cursor < len(self.sentences_idx))

    def has_next(self) -> bool:
        return self._chunks_left()

    def reset(self) -> None:
        self._cursor = 0
        self._epoch = 0

    def next(self, worker_id: str = "") -> Job:
        if self._cursor >= len(self.sentences_idx):
            self._cursor = 0
            self._epoch += 1
        sents = self.sentences_idx[self._cursor:self._cursor + self.chunk]
        self._cursor += self.chunk

        words = np.unique(np.concatenate(sents)) if sents else np.zeros(0, int)
        t = self.tables
        rows0 = {int(w): t.syn0[w].copy() for w in words}
        rows1: dict[int, np.ndarray] = {}
        if self.hs:
            for w in words:
                for li in range(int(t.lengths[w])):
                    p = int(t.points[w, li])
                    if p not in rows1:
                        rows1[p] = t.syn1[p].copy()
        rows1neg: dict[int, np.ndarray] = {}
        negatives = None
        if self.negative > 0 and t.syn1neg is not None and words.size:
            n_pairs_est = sum(int(s.size) for s in sents) * 2 * self.window
            negatives = self.rng.choice(
                t.unigram.size, size=(max(n_pairs_est, 1), self.negative),
                p=t.unigram).astype(np.int32)
            for p in np.unique(negatives):
                rows1neg[int(p)] = t.syn1neg[p].copy()
            for w in words:
                rows1neg.setdefault(int(w), t.syn1neg[w].copy())

        # linear alpha decay by the DISTRIBUTED words-processed counter
        # (Word2VecPerformer.java:82)
        seen = self.tracker.count(WORDS_KEY) if self.tracker else 0.0
        alpha = max(self.min_alpha,
                    self.alpha * (1.0 - seen / self.total_words))
        work = Word2VecWork(sentences=list(sents), rows0=rows0, rows1=rows1,
                            rows1neg=rows1neg, negatives=negatives,
                            alpha=alpha)
        return Job(work=work, worker_id=worker_id)


class Word2VecPerformer:
    """Worker side (``Word2VecPerformer.java:72-137``): train the shipped
    rows on the chunk's skip-gram pairs with the batched jitted kernels,
    return per-row deltas."""

    def __init__(self, tracker: StateTracker, *, window: int = 5,
                 negative: int = 0, codes: np.ndarray | None = None,
                 points: np.ndarray | None = None,
                 lengths: np.ndarray | None = None, seed: int = 7):
        self.tracker = tracker
        self.window = window
        self.negative = negative
        self.codes, self.points, self.lengths = codes, points, lengths
        self.rng = np.random.default_rng(seed)

    def update(self, *args) -> None:  # replication hook (tables ride works)
        pass

    def perform(self, job: Job) -> None:
        work: Word2VecWork = job.work
        centers, contexts = skipgram_pairs(work.sentences, self.window, self.rng)
        n_words = int(sum(s.size for s in work.sentences))
        if centers.size == 0:
            job.result = RowDeltas({}, {}, {}, n_words)
            return

        # local sub-tables from the shipped rows, remapped indices
        idx0 = {w: i for i, w in enumerate(sorted(work.rows0))}
        sub0 = np.stack([work.rows0[w] for w in sorted(work.rows0)])
        c_loc = np.array([idx0[int(c)] for c in centers], np.int32)

        d1, d1neg = {}, {}
        if self.codes is not None and work.rows1:
            keys1 = np.array(sorted(work.rows1), np.int64)
            idx1 = {int(p): i for i, p in enumerate(keys1)}
            sub1 = np.stack([work.rows1[int(p)] for p in keys1])
            L = self.codes.shape[1]
            pts = self.points[contexts]                     # (B, L) global
            lut1 = np.zeros(int(self.points.max()) + 1, np.int32)
            lut1[keys1] = np.arange(keys1.size, dtype=np.int32)
            pts_loc = lut1[pts]                             # masked slots → 0
            cds = self.codes[contexts]
            msk = (np.arange(L)[None, :]
                   < self.lengths[contexts][:, None]).astype(np.float32)
            s0, s1 = _hs_step(jnp.asarray(sub0), jnp.asarray(sub1),
                              jnp.asarray(c_loc), jnp.asarray(pts_loc),
                              jnp.asarray(cds), jnp.asarray(msk),
                              jnp.float32(work.alpha))
            s0, s1 = np.asarray(s0), np.asarray(s1)
            d1 = {p: s1[i] - work.rows1[p] for p, i in idx1.items()}
            sub0 = s0
        if self.negative > 0 and work.rows1neg:
            keysn = np.array(sorted(work.rows1neg), np.int64)
            idxn = {int(p): i for i, p in enumerate(keysn)}
            subn = np.stack([work.rows1neg[int(p)] for p in keysn])
            negs = work.negatives[np.arange(centers.size)
                                  % work.negatives.shape[0]]
            tgt = np.concatenate([contexts[:, None], negs], axis=1)
            lutn = np.zeros(int(keysn.max()) + 1, np.int32)
            lutn[keysn] = np.arange(keysn.size, dtype=np.int32)
            tgt_loc = lutn[tgt]
            labels = np.zeros_like(tgt, np.float32)
            labels[:, 0] = 1.0
            s0, sn = _ns_step(jnp.asarray(sub0), jnp.asarray(subn),
                              jnp.asarray(c_loc), jnp.asarray(tgt_loc),
                              jnp.asarray(labels), jnp.float32(work.alpha))
            s0, sn = np.asarray(s0), np.asarray(sn)
            d1neg = {p: sn[i] - work.rows1neg[p] for p, i in idxn.items()}
            sub0 = s0
        d0 = {w: sub0[i] - work.rows0[w] for w, i in idx0.items()}

        self.tracker.increment(WORDS_KEY, n_words)
        job.result = RowDeltas(d0=d0, d1=d1, d1neg=d1neg, n_words=n_words)


class RowDeltaAggregator:
    """Sums workers' per-row deltas into the master tables (the master-side
    apply in ``Word2VecWork.addDeltas`` / ``MasterActor`` broadcast)."""

    def __init__(self, tables: EmbeddingTables):
        self.tables = tables

    def accumulate(self, job: Job) -> None:
        r: RowDeltas = job.result
        for w, d in r.d0.items():
            self.tables.syn0[w] += d
        for p, d in r.d1.items():
            self.tables.syn1[p] += d
        if self.tables.syn1neg is not None:
            for p, d in r.d1neg.items():
                self.tables.syn1neg[p] += d

    def aggregate(self) -> EmbeddingTables:
        return self.tables
