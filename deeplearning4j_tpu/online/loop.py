"""The online learning coordinator (DESIGN.md §23).

One dataflow system spanning both halves of the stack: replay the
:class:`~.capture.CaptureStore`, fine-tune through the existing
supervised training pipeline (``TrainingSupervisor`` over a
``DataParallelTrainer`` — every resilience arm intact), publish
manifest-verified checkpoints, and hot-reload each new
``latest_valid_step()`` into live serving at generation-consistent
fences.  The robustness headline: every applied generation is **canaried
and SLO-gated** — a non-finite or regressed canary loss, or an SLO
burn-rate breach during the probation window, quarantines the offending
step and rolls serving back to the previous valid generation, with a
flight-recorder bundle naming the step.

Replay-is-the-cursor: each round replays the FULL capture history into a
prefix-stable batch stream (records pack into fixed ``(batch, seq+1)``
blocks in append order, partial tails excluded), and the trainer's
checkpoint data cursor skips every batch already trained — so the round
trains exactly the new tail, and a retried round re-joins the trajectory
bitwise.  A bootstrap checkpoint of the initial params is published at
step 0 before the first round, so rollback ALWAYS has a previous valid
generation to land on.

Chaos seams: ``online.publish`` (transient abort, or ``kind="poison"`` —
the published params are rewritten with garbage under *recomputed*
checksums, a semantically-bad but manifest-valid model the gates must
catch), ``online.reload`` (transient abort before the swap), and
``online.rollback`` (transient failures inside rollback itself, retried
until the site's ``max_fires`` exhausts).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import threading
import time
from typing import Any

import numpy as np

from ..models.transformer import lm_loss_local
from ..observability import FLIGHTREC, METRICS
from ..optimize import transforms as tfm
from ..parallel.mesh import local_mesh
from ..parallel.trainer import DataParallelTrainer
from ..resilience.faults import FAULTS, InjectedFault
from ..resilience.supervisor import RetryPolicy, TrainingSupervisor


@dataclasses.dataclass(frozen=True)
class OnlineConfig:
    """Knobs for one :class:`OnlineLoop`."""

    batch: int = 2                  # rows per training batch
    seq: int = 16                   # tokens per training row
    epochs: int = 1                 # passes per round (1: replay-is-cursor)
    learning_rate: float = 1e-2
    canary_factor: float = 2.0      # loss regression multiple that fails
    probation_s: float = 0.0        # SLO watch window after each swap
    probation_poll_s: float = 0.05
    rollback_attempts: int = 8      # bounded retries through online.rollback
    min_feedback: float = 0.0       # records with feedback < this are skipped


@dataclasses.dataclass
class RoundReport:
    """What one :meth:`OnlineLoop.run_once` did (JSON-safe)."""

    status: str = "ok"              # ok | no_new_data | *_fault | rolled_back
    base_step: int = 0
    trained_to: int | None = None
    reloaded: dict = dataclasses.field(default_factory=dict)
    rolled_back: bool = False
    rollback_reason: str | None = None
    quarantined: str | None = None
    generation: int = 0
    faults: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class OnlineLoop:
    """serve → capture → fine-tune → hot-reload, with SLO-gated rollback.

    ``engine`` and/or ``router`` are the reload fan-out targets; both are
    optional (a loop with neither still trains and publishes).  ``slo``
    is an attached :class:`~..observability.slo.SLOEvaluator` whose
    breach count gates the post-swap probation window.  ``params0`` must
    be the SAME tree the serving side was built with — it seeds the
    bootstrap step-0 checkpoint, making the pre-training generation
    itself a valid rollback target.
    """

    def __init__(self, store, manager, model, *, params0=None,
                 engine=None, router=None, cfg: OnlineConfig = OnlineConfig(),
                 slo=None, supervisor: TrainingSupervisor | None = None,
                 optimizer=None):
        self.store = store
        self.manager = manager
        self.model = model
        self.engine = engine
        self.router = router
        self.cfg = cfg
        self.slo = slo
        self.supervisor = supervisor or TrainingSupervisor(
            checkpoint_manager=manager,
            policy=RetryPolicy(max_attempts=4, backoff_base_s=0.01),
            install_signal_handlers=False)
        if self.supervisor.manager is None:
            self.supervisor.manager = manager
        self._params0 = params0
        self._optimizer = optimizer
        self._trainer: DataParallelTrainer | None = None
        self._canary_batch: tuple | None = None
        self._canary_baseline: float | None = None
        self.generation = 0             # applied forward swaps + rollbacks
        self._current_step: int | None = (
            engine.stats().get("loaded_step") if engine is not None else None)
        self._round_lock = threading.Lock()
        self._rounds = 0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ plumbing
    def _initial_params(self):
        if self._params0 is None:
            import jax
            self._params0 = self.model.init(jax.random.key(0))
        return self._params0

    def _make_trainer(self) -> DataParallelTrainer:
        if self._trainer is None:
            mcfg = self.model.cfg

            def loss(p, xb, yb, key=None):
                return lm_loss_local(p, xb, yb, mcfg)

            tx = self._optimizer or tfm.sgd_lr(self.cfg.learning_rate)
            self._trainer = DataParallelTrainer(loss, tx, mesh=local_mesh(1))
        return self._trainer

    def _ensure_bootstrap(self) -> None:
        """Publish the initial params as step 0 once — rollback's floor."""
        if self.manager.latest_valid_step() is None:
            self.manager.save(0, self._initial_params())

    # ------------------------------------------------------------- packing
    def _keep(self, rec: dict) -> bool:
        fb = rec.get("feedback")
        if fb is None:
            return True
        if isinstance(fb, bool):
            return fb
        try:
            return float(fb) >= self.cfg.min_feedback
        except (TypeError, ValueError):
            return True

    def _pack(self, records: list[dict]) -> list[tuple]:
        """Prefix-stable batches: records concatenate (prompt + tokens)
        into one flat stream in append order, sliced into fixed
        ``seq+1``-token rows, grouped into full ``batch``-row batches —
        appending records NEVER changes an earlier batch, so the
        checkpoint data cursor (= batches already trained) is exact."""
        flat: list[int] = []
        for r in records:
            if self._keep(r):
                flat.extend(int(t) for t in r.get("prompt", []))
                flat.extend(int(t) for t in r.get("tokens", []))
        block = self.cfg.seq + 1
        rows = [flat[i * block:(i + 1) * block]
                for i in range(len(flat) // block)]
        out = []
        for i in range(len(rows) // self.cfg.batch):
            chunk = np.asarray(rows[i * self.cfg.batch:(i + 1) * self.cfg.batch],
                               np.int32)
            out.append((chunk[:, :-1], chunk[:, 1:]))
        return out

    # ------------------------------------------------------------ one round
    def run_once(self, key=None) -> RoundReport:
        with self._round_lock:
            return self._run_once_locked(key)

    def _run_once_locked(self, key) -> RoundReport:
        rep = RoundReport(generation=self.generation)
        self._ensure_bootstrap()
        rep.base_step = base_step = self.manager.latest_valid_step() or 0
        self._rounds += 1
        METRICS.increment("online.rounds")

        # ---- fine-tune phase: replay everything, train the new tail
        try:
            batches = self._pack(list(self.store.replay()))
        except InjectedFault as e:
            rep.faults.append(f"capture.replay: {e}")
            batches = []
        if batches:
            self._canary_batch = batches[-1]
            if len(batches) > base_step:
                trainer = self._make_trainer()
                self.supervisor.fit(trainer, self._initial_params(), batches,
                                    epochs=self.cfg.epochs,
                                    checkpoint_every=1, key=key)

        new_step = self.manager.latest_valid_step()
        rep.trained_to = new_step
        if new_step is None or new_step <= (self._current_step or 0):
            rep.status = "no_new_data"
            return rep

        # ---- publish gate (chaos): transient aborts the round (the
        # checkpoint stays; the NEXT round's reload picks it up); poison
        # rewrites the published params under valid checksums — the
        # exact failure the canary/SLO gates exist for
        spec = FAULTS.check("online.publish")
        if spec is not None:
            if spec.kind == "poison":
                rep.faults.append("online.publish: poison")
                self._poison_checkpoint(new_step)
            else:
                rep.faults.append("online.publish: transient")
                rep.status = "publish_fault"
                return rep

        # ---- hot reload fan-out
        try:
            FAULTS.maybe_fire("online.reload")
        except InjectedFault as e:
            rep.faults.append(f"online.reload: {e}")
            rep.status = "reload_fault"
            return rep
        t0 = time.perf_counter()
        rep.reloaded = self._reload_targets(new_step)
        METRICS.gauge("online.reload_seconds", time.perf_counter() - t0)
        METRICS.increment("online.reloads")
        self._current_step = new_step
        self.generation += 1
        rep.generation = self.generation
        METRICS.gauge("online.generation", self.generation)

        # ---- canary + SLO probation; breach => rollback
        reason = self._canary(new_step)
        if reason is None and self._probation_breached():
            reason = "slo_breach"
        if reason is not None:
            self._rollback(new_step, reason, rep)
        return rep

    def _reload_targets(self, step: int) -> dict:
        out: dict[str, Any] = {}
        if self.engine is not None:
            out["engine"] = self.engine.reload(step=step)
        if self.router is not None:
            out.update(self.router.reload(step=step))
        return out

    # ------------------------------------------------------------- canary
    def _canary(self, step: int) -> str | None:
        """Gate the freshly-loaded generation on a held-out loss: restore
        the PUBLISHED bytes (what serving actually loaded, not the
        trainer's in-memory state) and score the newest packed batch.
        Non-finite, or worse than ``canary_factor`` × the best loss seen,
        fails the canary.  Returns the failure reason or None."""
        if self._canary_batch is None:
            return None
        try:
            restored = self.manager.restore(self._initial_params(),
                                            step=step)["params"]
            x, y = self._canary_batch
            loss = float(lm_loss_local(restored, x, y, self.model.cfg))
        except Exception as e:                     # noqa: BLE001
            return f"canary_error: {type(e).__name__}: {e}"
        METRICS.gauge("online.canary_loss", loss)
        if not np.isfinite(loss):
            return "canary_nonfinite"
        base = self._canary_baseline
        if base is not None and loss > self.cfg.canary_factor * max(base, 1e-8):
            return f"canary_regression: {loss:.4f} > " \
                   f"{self.cfg.canary_factor} * {base:.4f}"
        self._canary_baseline = loss if base is None else min(base, loss)
        return None

    def _probation_breached(self) -> bool:
        """Watch the SLO evaluator's breach count over the probation
        window; any NEW breach after the swap condemns the generation."""
        if self.slo is None or self.cfg.probation_s <= 0:
            return False
        start = self.slo.status()["breaches"]
        t_end = time.monotonic() + self.cfg.probation_s
        while time.monotonic() < t_end:
            if self.slo.status()["breaches"] > start:
                return True
            time.sleep(self.cfg.probation_poll_s)
        return self.slo.status()["breaches"] > start

    # ------------------------------------------------------------ rollback
    def _rollback(self, bad_step: int, reason: str, rep: RoundReport) -> None:
        """Quarantine ``bad_step`` and swing serving back to the previous
        valid generation.  The ``online.rollback`` chaos site injects
        transient failures INSIDE the recovery path — retried (bounded by
        ``rollback_attempts`` and the site's ``max_fires``) because
        rollback is the one step that must not stay failed."""
        for _ in range(self.cfg.rollback_attempts):
            try:
                FAULTS.maybe_fire("online.rollback")
                break
            except InjectedFault as e:
                rep.faults.append(f"online.rollback: {e}")
        bad_dir = self.manager.quarantine(bad_step)
        rep.quarantined = str(bad_dir)
        prev = self.manager.latest_valid_step()
        if prev is not None:
            rep.reloaded = self._reload_targets(prev)
            self._current_step = prev
        self.generation += 1
        rep.generation = self.generation
        METRICS.gauge("online.generation", self.generation)
        METRICS.increment("online.rollbacks")
        rep.rolled_back = True
        rep.rollback_reason = reason
        rep.status = "rolled_back"
        # the canary baseline came from a now-condemned trajectory only
        # if the bad step set it — it never did (rollback fires before
        # the baseline update), so keep it
        FLIGHTREC.dump("online_rollback", extra={
            "bad_step": int(bad_step),
            "restored_step": int(prev) if prev is not None else None,
            "reason": reason,
            "generation": self.generation,
            "quarantined": str(bad_dir),
        })

    # ------------------------------------------------------------- poison
    def _poison_checkpoint(self, step: int) -> None:
        """Chaos ``online.publish kind="poison"``: rewrite the published
        params with garbage and RECOMPUTE the manifest checksums — a
        checkpoint that verifies perfectly and serves terribly, the
        adversary the canary/SLO gates (not the manifest) must catch.
        Float leaves go NaN — the classic diverged-training artifact
        (constants would slip past the canary: layernorm makes an
        all-equal tree score a merely-uniform loss).  Rewrites go through
        the unique-tempfile + fsync + ``os.replace`` idiom (graftlint
        OL01)."""
        d = self.manager.directory / f"ckpt_{step:010d}"
        with np.load(d / "params.npz") as z:
            poisoned = {
                k: np.full_like(z[k], np.nan)
                if np.issubdtype(z[k].dtype, np.floating)
                else np.full_like(z[k], 1)
                for k in z.files}
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz")
        os.close(fd)
        np.savez(tmp, **poisoned)
        fd = os.open(tmp, os.O_RDONLY)
        os.fsync(fd)
        os.close(fd)
        os.replace(tmp, d / "params.npz")
        meta = json.loads((d / "meta.json").read_text())
        meta["checksums"]["params.npz"] = hashlib.sha256(
            (d / "params.npz").read_bytes()).hexdigest()
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".json")
        with os.fdopen(fd, "w") as f:
            f.write(json.dumps(meta, indent=2))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, d / "meta.json")

    # ---------------------------------------------------------- background
    def start(self, interval_s: float = 0.25) -> "OnlineLoop":
        """Run rounds on a daemon thread until :meth:`stop`."""
        if self._thread is None:
            self._stop.clear()

            def _run():
                while not self._stop.is_set():
                    try:
                        self.run_once()
                    except Exception:              # noqa: BLE001
                        METRICS.increment("online.round_errors")
                    self._stop.wait(interval_s)

            self._thread = threading.Thread(target=_run, daemon=True,
                                            name="online-loop")
            self._thread.start()
        return self

    def stop(self, timeout_s: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None

    def __enter__(self) -> "OnlineLoop":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------------- status
    def status(self) -> dict:
        return {
            "generation": self.generation,
            "current_step": self._current_step,
            "rounds": self._rounds,
            "canary_baseline": self._canary_baseline,
            "running": self._thread is not None,
        }
