"""Online learning loop tests (DESIGN.md §23, ISSUE 15).

The deterministic tier-1 subset of what ``tools/chaos_smoke.py --online``
keeps rolling dice on: capture-store durability under torn tails and
damaged media, the generation-consistency invariant (every completion's
tokens match offline sampling under the checkpoint its OWN stamp names,
even while a hot reload races the decode loop), canary/SLO-gated
auto-rollback of a poisoned publish with the flight bundle naming the
offending step, router-replica reload fan-out, the publish /
``latest_valid_step`` concurrent-writer contract, a fixed-seed chaos
plan, and the OL01 durable-write lint rule's trigger contract.
"""

import json
import textwrap
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import observability
from deeplearning4j_tpu.analysis import Analyzer, active, all_rules
from deeplearning4j_tpu.models.transformer import TransformerConfig, TransformerLM
from deeplearning4j_tpu.observability import FLIGHTREC, METRICS
from deeplearning4j_tpu.online import CaptureStore, OnlineConfig, OnlineLoop
from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
from deeplearning4j_tpu.resilience.faults import corrupt_file
from deeplearning4j_tpu.serving import InferenceEngine, ServingConfig
from deeplearning4j_tpu.serving.router import (EngineReplica, PrefixRouter,
                                               RouterConfig)

import random


@pytest.fixture(scope="module")
def olm():
    """Tiny f32 LM: the loop's contracts are about dataflow and parity,
    not model quality, so the smallest transformer that decodes wins."""
    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                            d_ff=32, max_len=32, dtype=jnp.float32,
                            remat=False)
    model = TransformerLM(cfg)
    return model, model.init(jax.random.key(7))


def _expected(model, params, prompt, n, seed, temperature=0.0):
    return model.sample(params, prompt, n, temperature=temperature,
                        key=jax.random.key(seed),
                        kv_cache=True)[len(prompt):]


def _traffic(rng, n, vocab=32):
    """Synthetic captured records: 4 prompt + 5 generated tokens each, so
    every 2 records fill exactly one (batch=2, seq=8) training block."""
    return [{"prompt": [rng.randrange(vocab) for _ in range(4)],
             "tokens": [rng.randrange(vocab) for _ in range(5)]}
            for _ in range(n)]


OCFG = OnlineConfig(batch=2, seq=8)


# --------------------------------------------------------------------------- capture store

def test_capture_roundtrip_rotates_segments(tmp_path):
    observability.enable()
    store = CaptureStore(tmp_path, segment_bytes=256)
    recs = _traffic(random.Random(0), 12)
    for r in recs:
        store.append(r)
    assert len(store.segments()) > 1          # rotation actually happened
    got = store.records()
    assert [g["prompt"] for g in got] == [r["prompt"] for r in recs]
    assert [g["tokens"] for g in got] == [r["tokens"] for r in recs]
    store.close()


def test_capture_torn_tail_truncate_keeps_verified_prefix(tmp_path):
    observability.enable()
    before = METRICS.snapshot()["counters"].get("capture.corrupt_records", 0)
    store = CaptureStore(tmp_path, segment_bytes=1 << 20)
    recs = _traffic(random.Random(1), 8)
    for r in recs:
        store.append(r)
    store.close()
    # tear the single segment mid-file: the classic crash artifact
    corrupt_file(store.segments()[0], "truncate")
    reopened = CaptureStore(tmp_path, segment_bytes=1 << 20)
    reopened.append({"prompt": [1, 2, 3, 4], "tokens": [5, 6, 7, 8, 9]})
    got = reopened.records()
    # every surviving record is bit-exact and in order; the torn range is
    # skipped, never parsed into garbage — and the post-damage append is
    # the final record
    assert 0 < len(got) < 10
    for g, r in zip(got[:-1], recs):
        assert g == r
    assert got[-1]["tokens"] == [5, 6, 7, 8, 9]
    # the damaged segment was sealed: the new append lives in a fresh one
    assert len(reopened.segments()) == 2
    after = METRICS.snapshot()["counters"]["capture.corrupt_records"]
    assert after > before
    reopened.close()


def test_capture_bitflip_costs_only_covered_records(tmp_path):
    observability.enable()
    store = CaptureStore(tmp_path, segment_bytes=1 << 20)
    recs = _traffic(random.Random(2), 10)
    for r in recs:
        store.append(r)
    store.close()
    corrupt_file(store.segments()[0], "bitflip")
    got = CaptureStore(tmp_path).records()
    # one flipped byte damages at most the record it lands in (it may
    # fall on a newline and merge two lines: two records, worst case)
    assert len(got) >= len(recs) - 2
    assert all(g in recs for g in got)


def test_capture_write_chaos_never_loses_the_store(tmp_path):
    observability.enable()
    store = CaptureStore(tmp_path, segment_bytes=1 << 20)
    recs = _traffic(random.Random(3), 8)
    with inject_faults(FaultSpec("capture.write", at_step=3, kind="bitflip"),
                       seed=0):
        for r in recs:
            store.append(r)      # damage lands mid-stream; appends go on
    store.close()
    got = store.records()
    assert len(got) >= len(recs) - 2
    counters = METRICS.snapshot()["counters"]
    assert counters["faults.injected.capture.write"] == 1


# --------------------------------------------------------------------------- loop plumbing

def _make_loop(tmp_path, olm, n_records=6, with_engine=False, **cfg_kw):
    model, params0 = olm
    store = CaptureStore(tmp_path / "capture")
    mgr = CheckpointManager(tmp_path / "ckpt", keep=64)
    for r in _traffic(random.Random(4), n_records):
        store.append(r)
    engine = None
    if with_engine:
        engine = InferenceEngine(model, params=params0, checkpoint=mgr,
                                 cfg=ServingConfig(slots=2, idle_wait_s=0.01))
        engine.start(warmup=False)
    loop = OnlineLoop(store, mgr, model, params0=params0, engine=engine,
                      cfg=OnlineConfig(**{**OCFG.__dict__, **cfg_kw}))
    return loop, store, mgr, engine


def test_round_trains_publishes_and_hot_reloads(tmp_path, olm):
    observability.enable()
    model, params0 = olm
    loop, store, mgr, engine = _make_loop(tmp_path, olm, with_engine=True)
    try:
        rep = loop.run_once().to_dict()
        assert rep["status"] == "ok", rep
        # 6 records x 9 tokens = 3 full (2, 9) blocks -> 3 steps
        assert rep["trained_to"] == 3 and rep["reloaded"]["engine"] == 3
        assert rep["generation"] == 1
        # a second round with no new captures must be a no-op
        assert loop.run_once().to_dict()["status"] == "no_new_data"
        # live requests now decode under the published step-3 bytes and
        # say so in their stamp
        out = engine.submit(prompt=[5, 9, 13], max_new_tokens=4,
                            temperature=0.0, seed=11).result(60.0)
        assert (out.generation, out.loaded_step) == (1, 3)
        trained = mgr.restore(params0, step=3)["params"]
        assert out.tokens == _expected(model, trained, [5, 9, 13], 4, 11)
    finally:
        engine.stop()
    # the loop bootstrapped the pre-training params as step 0: rollback's
    # floor existed before the first fine-tune ever ran
    assert 0 in mgr.all_steps()


@pytest.mark.lockguard
def test_generation_stamp_parity_under_concurrent_reload(tmp_path, olm):
    """The generation-consistency invariant under a racing swap: requests
    in flight while ``reload`` stages a new checkpoint must each complete
    entirely under ONE generation, and their stamp must name it."""
    observability.enable()
    model, params0 = olm
    params1 = model.init(jax.random.key(23))      # genuinely different weights
    mgr = CheckpointManager(tmp_path / "ckpt", keep=8)
    mgr.save(1, params1)
    engine = InferenceEngine(model, params=params0, checkpoint=mgr,
                             cfg=ServingConfig(slots=2, idle_wait_s=0.005))
    engine.start(warmup=False)
    rng = random.Random(5)
    reqs = [dict(prompt=[rng.randrange(32) for _ in range(rng.randint(2, 5))],
                 max_new_tokens=rng.randint(2, 6), temperature=0.0,
                 seed=rng.randrange(1 << 16)) for _ in range(12)]
    outs, lock = [], threading.Lock()

    def worker(mine):
        for r in mine:
            out = engine.submit(**r).result(60.0)
            with lock:
                outs.append((r, out))

    try:
        ts = [threading.Thread(target=worker, args=(reqs[i::2],))
              for i in range(2)]
        for t in ts:
            t.start()
        engine.reload(step=1)                     # races the decode loop
        for t in ts:
            t.join()
    finally:
        engine.stop()
    by_step = {None: params0, 1: params1}
    for r, out in outs:
        exp = _expected(model, by_step[out.loaded_step], r["prompt"],
                        len(out.tokens), r["seed"])
        assert out.tokens == exp, (r, out.loaded_step)
    assert engine.stats()["loaded_step"] == 1     # the swap did land


def test_poison_rollback_quarantines_and_dumps_bundle(tmp_path, olm):
    observability.enable()
    loop, store, mgr, engine = _make_loop(tmp_path, olm, with_engine=True)
    old_dump = FLIGHTREC.dump_dir
    FLIGHTREC.dump_dir = tmp_path / "rec"
    try:
        with inject_faults(
                FaultSpec("online.publish", at_step=1, kind="poison"),
                FaultSpec("online.rollback", at_step=1), seed=0):
            rep = loop.run_once().to_dict()
    finally:
        FLIGHTREC.dump_dir = old_dump
        engine.stop()
    assert rep["status"] == "rolled_back"
    assert rep["rollback_reason"] == "canary_nonfinite"
    # step 3 is quarantined under bad_*; the loop re-landed on step 2
    # (checkpoint_every=1 checkpoints every fine-tune step)
    assert rep["quarantined"].endswith("bad_0000000003")
    assert (tmp_path / "ckpt" / "bad_0000000003").is_dir()
    assert mgr.latest_valid_step() == 2
    assert engine.stats()["loaded_step"] == 2
    assert rep["generation"] == 2                 # forward swap + rollback
    bundles = sorted((tmp_path / "rec").glob("flightrec-online_rollback-*"))
    assert bundles, "rollback must leave a flight bundle"
    extra = json.loads(bundles[-1].read_text())["extra"]
    assert extra["bad_step"] == 3 and extra["restored_step"] == 2
    assert extra["reason"] == "canary_nonfinite"
    counters = METRICS.snapshot()["counters"]
    assert counters["checkpoint.quarantined"] >= 1
    assert counters["online.rollbacks"] >= 1


def test_slo_breach_during_probation_rolls_back(tmp_path, olm):
    """A healthy canary is not enough: a breach surfacing in the SLO
    evaluator during the probation window condemns the generation too
    (the stub implements ``SLOEvaluator.status()``'s documented shape)."""

    class _BreachesAfterSwap:
        def __init__(self):
            self.calls = 0

        def status(self):
            self.calls += 1
            return {"breaches": 0 if self.calls == 1 else 1}

    observability.enable()
    loop, store, mgr, _ = _make_loop(tmp_path, olm, probation_s=0.02,
                                     probation_poll_s=0.005)
    loop.slo = _BreachesAfterSwap()
    rep = loop.run_once().to_dict()
    assert rep["status"] == "rolled_back"
    assert rep["rollback_reason"] == "slo_breach"
    assert mgr.latest_valid_step() == 2


def test_router_reload_fans_out_to_every_replica(tmp_path, olm):
    observability.enable()
    model, params0 = olm
    params1 = model.init(jax.random.key(29))
    mgr = CheckpointManager(tmp_path / "ckpt", keep=8)
    mgr.save(1, params1)
    engines = [InferenceEngine(model, params=params0, checkpoint=mgr,
                               cfg=ServingConfig(slots=2, idle_wait_s=0.01))
               for _ in range(2)]
    for e in engines:
        e.start(warmup=False)
    reps = [EngineReplica(f"r{i}", e, own_engine=True)
            for i, e in enumerate(engines)]
    router = PrefixRouter(reps, RouterConfig())
    try:
        out = router.reload(step=1)
        assert out == {"r0": 1, "r1": 1}
        for e in engines:
            assert e.stats()["loaded_step"] == 1
        got = router.generate([3, 1, 4], 4, temperature=0.0, seed=9)
        assert got["tokens"] == _expected(model, params1, [3, 1, 4], 4, 9)
    finally:
        router.close()


# --------------------------------------------------------------------------- publish race

@pytest.mark.lockguard
def test_publish_race_latest_valid_step_never_sees_torn_checkpoint(tmp_path,
                                                                   olm):
    """Concurrent-writer regression (ISSUE 15 satellite): writers publish
    steps while a reader spins on ``latest_valid_step`` + ``restore`` —
    the meta.json-last publish order means a step is either invisible or
    fully restorable, never in between."""
    _, params0 = olm
    mgr = CheckpointManager(tmp_path / "ckpt", keep=64)
    errors: list[str] = []
    stop = threading.Event()

    def writer(steps):
        try:
            for s in steps:
                mgr.save(s, params0)
        except Exception as e:                     # noqa: BLE001
            errors.append(f"writer: {e!r}")

    def reader():
        seen = 0
        while not stop.is_set():
            try:
                s = mgr.latest_valid_step()
                if s is not None:
                    assert s >= seen, f"latest_valid_step went back: {s}<{seen}"
                    seen = s
                    mgr.restore(params0, step=s)   # must verify, always
            except Exception as e:                 # noqa: BLE001
                errors.append(f"reader: {e!r}")
                return
    ws = [threading.Thread(target=writer, args=(range(1, 17, 2),)),
          threading.Thread(target=writer, args=(range(2, 17, 2),))]
    rd = threading.Thread(target=reader)
    rd.start()
    for w in ws:
        w.start()
    for w in ws:
        w.join()
    stop.set()
    rd.join()
    assert not errors, errors
    assert mgr.latest_valid_step() == 16
    assert set(mgr.all_steps()) == set(range(1, 17))


# --------------------------------------------------------------------------- fixed-seed chaos

def test_fixed_seed_chaos_plan_rolls_back_then_heals(tmp_path, olm):
    """The chaos_smoke --online storyline, deterministically: a step
    fault inside the fine-tune, a poisoned publish, a failing rollback
    seam, and an aborted reload — three rounds later serving is on the
    cleanly republished step."""
    observability.enable()
    model, params0 = olm
    loop, store, mgr, engine = _make_loop(tmp_path, olm, with_engine=True)
    try:
        with inject_faults(FaultSpec("train.step", at_step=2),
                           FaultSpec("online.publish", at_step=1,
                                     kind="poison"),
                           FaultSpec("online.rollback", at_step=1),
                           FaultSpec("online.reload", at_step=2), seed=7):
            statuses = [loop.run_once().to_dict()["status"] for _ in range(3)]
        assert statuses == ["rolled_back", "reload_fault", "ok"]
        counters = METRICS.snapshot()["counters"]
        for site in ("train.step", "online.publish", "online.rollback",
                     "online.reload"):
            assert counters[f"faults.injected.{site}"] == 1, site
        # the healed generation serves the republished step-3 bytes
        assert engine.stats()["loaded_step"] == 3
        out = engine.submit(prompt=[2, 7, 1], max_new_tokens=4,
                            temperature=0.0, seed=3).result(60.0)
        healed = mgr.restore(params0, step=3)["params"]
        assert out.tokens == _expected(model, healed, [2, 7, 1], 4, 3)
        assert out.loaded_step == 3
    finally:
        engine.stop()


# --------------------------------------------------------------------------- OL01 lint

def _lint(source, path):
    analyzer = Analyzer(rules=[all_rules()["OL01"]])
    findings = analyzer.analyze_source(textwrap.dedent(source), path)
    assert not analyzer.errors
    return {f.rule for f in active(findings)}


BAD_WRITE = """
    def publish(path, blob):
        with open(path, "w") as f:
            f.write(blob)
"""

GOOD_WRITE = """
    import os
    import tempfile

    def publish(path, blob):
        fd, tmp = tempfile.mkstemp(dir=".")
        with os.fdopen(fd, "w") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
"""


def test_ol01_flags_bare_rewrite_on_publish_paths():
    assert _lint(BAD_WRITE, "deeplearning4j_tpu/online/writer.py") == {"OL01"}
    assert _lint(BAD_WRITE,
                 "deeplearning4j_tpu/parallel/checkpoint.py") == {"OL01"}


def test_ol01_quiet_on_durable_idiom_appends_and_other_paths():
    assert _lint(GOOD_WRITE, "deeplearning4j_tpu/online/writer.py") == set()
    append = """
        def log(path, line):
            with open(path, "a") as f:
                f.write(line)
    """
    assert _lint(append, "deeplearning4j_tpu/online/capture.py") == set()
    # the rule is scoped: the same bare rewrite elsewhere is other rules'
    # (and reviewers') business
    assert _lint(BAD_WRITE, "deeplearning4j_tpu/serving/engine.py") == set()


def test_ol01_shipping_tree_is_clean():
    analyzer = Analyzer(rules=[all_rules()["OL01"]], root=".")
    findings = analyzer.analyze_paths(["deeplearning4j_tpu"])
    assert not active(findings), [f.location() for f in active(findings)]
