"""Property-based tests (hypothesis) for the IO boundary code — formats
and splitters must hold their invariants for ARBITRARY well-formed inputs,
not just the fixtures the example-based tests chose.

Kept small and deterministic (fixed seeds, modest example counts) so suite
time stays bounded.
"""

import numpy as np
from hypothesis import assume, given, settings, strategies as st

from deeplearning4j_tpu.datasets.svmlight import load_svmlight, save_svmlight
from deeplearning4j_tpu.text.lm_dataset import LMCorpus, LMTokenBatchIterator

_SETTINGS = dict(max_examples=25, deadline=None, derandomize=True)


@st.composite
def sparse_dataset(draw):
    n = draw(st.integers(1, 30))
    d = draw(st.integers(1, 12))
    c = draw(st.integers(2, 5))
    rng = np.random.default_rng(draw(st.integers(0, 2**31)))
    feats = np.where(rng.random((n, d)) < 0.5,
                     (rng.random((n, d)) * draw(
                         st.sampled_from([1.0, 1e-3, 1e3]))).astype(np.float32),
                     0.0).astype(np.float32)
    labels = rng.integers(0, c, n)
    return feats, labels, c


@settings(**_SETTINGS)
@given(sparse_dataset())
def test_svmlight_round_trip_any_dataset(tmp_path_factory, data):
    feats, labels, c = data
    p = tmp_path_factory.mktemp("svm") / "t.svmlight"
    save_svmlight(p, feats, labels)
    f2, l2 = load_svmlight(p, feats.shape[1], c)
    # %g prints ~6 significant digits; relative tolerance covers it
    np.testing.assert_allclose(f2, feats, rtol=1e-5, atol=1e-8)
    np.testing.assert_array_equal(l2.argmax(-1), labels)


@settings(**_SETTINGS)
@given(sparse_dataset(), st.lists(st.integers(1, 10_000),
                                  min_size=1, max_size=4))
def test_svmlight_any_split_partitions_records(tmp_path_factory, data, cuts):
    """For ANY byte cut positions, the splits partition the records exactly
    (no loss, no duplication) — the HDFS input-split contract."""
    feats, labels, c = data
    p = tmp_path_factory.mktemp("svm") / "s.svmlight"
    save_svmlight(p, feats, labels)
    size = p.stat().st_size
    bounds = sorted({0, size, *[min(x, size) for x in cuts]})
    rows = []
    for s, e in zip(bounds, bounds[1:]):
        f, _ = load_svmlight(p, feats.shape[1], c, start=s, end=e)
        rows.extend(f.tolist())
    np.testing.assert_allclose(np.asarray(rows, np.float32), feats,
                               rtol=1e-5, atol=1e-8)


@settings(**_SETTINGS)
@given(st.lists(st.text(alphabet="abcdef ", min_size=1, max_size=40),
                min_size=1, max_size=20),
       st.integers(1, 4), st.integers(2, 8), st.integers(0, 100))
def test_lm_batches_are_always_views_of_the_corpus(sents, batch, seq, seed):
    """Every batch the iterator ever emits is made of contiguous corpus
    blocks with the shift property — regardless of corpus/batch/seq/seed."""
    corpus = LMCorpus(sents)
    span = seq + 1
    # steer hypothesis toward corpora big enough for one batch (the
    # too-small case is a documented ValueError, tested elsewhere)
    assume(len(corpus.ids) // span >= batch)
    it = LMTokenBatchIterator(corpus, batch=batch, seq=seq, seed=seed)
    blocks = {tuple(b) for b in it.blocks.tolist()}
    for _ in range(min(2 * it.batches_per_epoch, 8)):
        tokens, targets = it.next()
        assert tokens.shape == (batch, seq)
        np.testing.assert_array_equal(tokens[:, 1:], targets[:, :-1])
        for t, y in zip(tokens, targets):
            assert tuple(list(t) + [y[-1]]) in blocks
