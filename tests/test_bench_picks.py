"""Guards on bench.py's evidence-based config pickers: a 0.0-throughput
row is EVIDENCE of a broken config (not missing data), and a winner must
clear a >2% margin so one noisy TUNE row can't flip the headline config
on measurement jitter."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

import bench  # noqa: E402

OK_CHECK = {"max_err": 0.001}


def _att_rows(ring, flash, check=OK_CHECK):
    rows = []
    if check is not None:
        rows.append({"flash_check": check})
    if ring is not None:
        rows.append({"attention": "ring", "batch": 64, "tokens_per_sec": ring})
    if flash is not None:
        rows.append({"attention": "flash", "batch": 64,
                     "tokens_per_sec": flash})
    return rows


def test_pick_attention_needs_margin_not_just_a_win():
    choice, reason = bench._pick_attention(_att_rows(100.0, 101.0))
    assert choice == "ring"                      # 1% is inside jitter
    choice, reason = bench._pick_attention(_att_rows(100.0, 103.0))
    assert choice == "flash" and "TUNE" in reason


def test_pick_attention_treats_zero_throughput_as_evidence():
    # flash measured at 0.0 tok/s: a broken config, not a missing row —
    # it must participate in the comparison and lose, not be skipped
    assert bench._pick_attention(_att_rows(100.0, 0.0))[0] == "ring"
    # no ring evidence at all -> conservative default, never flash-by-void
    assert bench._pick_attention(_att_rows(None, 103.0))[0] == "ring"
    # correctness battery failed -> speed win is irrelevant
    bad = {"max_err": 0.2}
    assert bench._pick_attention(_att_rows(100.0, 103.0, bad))[0] == "ring"


def _bn_rows(off, on):
    rows = []
    if off is not None:
        rows.append({"bn_fold": False, "batch": 256, "mfu": off})
    if on is not None:
        rows.append({"bn_fold": True, "batch": 256, "mfu": on})
    return rows


def test_pick_bn_fold_margin_and_missing_evidence():
    assert bench._pick_bn_fold(_bn_rows(0.30, 0.303))[0] is False  # ~1%
    on, reason = bench._pick_bn_fold(_bn_rows(0.30, 0.31))
    assert on is True and "TUNE" in reason
    assert bench._pick_bn_fold(_bn_rows(None, 0.31))[0] is False
    assert bench._pick_bn_fold(_bn_rows(0.30, None))[0] is False
    assert bench._pick_bn_fold(_bn_rows(0.30, 0.0))[0] is False


def _kernel_rows(kind, cand, incumbent, cand_ts, inc_ts, check):
    return [
        {"kernel": kind, "candidate": cand, "check": check},
        {"kernel": kind, "candidate": cand, "tokens_per_sec": cand_ts},
        {"kernel": kind, "candidate": incumbent, "tokens_per_sec": inc_ts},
    ]


def test_pick_fused_ln_and_xent_follow_the_same_gate():
    ok = {"max_err": 1e-5}
    # margin respected
    assert bench._pick_fused_ln(_kernel_rows(
        "layernorm_residual", "fused", "unfused", 101.0, 100.0, ok))[0] is False
    on, reason = bench._pick_fused_ln(_kernel_rows(
        "layernorm_residual", "fused", "unfused", 110.0, 100.0, ok))
    assert on is True and "TUNE" in reason
    # failed correctness -> stays off regardless of speed
    assert bench._pick_fused_ln(_kernel_rows(
        "layernorm_residual", "fused", "unfused", 110.0, 100.0,
        {"max_err": 0.5}))[0] is False
    # xent picker: same chain, scan incumbent
    assert bench._pick_xent([])[0] == "scan"
    choice, reason = bench._pick_xent(_kernel_rows(
        "xent", "blocked", "scan", 110.0, 100.0, ok))
    assert choice == "blocked" and "TUNE" in reason


def test_pick_attention_generic_rows_can_adopt_fused():
    ok = {"max_err": 1e-4}
    rows = _kernel_rows("attention", "fused", "ring", 110.0, 100.0, ok)
    assert bench._pick_attention(rows)[0] == "fused"


def test_stale_guard_refuses_unless_flagged():
    artifact = {"metric": "bert_base_train_tokens_per_sec", "value": 87446.7,
                "stale": True, "asof_pr": 0}
    refused = bench._stale_guard(artifact, allow_stale=False)
    assert "refused_stale_comparison" in refused
    assert refused["asof_pr"] == 0
    assert "value" not in refused            # numbers do not leak through
    allowed = bench._stale_guard(artifact, allow_stale=True)
    assert allowed["value"] == 87446.7
    assert allowed["stale_comparison_allowed_by_flag"] is True
    # fresh artifacts pass untouched
    fresh = {"metric": "m", "value": 1.0, "stale": False}
    assert bench._stale_guard(fresh, allow_stale=False) is fresh
    assert bench._stale_guard(None, allow_stale=False) is None


def test_committed_artifact_is_marked_stale():
    # the checked-in TPU numbers predate the kernel tier: a CPU run must
    # not quote them without --allow-stale
    import json
    from pathlib import Path
    path = Path(bench.__file__).resolve().parent / "LAST_VALID_TPU_BENCH.json"
    artifact = json.loads(path.read_text())
    assert artifact["stale"] is True
    assert "asof_pr" in artifact


def test_kernel_picks_table_covers_every_kind():
    table = bench._kernel_picks()
    assert set(table) == {"attention", "layernorm_residual", "xent",
                          "int8_matmul", "paged_attention",
                          "paged_attention_int8"}
    for kind, pick in table.items():
        assert "choice" in pick and "dropped" in pick, kind
