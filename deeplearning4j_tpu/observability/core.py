"""Shared observability state: the global enable flag and its contract.

The contract (DESIGN.md §9): when observability is disabled, instrumented
hot paths must do no per-step Python allocation — ``span()`` hands back one
shared no-op context manager, registry mutators return before touching the
lock, and call sites gate their ``time.perf_counter()`` reads on
``enabled()``.  The flag is process-global and module-level so the check is
one attribute load + truth test.

Default is ON (observability is cheap relative to a jitted step dispatch);
benchmarks that want the bare loop set ``DL4J_TPU_OBS=0`` or call
``disable()``.
"""

from __future__ import annotations

import os

_ENABLED: bool = os.environ.get("DL4J_TPU_OBS", "1") not in ("0", "false", "off")


def enabled() -> bool:
    """Is observability collection on?  Safe to call per-step."""
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


class _NoopSpan:
    """Shared do-nothing context manager returned by every disabled-path
    ``span()``/``time()`` call — one instance for the whole process, so the
    disabled path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs) -> None:  # matches Span.set
        pass


NOOP_SPAN = _NoopSpan()
