"""Failure flight recorder: bounded rings of recent activity, dumped to a
timestamped JSON bundle when something goes wrong.

The recorder passively listens to the tracer (every completed span) and
the metrics registry (counter/gauge mutations — chaos-site fires arrive
as ``faults.injected.<site>`` counter increments), keeping only the last
few hundred entries.  On a triggering failure — ``DivergenceError``,
supervisor retry, ``CheckpointCorruptError``, a serving 429 burst —
``dump(trigger, extra=...)`` writes everything plus a full metrics
snapshot to ``<dump_dir>/flightrec-<trigger>-<ms>.json``, so the moments
*before* the crash survive the crash.

Zero-overhead contract: every record method returns before touching the
lock when observability is disabled; the listeners are registered once at
import and see nothing while disabled (the tracer/registry short-circuit
upstream).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any

from . import core
from .metrics import METRICS
from .tracing import TRACER

_SPAN_RING = 256
_METRIC_RING = 512
_FAULT_RING = 64
_429_RING = 64
_SPILL_RING = 64


class FlightRecorder:
    """Bounded rings of recent spans / metric deltas / chaos fires."""

    def __init__(self, dump_dir: str | Path | None = None):
        self._lock = threading.Lock()
        self.spans: deque[dict[str, Any]] = deque(maxlen=_SPAN_RING)
        self.metric_events: deque[tuple[str, str, float]] = deque(
            maxlen=_METRIC_RING)
        self.faults: deque[dict[str, Any]] = deque(maxlen=_FAULT_RING)
        self._429s: deque[float] = deque(maxlen=_429_RING)
        self._last_burst_dump = 0.0
        self.burst_n = 8            # 429s ...
        self.burst_window_s = 2.0   # ... within this window -> dump
        self.burst_cooldown_s = 30.0
        self._spills: deque[tuple[float, str]] = deque(maxlen=_SPILL_RING)
        self._last_spill_dump = 0.0
        self.spill_burst_n = 8          # spillovers ...
        self.spill_window_s = 2.0       # ... within this window -> dump
        self.spill_cooldown_s = 30.0
        self.dump_dir = Path(
            dump_dir if dump_dir is not None
            else os.environ.get("DL4J_TPU_FLIGHTREC_DIR", "flightrec"))
        self._seq = 0

    # ------------------------------------------------------------- listeners
    def record_span(self, ev: dict[str, Any]) -> None:
        """Tracer listener: keep a compact copy of each completed span."""
        if not core.enabled():
            return
        args = ev.get("args") or {}
        rec = {"name": ev["name"], "ts": ev["ts"], "dur": ev["dur"],
               "trace_id": args.get("trace_id")}
        err = args.get("error")
        if err:
            rec["error"] = err
        step = args.get("step")
        if step is not None:
            rec["step"] = step
        with self._lock:
            self.spans.append(rec)

    def record_metric(self, kind: str, name: str, value: float) -> None:
        """Registry listener: counter/gauge deltas; chaos-site fires show
        up as ``faults.injected.<site>`` counter increments."""
        if not core.enabled():
            return
        with self._lock:
            self.metric_events.append((kind, name, value))
            if name.startswith("faults.injected."):
                self.faults.append({"site": name[len("faults.injected."):],
                                    "time": time.time()})

    # ------------------------------------------------------------- triggers
    def note_429(self) -> Path | None:
        """Record one backpressure rejection; dump on a burst (``burst_n``
        within ``burst_window_s``, rate-limited by ``burst_cooldown_s``)."""
        if not core.enabled():
            return None
        now = time.monotonic()
        with self._lock:
            self._429s.append(now)
            burst = (len(self._429s) >= self.burst_n
                     and now - self._429s[-self.burst_n] <= self.burst_window_s
                     and now - self._last_burst_dump >= self.burst_cooldown_s)
            if burst:
                self._last_burst_dump = now
        if burst:
            return self.dump("serving_429_burst",
                             extra={"rejections_in_window": self.burst_n,
                                    "window_s": self.burst_window_s})
        return None

    def note_spillover(self, replica: str) -> Path | None:
        """Record one router spillover (a request shed off its affinity
        replica).  A burst — ``spill_burst_n`` within ``spill_window_s``
        — means a replica is effectively unavailable while still admitting
        probes, so dump a bundle naming the replicas that shed, rate-
        limited like :meth:`note_429`."""
        if not core.enabled():
            return None
        now = time.monotonic()
        with self._lock:
            self._spills.append((now, replica))
            burst = (len(self._spills) >= self.spill_burst_n
                     and now - self._spills[-self.spill_burst_n][0]
                     <= self.spill_window_s
                     and now - self._last_spill_dump >= self.spill_cooldown_s)
            if burst:
                self._last_spill_dump = now
                recent = [r for _, r in self._spills]
            else:
                recent = []
        if burst:
            return self.dump("router_spillover_burst",
                             extra={"spillovers_in_window": self.spill_burst_n,
                                    "window_s": self.spill_window_s,
                                    "recent_replicas": recent})
        return None

    # ------------------------------------------------------------- dump
    def dump(self, trigger: str, extra: dict[str, Any] | None = None
             ) -> Path | None:
        """Write the rings + a metrics snapshot to a timestamped bundle.
        Never raises (a broken disk must not mask the original failure)."""
        if not core.enabled():
            return None
        try:
            with self._lock:
                self._seq += 1
                bundle = {
                    "trigger": trigger,
                    "time": time.time(),
                    "spans": list(self.spans),
                    "metric_events": [list(e) for e in self.metric_events],
                    "faults": list(self.faults),
                    "extra": extra or {},
                }
                seq = self._seq
            bundle["metrics"] = METRICS.snapshot()
            self.dump_dir.mkdir(parents=True, exist_ok=True)
            name = f"flightrec-{trigger}-{int(time.time() * 1000)}-{seq}.json"
            path = self.dump_dir / name
            path.write_text(json.dumps(bundle, default=str))
            return path
        except Exception:
            return None

    def clear(self) -> None:
        with self._lock:
            self.spans.clear()
            self.metric_events.clear()
            self.faults.clear()
            self._429s.clear()
            self._last_burst_dump = 0.0
            self._spills.clear()
            self._last_spill_dump = 0.0


FLIGHTREC = FlightRecorder()

# Passive wiring: the recorder sees every completed span and every
# counter/gauge mutation for the life of the process.
TRACER.add_listener(FLIGHTREC.record_span)
METRICS.add_listener(FLIGHTREC.record_metric)
