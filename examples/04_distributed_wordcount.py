"""Distributed word count on OS-process workers — the scaleout hello world.

The reference ships a word-count performer as the smallest end-to-end
demonstration of its Job/Performer/StateTracker scaleout SPI
(``scaleout/perform/text/``). Here the same idea runs with real OS
processes over the file-backed state plane: the master shards lines into
jobs, worker processes count words and spill updates to disk, and a
router aggregates the counts.

Run:  python examples/04_distributed_wordcount.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deeplearning4j_tpu.parallel.performers import WordCountRouter
from deeplearning4j_tpu.parallel.procrunner import ProcessDistributedRunner
from deeplearning4j_tpu.parallel.scaleout import CollectionJobIterator

LINES = [
    "to be or not to be",
    "that is the question",
    "whether tis nobler in the mind",
    "to suffer the slings and arrows",
    "or to take arms against a sea of troubles",
]


def main():
    with tempfile.TemporaryDirectory() as state:
        runner = ProcessDistributedRunner(
            CollectionJobIterator(LINES),
            "deeplearning4j_tpu.parallel.performers:WordCountPerformer",
            state_dir=os.path.join(state, "st"), n_workers=2,
            router_cls=WordCountRouter,
            worker_env={"JAX_PLATFORMS": "cpu"})
        counts = runner.run(max_wall_s=120.0)
    top = sorted(counts.items(), key=lambda kv: -kv[1])[:5]
    print("top words:", top)
    assert counts["to"] == 4 and counts["the"] == 3


if __name__ == "__main__":
    main()
