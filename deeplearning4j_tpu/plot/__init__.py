"""L4 — visualization (reference: ``plot/``)."""

from .tsne import BarnesHutTsne, Tsne
from .render_app import EmbeddingRenderServer, render_word_vectors
from .renderers import FilterRenderer, NeuralNetPlotter, draw_mnist_grid

__all__ = ["BarnesHutTsne", "Tsne", "EmbeddingRenderServer",
           "render_word_vectors", "FilterRenderer", "NeuralNetPlotter",
           "draw_mnist_grid"]
