"""Sharding & collective consistency tier tests (SH01-SH04, NM01) plus
the runtime ShardGuard, the ``--diff`` incremental mode, and the
hostile-input (skip-don't-crash) contract.

Same contract as test_graftlint.py / test_concurrency_lint.py: every
rule is demonstrated on a known-bad fixture AND shown quiet on the
corresponding known-good rewrite, the pragma / baseline plumbing
round-trips, and the gauges publish.  The seeded regression test at the
bottom plants a wrong-axis collective and a mismatched NamedSharding and
shows the static tier catches both, then dispatches through a ShardGuard
wrap and shows the runtime half counts the same resharding live.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from deeplearning4j_tpu.analysis import (
    ACTIVE,
    BASELINED,
    SUPPRESSED,
    Analyzer,
    Baseline,
    active,
    all_rules,
    emit_metrics,
)
from deeplearning4j_tpu.analysis.sharding import (
    axis_registry,
    set_axis_registry,
    sharding_info,
)
from deeplearning4j_tpu.analysis.shardguard import (
    SHARDGUARD,
    ShardGuard,
    shardguard_active,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")


def lint(source, only=None, baseline=None, path="snippet.py"):
    rules = [all_rules()[only]] if only else None
    analyzer = Analyzer(rules=rules, baseline=baseline)
    findings = analyzer.analyze_source(textwrap.dedent(source), path)
    assert not analyzer.errors
    return findings


def rules_hit(findings):
    return {f.rule for f in findings if f.status == ACTIVE}


@pytest.fixture(autouse=True)
def _restore_axis_registry():
    yield
    set_axis_registry(None)


# ------------------------------------------------------------------- SH01

SH01_BAD = """
    import jax
    import numpy as np
    from jax import lax, shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def step(x):
        return lax.psum(x, "tp")   # axis the mesh never binds

    stepped = shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                        out_specs=P("dp"))
"""


def test_sh01_fires_on_unbound_axis():
    findings = active(lint(SH01_BAD, only="SH01"))
    assert len(findings) == 1
    assert "psum" in findings[0].message
    assert "'tp'" in findings[0].message


def test_sh01_quiet_when_axis_bound():
    src = SH01_BAD.replace('lax.psum(x, "tp")', 'lax.psum(x, "dp")')
    assert active(lint(src, only="SH01")) == []


def test_sh01_quiet_when_mesh_unresolvable():
    # the mesh arrives as a parameter: binding is unknown, not wrong
    src = """
        from jax import lax, shard_map
        from jax.sharding import PartitionSpec as P

        def build(mesh):
            def step(x):
                return lax.psum(x, "tp")
            return shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=P("dp"))
    """
    assert active(lint(src, only="SH01")) == []


def test_sh01_quiet_when_never_wrapped():
    # a collectives-wrapper module: axis comes in as a parameter, the
    # function is never visibly shard_map'ed — confidence says silent
    src = """
        from jax import lax

        def psum_helper(x, axis):
            return lax.psum(x, axis)
    """
    assert active(lint(src, only="SH01")) == []


def test_sh01_interprocedural_propagation():
    # the collective lives in a helper CALLED from the wrapped step
    src = """
        import jax
        import numpy as np
        from jax import lax, shard_map
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))

        def reduce_wrong(x):
            return lax.pmean(x, "tp")

        def step(x):
            return reduce_wrong(x)

        stepped = shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                            out_specs=P("dp"))
    """
    findings = active(lint(src, only="SH01"))
    assert len(findings) == 1
    assert "pmean" in findings[0].message


def test_sh01_pmap_axis_name_binds():
    src = """
        import jax
        from jax import lax

        def step(x):
            return lax.psum(x, "tp")

        stepped = jax.pmap(step, axis_name="dp")
    """
    findings = active(lint(src, only="SH01"))
    assert len(findings) == 1
    good = src.replace('axis_name="dp"', 'axis_name="tp"')
    assert active(lint(good, only="SH01")) == []


def test_sh01_mesh_helper_calls_bind_registry_axes():
    # make_mesh() binds the whole registry; local_mesh binds dp only
    src = """
        from jax import lax, shard_map
        from jax.sharding import PartitionSpec as P
        from deeplearning4j_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()

        def step(x):
            return lax.psum(x, "tp")

        stepped = shard_map(step, mesh=mesh, in_specs=(P("tp"),),
                            out_specs=P("tp"))
    """
    assert active(lint(src, only="SH01")) == []


# ------------------------------------------------------------------- SH02

SH02_BAD = """
    from jax.sharding import PartitionSpec as P

    spec = P("dpx", None)   # typo'd axis name
"""


def test_sh02_fires_on_unknown_axis_name():
    findings = active(lint(SH02_BAD, only="SH02"))
    assert len(findings) == 1
    assert "'dpx'" in findings[0].message
    assert "canonical axis registry" in findings[0].message


def test_sh02_quiet_on_registry_axis():
    src = SH02_BAD.replace('"dpx"', '"dp"')
    assert active(lint(src, only="SH02")) == []


def test_sh02_checks_multi_axis_dim_tuples():
    src = """
        from jax.sharding import PartitionSpec as P

        spec = P(("dp", "tpx"), None)
    """
    findings = active(lint(src, only="SH02"))
    assert len(findings) == 1
    assert "'tpx'" in findings[0].message


def test_sh02_registry_hook():
    set_axis_registry(("rows", "cols"))
    assert axis_registry() == frozenset({"rows", "cols"})
    src = """
        from jax.sharding import PartitionSpec as P

        spec = P("rows")
        bad = P("dp")
    """
    findings = active(lint(src, only="SH02"))
    assert len(findings) == 1
    assert "'dp'" in findings[0].message
    set_axis_registry(None)
    assert "dp" in axis_registry()


def test_sh02_registry_parsed_from_mesh_module():
    # the linter's ground truth IS parallel/mesh.py — never disagree
    from deeplearning4j_tpu.parallel import mesh as mesh_mod

    assert axis_registry() == frozenset(mesh_mod.AXES)


# ------------------------------------------------------------------- SH03

SH03_IN_BAD = """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def step(x, y):
        return x + y

    stepped = shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                        out_specs=P("dp"))
"""


def test_sh03_fires_on_in_specs_arity_mismatch():
    findings = active(lint(SH03_IN_BAD, only="SH03"))
    assert len(findings) == 1
    assert "in_specs has 1" in findings[0].message
    assert "`step`" in findings[0].message


def test_sh03_quiet_when_in_specs_match():
    src = SH03_IN_BAD.replace('in_specs=(P("dp"),)',
                              'in_specs=(P("dp"), P("dp"))')
    assert active(lint(src, only="SH03")) == []


def test_sh03_defaults_widen_the_accepted_range():
    src = """
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        def step(x, y, scale=1.0):
            return x + y * scale

        stepped = shard_map(step, mesh=mesh, in_specs=(P("dp"), P("dp")),
                            out_specs=P("dp"))
    """
    assert active(lint(src, only="SH03")) == []


def test_sh03_fires_on_out_specs_arity_mismatch():
    src = """
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        def step(x):
            return x, x * 2

        stepped = shard_map(step, mesh=mesh, in_specs=(P("dp"),),
                            out_specs=(P("dp"),))
    """
    findings = active(lint(src, only="SH03"))
    assert len(findings) == 1
    assert "out_specs has 1" in findings[0].message
    assert "2-tuple" in findings[0].message


def test_sh03_vararg_and_variable_specs_out_of_scope():
    src = """
        from jax import shard_map

        def step(*xs):
            return xs[0]

        stepped = shard_map(step, mesh=mesh, in_specs=specs,
                            out_specs=out)
    """
    assert active(lint(src, only="SH03")) == []


def test_sh03_same_named_nested_defs_disambiguate_by_lineno():
    # the sharded_embedding idiom: several builders each define `local`
    src = """
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        def build_a(mesh):
            def local(x):
                return x
            return shard_map(local, mesh=mesh, in_specs=(P("dp"),),
                             out_specs=P("dp"))

        def build_b(mesh):
            def local(x, y):
                return x + y
            return shard_map(local, mesh=mesh, in_specs=(P("dp"), P("dp")),
                             out_specs=P("dp"))
    """
    assert active(lint(src, only="SH03")) == []


# ------------------------------------------------------------------- SH04

SH04_BAD = """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from deeplearning4j_tpu.parallel.mesh import make_mesh

    mesh = make_mesh()
    step = jax.jit(fn, donate_argnums=(0,),
                   in_shardings=(NamedSharding(mesh, P()),))

    def run(x):
        x = jax.device_put(x, NamedSharding(mesh, P("dp")))
        return step(x)
"""


def test_sh04_fires_on_placement_vs_declaration_mismatch():
    findings = active(lint(SH04_BAD, only="SH04"))
    assert len(findings) == 1
    assert "donated at position 0" in findings[0].message
    assert "use-after-free" in findings[0].message


def test_sh04_quiet_when_placement_matches():
    src = SH04_BAD.replace('jax.device_put(x, NamedSharding(mesh, P("dp")))',
                           'jax.device_put(x, NamedSharding(mesh, P()))')
    assert active(lint(src, only="SH04")) == []


def test_sh04_rebinding_clears_the_placed_signature():
    src = SH04_BAD.replace(
        "        return step(x)",
        "        x = transform(x)\n        return step(x)")
    assert active(lint(src, only="SH04")) == []


def test_sh04_variable_shardings_out_of_scope():
    src = """
        import jax

        step = jax.jit(fn, donate_argnums=(0,), in_shardings=shardings)

        def run(x):
            x = jax.device_put(x, sh)
            return step(x)
    """
    assert active(lint(src, only="SH04")) == []


# ------------------------------------------------------------------- NM01

NM01_LOGSUMEXP_BAD = """
    import jax.numpy as jnp

    def lse(x):
        return jnp.log(jnp.sum(jnp.exp(x)))
"""

NM01_SOFTMAX_BAD = """
    import jax.numpy as jnp

    def softmax(x):
        e = jnp.exp(x)
        return e / jnp.sum(e, axis=-1, keepdims=True)
"""


def test_nm01_fires_on_naive_logsumexp_in_ops():
    findings = active(lint(NM01_LOGSUMEXP_BAD, only="NM01",
                           path="ops/losses.py"))
    assert len(findings) == 1
    assert "logsumexp" in findings[0].message


def test_nm01_fires_on_named_exp_softmax_in_models():
    findings = active(lint(NM01_SOFTMAX_BAD, only="NM01",
                           path="models/transformer.py"))
    assert len(findings) == 1
    assert "softmax" in findings[0].message


def test_nm01_quiet_with_max_subtraction():
    src = """
        import jax.numpy as jnp

        def lse(x):
            m = jnp.max(x)
            return m + jnp.log(jnp.sum(jnp.exp(x - m)))
    """
    assert active(lint(src, only="NM01", path="ops/losses.py")) == []


def test_nm01_quiet_outside_ops_and_models():
    assert active(lint(NM01_LOGSUMEXP_BAD, only="NM01",
                       path="serving/engine.py")) == []


def test_nm01_clip_guard_quiets():
    src = """
        import jax.numpy as jnp

        def lse(x):
            x = jnp.clip(x, -30.0, 30.0)   # bounded by construction
            return jnp.log(jnp.sum(jnp.exp(x)))
    """
    assert active(lint(src, only="NM01", path="ops/losses.py")) == []


# ------------------------------------------- registry / pragmas / baseline

def test_registry_has_twenty_seven_rules_incl_disagg_tier():
    rules = all_rules()
    assert len(rules) == 27
    for rid in ("SH01", "SH02", "SH03", "SH04", "NM01", "CT01", "DG01"):
        assert rid in rules
        assert rules[rid].title


@pytest.mark.parametrize("rid,src", [
    ("SH01", SH01_BAD), ("SH02", SH02_BAD), ("SH03", SH03_IN_BAD),
    ("SH04", SH04_BAD), ("NM01", NM01_LOGSUMEXP_BAD),
])
def test_sharding_rules_pragma_roundtrip(rid, src):
    path = "ops/x.py" if rid == "NM01" else "snippet.py"
    findings = lint(src, only=rid, path=path)
    assert [f.status for f in findings] == [ACTIVE]
    line = textwrap.dedent(src).splitlines()[findings[0].line - 1]
    suppressed_src = textwrap.dedent(src).replace(
        line, line + f"  # graftlint: disable={rid}")
    findings = lint(suppressed_src, only=rid, path=path)
    assert [f.status for f in findings] == [SUPPRESSED]


@pytest.mark.parametrize("rid,src", [
    ("SH01", SH01_BAD), ("SH02", SH02_BAD), ("SH03", SH03_IN_BAD),
    ("SH04", SH04_BAD), ("NM01", NM01_SOFTMAX_BAD),
])
def test_sharding_rules_baseline_roundtrip(rid, src):
    path = "models/x.py" if rid == "NM01" else "snippet.py"
    findings = active(lint(src, only=rid, path=path))
    assert findings
    bl = Baseline.from_findings(findings, justification="pre-tier legacy")
    refound = lint(src, only=rid, baseline=bl, path=path)
    assert [f.status for f in refound] == [BASELINED]


def test_emit_metrics_publishes_sharding_gauges():
    from deeplearning4j_tpu import observability as obs

    obs.enable()
    obs.METRICS.reset()
    emit_metrics(lint(SH01_BAD, only="SH01"), registry=obs.METRICS,
                 skipped=3)
    gauges = obs.METRICS.snapshot()["gauges"]
    assert gauges["graftlint.violations.SH01"] == 1
    assert gauges["graftlint.violations.SH02"] == 0
    assert gauges["graftlint.skipped_files"] == 3


# --------------------------------------------------------- hostile inputs

def test_bad_syntax_fixture_skips_gracefully():
    fixture = os.path.join(FIXTURES, "graftlint_bad_syntax.py")
    analyzer = Analyzer()
    findings = analyzer.analyze_paths([fixture])
    assert findings == []
    assert analyzer.visited_files == 1
    assert analyzer.skipped_files == 1
    assert len(analyzer.errors) == 1 and "graftlint_bad_syntax" in \
        analyzer.errors[0]


def test_nul_byte_and_non_utf8_sources_skip_gracefully(tmp_path):
    nul = tmp_path / "nul.py"
    nul.write_text("x = 1\x00\n")
    binary = tmp_path / "bin.py"
    binary.write_bytes(b"\xff\xfe\x00\x00 not python")
    ok = tmp_path / "ok.py"
    ok.write_text("x = 1\n")
    analyzer = Analyzer()
    findings = analyzer.analyze_paths([str(tmp_path)])
    assert findings == []          # the good file still parsed clean
    assert analyzer.skipped_files == 2
    assert len(analyzer.errors) == 2
    # the skip is COUNTED, not silent: the gauge publishes it
    from deeplearning4j_tpu import observability as obs
    obs.enable()
    obs.METRICS.reset()
    emit_metrics(findings, registry=obs.METRICS,
                 skipped=analyzer.skipped_files)
    assert obs.METRICS.snapshot()["gauges"]["graftlint.skipped_files"] == 2


def test_crashing_rule_is_contained(monkeypatch):
    class Bomb:
        id = "XX99"
        title = "always crashes"

        def check(self, module):
            raise RuntimeError("boom")

    analyzer = Analyzer(rules=[Bomb(), all_rules()["SH02"]])
    findings = analyzer.analyze_source(
        textwrap.dedent(SH02_BAD), "snippet.py")
    assert rules_hit(findings) == {"SH02"}   # SH02 still ran
    assert any("XX99" in e for e in analyzer.errors)


# ------------------------------------------------------------- --diff mode

def _git(cwd, *args):
    subprocess.run(
        ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
        cwd=cwd, check=True, capture_output=True)


@pytest.fixture
def diff_repo(tmp_path, monkeypatch):
    """A tiny two-file git repo with exactly one file changed since HEAD,
    with tools.graftlint retargeted at it."""
    repo = tmp_path / "repo"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "clean.py").write_text("x = 1\n")
    (pkg / "dirty.py").write_text("y = 2\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    (pkg / "dirty.py").write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "spec = P('dpx')\n")
    import tools.graftlint as gl

    monkeypatch.setattr(gl, "_REPO_ROOT", str(repo))
    return repo, gl


def test_diff_mode_visits_only_changed_files(diff_repo, capsys):
    repo, gl = diff_repo
    rc = gl.main(["--diff", "HEAD", "--json", "--no-metrics",
                  "--baseline", str(repo / "no-baseline.json"),
                  str(repo / "pkg")])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["visited_files"] == 1           # NOT a full-tree walk
    assert {f["rule"] for f in payload["findings"]} == {"SH02"}

    # and it agrees with the full run on the changed file's findings
    rc = gl.main(["--json", "--no-metrics",
                  "--baseline", str(repo / "no-baseline.json"),
                  str(repo / "pkg")])
    assert rc == 0
    full = json.loads(capsys.readouterr().out)
    assert full["visited_files"] == 2
    def key(f):
        return (f["rule"], os.path.basename(f["path"]), f["line"])
    assert ({key(f) for f in payload["findings"]}
            <= {key(f) for f in full["findings"]})
    assert ({key(f) for f in full["findings"] if "dirty" in f["path"]}
            == {key(f) for f in payload["findings"]})


def test_diff_mode_unknown_ref_falls_back_to_full_tree(diff_repo, capsys):
    repo, gl = diff_repo
    rc = gl.main(["--diff", "no-such-ref", "--json", "--no-metrics",
                  "--baseline", str(repo / "no-baseline.json"),
                  str(repo / "pkg")])
    assert rc == 0
    captured = capsys.readouterr()
    assert "falling back to full tree" in captured.err
    assert json.loads(captured.out)["visited_files"] == 2


def test_diff_mode_no_changes_short_circuits(diff_repo, capsys):
    repo, gl = diff_repo
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "absorb")
    rc = gl.main(["--diff", "HEAD", "--no-metrics",
                  "--baseline", str(repo / "no-baseline.json"),
                  str(repo / "pkg")])
    assert rc == 0
    assert "no .py files changed" in capsys.readouterr().out


# ------------------------------------------------------------- ShardGuard

@pytest.fixture
def mesh8():
    import jax
    from deeplearning4j_tpu.parallel.mesh import local_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device mesh")
    return local_mesh(8)


def _sharded(mesh, spec, n=8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    return jax.device_put(jnp.arange(float(n)), NamedSharding(mesh, spec))


def test_shardguard_explicit_mode_flags_drifted_input(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh8, P())
    g = ShardGuard().enable()
    f = g.wrap("t.step", jax.jit(lambda x: x + 1), in_shardings=(rep,))
    f(_sharded(mesh8, P()))
    assert not g.violations()
    f(_sharded(mesh8, P("dp")))
    assert g.counts()["resharded-input"] == 1
    [v] = [v for v in g.violations() if v.kind == "resharded-input"]
    assert v.site == "t.step"
    # the unchecked output side runs in baseline mode — the drifted
    # dispatch moved the result too, and the guard saw that as well
    assert g.counts()["resharded-output"] == 1


def test_shardguard_baseline_mode_flags_drift_not_first_placement(mesh8):
    import jax
    from jax.sharding import PartitionSpec as P

    g = ShardGuard().enable()
    f = g.wrap("t.step", jax.jit(lambda x: x * 2))
    f(_sharded(mesh8, P("dp")))          # first call SETS the baseline
    f(_sharded(mesh8, P("dp")))
    assert not g.violations()
    f(_sharded(mesh8, P()))              # later drift is the violation
    assert g.counts()["resharded-input"] == 1


def test_shardguard_output_expectations(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh8, P())
    dp = NamedSharding(mesh8, P("dp"))
    g = ShardGuard().enable()
    f = g.wrap("t.step", jax.jit(lambda x: x, out_shardings=dp),
               out_shardings=(rep,))
    f(_sharded(mesh8, P()))
    assert g.counts()["resharded-output"] == 1


def test_shardguard_disabled_costs_nothing_and_records_nothing(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    g = ShardGuard()          # never enabled
    f = g.wrap("t.step", jax.jit(lambda x: x + 1),
               in_shardings=(NamedSharding(mesh8, P()),))
    f(_sharded(mesh8, P("dp")))
    assert g.violations() == [] and g.counts()["resharded-input"] == 0


def test_shardguard_dedups_violations_but_counts_occurrences(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    g = ShardGuard().enable()
    f = g.wrap("t.step", jax.jit(lambda x: x + 1),
               in_shardings=(NamedSharding(mesh8, P()),))
    for _ in range(3):
        f(_sharded(mesh8, P("dp")))
    assert len(g.violations()) == 1          # one finding per leaf/site
    assert g.counts()["resharded-input"] == 3  # every dispatch counted


def test_shardguard_reset_and_report(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    g = ShardGuard().enable()
    assert g.report() == "shardguard: clean (0 violations)"
    f = g.wrap("t.step", jax.jit(lambda x: x + 1),
               in_shardings=(NamedSharding(mesh8, P()),))
    f(_sharded(mesh8, P("dp")))
    assert "t.step" in g.report()
    g.reset()
    assert g.violations() == [] and g.counts()["resharded-input"] == 0


def test_shardguard_emit_metrics_gauges(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu import observability as obs

    g = ShardGuard().enable()
    f = g.wrap("t.step", jax.jit(lambda x: x + 1),
               in_shardings=(NamedSharding(mesh8, P()),))
    f(_sharded(mesh8, P("dp")))
    f(_sharded(mesh8, P("dp")))
    obs.enable()
    obs.METRICS.reset()
    g.emit_metrics()
    gauges = obs.METRICS.snapshot()["gauges"]
    assert gauges["shardguard.violations.resharded_input"] == 2
    assert gauges["shardguard.violations.resharded_output"] == 0


def test_shardguard_wrapper_forwards_lower(mesh8):
    import jax

    g = ShardGuard()
    f = g.wrap("t.step", jax.jit(lambda x: x + 1))
    lowered = f.lower(_sharded(mesh8, jax.sharding.PartitionSpec()))
    assert lowered.compile() is not None


@pytest.mark.shardguard
def test_shardguard_marker_enables_the_singleton(mesh8):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    assert SHARDGUARD.enabled
    f = SHARDGUARD.wrap("t.clean", jax.jit(lambda x: x + 1),
                        in_shardings=(NamedSharding(mesh8, P()),))
    f(_sharded(mesh8, P()))     # clean dispatch: the fixture's teardown
    # assertion (zero violations) is the actual test


def test_shardguard_trainer_sync_step_clean_under_guard():
    import numpy as np

    from deeplearning4j_tpu.datasets.dataset import DataSet
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer

    def loss_fn(p, x, y, key=None):
        return ((x @ p["w"] - y) ** 2).mean()

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(4, 1))
    x = rng.normal(size=(16, 4)).astype(np.float32)
    y = (x @ w_true).astype(np.float32)

    with shardguard_active() as g:
        tr = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
        state = tr.init_state({"w": np.zeros((4, 1), np.float32)})
        for _ in range(3):
            state, _ = tr.step(state, x, y)
        assert not g.violations(), g.report()


# --------------------------------------------------- seeded regression

PLANTED = """
    import jax
    import numpy as np
    from jax import lax, shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("dp",))

    def step(x):
        return lax.psum(x, "tp")          # planted: wrong-axis collective

    stepped = shard_map(step, mesh=mesh, in_specs=(P("dpz"),),
                        out_specs=P("dp"))  # planted: axis nobody creates
"""


def test_seeded_regression_static_and_runtime(mesh8):
    """Acceptance seed: the planted wrong-axis collective and mismatched
    axis name are caught statically (SH01 + SH02), and the same class of
    mistake made at runtime — dispatching against a drifted placement —
    is counted live by ShardGuard."""
    findings = active(lint(PLANTED))
    assert {"SH01", "SH02"} <= rules_hit(findings)

    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu import observability as obs

    g = ShardGuard().enable()
    f = g.wrap("regress.step", jax.jit(lambda x: x + 1),
               in_shardings=(NamedSharding(mesh8, P()),))
    f(_sharded(mesh8, P()))
    assert not g.violations()
    f(_sharded(mesh8, P("dp")))          # the runtime mismatch, counted
    assert g.counts()["resharded-input"] == 1
    obs.enable()
    obs.METRICS.reset()
    g.emit_metrics()
    assert obs.METRICS.snapshot()["gauges"][
        "shardguard.violations.resharded_input"] == 1
