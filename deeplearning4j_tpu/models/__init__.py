"""Model zoo.

Reference-parity families (SURVEY.md §2.2): MLP/DBN and LeNet-style conv
nets are built from ``nn`` configs (see ``zoo.py``); LSTM classifier with
beam search in ``lstm.py``; the NLP embedding models live in ``..text``.

Beyond-v0 north-star families (BASELINE.json configs): ``transformer.py`` —
a BERT/GPT-class encoder with explicit SPMD sharding (dp/tp/sp with ring
attention) — and ``resnet.py``.
"""

from .transformer import TransformerConfig, TransformerLM

__all__ = ["TransformerConfig", "TransformerLM"]
