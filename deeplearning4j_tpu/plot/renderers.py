"""Weight/activation renderers.

Capability match of ``plot/NeuralNetPlotter.java:32`` (weight & gradient
histograms — the reference shells out to a bundled Python/matplotlib script,
``:250``; here matplotlib is called in-process), ``plot/FilterRenderer.java``
(weight-filter grids to PNG), and ``datasets/mnist/draw`` (render
reconstructions).  All writes are headless (Agg backend) files.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np


def _plt():
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    return plt


class NeuralNetPlotter:
    """Histogram plots of params/gradients/activations per layer."""

    def plot_network_gradient(self, params, grads, out_dir: str | Path) -> list[Path]:
        out_dir = Path(out_dir)
        out_dir.mkdir(parents=True, exist_ok=True)
        plt = _plt()
        written = []
        for i, (p, g) in enumerate(zip(params, grads)):
            fig, axes = plt.subplots(2, max(len(p), 1), figsize=(4 * len(p), 6),
                                     squeeze=False)
            for j, key in enumerate(sorted(p)):
                axes[0][j].hist(np.asarray(p[key]).ravel(), bins=50)
                axes[0][j].set_title(f"layer{i} {key}")
                axes[1][j].hist(np.asarray(g[key]).ravel(), bins=50)
                axes[1][j].set_title(f"layer{i} d{key}")
            path = out_dir / f"layer_{i}.png"
            fig.savefig(path)
            plt.close(fig)
            written.append(path)
        return written

    def plot_activations(self, activations, out_path: str | Path) -> Path:
        plt = _plt()
        fig, ax = plt.subplots(figsize=(6, 4))
        for i, a in enumerate(activations):
            ax.hist(np.asarray(a).ravel(), bins=50, alpha=0.5, label=f"layer {i}")
        ax.legend()
        fig.savefig(out_path)
        plt.close(fig)
        return Path(out_path)


class FilterRenderer:
    """Render first-layer weight filters as an image grid
    (``FilterRenderer.java``)."""

    def render_filters(self, weights, out_path: str | Path,
                       filter_shape: tuple[int, int] | None = None,
                       cols: int = 10) -> Path:
        w = np.asarray(weights)
        if w.ndim == 2:  # (n_in, n_filters) dense weights -> square images
            side = int(np.sqrt(w.shape[0]))
            filter_shape = filter_shape or (side, side)
            filters = w.T.reshape(-1, *filter_shape)
        elif w.ndim == 4:  # (fh, fw, cin, cout) conv weights
            filters = np.moveaxis(w, -1, 0)[:, :, :, 0]
        else:
            raise ValueError(f"cannot render weights of ndim {w.ndim}")
        n = filters.shape[0]
        rows = (n + cols - 1) // cols
        fh, fw = filters.shape[1:3]
        grid = np.zeros((rows * (fh + 1), cols * (fw + 1)), np.float32)
        for i, f in enumerate(filters):
            r, c = divmod(i, cols)
            lo, hi = f.min(), f.max()
            norm = (f - lo) / (hi - lo + 1e-12)
            grid[r * (fh + 1):r * (fh + 1) + fh,
                 c * (fw + 1):c * (fw + 1) + fw] = norm
        plt = _plt()
        fig, ax = plt.subplots(figsize=(cols, rows))
        ax.imshow(grid, cmap="gray")
        ax.axis("off")
        fig.savefig(out_path, bbox_inches="tight")
        plt.close(fig)
        return Path(out_path)


def draw_mnist_grid(images, out_path: str | Path, cols: int = 10,
                    side: int | None = None) -> Path:
    """Render MNIST-style images (reconstructions) in a grid
    (``datasets/mnist/draw/DrawReconstruction``)."""
    imgs = np.asarray(images)
    if imgs.ndim == 2:
        side = side or int(np.sqrt(imgs.shape[1]))
        imgs = imgs.reshape(-1, side, side)
    elif imgs.ndim == 4:
        imgs = imgs[..., 0]
    n = imgs.shape[0]
    rows = (n + cols - 1) // cols
    h, w = imgs.shape[1:3]
    grid = np.zeros((rows * (h + 1), cols * (w + 1)), np.float32)
    for i, im in enumerate(imgs):
        r, c = divmod(i, cols)
        grid[r * (h + 1):r * (h + 1) + h, c * (w + 1):c * (w + 1) + w] = im
    plt = _plt()
    fig, ax = plt.subplots(figsize=(cols, rows))
    ax.imshow(grid, cmap="gray")
    ax.axis("off")
    fig.savefig(out_path, bbox_inches="tight")
    plt.close(fig)
    return Path(out_path)


def plot_vocab_2d(words, coords, out_path: str | Path, max_words: int = 200) -> Path:
    """Scatter labeled word embeddings (t-SNE output) — parity with the
    NLP ``plotVocab`` / dropwizard render UI's plot."""
    plt = _plt()
    coords = np.asarray(coords)
    fig, ax = plt.subplots(figsize=(10, 10))
    for w, (x, y) in list(zip(words, coords))[:max_words]:
        ax.scatter(x, y, s=4)
        ax.annotate(w, (x, y), fontsize=7)
    fig.savefig(out_path, bbox_inches="tight")
    plt.close(fig)
    return Path(out_path)
