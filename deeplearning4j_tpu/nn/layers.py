"""Functional layer modules with named param tables.

TPU-native re-design of the reference's layer system (``nn/api/Layer.java:
18-93`` contract, ``nn/layers/*`` impls, ``nn/params/*ParamInitializer`` and
``nn/layers/factory/LayerFactories``).  Key differences by design:

- layers are *stateless* descriptors: ``init(key) -> params`` returns a dict
  pytree; ``activate(params, x)`` is pure.  The reference's mutable
  ``Layer.setParams/getParam`` becomes explicit pytree threading, which is
  what jit/grad/vmap need;
- parameter names keep the reference's keys ("W", "b", "vb",
  "convweights"/"convbias", "recurrentweights"/"decoderweights"/
  "decoderbias") so param-table introspection and serde feel familiar;
- backprop is `jax.grad` over the pure apply; the hand-written delta chains
  (``MultiLayerNetwork.computeDeltas``) and the LSTM manual BPTT
  (``models/classifiers/lstm/LSTM.java:63-140``) are not re-implemented —
  autodiff subsumes them.  RBM contrastive divergence keeps explicit
  sampling (CD-k is not the gradient of a tractable loss) under the
  stateless threefry RNG;
- ``merge`` (parameter averaging for distributed training,
  ``Layer.java:merge``) is a pytree mean;
- conv has forward AND backward (the reference's conv backward is a stub,
  ``ConvolutionDownSampleLayer.java:105-112`` returns null).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

from ..ops import activations as act
from ..ops import losses as losses_mod
from ..ops.dtypes import get_policy
from ..ops.losses import LossFunction
from .conf import (
    LayerKind,
    NeuralNetConfiguration,
    RBMHiddenUnit,
    RBMVisibleUnit,
)
from .weights import init_from_conf

Params = dict[str, jnp.ndarray]

# Canonical param-table keys (nn/params/*ParamInitializer.java).
W, B, VBIAS = "W", "b", "vb"
CONV_W, CONV_B = "convweights", "convbias"
REC_W, DEC_W, DEC_B = "recurrentweights", "decoderweights", "decoderbias"


# --------------------------------------------------------------------------- utils

def dropout_mask(key, shape, rate: float, dtype):
    """Inverted-dropout mask (reference applies raw binomial masks;
    inverted scaling keeps eval-time activations calibrated)."""
    from ..ops.sampling import dropout_mask as _mask
    return _mask(key, shape, rate, dtype)


def merge_params(params_list: Sequence[Params]) -> Params:
    """Parameter averaging (``Layer.merge``; used by iterative-reduce DP)."""
    return jax.tree_util.tree_map(lambda *xs: sum(xs) / float(len(xs)), *params_list)


def flatten_params(params: Params, order: Sequence[str]) -> jnp.ndarray:
    """Flatten named params in deterministic key order
    (``conf.getGradientList()`` idea; ``MultiLayerNetwork.params():744-788``)."""
    return jnp.concatenate([params[k].reshape(-1) for k in order])


def unflatten_params(flat: jnp.ndarray, template: Params, order: Sequence[str]) -> Params:
    out, off = {}, 0
    for k in order:
        size = template[k].size
        out[k] = flat[off:off + size].reshape(template[k].shape).astype(template[k].dtype)
        off += size
    return out


# --------------------------------------------------------------------------- base

class Layer:
    """Descriptor + pure functions; subclasses define param_order/init/activate.

    Contract parity with ``nn/api/Layer.java``: activate, preOutput
    (pre_output), param table (init), merge (module-level merge_params),
    transpose (on pretrain layers).
    """

    kind: LayerKind = LayerKind.DENSE
    param_order: tuple[str, ...] = (W, B)

    def __init__(self, conf: NeuralNetConfiguration):
        self.conf = conf

    # -- params ----------------------------------------------------------
    def init(self, key) -> Params:
        raise NotImplementedError

    def n_params(self, params: Params) -> int:
        return sum(params[k].size for k in self.param_order)

    def flatten(self, params: Params) -> jnp.ndarray:
        return flatten_params(params, self.param_order)

    def unflatten(self, flat: jnp.ndarray, template: Params) -> Params:
        return unflatten_params(flat, template, self.param_order)

    # -- forward ---------------------------------------------------------
    def pre_output(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None,
                 train: bool = False) -> jnp.ndarray:
        raise NotImplementedError

    # -- shape bookkeeping ----------------------------------------------
    def output_dim(self) -> int:
        return self.conf.n_out


class DenseLayer(Layer):
    """``nn/layers/BaseLayer.java:31,130-171`` — f(xW + b) with dropout."""

    kind = LayerKind.DENSE
    param_order = (W, B)

    def init(self, key) -> Params:
        kw, _ = jax.random.split(key)
        conf = self.conf
        w = init_from_conf(kw, (conf.n_in, conf.n_out), conf)
        b = jnp.zeros((conf.n_out,), get_policy().param_dtype)
        return {W: w, B: b}

    def pre_output(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        pol = get_policy()
        z = pol.cast_compute(x) @ pol.cast_compute(params[W]) + params[B].astype(pol.compute_dtype)
        return z

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        if train and self.conf.dropout > 0 and rng is not None:
            x = x * dropout_mask(rng, x.shape, self.conf.dropout, x.dtype)
        return act.apply(self.conf.activation, self.pre_output(params, x))


class OutputLayer(DenseLayer):
    """``nn/layers/OutputLayer.java`` — classifier/regression head.

    The reference hand-codes per-loss weight gradients (``:93-154``); here the
    loss is a differentiable function of (labels, activated output) and
    training uses `jax.grad`.
    """

    kind = LayerKind.OUTPUT
    param_order = (W, B)

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        return act.apply(self.conf.activation, self.pre_output(params, x))

    def loss(self, params: Params, x: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
        # L2 is NOT added here: regularization lives in the gradient-transform
        # chain (optimize.transforms.from_conf / weight_decay), matching the
        # reference where BaseOptimizer post-processes gradients. Adding it in
        # both places would double-count.
        out = self.activate(params, x)
        return losses_mod.score(self.conf.loss, labels, out)

    def label_probabilities(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        return self.activate(params, x)


# --------------------------------------------------------------------------- pretrain

class BasePretrainLayer(Layer):
    """``nn/layers/BasePretrainNetwork.java:26-144`` equivalent: adds visible
    bias, reconstruction score, sampling SPI, and CD/denoising gradients."""

    param_order = (W, B, VBIAS)

    def init(self, key) -> Params:
        kw, _ = jax.random.split(key)
        conf = self.conf
        pol = get_policy()
        w = init_from_conf(kw, (conf.n_in, conf.n_out), conf)
        return {
            W: w,
            B: jnp.zeros((conf.n_out,), pol.param_dtype),
            VBIAS: jnp.zeros((conf.n_in,), pol.param_dtype),
        }

    def pre_output(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        pol = get_policy()
        return pol.cast_compute(x) @ pol.cast_compute(params[W]) + params[B].astype(pol.compute_dtype)

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        if train and self.conf.dropout > 0 and rng is not None:
            x = x * dropout_mask(rng, x.shape, self.conf.dropout, x.dtype)
        return act.apply(self.conf.activation, self.pre_output(params, x))

    def transpose(self) -> "BasePretrainLayer":
        """``Layer.transpose()`` — decoder view (W^T, swapped biases)."""
        conf = self.conf.replace(n_in=self.conf.n_out, n_out=self.conf.n_in)
        return type(self)(conf)

    # pretrain gradient SPI — subclasses return (score, grads)
    def pretrain_value_and_grad(self, params: Params, x: jnp.ndarray, key):
        raise NotImplementedError


class AutoEncoder(BasePretrainLayer):
    """Denoising autoencoder (``models/featuredetectors/autoencoder/
    AutoEncoder.java:23,44-115``): corrupt input, encode with (W, b), decode
    with (W^T, vb), reconstruction cross-entropy; gradient via autodiff."""

    kind = LayerKind.AUTOENCODER

    def encode(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        return act.apply(self.conf.activation, self.pre_output(params, x))

    def decode(self, params: Params, h: jnp.ndarray) -> jnp.ndarray:
        pol = get_policy()
        z = pol.cast_compute(h) @ pol.cast_compute(params[W]).T + params[VBIAS].astype(pol.compute_dtype)
        return act.apply(self.conf.activation, z)

    def reconstruct(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        return self.decode(params, self.encode(params, x))

    def corrupt(self, key, x: jnp.ndarray) -> jnp.ndarray:
        """Masking corruption at conf.corruption_level (``getCorruptedInput``)."""
        keep = jax.random.bernoulli(key, 1.0 - self.conf.corruption_level, x.shape)
        return x * keep.astype(x.dtype)

    def pretrain_loss(self, params: Params, x: jnp.ndarray, key) -> jnp.ndarray:
        corrupted = self.corrupt(key, x) if self.conf.corruption_level > 0 else x
        recon = self.reconstruct(params, corrupted)
        l = losses_mod.reconstruction_crossentropy(x, recon)
        if self.conf.sparsity > 0 or self.conf.apply_sparsity:
            h = self.encode(params, x)
            l = l + jnp.mean((jnp.mean(h, axis=0) - self.conf.sparsity) ** 2)
        # L2 handled by the transform chain (see OutputLayer.loss note).
        return l

    def pretrain_value_and_grad(self, params: Params, x: jnp.ndarray, key):
        return jax.value_and_grad(self.pretrain_loss)(params, x, key)


class RBM(BasePretrainLayer):
    """Restricted Boltzmann machine with CD-k.

    Capability match of ``models/featuredetectors/rbm/RBM.java``: visible
    units BINARY/GAUSSIAN/SOFTMAX/LINEAR, hidden RECTIFIED/BINARY/GAUSSIAN/
    SOFTMAX (``:54-70``), k-step Gibbs chain gradient (``:95-160``), free
    energy.  The Gibbs chain runs under ``lax.scan`` with threefry keys —
    stateless-RNG threading replaces the shared mutable RNG.
    """

    kind = LayerKind.RBM

    # -- conditionals ----------------------------------------------------
    def prop_up(self, params: Params, v: jnp.ndarray) -> jnp.ndarray:
        pre = self.pre_output(params, v)
        hu = self.conf.hidden_unit
        if hu == RBMHiddenUnit.BINARY:
            return jax.nn.sigmoid(pre)
        if hu == RBMHiddenUnit.GAUSSIAN:
            return pre
        if hu == RBMHiddenUnit.RECTIFIED:
            return jax.nn.relu(pre)
        if hu == RBMHiddenUnit.SOFTMAX:
            return jax.nn.softmax(pre, axis=-1)
        raise ValueError(hu)

    def prop_down(self, params: Params, h: jnp.ndarray) -> jnp.ndarray:
        pol = get_policy()
        pre = pol.cast_compute(h) @ pol.cast_compute(params[W]).T + params[VBIAS].astype(pol.compute_dtype)
        vu = self.conf.visible_unit
        if vu == RBMVisibleUnit.BINARY:
            return jax.nn.sigmoid(pre)
        if vu in (RBMVisibleUnit.GAUSSIAN, RBMVisibleUnit.LINEAR):
            return pre
        if vu == RBMVisibleUnit.SOFTMAX:
            return jax.nn.softmax(pre, axis=-1)
        raise ValueError(vu)

    def sample_hidden_given_visible(self, params: Params, v: jnp.ndarray, key):
        mean = self.prop_up(params, v)
        hu = self.conf.hidden_unit
        if hu == RBMHiddenUnit.BINARY:
            sample = jax.random.bernoulli(key, mean).astype(mean.dtype)
        elif hu == RBMHiddenUnit.GAUSSIAN:
            sample = mean + jax.random.normal(key, mean.shape, mean.dtype)
        elif hu == RBMHiddenUnit.RECTIFIED:
            # NReLU sampling: relu(pre + N(0, sigmoid(pre))) (reference follows
            # Nair&Hinton; RBM.java rectified branch)
            pre = self.pre_output(params, v)
            noise = jax.random.normal(key, pre.shape, pre.dtype) * jnp.sqrt(jax.nn.sigmoid(pre))
            sample = jax.nn.relu(pre + noise)
        elif hu == RBMHiddenUnit.SOFTMAX:
            idx = jax.random.categorical(key, jnp.log(mean + 1e-12), axis=-1)
            sample = jax.nn.one_hot(idx, mean.shape[-1], dtype=mean.dtype)
        else:
            raise ValueError(hu)
        return mean, sample

    def sample_visible_given_hidden(self, params: Params, h: jnp.ndarray, key):
        mean = self.prop_down(params, h)
        vu = self.conf.visible_unit
        if vu == RBMVisibleUnit.BINARY:
            sample = jax.random.bernoulli(key, mean).astype(mean.dtype)
        elif vu == RBMVisibleUnit.GAUSSIAN:
            sample = mean + jax.random.normal(key, mean.shape, mean.dtype)
        elif vu == RBMVisibleUnit.LINEAR:
            sample = mean
        elif vu == RBMVisibleUnit.SOFTMAX:
            idx = jax.random.categorical(key, jnp.log(mean + 1e-12), axis=-1)
            sample = jax.nn.one_hot(idx, mean.shape[-1], dtype=mean.dtype)
        else:
            raise ValueError(vu)
        return mean, sample

    def gibbs_hvh(self, params: Params, h: jnp.ndarray, key):
        kv, kh = jax.random.split(key)
        v_mean, v_sample = self.sample_visible_given_hidden(params, h, kv)
        h_mean, h_sample = self.sample_hidden_given_visible(params, v_sample, kh)
        return v_mean, v_sample, h_mean, h_sample

    def free_energy(self, params: Params, v: jnp.ndarray) -> jnp.ndarray:
        """F(v) = -v·vb - sum log(1+exp(xW+b)) (binary-binary form)."""
        pre = self.pre_output(params, v)
        vbias_term = v @ params[VBIAS]
        hidden_term = jnp.sum(jax.nn.softplus(pre), axis=-1)
        return -vbias_term - hidden_term

    def pretrain_value_and_grad(self, params: Params, x: jnp.ndarray, key):
        """CD-k gradient (positive phase − negative phase after k Gibbs steps).

        Returns (score, grads) where score is reconstruction cross-entropy
        (the reference's ``BasePretrainNetwork`` score) and grads is in
        *descent* orientation (apply with gradient-descent updates).
        """
        conf = self.conf
        k0, kchain = jax.random.split(key)
        ph_mean, ph_sample = self.sample_hidden_given_visible(params, x, k0)

        def body(carry, kk):
            h = carry
            v_mean, v_sample, h_mean, h_sample = self.gibbs_hvh(params, h, kk)
            return h_sample, (v_mean, v_sample, h_mean)

        keys = jax.random.split(kchain, max(conf.k, 1))
        _, (v_means, v_samples, h_means) = jax.lax.scan(body, ph_sample, keys)
        nv_mean, nv_sample, nh_mean = v_means[-1], v_samples[-1], h_means[-1]

        n = x.shape[0]
        # descent orientation: -(positive - negative)/n
        w_grad = -(x.T @ ph_mean - nv_sample.T @ nh_mean) / n
        hb_grad = -jnp.mean(ph_mean - nh_mean, axis=0)
        vb_grad = -jnp.mean(x - nv_sample, axis=0)
        if conf.sparsity > 0 or conf.apply_sparsity:
            hb_grad = hb_grad + (jnp.mean(ph_mean, axis=0) - conf.sparsity)
        # L2 handled by the transform chain (see OutputLayer.loss note).
        grads = {W: w_grad.astype(params[W].dtype),
                 B: hb_grad.astype(params[B].dtype),
                 VBIAS: vb_grad.astype(params[VBIAS].dtype)}
        score = losses_mod.reconstruction_crossentropy(x, nv_mean)
        return score, grads

    def reconstruct(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        return self.prop_down(params, self.prop_up(params, x))


class RecursiveAutoEncoder(BasePretrainLayer):
    """Recursive AE over a left-fold of the input rows.

    Capability match of ``models/featuredetectors/autoencoder/recursive/
    RecursiveAutoEncoder.java``: combine the running parent representation
    with the next input row, encode, accumulate reconstruction loss.  The
    recursion is a ``lax.scan`` over rows — compiler-friendly control flow
    instead of host recursion.  Requires n_in == n_out (representation size).
    """

    kind = LayerKind.RECURSIVE_AUTOENCODER
    param_order = (W, B, VBIAS)

    def init(self, key) -> Params:
        conf = self.conf
        pol = get_policy()
        kw, _ = jax.random.split(key)
        # combine [parent; child] (2*d) -> d
        w = init_from_conf(kw, (2 * conf.n_in, conf.n_out), conf)
        return {W: w, B: jnp.zeros((conf.n_out,), pol.param_dtype),
                VBIAS: jnp.zeros((2 * conf.n_in,), pol.param_dtype)}

    def combine(self, params: Params, parent: jnp.ndarray, child: jnp.ndarray):
        z = jnp.concatenate([parent, child], axis=-1)
        h = act.apply(self.conf.activation, z @ params[W] + params[B])
        recon = act.apply(self.conf.activation, h @ params[W].T + params[VBIAS])
        loss = jnp.mean((recon - z) ** 2)
        return h, loss

    def pretrain_loss(self, params: Params, x: jnp.ndarray, key=None) -> jnp.ndarray:
        def body(parent, child):
            h, l = self.combine(params, parent, child)
            return h, l

        parent0 = x[0]
        _, ls = jax.lax.scan(body, parent0, x[1:])
        return jnp.mean(ls)

    def pretrain_value_and_grad(self, params: Params, x: jnp.ndarray, key):
        return jax.value_and_grad(self.pretrain_loss)(params, x, key)

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        def body(parent, child):
            h, _ = self.combine(params, parent, child)
            return h, h

        parent0 = x[0]
        _, hs = jax.lax.scan(body, parent0, x[1:])
        return jnp.concatenate([x[:1], hs], axis=0)


# --------------------------------------------------------------------------- recurrent

class LSTM(Layer):
    """Single-layer LSTM (char-rnn style).

    Capability match of ``models/classifiers/lstm/LSTM.java:33-140``: the
    i/f/o/g gates live in ONE concatenated weight matrix (the reference's
    ``iFog``), input is [1, x_t, h_{t-1}] (leading 1 folds the bias in, as
    the reference hstacks a ones column), decoder head produces per-step
    softmax.  The manual BPTT (``:63-140``) is replaced by autodiff through
    ``lax.scan``; beam-search decode (``:241-340``) lives in
    ``models/classifiers`` (host-side).
    """

    kind = LayerKind.LSTM
    param_order = (REC_W, DEC_W, DEC_B)

    def init(self, key) -> Params:
        conf = self.conf
        pol = get_policy()
        d = conf.hidden_size or conf.n_out
        k1, k2 = jax.random.split(key)
        rec = init_from_conf(k1, (1 + conf.n_in + d, 4 * d), conf)
        dec = init_from_conf(k2, (d, conf.n_out), conf)
        return {REC_W: rec, DEC_W: dec, DEC_B: jnp.zeros((conf.n_out,), pol.param_dtype)}

    def _step(self, params: Params, carry, x_t):
        h_prev, c_prev = carry
        d = h_prev.shape[-1]
        inp = jnp.concatenate([jnp.ones(x_t.shape[:-1] + (1,), x_t.dtype), x_t, h_prev], axis=-1)
        gates = inp @ params[REC_W]
        i = jax.nn.sigmoid(gates[..., 0:d])
        f = jax.nn.sigmoid(gates[..., d:2 * d])
        o = jax.nn.sigmoid(gates[..., 2 * d:3 * d])
        g = jnp.tanh(gates[..., 3 * d:4 * d])
        c = f * c_prev + i * g
        h = o * jnp.tanh(c)
        return (h, c), h

    def hidden_states(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        """x: (T, n_in) or (B, T, n_in) -> hidden (T, d) / (B, T, d)."""
        d = (self.conf.hidden_size or self.conf.n_out)
        batched = x.ndim == 3
        if batched:
            bsz = x.shape[0]
            carry0 = (jnp.zeros((bsz, d), x.dtype), jnp.zeros((bsz, d), x.dtype))
            xs = jnp.swapaxes(x, 0, 1)  # (T, B, n_in)
        else:
            carry0 = (jnp.zeros((d,), x.dtype), jnp.zeros((d,), x.dtype))
            xs = x
        _, hs = jax.lax.scan(lambda c, xt: self._step(params, c, xt), carry0, xs)
        return jnp.swapaxes(hs, 0, 1) if batched else hs

    def pre_output(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        hs = self.hidden_states(params, x)
        return hs @ params[DEC_W] + params[DEC_B]

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        if train and self.conf.dropout > 0 and rng is not None:
            x = x * dropout_mask(rng, x.shape, self.conf.dropout, x.dtype)
        return act.apply(self.conf.activation, self.pre_output(params, x))

    def loss(self, params: Params, x: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
        """Per-step softmax cross entropy (the reference trains x -> x shifted)."""
        logits = self.pre_output(params, x)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))


# --------------------------------------------------------------------------- conv

class ConvolutionDownSampleLayer(Layer):
    """conv2d + bias + activation + max-pool.

    Capability match of ``nn/layers/convolution/ConvolutionDownSampleLayer
    .java:21,33-80`` (forward); backward comes free via autodiff — the
    reference's backward is unimplemented (``:105-112``).

    Layout: NHWC (TPU-native); weights HWIO.  The reference used
    [examples, channels, rows, cols]; NHWC keeps XLA conv layout-optimal.
    """

    kind = LayerKind.CONVOLUTION_DOWNSAMPLE
    param_order = (CONV_W, CONV_B)

    def init(self, key) -> Params:
        conf = self.conf
        pol = get_policy()
        fh, fw = conf.filter_size
        cin = conf.n_in or 1
        kw, _ = jax.random.split(key)
        w = init_from_conf(kw, (fh, fw, cin, conf.num_filters), conf)
        return {CONV_W: w, CONV_B: jnp.zeros((conf.num_filters,), pol.param_dtype)}

    def pre_output(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        pol = get_policy()
        x4 = x if x.ndim == 4 else x[..., None]
        y = jax.lax.conv_general_dilated(
            pol.cast_compute(x4), pol.cast_compute(params[CONV_W]),
            window_strides=(1, 1), padding=self.conf.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        return y + params[CONV_B].astype(y.dtype)

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        y = act.apply(self.conf.activation, self.pre_output(params, x))
        sh, sw = self.conf.stride
        return jax.lax.reduce_window(
            y, -jnp.inf, jax.lax.max,
            window_dimensions=(1, sh, sw, 1), window_strides=(1, sh, sw, 1),
            padding="VALID",
        )


# --------------------------------------------------------------------------- beyond-v0 blocks

class BatchNorm(Layer):
    """Batch normalization (beyond-v0; needed by the ResNet north star)."""

    kind = LayerKind.BATCHNORM
    param_order = ("scale", "bias")

    def init(self, key) -> Params:
        pol = get_policy()
        d = self.conf.n_out or self.conf.n_in
        return {"scale": jnp.ones((d,), pol.param_dtype),
                "bias": jnp.zeros((d,), pol.param_dtype)}

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        xn = (x - mean) * jax.lax.rsqrt(var + 1e-5)
        return xn * params["scale"] + params["bias"]


class Embedding(Layer):
    """Token embedding lookup (beyond-v0; BERT north star + NLP stack)."""

    kind = LayerKind.EMBEDDING
    param_order = (W,)

    def init(self, key) -> Params:
        conf = self.conf
        return {W: init_from_conf(key, (conf.n_in, conf.n_out), conf)}

    def activate(self, params: Params, x: jnp.ndarray, *, rng=None, train=False):
        return jnp.take(params[W], x, axis=0)

    def pre_output(self, params: Params, x: jnp.ndarray) -> jnp.ndarray:
        return self.activate(params, x)


# --------------------------------------------------------------------------- registry

REGISTRY: dict[LayerKind, type[Layer]] = {
    LayerKind.DENSE: DenseLayer,
    LayerKind.OUTPUT: OutputLayer,
    LayerKind.RBM: RBM,
    LayerKind.AUTOENCODER: AutoEncoder,
    LayerKind.RECURSIVE_AUTOENCODER: RecursiveAutoEncoder,
    LayerKind.LSTM: LSTM,
    LayerKind.CONVOLUTION_DOWNSAMPLE: ConvolutionDownSampleLayer,
    LayerKind.BATCHNORM: BatchNorm,
    LayerKind.EMBEDDING: Embedding,
}


def create_layer(conf: NeuralNetConfiguration) -> Layer:
    """``LayerFactories.getFactory(conf).create(conf)`` equivalent."""
    try:
        cls = REGISTRY[conf.kind]
    except KeyError:
        raise ValueError(f"no layer registered for kind {conf.kind}") from None
    return cls(conf)
