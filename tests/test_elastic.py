"""End-to-end elastic recovery (SURVEY.md §5.3/§5.4).

The reference's behavior under worker death (``MasterActor.java:123-153``):
heartbeats stop -> master evicts the stale worker -> its in-flight job is
re-routed -> training completes as if uninterrupted.  These tests kill a
real worker thread mid-run and assert the full chain, ending in *model
parity* with an uninterrupted run — and the checkpoint flavor: crash the
trainer process state mid-stream, restore, and match the uninterrupted
trajectory exactly.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.scaleout import (
    CollectionJobIterator, DistributedRunner, StateTracker)
from deeplearning4j_tpu.parallel.trainer import DataParallelTrainer


# --------------------------------------------------------------------------
# DistributedRunner: kill a worker thread mid-run
# --------------------------------------------------------------------------

class DeltaPerformer:
    """Param-averaging-style performer whose final model is ORDER-FREE:
    each job adds a deterministic delta to the current model, so the final
    model equals init + sum(deltas) iff every job ran exactly once —
    re-routing bugs (lost or duplicated orphans) change the sum."""

    def __init__(self, tracker: StateTracker):
        self.tracker = tracker

    def perform(self, job):
        current = self.tracker.get_current()
        base = np.zeros(4) if current is None else np.asarray(current)
        job.result = base + np.full(4, float(job.work))

    def update(self, *args):
        pass


class DyingPerformer(DeltaPerformer):
    """First worker to pick a job dies mid-perform (thread exits with the
    job still assigned and heartbeats stopped) — the thread-level analog of
    SIGKILL on a worker node."""

    died = None          # class-level: worker_id that died
    _lock = threading.Lock()

    def perform(self, job):
        with DyingPerformer._lock:
            if DyingPerformer.died is None:
                DyingPerformer.died = job.worker_id
                raise RuntimeError("simulated worker death")
        super().perform(job)


def _run_jobs(performer_factory, jobs, n_workers, eviction_timeout_s=120.0):
    tracker = StateTracker()
    tracker.set_current(np.zeros(4))
    runner = DistributedRunner(
        CollectionJobIterator(jobs),
        performer_factory,
        n_workers=n_workers,
        tracker=tracker,
        eviction_timeout_s=eviction_timeout_s,
    )
    result = runner.run(max_wall_s=60.0)
    return np.asarray(result), tracker


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_worker_death_evict_requeue_parity():
    """Kill one of two workers mid-job; master must evict it, re-route the
    orphaned job, and finish with the same model as an uninterrupted run."""
    DyingPerformer.died = None
    jobs = [1.0, 2.0, 3.0, 4.0, 5.0]

    ref, _ = _run_jobs(DeltaPerformer, jobs, n_workers=1)

    got, tracker = _run_jobs(DyingPerformer, jobs, n_workers=2,
                             eviction_timeout_s=0.5)
    # the dying worker was evicted...
    assert DyingPerformer.died is not None
    assert DyingPerformer.died not in tracker.workers()
    # ...its orphaned job was re-routed and the final model matches
    np.testing.assert_allclose(got, ref, atol=1e-12)
    assert tracker.is_done()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_all_jobs_survive_death_no_duplicates():
    """With 3 workers (one dying), every job still executes EXACTLY once —
    the orphan is re-routed, not lost, not duplicated.  (Model parity is
    not asserted here: with >1 surviving worker the iterative-reduce wave
    AVERAGE legitimately depends on wave grouping.)"""
    DyingPerformer.died = None
    jobs = [float(i) for i in range(1, 9)]
    executed: list[float] = []
    lock = threading.Lock()

    class Recording(DyingPerformer):
        def perform(self, job):
            super().perform(job)          # raises once for the dying worker
            with lock:
                executed.append(float(job.work))

    _, tracker = _run_jobs(Recording, jobs, n_workers=3,
                           eviction_timeout_s=0.5)
    assert sorted(executed) == jobs       # exactly once each, incl. orphan
    assert DyingPerformer.died not in tracker.workers()
    assert tracker.is_done()


# --------------------------------------------------------------------------
# DataParallelTrainer: crash mid-stream, restore from checkpoint, match the
# uninterrupted trajectory
# --------------------------------------------------------------------------

def _toy_problem():
    w_true = jnp.asarray([1.0, -2.0, 0.5])
    x = jax.random.normal(jax.random.key(3), (64, 3))
    y = x @ w_true
    params = {"w": jnp.zeros(3)}

    def loss_fn(p, xb, yb, key=None):
        pred = xb @ p["w"]
        return jnp.mean((pred - yb) ** 2)

    return params, loss_fn, x, y


class _Batch:
    def __init__(self, x, y):
        self.features, self.labels = x, y


def _batches(x, y, n=8, bs=8):
    return [_Batch(x[i * bs:(i + 1) * bs], y[i * bs:(i + 1) * bs])
            for i in range(n)]


def test_trainer_crash_restore_parity(tmp_path):
    """8 uninterrupted steps == 4 steps + process 'crash' (state discarded)
    + checkpoint restore + 4 more steps, exactly."""
    mesh = make_mesh(MeshSpec(dp=8), devices=jax.devices()[:8])
    params, loss_fn, x, y = _toy_problem()
    data = _batches(x, y)

    def new_trainer():
        return DataParallelTrainer(loss_fn, T.chain(T.momentum(0.9),
                                                    T.sgd_lr(5e-2)),
                                   mesh=mesh)

    # uninterrupted reference: epochs=1 over the 8 batches = 8 steps
    t_ref = new_trainer()
    s_ref, ref_losses = t_ref.fit(t_ref.init_state(params), data,
                                  epochs=1)
    assert len(ref_losses) == len(data)

    # interrupted run: stop (crash) after 4 steps, checkpointing every 2
    mgr = CheckpointManager(tmp_path / "ckpt", keep=2)
    t1 = new_trainer()
    s1 = t1.init_state(params)
    for _ in range(4):
        b = data[s1.step % len(data)]
        s1, _ = t1.step(s1, b.features, b.labels)
        if s1.step % 2 == 0:
            t1.checkpoint(s1, mgr)
    del t1, s1                                # the "crash": state is gone
    assert mgr.latest_step() == 4

    # fresh process: restore and continue to the same total step count
    t2 = new_trainer()
    s2, _ = t2.fit(t2.init_state(params), data, epochs=1,
                   checkpoint_manager=mgr, resume=True)

    assert s2.step == s_ref.step
    for a, b in zip(jax.tree_util.tree_leaves(s_ref.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_trainer_restore_includes_optimizer_state(tmp_path):
    """Momentum buffers survive the crash: a restore that silently zeroed
    them would diverge from the uninterrupted trajectory."""
    mesh = make_mesh(MeshSpec(dp=8), devices=jax.devices()[:8])
    params, loss_fn, x, y = _toy_problem()
    data = _batches(x, y)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(5e-2))

    t = DataParallelTrainer(loss_fn, tx, mesh=mesh)
    s = t.init_state(params)
    for _ in range(3):
        b = data[s.step % len(data)]
        s, _ = t.step(s, b.features, b.labels)
    mgr = CheckpointManager(tmp_path / "ckpt")
    t.checkpoint(s, mgr)

    t2 = DataParallelTrainer(loss_fn, tx, mesh=mesh)
    s2 = t2.restore(t2.init_state(params), mgr)
    # momentum buffer must be nonzero and equal to the pre-crash one
    mom_a = jax.tree_util.tree_leaves(s.tstate)
    mom_b = jax.tree_util.tree_leaves(s2.tstate)
    nonzero = False
    for a, b in zip(mom_a, mom_b):
        if isinstance(a, (jnp.ndarray, np.ndarray)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-7)
            nonzero = nonzero or float(np.abs(np.asarray(a)).sum()) > 0
    assert nonzero
