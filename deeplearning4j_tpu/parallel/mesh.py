"""Device-mesh construction and multi-host bootstrap.

TPU-native replacement for the reference's cluster formation
(``DeepLearning4jDistributed.java:128-187`` Akka seed join +
``BaseHazelCastStateTracker.java:454-539`` embedded-vs-client): topology is a
`jax.sharding.Mesh` with named axes, and multi-host bootstrap is
``jax.distributed.initialize`` (the JAX coordination service) — one program,
no master/worker asymmetry.

Axis convention (used across trainers/models):
``dp`` data, ``tp`` tensor/model, ``pp`` pipeline stages, ``sp`` sequence
(ring attention / context parallel), ``ep`` expert.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP, TP, PP, SP, EP = "dp", "tp", "pp", "sp", "ep"


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named mesh shape; -1 on one axis means 'absorb remaining devices'."""

    dp: int = -1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {DP: self.dp, TP: self.tp, PP: self.pp, SP: self.sp, EP: self.ep}
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes


def make_mesh(spec: MeshSpec | None = None, devices: Sequence | None = None) -> Mesh:
    """Build a named mesh over the given (default: all) devices.

    Axis order is (pp, dp, sp, tp, ep) — tp innermost so tensor-parallel
    collectives ride the fastest ICI links; pp outermost so pipeline stages
    can span slower (DCN) boundaries.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    order = (PP, DP, SP, TP, EP)
    shape = tuple(sizes[a] for a in order)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, order)


def local_mesh(n: int | None = None, axis: str = DP) -> Mesh:
    """1-axis mesh over local devices (the common data-parallel case)."""
    devices = jax.devices()[: (n or len(jax.devices()))]
    return Mesh(np.array(devices), (axis,))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DP, ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def initialize_multihost(coordinator: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> None:
    """Multi-host bootstrap (replaces Akka-seed/ZooKeeper discovery).

    No-op when single-process.  Env-var driven (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) like the reference's
    Hadoop-style ``Configuration`` keys.
    """
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coordinator:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_processes or os.environ.get("JAX_NUM_PROCESSES", 1)),
        process_id=int(process_id or os.environ.get("JAX_PROCESS_ID", 0)),
    )
