"""Flagship transformer tests: single-device math, and the key sharding
correctness check — the explicit-SPMD (dp, sp, tp) step with ring attention
must produce the SAME loss/params as the single-device step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    forward_local,
    init_params,
    lm_loss_local,
    param_specs,
)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh


def tiny_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)  # exact math for comparisons
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


def data(cfg, batch=8, seq=16, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    tokens = jax.random.randint(k1, (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(k2, (batch, seq), 0, cfg.vocab_size)
    return tokens, targets


def test_forward_shapes():
    cfg = tiny_cfg()
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    logits = forward_local(params, tokens, cfg)
    assert logits.shape == (8, 16, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_causal_masking():
    """Changing future tokens must not change past logits (causal)."""
    cfg = tiny_cfg(causal=True)
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    logits1 = forward_local(params, tokens, cfg)
    tokens2 = tokens.at[:, 10:].set((tokens[:, 10:] + 7) % cfg.vocab_size)
    logits2 = forward_local(params, tokens2, cfg)
    np.testing.assert_allclose(np.asarray(logits1[:, :10]),
                               np.asarray(logits2[:, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(logits1[:, 10:]), np.asarray(logits2[:, 10:]))


def test_bidirectional_mode():
    cfg = tiny_cfg(causal=False)
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    logits1 = forward_local(params, tokens, cfg)
    tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 3) % cfg.vocab_size)
    logits2 = forward_local(params, tokens2, cfg)
    # bidirectional: even position 0 sees the change
    assert not np.allclose(np.asarray(logits1[:, 0]), np.asarray(logits2[:, 0]))


def test_single_device_training_reduces_loss():
    cfg = tiny_cfg()
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    mom = model.init_momentum(params)
    tokens, _ = data(cfg)
    targets = jnp.roll(tokens, -1, axis=1)
    step = model.build_train_step(lr=0.05)
    loss0 = None
    for i in range(30):
        params, mom, loss = step(params, mom, tokens, targets)
        if loss0 is None:
            loss0 = float(loss)
    assert float(loss) < loss0 * 0.7


@pytest.mark.parametrize("meshspec", [
    MeshSpec(dp=8, sp=1, tp=1),
    MeshSpec(dp=2, sp=2, tp=2),
    MeshSpec(dp=1, sp=4, tp=2),
    MeshSpec(dp=1, sp=8, tp=1),
])
def test_sharded_step_matches_single_device(meshspec):
    """THE sharding correctness check: dp/sp/tp explicit-SPMD step (ring
    attention + Megatron tp psums + dp grad pmean) == single-device step."""
    cfg = tiny_cfg()
    tokens, _ = data(cfg, batch=8, seq=16)
    targets = jnp.roll(tokens, -1, axis=1)

    # single-device ground truth
    solo = TransformerLM(cfg)
    p0 = solo.init(jax.random.key(1))
    m0 = solo.init_momentum(p0)
    step0 = solo.build_train_step(lr=0.1)
    p0b, m0b, loss0 = step0(jax.tree_util.tree_map(jnp.array, p0),
                            jax.tree_util.tree_map(jnp.array, m0),
                            tokens, targets)

    mesh = make_mesh(meshspec)
    model = TransformerLM(cfg, mesh=mesh)
    p1 = solo.init(jax.random.key(1))
    m1 = model.init_momentum(p1)
    p1 = model.place(p1)
    m1 = model.place(m1)
    step1 = model.build_train_step(lr=0.1)
    p1b, m1b, loss1 = step1(p1, m1, tokens, targets)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(p1b["layers"][0]["w1"]),
                               np.asarray(p0b["layers"][0]["w1"]), atol=2e-4)
    np.testing.assert_allclose(np.asarray(p1b["tok_embed"]),
                               np.asarray(p0b["tok_embed"]), atol=2e-4)


def test_remat_matches_no_remat():
    cfg = tiny_cfg(remat=False)
    cfg_r = tiny_cfg(remat=True)
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    targets = jnp.roll(tokens, -1, axis=1)
    g1 = jax.grad(lambda p: lm_loss_local(p, tokens, targets, cfg))(params)
    g2 = jax.grad(lambda p: lm_loss_local(p, tokens, targets, cfg_r))(params)
    np.testing.assert_allclose(np.asarray(g1["layers"][0]["w1"]),
                               np.asarray(g2["layers"][0]["w1"]), rtol=1e-4)
