"""Render TUNE_r05.jsonl (the on-chip battery's output) as a markdown
table ready for BASELINE.md's measured section, plus the flash/bn_fold
adoption verdicts bench.py would derive from it.

Usage: python tools/summarize_tune.py [path-to-jsonl]
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    path = sys.argv[1] if len(sys.argv) > 1 else "TUNE_r05.jsonl"
    # absolute: bench._tune_rows resolves relative paths against the REPO
    # root, not the caller's cwd
    path = os.path.abspath(path)
    import bench
    rows = bench._tune_rows(path)
    if not rows:
        print(f"no rows in {path} (battery not run yet?)")
        return 1

    errors = [r for r in rows
              if any("error" in k for k in r if isinstance(k, str))]
    if errors:
        print(f"!! {len(errors)} battery leg(s) ERRORED — the tables below "
              "cover only the legs that ran:")
        for r in errors:
            print("   ", json.dumps(r)[:200])
        print()

    print("## BERT variants\n")
    print("| batch | seq | attention | remat | median ms | tokens/s | MFU |")
    print("|---|---|---|---|---|---|---|")
    for r in rows:
        if "tokens_per_sec" in r and "attention" in r:
            print(f"| {r['batch']} | {r['seq']} | {r['attention']} | "
                  f"{r.get('remat', False)} | {r['median_ms']} | "
                  f"{r['tokens_per_sec']:,.0f} | {r['mfu']:.1%} |")

    print("\n## ResNet-50 variants\n")
    print("| batch | bn_fold | median ms | img/s | MFU |")
    print("|---|---|---|---|---|")
    for r in rows:
        if "images_per_sec" in r:
            print(f"| {r['batch']} | {r.get('bn_fold', False)} | "
                  f"{r['median_ms']} | {r['images_per_sec']:,.0f} | "
                  f"{r['mfu']:.1%} |")

    for r in rows:
        if isinstance(r.get("flash_check"), dict):
            print("\nflash_check:", json.dumps(r["flash_check"]))
        for k in ("resnet_trace", "resnet_ablate"):
            if k in r:
                print(f"\n{k}:", json.dumps(r[k])[:600])
        if "full_step_ms" in r:
            print("\nbert ablation:", json.dumps(r))

    att, att_why = bench._pick_attention(rows)
    fold, fold_why = bench._pick_bn_fold(rows)
    print(f"\nbench would adopt: attention={att} ({att_why}); "
          f"bn_fold={fold} ({fold_why})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
