"""CLI smoke tests (VERDICT r3 #8) — ``python -m deeplearning4j_tpu``, the
``DeepLearning4jDistributedApp.main`` analog."""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def _run(*argv, timeout=240):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu", *argv],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)


def test_cli_train_evaluate_roundtrip(tmp_path):
    model = tmp_path / "iris.model"
    p = _run("train", "--dataset", "iris", "--iterations", "120",
             "--out", str(model))
    assert p.returncode == 0, p.stderr[-1500:]
    assert "f1" in p.stdout.lower() or "accuracy" in p.stdout.lower(), p.stdout
    assert model.exists()

    p = _run("evaluate", str(model), "--dataset", "iris")
    assert p.returncode == 0, p.stderr[-1500:]
    assert "accuracy" in p.stdout.lower() or "f1" in p.stdout.lower()


def test_cli_scaleout_word_count(tmp_path):
    jobs = tmp_path / "jobs.txt"
    jobs.write_text("a b a\nb c\n")
    p = _run("scaleout", "--state-dir", str(tmp_path / "state"),
             "--jobs", str(jobs), "--workers", "2")
    assert p.returncode == 0, p.stderr[-1500:]
    assert "'a': 2" in p.stdout or '"a": 2' in p.stdout, p.stdout


def test_cli_usage_error():
    p = _run("train", "--dataset", "nope")
    assert p.returncode != 0
