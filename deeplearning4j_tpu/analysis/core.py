"""graftlint core: findings, rule registry, and shared AST utilities.

The analyzer is deliberately a *hazard* linter, not a type checker: every
rule encodes one way this codebase has already been burned by the JAX/XLA
execution model (PR 2's hand-removed host syncs, recompile storms, and
donated-buffer reuse).  Rules are heuristic by design — they trade
soundness for catching the real patterns in this tree, and every rule can
be silenced per-line (``# graftlint: disable=HS01``) or per-file
(``# graftlint: disable-file=HS01``) when a hit is deliberate.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable, Iterator

#: statuses a finding can end up in after suppression/baseline filtering
ACTIVE = "active"
SUPPRESSED = "suppressed"
BASELINED = "baselined"


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    code: str = ""            # stripped source line (baseline matching key)
    status: str = ACTIVE

    def key(self) -> tuple[str, str, str]:
        """Line-number-free identity used for baseline matching: the rule,
        the file, and the stripped source text.  Survives unrelated edits
        that shift line numbers; a real change to the flagged line
        invalidates the baseline entry (which is the point)."""
        return (self.rule, self.path.replace("\\", "/"), self.code)

    def location(self) -> str:
        return f"{self.path}:{self.line}"


class Rule:
    """Base class: subclasses set ``id``/``title`` and implement ``check``."""

    id: str = ""
    title: str = ""

    def check(self, module) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, module, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        code = module.line(line)
        return Finding(rule=self.id, path=module.path, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message, code=code)


_REGISTRY: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    """Class decorator adding a rule to the default rule set."""
    _REGISTRY[rule_cls.id] = rule_cls()
    return rule_cls


def all_rules() -> dict[str, Rule]:
    # import for side effect: rule registration happens at module import
    from . import rules as _rules  # noqa: F401
    return dict(_REGISTRY)


# --------------------------------------------------------------------------- AST helpers

def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted_name(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def last_segment(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def literal_int_tuple(node: ast.AST | None) -> tuple[int, ...] | None:
    """Evaluate a literal int / tuple-of-ints AST node (donate_argnums,
    static_argnums values); None when it is not a safe literal."""
    if node is None:
        return None
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(val, int):
        return (val,)
    if isinstance(val, (tuple, list)) and all(isinstance(v, int) for v in val):
        return tuple(val)
    return None


def iter_functions(tree: ast.Module) -> Iterator[ast.FunctionDef]:
    """Every (async) function/method definition in the module."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def assigned_names(target: ast.AST) -> Iterator[str]:
    """Dotted names bound by an assignment target (handles tuple/star
    unpacking; subscripts yield nothing)."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from assigned_names(elt)
    elif isinstance(target, ast.Starred):
        yield from assigned_names(target.value)
    else:
        name = dotted_name(target)
        if name is not None:
            yield name


def statement_targets(stmt: ast.stmt) -> set[str]:
    """All dotted names a statement (re)binds."""
    out: set[str] = set()
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out.update(assigned_names(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        out.update(assigned_names(stmt.target))
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        out.update(assigned_names(stmt.target))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out.update(assigned_names(item.optional_vars))
    return out


def names_read(node: ast.AST) -> set[str]:
    """Dotted names loaded anywhere under ``node`` (longest chains only:
    reading ``self.syn0`` reports ``self.syn0``, not also ``self``)."""
    out: set[str] = set()

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and isinstance(n.ctx, ast.Load):
            name = dotted_name(n)
            if name is not None:
                out.add(name)
                return  # don't descend: keep the longest chain
        elif isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
            out.add(n.id)
            return
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


def body_statements(body: Iterable[ast.stmt],
                    into_defs: bool = False) -> Iterator[ast.stmt]:
    """Statements in source order, descending into compound statements
    (but not into nested function/class definitions unless asked)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if into_defs:
                yield from body_statements(stmt.body, into_defs)
            continue
        for field in ("body", "orelse", "finalbody"):
            sub = getattr(stmt, field, None)
            if sub:
                yield from body_statements(sub, into_defs)
        for handler in getattr(stmt, "handlers", []) or []:
            yield from body_statements(handler.body, into_defs)
