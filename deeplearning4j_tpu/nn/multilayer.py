"""MultiLayerNetwork — the training container.

TPU-native re-design of ``nn/multilayer/MultiLayerNetwork.java:45-1596``:
build a layer stack from a ``MultiLayerConfiguration``, greedy layerwise
``pretrain`` (``:115-199``), supervised ``finetune`` (``:996-1048``),
``feedForward``/``output``/``predict``/``score``/``evaluate``
(``:408-474,1058-1169``), parameter flatten/unflatten (``:744-788``), and
``merge`` parameter averaging (``:1302``).

Architecture notes (TPU-first, not a translation):
- params are a tuple of per-layer dicts (a pytree); the whole supervised
  train step — forward, loss, backward (autodiff), gradient post-processing,
  update — is ONE jitted function, compiled once per (shape, mesh).  The
  reference's per-iteration Java loop with hand-written deltas
  (``computeDeltas:611-670``) becomes `jax.value_and_grad` inside that step;
- pretrain steps are likewise jitted per layer (CD-k sampling runs under
  `lax.scan` with threefry keys);
- second-order finetuning (CG/LBFGS/Hessian-free) dispatches to the L2
  solvers, whose curvature products use `jax.jvp` over `jax.grad`
  (replacing ``feedForwardR/computeDeltasR/backPropGradientR:1415-1487``);
- data-parallel training over a `jax.sharding.Mesh` is available via
  ``parallel.trainer`` which shards the same step with `pjit` (parameter
  averaging ≡ gradient `pmean` implied by sharded batch + replicated params).
"""

from __future__ import annotations

import logging
import pickle
import time
from pathlib import Path
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..datasets.dataset import DataSet, to_outcome_matrix
from ..evaluation import Evaluation
from ..observability import METRICS, enabled as _obs_enabled, trace
from ..optimize import transforms as tfm
from ..parallel.compile_cache import setup_compile_cache
from ..utils import tree_math as tm
from .conf import LayerKind, MultiLayerConfiguration, OptimizationAlgorithm
from .layers import (
    BasePretrainLayer,
    Layer,
    OutputLayer,
    create_layer,
    merge_params,
)

log = logging.getLogger(__name__)

Params = tuple[dict[str, jnp.ndarray], ...]

# OutputPreProcessor registry (reference: ``nn/conf/preprocessor/
# ReshapePreProcessor`` + ``nn/layers/convolution/preprocessor/*``): named
# transforms applied to a layer's OUTPUT before the next layer.
PREPROCESSORS: dict[str, Callable] = {
    "flatten": lambda h: h.reshape(h.shape[0], -1),
    "none": lambda h: h,
}


class MultiLayerNetwork:
    """Layer stack + training orchestration."""

    def __init__(self, conf: MultiLayerConfiguration, *, score_every: int = 1):
        self.conf = conf
        self.layers: list[Layer] = [create_layer(c) for c in conf.confs]
        self.params: Params | None = None
        self._tstates: list[Any] | None = None
        self.listeners: list = []
        self._jit_cache: dict = {}
        self._score = float("nan")
        # How often pretrain/finetune sync the on-device loss into the host
        # ``_score`` float.  1 (default) keeps the reference per-iteration
        # behavior; larger values keep the hot loop asynchronous — jax only
        # runs ahead of the device if nothing forces a device->host read.
        # The final iteration always syncs, so ``score()`` stays correct.
        self.score_every = max(1, int(score_every))
        setup_compile_cache()  # persistent XLA cache (env-gated no-op)

    # ------------------------------------------------------------------ init
    def init(self, key=None) -> Params:
        """``MultiLayerNetwork.init():284-339`` — build all param tables."""
        key = key if key is not None else jax.random.key(self.conf.confs[0].seed)
        keys = jax.random.split(key, len(self.layers))
        self.params = tuple(l.init(k) for l, k in zip(self.layers, keys))
        self._tstates = None
        self._jit_cache.clear()
        return self.params

    def _ensure_init(self):
        if self.params is None:
            self.init()

    # ------------------------------------------------------------------ forward
    def _preproc(self, i: int, h):
        """Apply layer i's OutputPreProcessor (``feedForward:419-421``)."""
        name = self.conf.preprocessors.get(i)
        return PREPROCESSORS[name](h) if name else h

    def feed_forward_fn(self, params: Params, x, rng=None, train: bool = False):
        """Pure forward returning all activations (``feedForward:408-474``)."""
        acts = [x]
        rngs = (jax.random.split(rng, len(self.layers))
                if rng is not None else [None] * len(self.layers))
        h = x
        for i, (layer, p, r) in enumerate(zip(self.layers, params, rngs)):
            h = self._preproc(i, layer.activate(p, h, rng=r, train=train))
            acts.append(h)
        return acts

    def _forward(self, params: Params, x):
        h = x
        for i, (layer, p) in enumerate(zip(self.layers, params)):
            h = self._preproc(i, layer.activate(p, h))
        return h

    def feed_forward(self, x) -> list:
        self._ensure_init()
        return self.feed_forward_fn(self.params, jnp.asarray(x))

    def output(self, x) -> jnp.ndarray:
        """Probabilities/activations of the final layer (``output:1140``)."""
        self._ensure_init()
        fn = self._jit_cache.get("output")
        if fn is None:
            fn = jax.jit(self._forward)
            self._jit_cache["output"] = fn
        return fn(self.params, jnp.asarray(x))

    def predict(self, x) -> np.ndarray:
        """Argmax class per row (``predict:1058-1062``)."""
        return np.asarray(jnp.argmax(self.output(x), axis=-1))

    def label_probabilities(self, x) -> jnp.ndarray:
        return self.output(x)

    def reconstruct(self, x, layer_idx: int) -> jnp.ndarray:
        """Activations at layer ``layer_idx`` (``reconstruct:1152-1169``)."""
        acts = self.feed_forward(x)
        return acts[layer_idx]

    # ------------------------------------------------------------------ loss
    def supervised_loss(self, params: Params, x, labels, rng=None, train: bool = False):
        out_layer = self.layers[-1]
        h = x
        rngs = (jax.random.split(rng, len(self.layers))
                if rng is not None else [None] * len(self.layers))
        for i, (layer, p, r) in enumerate(zip(self.layers[:-1], params[:-1], rngs[:-1])):
            h = self._preproc(i, layer.activate(p, h, rng=r, train=train))
        if hasattr(out_layer, "loss"):  # OutputLayer, LSTM, or any loss-bearing tail
            return out_layer.loss(params[-1], h, labels)
        raise TypeError(f"final layer {type(out_layer).__name__} has no loss")

    def score(self, data: DataSet | None = None) -> float:
        """``score()`` — supervised loss on the given/last batch."""
        if data is not None:
            self._ensure_init()
            self._score = float(self.supervised_loss(
                self.params, jnp.asarray(data.features), jnp.asarray(data.labels)))
        return self._score

    # ------------------------------------------------------------------ pretrain
    def pretrain(self, data_or_iter, key=None) -> None:
        """Greedy layerwise pretraining (``pretrain:115-199``): feed inputs
        through layers [0..i-1], then run layer i's unsupervised objective.
        Each (layer, shape) gets one jitted update step; AdaGrad/momentum
        state threads through the loop on-device."""
        self._ensure_init()
        key = key if key is not None else jax.random.key(self.conf.confs[0].seed + 7)
        batches = self._as_batches(data_or_iter)
        for i, layer in enumerate(self.layers):
            if not isinstance(layer, BasePretrainLayer):
                continue
            conf = layer.conf
            with trace.span("multilayer.pretrain_layer", layer=i,
                            kind=conf.kind.value):
                transform = tfm.from_conf(conf)
                step = self._pretrain_step(i, layer, transform)
                lparams = self.params[i]
                tstate = transform.init(lparams)
                loss = None
                for b, batch in enumerate(batches):
                    x = jnp.asarray(batch.features)
                    # inputs to layer i are fixed while layer i trains
                    inp = self._forward_to(i, x)
                    for it in range(max(1, conf.num_iterations)):
                        key, sub = jax.random.split(key)
                        lparams, tstate, loss = step(lparams, tstate, inp, sub,
                                                     jnp.asarray(it))
                    # syncing the score is a device->host read; keep it off
                    # the hot loop unless asked for every batch
                    if (b + 1) % self.score_every == 0:
                        self._score = float(loss)
                if loss is not None:
                    self._score = float(loss)
                new_params = list(self.params)
                new_params[i] = lparams
                self.params = tuple(new_params)
            METRICS.increment("multilayer.pretrain_layers")
            log.info("pretrained layer %d (%s) score %.5f", i, conf.kind.value, self._score)

    def _forward_to(self, i: int, x):
        """Inputs to layer i = activations of layers [0..i-1]."""
        fn = self._jit_cache.get(("fwd_to", i))
        if fn is None:
            def forward_to(params, x):
                h = x
                for j, (layer, p) in enumerate(zip(self.layers[:i], params[:i])):
                    h = self._preproc(j, layer.activate(p, h))
                return h
            fn = jax.jit(forward_to)
            self._jit_cache[("fwd_to", i)] = fn
        return fn(self.params, x)

    def _pretrain_step(self, i: int, layer: BasePretrainLayer, transform):
        cache_key = ("pretrain_step", i)
        fn = self._jit_cache.get(cache_key)
        if fn is None:
            def step(lparams, tstate, x, key, iteration):
                loss, grads = layer.pretrain_value_and_grad(lparams, x, key)
                updates, tstate = transform.update(grads, tstate, lparams, iteration)
                lparams = tfm.apply_updates(lparams, updates)
                return lparams, tstate, loss
            fn = jax.jit(step)
            self._jit_cache[cache_key] = fn
        return fn

    # ------------------------------------------------------------------ finetune
    def finetune(self, data_or_iter, key=None) -> None:
        """Supervised training of the whole stack (``finetune:996-1048``).

        First-order algos run the jitted minibatch step; CG/LBFGS/HF
        dispatch to the L2 solvers on the batch objective.
        """
        self._ensure_init()
        out_conf = self.layers[-1].conf
        key = key if key is not None else jax.random.key(out_conf.seed + 13)
        batches = self._as_batches(data_or_iter)
        algo = out_conf.optimization_algo
        if algo in (OptimizationAlgorithm.GRADIENT_DESCENT,
                    OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT):
            self._finetune_first_order(batches, key)
        else:
            self._finetune_solver(batches, key, algo)

    def _finetune_first_order(self, batches: Sequence[DataSet], key) -> None:
        out_conf = self.layers[-1].conf
        transform = tfm.from_conf(out_conf)
        step = self._train_step(transform)
        tstate = (self._tstates if self._tstates is not None
                  else transform.init(self.params))
        it = 0
        loss = None
        n_total = len(batches) * max(1, out_conf.num_iterations)
        for batch in batches:
            x, y = jnp.asarray(batch.features), jnp.asarray(batch.labels)
            for _ in range(max(1, out_conf.num_iterations)):
                obs = _obs_enabled()
                t0 = time.perf_counter() if obs else 0.0
                key, sub = jax.random.split(key)
                # Rebind self.params/self._tstates IMMEDIATELY: the step
                # donates its inputs, so the previous buffers are dead the
                # moment it returns — listeners (which may call output())
                # and crash recovery must see the fresh ones.
                self.params, tstate, loss = step(
                    self.params, tstate, x, y, sub, jnp.asarray(it))
                self._tstates = tstate
                it += 1
                # ``float(loss)`` is a device->host sync that stalls jax's
                # async dispatch; only pay it every ``score_every`` steps
                # (and on the last step, so ``score()``/the loss gauge end
                # correct).  score_every=1 is exactly the old behavior.
                if it % self.score_every == 0 or it == n_total:
                    self._score = float(loss)
                    if obs:
                        METRICS.gauge("multilayer.loss", self._score)
                if obs:
                    METRICS.observe_time("multilayer.fit_iteration",
                                         time.perf_counter() - t0)
                    METRICS.increment("multilayer.iterations")
                for l in self.listeners:
                    l.iteration_done(self, it)

    def _train_step(self, transform):
        fn = self._jit_cache.get("train_step")
        if fn is None:
            def step(params, tstate, x, y, key, iteration):
                loss, grads = jax.value_and_grad(self.supervised_loss)(
                    params, x, y, rng=key, train=True)
                updates, tstate = transform.update(grads, tstate, params, iteration)
                params = tfm.apply_updates(params, updates)
                return params, tstate, loss
            fn = jax.jit(step, donate_argnums=(0, 1))
            self._jit_cache["train_step"] = fn
        return fn

    def _finetune_solver(self, batches: Sequence[DataSet], key, algo) -> None:
        from ..optimize.solvers import Solver  # deferred: avoids import cycle
        # Mini-batch mode: the solver cycles batches across outer iterations
        # instead of the r4 DataSet.merge of the whole corpus — DEVICE
        # memory is bounded by one batch (batches stay host-side numpy;
        # each jitted call transfers only the iteration's batch).  Keeping
        # shapes uniform (batch_by pads nothing, so the tail batch may
        # recompile once) bounds compilation at two variants.
        data = [(np.asarray(b.features), np.asarray(b.labels))
                for b in batches]

        def objective(params, k, x, y):
            return jax.value_and_grad(self.supervised_loss)(params, x, y)

        out_conf = self.layers[-1].conf
        extra = {}
        if algo == OptimizationAlgorithm.HESSIAN_FREE:
            # Gauss-Newton split (the reference CGs on GN products,
            # StochasticHessianFree.java:27): predict = network up to the
            # final pre-activation z; loss_out = convex loss of z.
            from ..ops import activations as _act
            from ..ops import losses as _losses

            def predict(params, k, x, y):
                h = jnp.asarray(x)
                for i, (layer, p) in enumerate(zip(self.layers[:-1], params[:-1])):
                    h = self._preproc(i, layer.activate(p, h))
                return self.layers[-1].pre_output(params[-1], h)

            def loss_out(z, x, y):
                return _losses.score(out_conf.loss, y,
                                     _act.apply(out_conf.activation, z))

            extra = {"damping": self.conf.damping_factor,
                     "gauss_newton": (predict, loss_out)}
        solver = Solver(out_conf, objective, listeners=self.listeners,
                        batches=data, **extra)
        result = solver.optimize(self.params, key)
        self.params = result.params
        self._score = result.score

    # ------------------------------------------------------------------ fit
    def fit(self, data_or_iter, key=None) -> "MultiLayerNetwork":
        """``fit = pretrain + finetune`` (``fit:985-1022``)."""
        self._ensure_init()
        k_pre = k_fine = None
        if key is not None:
            k_pre, k_fine = jax.random.split(key)
        with trace.span("multilayer.fit", n_layers=len(self.layers)):
            if self.conf.pretrain:
                with trace.span("multilayer.pretrain"):
                    self.pretrain(data_or_iter, k_pre)
            if self.conf.backprop:
                with trace.span("multilayer.finetune"):
                    self.finetune(data_or_iter, k_fine)
        return self

    def fit_arrays(self, features, labels_or_idx, key=None) -> "MultiLayerNetwork":
        """Classifier.fit(x, labels) — int labels become one-hot
        (``MultiLayerNetwork.java:1127`` FeatureUtil.toOutcomeMatrix)."""
        labels = np.asarray(labels_or_idx)
        if labels.ndim == 1:
            labels = to_outcome_matrix(labels, self.layers[-1].conf.n_out)
        return self.fit(DataSet(np.asarray(features), labels), key)

    def _as_batches(self, data_or_iter) -> list[DataSet]:
        if isinstance(data_or_iter, DataSet):
            bs = self.layers[-1].conf.batch_size
            return data_or_iter.batch_by(bs) if bs > 0 else [data_or_iter]
        return list(data_or_iter)

    # ------------------------------------------------------------------ eval
    def evaluate(self, data_or_iter) -> Evaluation:
        ev = Evaluation()
        with trace.span("multilayer.evaluate"):
            for batch in self._as_batches(data_or_iter):
                ev.eval(batch.labels, np.asarray(self.output(batch.features)))
                METRICS.increment("evaluate.batches")
        return ev

    # ------------------------------------------------------------------ params plumbing
    def params_flat(self) -> jnp.ndarray:
        """Flatten all params (``params():744-788``) in layer/key order."""
        self._ensure_init()
        return jnp.concatenate([
            layer.flatten(p) for layer, p in zip(self.layers, self.params)])

    def set_params_flat(self, flat) -> None:
        self._ensure_init()
        flat = jnp.asarray(flat)
        out, off = [], 0
        for layer, p in zip(self.layers, self.params):
            n = layer.n_params(p)
            out.append(layer.unflatten(flat[off:off + n], p))
            off += n
        self.params = tuple(out)

    def num_params(self) -> int:
        self._ensure_init()
        return sum(l.n_params(p) for l, p in zip(self.layers, self.params))

    def merge(self, *others: "MultiLayerNetwork") -> None:
        """Parameter averaging with peers (``merge:1302``; DP aggregation)."""
        self._ensure_init()
        all_params = [self.params] + [o.params for o in others]
        self.params = merge_params(all_params)

    def clone(self) -> "MultiLayerNetwork":
        net = MultiLayerNetwork(self.conf)
        if self.params is not None:
            net.params = jax.tree_util.tree_map(lambda x: x, self.params)
        return net

    # ------------------------------------------------------------------ persistence
    def save(self, path: str | Path) -> None:
        """Config JSON + params npz in one pickle envelope (replaces the
        reference's Java serialization ``SerializationUtils``)."""
        payload = {
            "conf_json": self.conf.to_json(),
            "params": None if self.params is None else
            [{k: np.asarray(v) for k, v in p.items()} for p in self.params],
        }
        with open(path, "wb") as f:
            pickle.dump(payload, f)

    @classmethod
    def load(cls, path: str | Path) -> "MultiLayerNetwork":
        with open(path, "rb") as f:
            payload = pickle.load(f)
        net = cls(MultiLayerConfiguration.from_json(payload["conf_json"]))
        if payload["params"] is not None:
            net.params = tuple({k: jnp.asarray(v) for k, v in p.items()}
                               for p in payload["params"])
        return net
