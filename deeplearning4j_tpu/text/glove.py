"""GloVe — co-occurrence counting + AdaGrad weighted least squares.

Capability match of ``models/glove/Glove.java:42`` + ``CoOccurrences.java`` +
``GloveWeightLookupTable.java``: window-weighted co-occurrence counts on the
host, then batched AdaGrad updates of (w, w~, b, b~) on device minimizing
f(X_ij) (w_i . w~_j + b_i + b~_j - log X_ij)^2 — the reference's per-pair
host loop becomes one jitted scatter-add step per batch.
"""

from __future__ import annotations

from collections import defaultdict
from functools import partial
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import METRICS, trace
from .tokenization import CommonPreprocessor, DefaultTokenizerFactory
from .vocab import VocabCache, build_vocab


@partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def _glove_step(w, wc, b, bc, hw, hwc, hb, hbc, rows, cols, logx, fx, lr):
    """AdaGrad step on a batch of co-occurrence entries."""
    wi, wj = w[rows], wc[cols]
    diff = jnp.einsum("bd,bd->b", wi, wj) + b[rows] + bc[cols] - logx
    wdiff = fx * diff                                   # (B,)
    gw = wdiff[:, None] * wj
    gwc = wdiff[:, None] * wi
    gb = wdiff
    # adagrad accumulators
    hw = hw.at[rows].add(gw * gw)
    hwc = hwc.at[cols].add(gwc * gwc)
    hb = hb.at[rows].add(gb * gb)
    hbc = hbc.at[cols].add(gb * gb)
    w = w.at[rows].add(-lr * gw * jax.lax.rsqrt(hw[rows] + 1e-8))
    wc = wc.at[cols].add(-lr * gwc * jax.lax.rsqrt(hwc[cols] + 1e-8))
    b = b.at[rows].add(-lr * gb * jax.lax.rsqrt(hb[rows] + 1e-8))
    bc = bc.at[cols].add(-lr * gb * jax.lax.rsqrt(hbc[cols] + 1e-8))
    loss = 0.5 * jnp.mean(fx * diff * diff)
    return w, wc, b, bc, hw, hwc, hb, hbc, loss


class CoOccurrences:
    """Window-weighted co-occurrence counts (``CoOccurrences.java``):
    increment by 1/distance within the window."""

    def __init__(self, vocab: VocabCache, tokenizer_factory, window: int = 15):
        self.vocab = vocab
        self.tokenizer_factory = tokenizer_factory
        self.window = window
        self.counts: dict[tuple[int, int], float] = defaultdict(float)
        self._arrays = None          # native-path result (rows, cols, vals)

    def fit(self, sentences: Iterable[str]) -> "CoOccurrences":
        sent_idx = []
        for s in sentences:
            toks = self.tokenizer_factory.create(s).get_tokens()
            idx = [self.vocab.index_of(t) for t in toks]
            sent_idx.append([i for i in idx if i >= 0])
        # native fast path (the GloVe host hot loop, like word2vec's
        # skip-gram generation); exact Python fallback below
        try:
            from ..native import runtime as native_rt
            native = native_rt.cooccurrence(
                [np.asarray(s, np.int32) for s in sent_idx if s], self.window)
        except ImportError:
            native = None
        if native is not None:
            self._arrays = native
            return self
        for idx in sent_idx:
            for pos, wi in enumerate(idx):
                for off in range(1, self.window + 1):
                    j = pos + off
                    if j >= len(idx):
                        break
                    inc = 1.0 / off
                    self.counts[(wi, idx[j])] += inc
                    self.counts[(idx[j], wi)] += inc
        return self

    def arrays(self):
        if getattr(self, "_arrays", None) is not None:
            return self._arrays
        items = list(self.counts.items())
        rows = np.array([ij[0] for ij, _ in items], np.int32)
        cols = np.array([ij[1] for ij, _ in items], np.int32)
        vals = np.array([v for _, v in items], np.float32)
        return rows, cols, vals


class Glove:
    """GloVe model with the reference's knobs (layer size, xMax, alpha,
    learning rate, iterations)."""

    def __init__(self, sentences: Iterable[str] | None = None, *,
                 layer_size: int = 100, window: int = 15,
                 min_word_frequency: float = 1.0, iterations: int = 5,
                 learning_rate: float = 0.05, x_max: float = 100.0,
                 alpha: float = 0.75, batch_size: int = 8192, seed: int = 42,
                 resolve_every: int = 32, tokenizer_factory=None):
        self.sentences = list(sentences) if sentences is not None else []
        self.layer_size = layer_size
        self.window = window
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.batch_size = batch_size
        self.seed = seed
        self.resolve_every = max(1, resolve_every)
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory(
            CommonPreprocessor())
        self.vocab: VocabCache | None = None
        self.syn0 = None
        self.losses: list[float] = []

    # ------------------------------------------------------------------ step seams
    # (overridden by ShardedGlove to run over mesh-sharded tables)
    def _init_tables(self, n: int, d: int, rng) -> None:
        self._tables = [
            jnp.asarray((rng.random((n, d), np.float32) - 0.5) / d),   # w
            jnp.asarray((rng.random((n, d), np.float32) - 0.5) / d),   # wc
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32),  # b, bc
            jnp.zeros((n, d), jnp.float32), jnp.zeros((n, d), jnp.float32),
            jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32),
        ]

    def _apply_step(self, rows, cols, logx, fx):
        """One AdaGrad batch; returns the DEVICE loss (0-d array) so the
        caller decides when to pay the host sync (LazyLoss discipline)."""
        *self._tables, loss = _glove_step(
            *self._tables, rows, cols, logx, fx,
            jnp.float32(self.learning_rate))
        return loss

    def _final_embeddings(self, n: int):
        w, wc = self._tables[0], self._tables[1]
        return (w + wc)[:n]  # standard GloVe: sum of both embeddings

    def fit(self) -> "Glove":
        self.vocab = build_vocab(self.sentences, self.tokenizer_factory,
                                 self.min_word_frequency)
        co = CoOccurrences(self.vocab, self.tokenizer_factory, self.window)
        co.fit(self.sentences)
        rows, cols, vals = co.arrays()
        n, d = len(self.vocab), self.layer_size
        rng = np.random.default_rng(self.seed)
        self._init_tables(n, d, rng)
        logx = np.log(np.maximum(vals, 1e-12)).astype(np.float32)
        fx = np.minimum(1.0, (vals / self.x_max) ** self.alpha).astype(np.float32)
        m = rows.shape[0]
        for it in range(self.iterations):
            perm = rng.permutation(m)
            epoch_loss = 0.0
            nb = 0
            pending: list = []   # device losses awaiting one batched sync

            def _resolve() -> None:
                # pull then accumulate one batch at a time, in dispatch
                # order: bitwise-identical to per-batch float(loss) sums
                nonlocal epoch_loss
                for v in jax.device_get(pending):
                    epoch_loss += float(v)
                pending.clear()

            with trace.span("glove.epoch", iteration=it, entries=m):
                for off in range(0, m, self.batch_size):
                    sl = perm[off:off + self.batch_size]
                    pending.append(self._apply_step(
                        jnp.asarray(rows[sl]), jnp.asarray(cols[sl]),
                        jnp.asarray(logx[sl]), jnp.asarray(fx[sl])))
                    nb += 1
                    METRICS.increment("glove.batches")
                    if len(pending) >= self.resolve_every:
                        _resolve()
                _resolve()
            self.losses.append(epoch_loss / max(1, nb))
            METRICS.gauge("glove.epoch_loss", self.losses[-1])
        self.syn0 = self._final_embeddings(n)
        return self

    # query API mirrors Word2Vec
    def get_word_vector(self, word: str):
        i = self.vocab.index_of(word)
        return None if i < 0 else np.asarray(self.syn0[i])

    def similarity(self, w1: str, w2: str) -> float:
        from .similarity import cosine
        return cosine(self.get_word_vector(w1), self.get_word_vector(w2))

    def words_nearest(self, word: str, n: int = 10) -> list[str]:
        from .similarity import nearest
        vec = self.get_word_vector(word)
        if vec is None:
            return []
        return nearest(np.asarray(self.syn0), vec, self.vocab.word_at, n, {word})
