"""Minimal stdlib client for :class:`~.server.ModelServer`.

``urllib``-based (the repo ships no HTTP client dependency) — the serving
counterpart of the reference's REST client seams.  Rejections surface as
:class:`ServingError` carrying the HTTP status, so callers can tell
backpressure (429 — back off and retry) from bad requests (400) apart
without parsing strings.

Timeouts and retries: every call takes an optional per-call ``timeout_s``
(falling back to the constructor default), and **idempotent GETs only**
(``/healthz``, ``/metrics``, ``/metrics.prom``) are retried once with a
short backoff on connection reset / refused / timeout.  POSTs are never
retried here — a ``/v1/generate`` whose connection died may well have
decoded to completion server-side, and replaying it is the router's
decision (it knows spillover semantics), not the transport's.  The retry
exists so a health prober polling a wedged replica gets a prompt, bounded
failure instead of hanging a probe cycle.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

from ..observability import trace


class ServingError(RuntimeError):
    """An HTTP error answer from the model server."""

    def __init__(self, status: int, detail: str):
        super().__init__(f"HTTP {status}: {detail}")
        self.status = status
        self.detail = detail


class ServingClient:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 timeout_s: float = 60.0, retries: int = 1,
                 retry_backoff_s: float = 0.05):
        self.base = f"http://{host}:{port}"
        self.timeout_s = timeout_s
        self.retries = retries
        self.retry_backoff_s = retry_backoff_s

    # ------------------------------------------------------------ transport
    def _request(self, path: str, payload: dict | None = None,
                 timeout_s: float | None = None):
        data = json.dumps(payload).encode() if payload is not None else None
        headers = {"Content-Type": "application/json"} if data else {}
        # W3C trace propagation: when the caller is inside a span (or a
        # bound context), the server joins that trace instead of minting
        tp = trace.current_traceparent()
        if tp is not None and data is not None:
            headers["traceparent"] = tp
        req = urllib.request.Request(
            self.base + path, data=data, method="POST" if data else "GET",
            headers=headers)
        deadline = timeout_s if timeout_s is not None else self.timeout_s
        # idempotent GETs only: a dead POST may have executed server-side
        attempts = 1 + (self.retries if data is None else 0)
        for attempt in range(attempts):
            try:
                with urllib.request.urlopen(req, timeout=deadline) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                # the server answered — retrying an answered request is
                # never the transport's call
                raw = e.read()
                try:
                    detail = json.loads(raw).get(
                        "error", raw.decode("utf-8", "replace"))
                except (ValueError, AttributeError):
                    detail = raw.decode("utf-8", "replace")
                raise ServingError(e.code, detail) from e
            except OSError:
                # URLError (refused / reset), socket timeout, ECONNRESET
                if attempt + 1 >= attempts:
                    raise
                time.sleep(self.retry_backoff_s * (2 ** attempt))

    def _json(self, path: str, payload: dict | None = None,
              timeout_s: float | None = None) -> dict:
        return json.loads(self._request(path, payload, timeout_s=timeout_s))

    # ------------------------------------------------------------ API
    def generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 eos_id: int | None = None,
                 deadline_ms: float | None = None,
                 tenant: str | None = None,
                 priority: int = 0,
                 timeout_s: float | None = None) -> dict:
        body = {"prompt": list(prompt), "max_new_tokens": max_new_tokens,
                "temperature": temperature, "seed": seed}
        if eos_id is not None:
            body["eos_id"] = eos_id
        if deadline_ms is not None:
            body["deadline_ms"] = deadline_ms
        if tenant:
            body["tenant"] = tenant
        if priority:
            body["priority"] = int(priority)
        return self._json("/v1/generate", body, timeout_s=timeout_s)

    def score(self, inputs) -> list:
        return self._json("/v1/score", {"inputs": [list(map(float, r))
                                                   for r in inputs]})["outputs"]

    def migrate_probe(self, prompt, timeout_s: float | None = None) -> dict:
        """Ask the decode side which prompt positions are already
        resident (``{"cached_len", "page_size"}``) — the export plans
        its wire payload around the answer: resident pages ship as
        hash-only claims, zero bytes."""
        return self._json("/v1/migrate", {"probe": {"prompt": list(prompt)}},
                          timeout_s=timeout_s)

    def migrate(self, payload: dict, timeout_s: float | None = None) -> dict:
        """Submit a ``KVMigrator.export_payload`` wire migration and
        block until decode completes (answers like :meth:`generate`).
        A 409 means the probed prefix was evicted between probe and
        import — re-export with full bytes and resubmit."""
        return self._json("/v1/migrate", payload, timeout_s=timeout_s)

    def reload(self, step: int | None = None) -> int:
        body = {} if step is None else {"step": step}
        return self._json("/v1/reload", body)["step"]

    def healthz(self, timeout_s: float | None = None) -> dict:
        return self._json("/healthz", timeout_s=timeout_s)

    def metrics(self, timeout_s: float | None = None) -> dict:
        return self._json("/metrics", timeout_s=timeout_s)

    def metrics_prom(self, timeout_s: float | None = None) -> str:
        return self._request("/metrics.prom", timeout_s=timeout_s).decode()
