"""All-four-axes composition test (VERDICT r3 #6): dp2·pp2·tp2·sp2 in ONE
shard_map program on 16 virtual devices.

The suite's own pool is 8 devices (conftest), so this runs the driver's
``dryrun_multichip`` entry in a subprocess with a 16-device pool — the same
program the driver uses to validate multi-chip sharding.
"""

import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parents[1]


def test_dryrun_16_devices_uses_all_four_axes():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    proc = subprocess.run(
        [sys.executable, str(REPO / "__graft_entry__.py"), "16"],
        capture_output=True, text=True, timeout=500, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1500:]
    out = proc.stdout
    assert "dryrun_multichip OK" in out, out
    for axis in ("'dp': 2", "'tp': 2", "'pp': 2", "'sp': 2"):
        assert axis in out, f"axis {axis} missing from factoring: {out}"
