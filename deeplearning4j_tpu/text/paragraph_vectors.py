"""ParagraphVectors (PV-DBOW).

Capability match of ``models/paragraphvectors/ParagraphVectors.java:38,173``:
document/label vectors trained to predict the words of their documents
(distributed bag of words), sharing the word-side machinery (Huffman HS or
negative sampling) with Word2Vec.  Inference for unseen documents trains a
fresh doc vector with words frozen.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .word2vec import Word2Vec, _hs_step, _ns_step, _sample_negatives


class ParagraphVectors(Word2Vec):
    """PV-DBOW on top of the Word2Vec substrate: 'centers' are doc ids into
    a separate doc-vector table."""

    def __init__(self, sentences: Iterable[str], labels: Sequence[str] | None = None,
                 **kw):
        super().__init__(sentences, **kw)
        self.labels = (list(labels) if labels is not None
                       else [f"DOC_{i}" for i in range(len(self.sentences))])
        assert len(self.labels) == len(self.sentences)
        self.doc_vectors = None
        self._label_index = {l: i for i, l in enumerate(self.labels)}

    def fit(self) -> "ParagraphVectors":
        # 1) word vectors via plain skip-gram
        super().fit()
        # 2) doc vectors via PV-DBOW against the (frozen-structure) softmax
        rng = np.random.default_rng(self.seed + 1)
        key = jax.random.key(self.seed + 1)
        n_docs, d = len(self.sentences), self.layer_size
        self.doc_vectors = jnp.asarray(
            (rng.random((n_docs, d), np.float32) - 0.5) / d)
        codes = jnp.asarray(self._codes, jnp.float32)
        points = jnp.asarray(self._points)
        L = self._codes.shape[1]
        mask_table = jnp.asarray(
            (np.arange(L)[None, :] < self._lengths[:, None]).astype(np.float32))

        doc_ids, word_ids = [], []
        for di, s in enumerate(self.sentences):
            toks = self.tokenizer_factory.create(s).get_tokens()
            idx = [self.vocab.index_of(t) for t in toks]
            idx = [i for i in idx if i >= 0]
            for wi in idx:
                doc_ids.append(di)
                word_ids.append(wi)
        doc_ids = np.asarray(doc_ids, np.int32)
        word_ids = np.asarray(word_ids, np.int32)
        alpha = self.learning_rate
        for it in range(max(1, self.iterations)):
            perm = rng.permutation(doc_ids.shape[0])
            for off in range(0, doc_ids.shape[0], self.batch_size):
                sl = perm[off:off + self.batch_size]
                db = jnp.asarray(doc_ids[sl])
                wb = jnp.asarray(word_ids[sl])
                if self.use_hs:
                    self.doc_vectors, self.syn1 = _hs_step(
                        self.doc_vectors, self.syn1, db,
                        points[wb], codes[wb], mask_table[wb], jnp.float32(alpha))
                if self.negative > 0:
                    key, sub = jax.random.split(key)
                    negs = _sample_negatives(
                        sub, self._unigram_log, (db.shape[0], self.negative))
                    targets = jnp.concatenate([wb[:, None], negs], axis=1)
                    labels = jnp.concatenate(
                        [jnp.ones((db.shape[0], 1), jnp.float32),
                         jnp.zeros((db.shape[0], self.negative), jnp.float32)],
                        axis=1)
                    self.doc_vectors, self.syn1neg = _ns_step(
                        self.doc_vectors, self.syn1neg, db, targets, labels,
                        jnp.float32(alpha))
        return self

    # ------------------------------------------------------------------ queries
    def get_doc_vector(self, label: str) -> np.ndarray | None:
        i = self._label_index.get(label)
        return None if i is None else np.asarray(self.doc_vectors[i])

    def doc_similarity(self, l1: str, l2: str) -> float:
        from .similarity import cosine
        return cosine(self.get_doc_vector(l1), self.get_doc_vector(l2))

    def docs_nearest(self, label: str, n: int = 10) -> list[str]:
        from .similarity import nearest
        vec = self.get_doc_vector(label)
        if vec is None:
            return []
        return nearest(np.asarray(self.doc_vectors), vec, self.labels, n, {label})

    def infer_vector(self, text: str, steps: int = 50,
                     alpha: float = 0.025) -> np.ndarray:
        """Train a fresh doc vector for unseen text (words frozen)."""
        toks = self.tokenizer_factory.create(text).get_tokens()
        idx = np.asarray([self.vocab.index_of(t) for t in toks
                          if self.vocab.index_of(t) >= 0], np.int32)
        rng = np.random.default_rng(0)
        dv = jnp.asarray((rng.random((1, self.layer_size), np.float32) - 0.5)
                         / self.layer_size)
        if idx.size == 0:
            return np.asarray(dv[0])
        db = jnp.zeros((idx.size,), jnp.int32)
        wb = jnp.asarray(idx)
        key = jax.random.key(17)
        if self.use_hs:
            codes = jnp.asarray(self._codes, jnp.float32)
            points = jnp.asarray(self._points)
            L = self._codes.shape[1]
            mask_table = jnp.asarray(
                (np.arange(L)[None, :] < self._lengths[:, None]).astype(np.float32))
            # local COPY: _hs_step donates its inputs — passing self.syn1
            # directly would delete the model's buffer
            syn1 = jnp.array(self.syn1)
            for _ in range(steps):
                dv, syn1 = _hs_step(dv, syn1, db, points[wb], codes[wb],
                                    mask_table[wb], jnp.float32(alpha))
        else:
            # NS-only model: the HS tree is untrained zeros — infer against
            # the trained syn1neg with fresh negatives per step
            syn1neg = jnp.array(self.syn1neg)
            ones = jnp.ones((idx.size, 1), jnp.float32)
            zeros = jnp.zeros((idx.size, self.negative), jnp.float32)
            labels = jnp.concatenate([ones, zeros], axis=1)
            for _ in range(steps):
                key, sub = jax.random.split(key)
                negs = _sample_negatives(sub, self._unigram_log,
                                         (idx.size, self.negative))
                targets = jnp.concatenate([wb[:, None], negs], axis=1)
                dv, syn1neg = _ns_step(dv, syn1neg, db, targets, labels,
                                       jnp.float32(alpha))
        return np.asarray(dv[0])
