"""Worker-process entry point: ``python -m
deeplearning4j_tpu.parallel.worker_main <state_dir> <worker_id>
<performer_spec> [heartbeat_s] [poll_s]``.

The process analog of the reference's ``WorkerActor`` mainline — spawned by
:class:`~.procrunner.ProcessDistributedRunner`.
"""

import sys

from .procrunner import worker_loop

if __name__ == "__main__":
    state_dir, worker_id, performer_spec = sys.argv[1:4]
    heartbeat_s = float(sys.argv[4]) if len(sys.argv) > 4 else 0.05
    poll_s = float(sys.argv[5]) if len(sys.argv) > 5 else 0.02
    worker_loop(state_dir, worker_id, performer_spec, heartbeat_s, poll_s)
