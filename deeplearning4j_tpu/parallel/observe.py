"""Compat shim — the observability layer moved to
``deeplearning4j_tpu.observability``.

The seed's 164-line counter registry + JSON status server grew into a
subpackage (span tracer with Chrome-trace/JSONL export, histogram metrics
with Prometheus exposition, device-memory gauges).  Import from
``deeplearning4j_tpu.observability``; this module re-exports the old names
so existing callers keep working.
"""

from __future__ import annotations

from ..observability import (  # noqa: F401
    METRICS,
    MetricsRegistry,
    StatusServer,
    StepTimer,
    profiler_trace,
)

__all__ = ["METRICS", "MetricsRegistry", "StatusServer", "StepTimer",
           "profiler_trace"]
