"""Paged-attention decode kernel: K/V read through block tables.

The serving engine's paged decode (DESIGN.md §17) stores K/V as
fixed-size pages in a ``(num_pages, page_size, H, Dh)`` pool and
addresses each sequence through an ``(B, n_pages)`` block table.  The
exact-parity read path gathers a row's logical K/V into a dense
``(B, max_len, H, Dh)`` buffer and reuses the dense attention ops —
bitwise, but it materializes max_len per row per layer.  This module's
Pallas candidate streams the pages instead: one program per
(sequence, page), the block table SCALAR-PREFETCHED so each program's
K/V block is DMA'd straight from its physical page, a running softmax
in VMEM scratch across the page axis.  No (B, max_len) intermediate is
ever built.

Two axes ride this read path (DESIGN.md §20):

- **GQA/MQA**: the page pools carry ``n_kv_heads <= n_heads`` heads;
  the kernel broadcasts each K/V head across its static group of
  ``n_heads // n_kv_heads`` query heads in-register instead of
  materializing repeated heads.
- **int8 KV** (kind ``paged_attention_int8``): pages are stored int8
  (or fp8) with per-page, per-head absmax scales (``ops/pallas/
  kv_quant.py``); the kernel dequantizes each page inside the same
  streamed read — one broadcast multiply on the block it DMA'd anyway.

Like every kernel in this tier both kinds enter production only through
the bench auto-pick gate: :func:`reference_paged_attention` /
:func:`reference_paged_attention_int8` (pure jnp, the same gather the
engine's parity path uses) are both the incumbent candidates
("gather"/"gather_int8", source="xla") and the correctness references
the TUNE battery checks the Pallas candidates against — the int8 kind
additionally gated on the ≥0.999 token top-1-agreement floor.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..flash_attention import _VMEM, pltpu

from . import registry

_NEG_INF = -1e30


def reference_paged_attention(q, k_pages, v_pages, block_tables, lengths,
                              **_):
    """Ground truth: gather each row's pages to a dense (B, T, H, Dh)
    view and run the dense decode attention ops over it.

    ``q`` (B, H, Dh) single-position queries, ``k_pages``/``v_pages``
    (P, ps, Kv, Dh) where Kv divides H (Kv < H is GQA/MQA: each K/V
    head serves H//Kv query heads), ``block_tables`` (B, n_pages)
    physical page ids, ``lengths`` (B,) valid K/V prefix per row
    (>= 1).  Returns (B, H, Dh) in ``q``'s dtype.  These are
    byte-for-byte the engine's masked-gather attention ops (repeat-
    heads then dense attend), so this reference IS the parity path.
    """
    ps = k_pages.shape[1]
    B = q.shape[0]
    T = block_tables.shape[1] * ps
    scale = q.shape[-1] ** -0.5
    t = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    flat = jnp.take_along_axis(block_tables, t // ps, axis=1) * ps + t % ps
    k = k_pages.reshape((-1,) + k_pages.shape[2:])[flat]     # (B, T, Kv, Dh)
    v = v_pages.reshape((-1,) + v_pages.shape[2:])[flat]
    n_rep = q.shape[1] // k.shape[2]
    if n_rep > 1:
        k = jnp.repeat(k, n_rep, axis=2)                     # (B, T, H, Dh)
        v = jnp.repeat(v, n_rep, axis=2)
    s = jnp.einsum("bhd,bthd->bht", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where((t < lengths[:, None])[:, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bht,bthd->bhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _accumulate_page(b, j, q, k, v, len_ref, o_ref, acc_ref, m_ref, l_ref,
                     *, page_size: int, n_pages: int):
    """Shared running-softmax body: fold one (ps, Kv, Dh) K/V page into
    the (H, Dh) accumulator for already-scaled f32 queries ``q``
    (H, Dh).  Kv < H is the GQA path: each K/V head is broadcast across
    its static group of H//Kv query heads in-register — no repeated-
    head buffer is ever built."""
    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    H = q.shape[0]
    Kv = k.shape[1]
    if Kv == H:
        s = jnp.sum(q[None, :, :] * k, axis=-1).T            # (H, ps)
    else:
        g = H // Kv
        qg = q.reshape(Kv, g, q.shape[-1])                   # (Kv, g, Dh)
        kt = k.transpose(1, 0, 2)                            # (Kv, ps, Dh)
        s = jnp.sum(qg[:, :, None, :] * kt[:, None, :, :],
                    axis=-1).reshape(H, page_size)           # (H, ps)
    pos = j * page_size + lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)                        # (1, ps)
    mask = pos < len_ref[b]
    s = jnp.where(mask, s, _NEG_INF)
    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, s.max(axis=1))
    # a fully-masked page leaves m_new at -inf; zero its weights
    # explicitly so exp(-inf - -inf) == 1 cannot leak into the sum
    p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)    # (H, ps)
    corr = jnp.exp(m_prev - m_new)
    l_ref[:, 0] = l_ref[:, 0] * corr + p.sum(axis=1)
    if Kv == H:
        pv = jnp.sum(p.T[:, :, None] * v, axis=0)            # (H, Dh)
    else:
        g = H // Kv
        pg = p.reshape(Kv, g, page_size)                     # (Kv, g, ps)
        vt = v.transpose(1, 0, 2)                            # (Kv, ps, Dh)
        pv = jnp.sum(pg[:, :, :, None] * vt[:, None, :, :],
                     axis=2).reshape(H, v.shape[-1])         # (H, Dh)
    acc_ref[...] = acc_ref[...] * corr[:, None] + pv
    m_ref[:, 0] = m_new

    @pl.when(j == n_pages - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, page_size: int, n_pages: int,
                  scale: float):
    b = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                 # (H, Dh)
    k = k_ref[0].astype(jnp.float32)                         # (ps, Kv, Dh)
    v = v_ref[0].astype(jnp.float32)
    _accumulate_page(b, j, q, k, v, len_ref, o_ref, acc_ref, m_ref, l_ref,
                     page_size=page_size, n_pages=n_pages)


def _paged_int8_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                       o_ref, acc_ref, m_ref, l_ref, *, page_size: int,
                       n_pages: int, scale: float):
    b = pl.program_id(0)
    j = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale                 # (H, Dh)
    # dequantize THIS page inside the streamed read: one broadcast
    # multiply by its (Kv,) per-head scale row, DMA'd beside the page
    k = k_ref[0].astype(jnp.float32) * ks_ref[0][None, :, None]
    v = v_ref[0].astype(jnp.float32) * vs_ref[0][None, :, None]
    _accumulate_page(b, j, q, k, v, len_ref, o_ref, acc_ref, m_ref, l_ref,
                     page_size=page_size, n_pages=n_pages)


def paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                    interpret: bool | None = None):
    """Pallas paged decode attention; same signature/result contract as
    :func:`reference_paged_attention` (within the registered tolerance —
    running softmax reassociates the reduction, so NOT bitwise).
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, Dh = q.shape
    ps = k_pages.shape[1]
    Kv = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    scale = Dh ** -0.5
    kernel = functools.partial(_paged_kernel, page_size=ps, n_pages=n_pages,
                               scale=scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda b, j, bt, ln: (b, 0, 0), **mem),
            # the paged read itself: this program's K/V block is whatever
            # physical page the scalar-prefetched table names
            pl.BlockSpec((1, ps, Kv, Dh),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0, 0), **mem),
            pl.BlockSpec((1, ps, Kv, Dh),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0, 0), **mem),
        ],
        out_specs=pl.BlockSpec((1, H, Dh), lambda b, j, bt, ln: (b, 0, 0),
                               **mem),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages)


def reference_paged_attention_int8(q, k_pages, v_pages, k_scale, v_scale,
                                   block_tables, lengths, **_):
    """Ground truth for the quantized kind: dequantize the whole pool
    via the same :mod:`kv_quant` helpers the engine's parity gather
    uses, then delegate to :func:`reference_paged_attention`.  This IS
    the engine's jnp path when ``kv_quant`` is on, so candidate-vs-
    reference agreement is exactly served-vs-offline agreement."""
    from . import kv_quant
    kf = kv_quant.dequantize_pool(k_pages, k_scale, q.dtype)
    vf = kv_quant.dequantize_pool(v_pages, v_scale, q.dtype)
    return reference_paged_attention(q, kf, vf, block_tables, lengths)


def paged_attention_int8(q, k_pages, v_pages, k_scale, v_scale,
                         block_tables, lengths, *,
                         interpret: bool | None = None):
    """Pallas paged decode attention over int8/fp8 pages with
    per-(page, kv_head) f32 scales; same contract as
    :func:`reference_paged_attention_int8` within the registered
    tolerance.  The dequantize happens in-kernel on each streamed
    page block — the full-precision pool is never materialized."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, Dh = q.shape
    ps = k_pages.shape[1]
    Kv = k_pages.shape[2]
    n_pages = block_tables.shape[1]
    scale = Dh ** -0.5
    kernel = functools.partial(_paged_int8_kernel, page_size=ps,
                               n_pages=n_pages, scale=scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    page_spec = pl.BlockSpec((1, ps, Kv, Dh),
                             lambda b, j, bt, ln: (bt[b, j], 0, 0, 0), **mem)
    # each page's (Kv,) scale row rides the same block-table index as
    # the page it scales
    scale_spec = pl.BlockSpec((1, Kv),
                              lambda b, j, bt, ln: (bt[b, j], 0), **mem)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda b, j, bt, ln: (b, 0, 0), **mem),
            page_spec, page_spec, scale_spec, scale_spec,
        ],
        out_specs=pl.BlockSpec((1, H, Dh), lambda b, j, bt, ln: (b, 0, 0),
                               **mem),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages, k_scale.astype(jnp.float32),
      v_scale.astype(jnp.float32))


registry.register(registry.KernelCandidate(
    kind="paged_attention", name="pallas", fn=paged_attention,
    reference=reference_paged_attention,
    blocks=({},),              # the page size IS the block: nothing to sweep
    tolerances={"max_err": 0.05},
))

registry.register(registry.KernelCandidate(
    kind="paged_attention", name="gather", fn=reference_paged_attention,
    reference=reference_paged_attention, source="xla",
))

registry.register(registry.KernelCandidate(
    kind="paged_attention_int8", name="pallas_int8", fn=paged_attention_int8,
    reference=reference_paged_attention_int8,
    blocks=({},),
    # same numeric band as the float kind, PLUS the served-token
    # agreement floor the int8 weight path already enforces: autopick
    # cannot adopt a cache precision that flips >1/1000 greedy tokens
    tolerances={"max_err": 0.05, "min": {"top1_agree": 0.999}},
))

registry.register(registry.KernelCandidate(
    kind="paged_attention_int8", name="gather_int8",
    fn=reference_paged_attention_int8,
    reference=reference_paged_attention_int8, source="xla",
))
