"""Model zoo: reference-parity network builders.

The reference composes its model families from configs
(``MultiLayerConfiguration``); these builders produce the classic stacks:
MLP, DBN (RBM pretrain + softmax head, ``MultiLayerTest.java:33-70``),
stacked denoising autoencoders, and LeNet-style conv nets (BASELINE.json's
"LeNet MNIST" smoke config).
"""

from __future__ import annotations

from ..nn.conf import (
    LayerKind,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OptimizationAlgorithm,
    RBMHiddenUnit,
    RBMVisibleUnit,
    list_builder,
)
from ..nn.multilayer import MultiLayerNetwork


def mlp(n_in: int, n_out: int, hidden: tuple[int, ...] = (256,), *,
        activation: str = "tanh", lr: float = 0.1, num_iterations: int = 100,
        seed: int = 123) -> MultiLayerNetwork:
    base = NeuralNetConfiguration(
        n_in=n_in, n_out=n_out, lr=lr, use_adagrad=True, momentum=0.9,
        num_iterations=num_iterations, activation=activation, seed=seed,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    conf = (list_builder(base, len(hidden) + 1)
            .hidden_layer_sizes(*hidden)
            .override(len(hidden), kind="output", activation="softmax",
                      loss="mcxent")
            .pretrain(False)
            .build())
    return MultiLayerNetwork(conf)


def dbn(n_in: int, n_out: int, hidden: tuple[int, ...] = (500, 250), *,
        visible_unit: RBMVisibleUnit = RBMVisibleUnit.BINARY,
        hidden_unit: RBMHiddenUnit = RBMHiddenUnit.BINARY,
        k: int = 1, lr: float = 0.05, pretrain_iterations: int = 100,
        finetune_iterations: int = 200, seed: int = 123) -> MultiLayerNetwork:
    """Deep belief net: greedy RBM pretrain + supervised softmax finetune."""
    base = NeuralNetConfiguration(
        n_in=n_in, n_out=n_out, lr=lr, use_adagrad=True, k=k,
        kind=LayerKind.RBM, visible_unit=visible_unit, hidden_unit=hidden_unit,
        num_iterations=pretrain_iterations, activation="sigmoid", seed=seed,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    conf = (list_builder(base, len(hidden) + 1)
            .hidden_layer_sizes(*hidden)
            .override(len(hidden), kind="output", activation="softmax",
                      loss="mcxent", num_iterations=finetune_iterations)
            .pretrain(True)
            .build())
    return MultiLayerNetwork(conf)


def stacked_denoising_autoencoder(n_in: int, n_out: int,
                                  hidden: tuple[int, ...] = (500, 250), *,
                                  corruption_level: float = 0.3,
                                  lr: float = 0.05, pretrain_iterations: int = 100,
                                  finetune_iterations: int = 200,
                                  seed: int = 123) -> MultiLayerNetwork:
    base = NeuralNetConfiguration(
        n_in=n_in, n_out=n_out, lr=lr, use_adagrad=True,
        kind=LayerKind.AUTOENCODER, corruption_level=corruption_level,
        num_iterations=pretrain_iterations, activation="sigmoid", seed=seed,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    conf = (list_builder(base, len(hidden) + 1)
            .hidden_layer_sizes(*hidden)
            .override(len(hidden), kind="output", activation="softmax",
                      loss="mcxent", num_iterations=finetune_iterations)
            .pretrain(True)
            .build())
    return MultiLayerNetwork(conf)


def lenet(n_classes: int = 10, *, input_side: int = 28, channels: int = 1,
          num_filters: int = 6, filter_size: tuple[int, int] = (5, 5),
          pool: tuple[int, int] = (2, 2), lr: float = 0.05,
          num_iterations: int = 100, seed: int = 123) -> MultiLayerNetwork:
    """LeNet-style conv -> pool -> dense -> softmax (the reference's conv
    capability is a single ConvolutionDownSampleLayer; this mirrors that
    plus a working backward pass)."""
    conv_out_side = (input_side - filter_size[0] + 1) // pool[0]
    flat = conv_out_side * conv_out_side * num_filters
    conv_conf = NeuralNetConfiguration(
        kind=LayerKind.CONVOLUTION_DOWNSAMPLE, n_in=channels,
        num_filters=num_filters, filter_size=filter_size, stride=pool,
        activation="relu", lr=lr, seed=seed,
        num_iterations=num_iterations,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    dense_conf = NeuralNetConfiguration(
        kind=LayerKind.DENSE, n_in=flat, n_out=120, activation="tanh", lr=lr,
        seed=seed + 1, num_iterations=num_iterations,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    out_conf = NeuralNetConfiguration(
        kind=LayerKind.OUTPUT, n_in=120, n_out=n_classes,
        activation="softmax", loss="mcxent", lr=lr, seed=seed + 2,
        num_iterations=num_iterations,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    conf = MultiLayerConfiguration(
        confs=(conv_conf, dense_conf, out_conf), pretrain=False,
        preprocessors={0: "flatten"})  # conv output -> dense input
    return MultiLayerNetwork(conf)


def draft_lm(target_cfg, *, n_layers: int = 1, width_divisor: int = 2,
             seed: int = 0):
    """Zoo recipe for a speculative-decoding draft: a shallower, thinner
    ``TransformerLM`` sharing the target's ``vocab_size``/``max_len``
    (the :class:`~..serving.engine.InferenceEngine` compatibility
    contract — the draft proposes token ids the target verifies, and its
    KV cache is indexed by the same positions).  Returns
    ``(model, params)``; train the params or use them as-is — a bad
    draft only lowers ``serving.spec_accept_len``, never changes served
    tokens.
    """
    import dataclasses as _dc

    import jax as _jax

    from .transformer import TransformerLM

    heads = max(1, target_cfg.n_heads // width_divisor)
    d_model = max(heads * (target_cfg.d_model // target_cfg.n_heads),
                  target_cfg.d_model // width_divisor)
    d_model -= d_model % heads
    cfg = _dc.replace(
        target_cfg, n_layers=max(1, n_layers), d_model=d_model,
        n_heads=heads, d_ff=max(d_model, target_cfg.d_ff // width_divisor),
        remat=False)
    model = TransformerLM(cfg)
    return model, model.init(_jax.random.key(seed))
