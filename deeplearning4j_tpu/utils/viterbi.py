"""Viterbi sequence decoder.

Capability match of ``util/Viterbi.java:15,47-57``: most-likely label
sequence given per-step emission scores and a transition matrix.  The DP
recursion runs under ``lax.scan`` (device-friendly) with host argmax
traceback.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def viterbi_decode(emissions, transitions, initial=None):
    """emissions: (T, S) log scores; transitions: (S, S) log p(j <- i).

    Returns (path indices (T,), best log score)."""
    emissions = jnp.asarray(emissions, jnp.float32)
    transitions = jnp.asarray(transitions, jnp.float32)
    T, S = emissions.shape
    init = (jnp.zeros((S,), jnp.float32) if initial is None
            else jnp.asarray(initial, jnp.float32))

    def step(prev_scores, emit):
        scores = prev_scores[:, None] + transitions + emit[None, :]
        best_prev = jnp.argmax(scores, axis=0)
        new_scores = jnp.max(scores, axis=0)
        return new_scores, best_prev

    first = init + emissions[0]
    final_scores, backptrs = jax.lax.scan(step, first, emissions[1:])
    path = np.zeros(T, np.int64)
    path[-1] = int(jnp.argmax(final_scores))
    bp = np.asarray(backptrs)
    for t in range(T - 2, -1, -1):
        path[t] = bp[t, path[t + 1]]
    return path, float(jnp.max(final_scores))


class Viterbi:
    """Binary-label decoder over window predictions (the reference decodes
    word-window label sequences with a fixed switching penalty)."""

    def __init__(self, possible_labels, transition_prob: float = 0.95):
        self.labels = list(possible_labels)
        s = len(self.labels)
        stay = np.log(transition_prob)
        switch = np.log(max(1e-12, (1 - transition_prob) / max(1, s - 1)))
        self.transitions = np.full((s, s), switch)
        np.fill_diagonal(self.transitions, stay)

    def decode(self, emission_probs) -> list:
        em = np.log(np.maximum(np.asarray(emission_probs, np.float64), 1e-12))
        path, _ = viterbi_decode(em, self.transitions)
        return [self.labels[i] for i in path]
