"""Loss functions.

TPU-native equivalent of ND4J ``LossFunctions`` as consumed by the reference's
``nn/layers/OutputLayer.java:70-73,125-150`` and the pretrain score in
``nn/layers/BasePretrainNetwork.java``.  All losses take ``(labels, output)``
with ``output`` already activated (e.g. softmax probabilities for MCXENT) and
return the *mean over examples* as a scalar.  Each loss is a pure jnp
composition so it fuses into the surrounding jitted step, and is
differentiable so `jax.grad` reproduces (and generalizes) the reference's
hand-coded loss-specific weight gradients (``OutputLayer.java:93-154``).
"""

from __future__ import annotations

import enum
from typing import Callable

import jax
import jax.numpy as jnp

_EPS = 1e-7


class LossFunction(str, enum.Enum):
    """Names mirror the reference's LossFunctions.LossFunction enum."""

    MSE = "mse"
    EXPLL = "expll"                 # exponential log likelihood (Poisson-like)
    XENT = "xent"                   # elementwise binary cross entropy
    MCXENT = "mcxent"               # multiclass cross entropy (softmax output)
    RMSE_XENT = "rmse_xent"         # sqrt of squared-error (reference quirk)
    SQUARED_LOSS = "squared_loss"   # summed squared error (no 1/2)
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"

    # --- additions beyond the v0 reference (needed by modern heads) ---
    COSINE_PROXIMITY = "cosine_proximity"
    HINGE = "hinge"
    L1 = "l1"
    BLOCKED_MCXENT = "blocked_mcxent"   # streaming xent — takes RAW logits
    #                                     (or an (h, head) pair), not
    #                                     softmax output; see blocked_mcxent


def _clip(p):
    return jnp.clip(p, _EPS, 1.0 - _EPS)


def mse(labels, output):
    return jnp.mean(jnp.sum((labels - output) ** 2, axis=-1)) / 2.0


def squared_loss(labels, output):
    return jnp.mean(jnp.sum((labels - output) ** 2, axis=-1))


def rmse_xent(labels, output):
    # Reference computes sqrt(pow(labels - output, 2)) i.e. mean |error|-ish;
    # kept as root of summed squared error per row for parity of intent.
    return jnp.mean(jnp.sqrt(jnp.sum((labels - output) ** 2, axis=-1) + _EPS))


def xent(labels, output):
    p = _clip(output)
    return -jnp.mean(jnp.sum(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p), axis=-1))


def mcxent(labels, output):
    return -jnp.mean(jnp.sum(labels * jnp.log(_clip(output)), axis=-1))


def expll(labels, output):
    p = jnp.clip(output, _EPS, None)
    return jnp.mean(jnp.sum(p - labels * jnp.log(p), axis=-1))


def negativeloglikelihood(labels, output):
    return -jnp.mean(jnp.sum(labels * jnp.log(_clip(output)), axis=-1))


def reconstruction_crossentropy(labels, output):
    return xent(labels, output)


def cosine_proximity(labels, output):
    ln = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + _EPS)
    on = output / (jnp.linalg.norm(output, axis=-1, keepdims=True) + _EPS)
    return -jnp.mean(jnp.sum(ln * on, axis=-1))


def hinge(labels, output):
    # labels in {0,1} one-hot or {-1,1}
    y = jnp.where(labels > 0, 1.0, -1.0)
    return jnp.mean(jnp.sum(jnp.maximum(0.0, 1.0 - y * output), axis=-1))


def l1(labels, output):
    return jnp.mean(jnp.sum(jnp.abs(labels - output), axis=-1))


# --------------------------------------------------------- blocked xent tier
#
# The streaming token cross entropy consumed by the transformer's
# lm_head_loss.  Backend selection happens ONCE at import: the Pallas
# blocked kernel when the wheel has a working jax.experimental.pallas,
# else the zero-weight-padded scan fallback (same math, tile logits do
# materialize) — never a per-call try/except on the hot path.

try:
    from .pallas.xent import blocked_cross_entropy as _BLOCKED_XENT_IMPL
    BLOCKED_XENT_BACKEND = "pallas"
except Exception:  # pragma: no cover - old wheel / broken pallas
    _BLOCKED_XENT_IMPL = None
    BLOCKED_XENT_BACKEND = "reference"


def _blocked_xent_fallback(h, head, targets, weights=None, *,
                           block_t: int = 256, **_):
    """Scan over zero-weight-padded token tiles (the PR-5 near-prime
    schedule, shape-generalized) — used only when Pallas is absent."""
    from jax import lax

    n, d = h.shape
    block_t = min(block_t, n)
    w = (jnp.ones((n,), jnp.float32) if weights is None
         else weights.astype(jnp.float32))
    t = targets
    pad = -n % block_t
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])

    @jax.checkpoint
    def tile(h_t, t_t, w_t):
        logits = jnp.dot(h_t, head,
                         preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits.astype(jnp.float32), -1)
        gold = jnp.take_along_axis(
            logits.astype(jnp.float32), t_t[:, None], axis=-1)[:, 0]
        return ((lse - gold) * w_t).sum()

    def body(tot, xs):
        return tot + tile(*xs), None

    total, _ = lax.scan(
        body, jnp.zeros((), jnp.float32),
        (h.reshape(-1, block_t, d), t.reshape(-1, block_t),
         w.reshape(-1, block_t)))
    return total


if _BLOCKED_XENT_IMPL is None:
    _BLOCKED_XENT_IMPL = _blocked_xent_fallback


def blocked_token_xent(h, head, targets, weights=None, **kw) -> jnp.ndarray:
    """Weighted SUM of per-token cross entropy of (N, D) hiddens against
    a (D, V) head, streamed tile-by-tile (full logits never materialize
    on the pallas backend).  Shape-independent: any N/V.  The backend was
    selected at import (``BLOCKED_XENT_BACKEND``)."""
    return _BLOCKED_XENT_IMPL(h, head, targets, weights, **kw)


def blocked_mcxent(labels, output):
    """Dispatch-table face of the blocked xent tier.

    Unlike every other entry, ``output`` is NOT softmax probabilities:
    pass either raw logits (N, C) — computed stably from the lse — or an
    ``(hiddens, head)`` tuple, in which case the selected streaming
    backend runs and (N, V) logits never materialize.  ``labels`` are
    one-hot rows either way; returns the mean over examples."""
    if isinstance(output, tuple):
        h, head = output
        targets = jnp.argmax(labels, axis=-1)
        return blocked_token_xent(h, head, targets) / labels.shape[0]
    logits = output.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.sum(labels * logits, axis=-1)
    return jnp.mean(lse - gold)


_FNS: dict[LossFunction, Callable] = {
    LossFunction.MSE: mse,
    LossFunction.EXPLL: expll,
    LossFunction.XENT: xent,
    LossFunction.MCXENT: mcxent,
    LossFunction.RMSE_XENT: rmse_xent,
    LossFunction.SQUARED_LOSS: squared_loss,
    LossFunction.NEGATIVELOGLIKELIHOOD: negativeloglikelihood,
    LossFunction.RECONSTRUCTION_CROSSENTROPY: reconstruction_crossentropy,
    LossFunction.COSINE_PROXIMITY: cosine_proximity,
    LossFunction.HINGE: hinge,
    LossFunction.L1: l1,
    LossFunction.BLOCKED_MCXENT: blocked_mcxent,
}


def get(loss: LossFunction | str) -> Callable:
    return _FNS[LossFunction(loss)]


def score(loss: LossFunction | str, labels, output) -> jnp.ndarray:
    return get(loss)(labels, output)
