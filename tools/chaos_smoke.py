"""Chaos smoke: randomized fault injection against the training supervisor.

Draws a random fault plan (transient step failure, corrupted checkpoint
write, data-pipeline failure, simulated preemption) from a seed, runs a
short supervised CPU fit under it, and asserts the run COMPLETES with
parameters bitwise identical to a fault-free reference — the end-to-end
recovery contract of DESIGN.md §12.  One plan runs per ZeRO stage
(0/1/2/3, DESIGN.md §15), each compared against the replicated fault-free
reference, so sharded-state checkpoints prove the same recovery contract.
The seed is printed in the JSON result line, so any failing draw is
replayable with ``python tools/chaos_smoke.py --seed N [--stage K]``.
``--shardguard`` runs every leg with runtime sharding-drift detection
(analysis/shardguard.py) and fails on any implicit resharding.

A second leg (``run_serving``) points the same dice at the serving
subsystem: ``serving.request`` submission faults and ``serving.decode``
dispatch skips, asserting completions stay token-identical to the
fault-free ``Transformer.sample`` reference.

A disagg leg (``run_disagg``, replay with ``--disagg --seed N``, part
of the default composite) points the dice at the disaggregated tier
(DESIGN.md §27): prefill workers killed before/after their prefill and
migrations aborted with decode-side claims held, asserting every
completion still matches the offline reference token-for-token, every
abort requeued, and both pools' refcounts balance to zero leaked pages.

A third leg (``run_elastic``, replay with ``--elastic --seed N``) rolls
the elasticity dice: ``mesh.shrink`` kills 1-3 chips mid-run (sometimes
handed back via ``mesh.grow``, sometimes with the resharding restore
itself failing once via ``checkpoint.reshard``) and asserts training
finishes on the surviving mesh inside the documented loss window with a
``mesh_resize`` flight bundle emitted (DESIGN.md §21).

A fifth leg (``run_overload``, replay with ``--overload --seed N``)
walks the control plane (DESIGN.md §26): the brownout ladder up and
down with token parity asserted for everything served at EVERY level
(the level-2 clamp must serve the exact offline-sample prefix, level 3
must shed background work while interactive keeps parity), a tight
fair-share bucket that throttles only the noisy tenant, and a
``control.autoscaler`` chaos kill mid-run that must freeze a real
router pool at static capacity with routing still exact.

A fourth leg (``run_online``, replay with ``--online --seed N``) points
the dice at the online learning loop (DESIGN.md §23): capture damage,
replay faults, fine-tune step failures, a poisoned publish, an aborted
reload, and a failing rollback seam — asserting every served response
still matches offline sampling under its OWN generation stamp, the
poisoned checkpoint always quarantines and rolls back with a flight
bundle, the loop republishes and heals, and the faulted fine-tune's
goodput audit passes.

Every supervised leg also audits the goodput accounting (DESIGN.md §22):
the run's state timeline must be exhaustive, sum to independently
measured wall-clock within 1%, and ``goodput.fraction`` must strictly
decrease versus the no-fault reference of the same seed — faults cost
wall-clock, and the accounting has to see exactly how much.

The deterministic tier-1 subset lives in ``tests/test_resilience.py`` and
``tests/test_serving.py`` (fixed plans, per-mechanism assertions); this
tool exists to keep rolling the dice on plan *combinations* nobody
hand-picked.
"""

from __future__ import annotations

import json
import math
import random
import sys
import tempfile
import time

N_BATCHES = 8
BATCH = 8


def _goodput_check(sup, ref_report: dict, measured_wall_s: float,
                   seed: int) -> dict:
    """Shared goodput acceptance (ISSUE 14): the supervised run's state
    timeline must be exhaustive over the documented states, sum to the
    independently measured wall-clock within 1%, and its productive
    fraction must be STRICTLY below the no-fault reference run of the
    same seed (faults cost wall-clock; the accounting must see it)."""
    from deeplearning4j_tpu.observability.goodput import STATES

    rep = sup.report.goodput
    assert rep is not None, f"seed {seed}: supervisor produced no goodput report"
    assert set(rep["states"]) <= set(STATES), rep["states"]
    acct, wall = rep["accounted_seconds"], rep["wall_seconds"]
    # timeline intervals are contiguous by construction: they must cover
    # the tracker's own wall exactly, and the tracker's wall must agree
    # with the clock we ran around the whole supervised fit
    assert abs(acct - wall) <= max(0.01 * wall, 1e-6), \
        f"seed {seed}: goodput timeline {acct:.4f}s != wall {wall:.4f}s"
    assert abs(acct - measured_wall_s) <= max(0.01 * measured_wall_s, 0.02), \
        (f"seed {seed}: goodput timeline {acct:.4f}s vs measured "
         f"wall {measured_wall_s:.4f}s (>1%)")
    overhead = sum(v for k, v in rep["seconds"].items() if k != "productive")
    assert rep["fraction"] < ref_report["fraction"], \
        (f"seed {seed}: goodput fraction {rep['fraction']:.4f} did not "
         f"decrease vs no-fault {ref_report['fraction']:.4f}")
    assert overhead > 0.0, f"seed {seed}: fault run accounted no overhead"
    return {
        "fraction": rep["fraction"],
        "ref_fraction": ref_report["fraction"],
        "seconds": {k: round(v, 6) for k, v in rep["seconds"].items()},
        "states": rep["states"],
        "wall_seconds": rep["wall_seconds"],
        "measured_wall_seconds": measured_wall_s,
    }


def _draw_plan(rng: random.Random):
    """A random-but-replayable fault plan over the supervised sites."""
    from deeplearning4j_tpu.resilience import FaultSpec

    specs = [
        FaultSpec("train.step", at_step=rng.randint(2, N_BATCHES)),
        # checkpoint_every=2 -> corrupt a write that actually happens
        FaultSpec("checkpoint.write",
                  at_step=2 * rng.randint(1, N_BATCHES // 2),
                  kind=rng.choice(["truncate", "bitflip"])),
    ]
    if rng.random() < 0.5:
        specs.append(FaultSpec("data.next", at_step=rng.randint(2, N_BATCHES)))
    if rng.random() < 0.5:
        specs.append(FaultSpec("preempt", at_step=rng.randint(2, N_BATCHES - 1)))
    return specs


def run(seed: int | None = None, zero_stage: int = 0) -> dict:
    import jax
    import numpy as np

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer
    from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
    from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
    from deeplearning4j_tpu.resilience import (
        RetryPolicy, TrainingSupervisor, inject_faults)

    if seed is None:
        seed = random.SystemRandom().randrange(2 ** 31)
    rng = random.Random(seed)

    observability.enable()
    METRICS.reset()

    w_true = np.asarray([1.0, -2.0, 0.5], np.float32)
    xs = np.asarray(jax.random.normal(jax.random.key(3),
                                      (N_BATCHES * BATCH, 3)))
    ys = xs @ w_true

    class Batch:
        def __init__(self, x, y):
            self.features, self.labels = x, y

    data = [Batch(xs[i * BATCH:(i + 1) * BATCH],
                  ys[i * BATCH:(i + 1) * BATCH]) for i in range(N_BATCHES)]

    def loss_fn(p, xb, yb, key=None):
        return jax.numpy.mean(((xb @ p["w"]) - yb) ** 2)

    def new_trainer(stage=zero_stage):
        mesh = make_mesh(MeshSpec(dp=8), devices=jax.devices()[:8])
        return DataParallelTrainer(loss_fn, T.chain(T.momentum(0.9),
                                                    T.sgd_lr(5e-2)),
                                   mesh=mesh, zero_stage=stage)

    params = {"w": np.zeros(3, np.float32)}
    # the fault-free reference always runs REPLICATED (stage 0): the chaos
    # claim under ZeRO is recovery parity against classic numerics, not
    # just against another sharded run
    from deeplearning4j_tpu.observability import GoodputTracker
    t_ref = new_trainer(stage=0)
    gp_ref = GoodputTracker()
    s_ref, ref_losses = t_ref.fit(t_ref.init_state(params), data, epochs=1,
                                  goodput=gp_ref)
    ref_goodput = gp_ref.finish()

    plan = _draw_plan(rng)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        mgr = CheckpointManager(ckpt_dir, keep=10)
        with inject_faults(*plan, seed=seed):
            sup = TrainingSupervisor(
                mgr, RetryPolicy(max_attempts=8, backoff_base_s=0.01),
                install_signal_handlers=False)
            trainer = new_trainer()
            t_wall = time.monotonic()
            state, losses = sup.fit(trainer, params, data, epochs=1,
                                    checkpoint_every=2)
            wall_s = time.monotonic() - t_wall

    # compare NATURAL layouts: under zero_stage=3 state.params are the
    # flat dp-sharded chunks, so collapse both sides via final_params
    params_equal = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree_util.tree_leaves(t_ref.final_params(s_ref)),
            jax.tree_util.tree_leaves(trainer.final_params(state))))
    # losses from aborted attempts die with the pending ring, leaving
    # gaps, so align by STEP: every loss a successful attempt resolved
    # must match the reference loss at the same step exactly
    by_step = sup.report.losses_by_step
    loss_parity = all(v == ref_losses[s - 1] for s, v in by_step.items())
    counters = METRICS.snapshot()["counters"]
    result = {
        "seed": seed,
        "zero_stage": zero_stage,
        "plan": [f"{s.site}:at={s.at_step},kind={s.kind}" for s in plan],
        "final_step": int(state.step),
        "ref_step": int(s_ref.step),
        "params_bitwise_equal": params_equal,
        "loss_parity": loss_parity,
        "losses_recovered": len(by_step),
        "losses_finite": all(math.isfinite(v) for v in losses),
        "attempts": sup.report.attempts,
        "retries": sup.report.retries,
        "preemptions": sup.report.preemptions,
        "resumed_from": sup.report.resumed_from,
        "faults_injected": {k: int(v) for k, v in counters.items()
                            if k.startswith("faults.injected.")},
        "corrupt_detected": int(counters.get("checkpoint.corrupt_detected", 0)),
        "goodput": _goodput_check(sup, ref_goodput, wall_s, seed),
    }
    assert result["final_step"] == result["ref_step"], \
        f"seed {seed}: chaos run stopped at step {result['final_step']}"
    assert params_equal, f"seed {seed}: parameters diverged from reference"
    assert loss_parity, f"seed {seed}: recovered losses diverged"
    assert result["faults_injected"], f"seed {seed}: plan never fired"
    return result


def run_serving(seed: int, kv_quant: str | None = None) -> dict:
    """Chaos leg for the serving subsystem: fire ``serving.request`` at a
    random submit index and ``serving.decode`` for a random number of
    decode rounds, and assert every completion is STILL token-identical
    to the fault-free ``Transformer.sample`` reference — the engine's
    skip-and-retry contract (a skipped dispatch leaves state untouched).

    With ``kv_quant`` set the same dice roll runs against a paged +
    prefix-cache engine with quantized KV pages and ALL-greedy requests:
    exact parity relaxes to the >= 0.999 served-token top-1 agreement
    floor (the same floor the autopick gate enforces), so fault-driven
    retry/skip paths are exercised through the quantized write path too.

    The whole leg runs under lockguard: injected faults drive the
    engine's error paths (submit retry, decode skip, eviction on
    failure), which are exactly the paths the lock discipline is easiest
    to get wrong on — any lock-order inversion or unguarded shared write
    observed fails the leg alongside the parity assertion."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.analysis.lockguard import LockGuard
    from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
    from deeplearning4j_tpu.resilience.faults import FAULTS, InjectedFault
    from deeplearning4j_tpu.serving import InferenceEngine, ServingConfig

    rng = random.Random(seed + 1)
    observability.enable()
    METRICS.reset()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=32, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(11))
    # quantized KV holds a top-1 agreement floor, not bitwise parity —
    # meaningful only for greedy decoding, so the int8 leg pins temp to 0
    # and sharpens the model so margins measure the quantizer, not init
    # noise (see serving_smoke._sharpen)
    temps = [0.0, 0.8]
    if kv_quant is not None:
        from tools.serving_smoke import _sharpen
        params = _sharpen(model, params, cfg)
        temps = [0.0]
    reqs = [dict(prompt=[rng.randrange(cfg.vocab_size)
                         for _ in range(rng.randint(1, 10))],
                 max_new_tokens=rng.randint(1, 8),
                 temperature=rng.choice(temps),
                 seed=rng.randrange(1 << 16))
            for _ in range(5)]
    expected = [model.sample(params, r["prompt"], r["max_new_tokens"],
                             temperature=r["temperature"],
                             key=jax.random.key(r["seed"]),
                             kv_cache=True)[len(r["prompt"]):]
                for r in reqs]

    decode_fires = rng.randint(1, 3)
    submit_fire_at = rng.randint(1, len(reqs))
    specs = [FaultSpec("serving.decode", probability=1.0,
                       max_fires=decode_fires),
             FaultSpec("serving.request", at_step=submit_fire_at)]
    submit_faults = 0
    scfg = (ServingConfig(slots=3, resolve_every=2) if kv_quant is None
            else ServingConfig(slots=3, resolve_every=2, paged=True,
                               page_size=4, prefix_cache=True,
                               kv_quant=kv_quant))
    guard = LockGuard().install()
    try:
        with inject_faults(*specs, seed=seed):
            engine = InferenceEngine(model, params=params, cfg=scfg).start()
            handles = []
            for r in reqs:
                try:
                    handles.append(engine.submit(**r))
                except InjectedFault:
                    submit_faults += 1
                    handles.append(engine.submit(**r))  # transient: retry wins
            outs = [h.result(60.0) for h in handles]
            engine.stop()
            fired = {"serving.decode": FAULTS.fire_count("serving.decode"),
                     "serving.request": FAULTS.fire_count("serving.request")}
    finally:
        guard.uninstall()

    parity = all(o.tokens == e for o, e in zip(outs, expected))
    total = sum(len(e) for e in expected)
    agree = sum(1 for o, e in zip(outs, expected)
                for x, y in zip(o.tokens, e) if x == y)
    agreement = agree / total if total else 0.0
    result = {
        "seed": seed,
        "requests": len(reqs),
        "kv_quant": kv_quant,
        "token_parity_under_faults": parity,
        "token_agreement_under_faults": agreement,
        "decode_faults_fired": fired["serving.decode"],
        "submit_faults_fired": fired["serving.request"],
        "submit_retries": submit_faults,
        "lockguard_violations": len(guard.violations()),
    }
    if kv_quant is None:
        assert parity, f"seed {seed}: served tokens diverged under injection"
    else:
        assert agreement >= 0.999, (
            f"seed {seed}: kv_quant={kv_quant} token agreement "
            f"{agreement:.4f} under the 0.999 floor")
    assert fired["serving.decode"] == decode_fires, result
    assert fired["serving.request"] == 1 and submit_faults == 1, result
    assert not guard.violations(), guard.report()
    return result


def run_disagg(seed: int) -> dict:
    """Chaos leg for the disaggregated tier (DESIGN.md §27): fire
    ``disagg.prefill_worker`` (a prefill worker dies before or after its
    prefill ran) and ``disagg.migrate`` (the page transfer aborts
    mid-flight, decode-side claims already held) at random draw points
    while a batch of requests streams through prefill + migration +
    decode, and assert the tier's whole failure contract at once: every
    completion is STILL token-identical to the fault-free
    ``Transformer.sample`` reference (a killed migration only ever
    REQUEUES — the single-shot completion can never carry tokens from a
    half-migrated decode), the requeue counter saw every abort, and
    after the dust settles both pools' refcounts balance — zero leaked
    pages.  Runs under lockguard: the abort paths cross the pool,
    engine and scheduler locks in exactly the orders easiest to get
    wrong."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.analysis.lockguard import LockGuard
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
    from deeplearning4j_tpu.resilience.faults import FAULTS
    from deeplearning4j_tpu.serving import DisaggScheduler, InferenceEngine, \
        ServingConfig

    rng = random.Random(seed + 6)
    observability.enable()
    METRICS.reset()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=64, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(11))

    def mk(role):
        return InferenceEngine(
            model, params=params,
            cfg=ServingConfig(slots=4, resolve_every=4, max_queue=64,
                              paged=True, page_size=8, prefix_cache=True,
                              role=role))

    reqs = [dict(prompt=[rng.randrange(cfg.vocab_size)
                         for _ in range(rng.randint(2, 12))],
                 max_new_tokens=rng.randint(1, 8),
                 temperature=rng.choice([0.0, 0.8]),
                 seed=rng.randrange(1 << 16))
            for _ in range(6)]
    expected = [model.sample(params, r["prompt"], r["max_new_tokens"],
                             temperature=r["temperature"],
                             key=jax.random.key(r["seed"]),
                             kv_cache=True)[len(r["prompt"]):]
                for r in reqs]

    # the worker site fires twice per attempt (before and after the
    # prefill), the migrate site twice per migration — draw the abort
    # points so both "nothing acquired yet" and "claims held" unwind
    # paths get exercised across seeds
    worker_fires = rng.randint(1, 2)
    specs = [FaultSpec("disagg.prefill_worker",
                       at_step=rng.randint(1, 4), max_fires=worker_fires),
             FaultSpec("disagg.migrate",
                       at_step=rng.randint(1, 6), max_fires=1)]

    guard = LockGuard().install()
    pf = mk("prefill")
    dec = mk("decode")
    try:
        with inject_faults(*specs, seed=seed):
            sched = DisaggScheduler([pf], dec).start()
            try:
                pendings = [sched.submit(**r) for r in reqs]
                outs = [p.result(120.0) for p in pendings]
                fired = {
                    s.site: FAULTS.fire_count(s.site) for s in specs}
                time.sleep(0.3)      # let abandoned-ticket unwinds land
                # zero-leak audit: drop the prefix-cache pins (the only
                # legitimate surviving references) and every page must
                # return to the free list with refcounts balanced
                leaks = {}
                for name, pool in (("prefill", pf.page_pool),
                                   ("decode", dec.page_pool)):
                    pool.requeue(pool.clear_prefix())
                    leaks[name] = (pool.num_pages - pool.free_count(),
                                   sum(pool.refcounts()))
            finally:
                sched.stop()
    finally:
        guard.uninstall()

    requeues = METRICS.snapshot()["counters"].get("disagg.requeues", 0.0)
    parity = all(o.tokens == e for o, e in zip(outs, expected))
    result = {
        "seed": seed,
        "requests": len(reqs),
        "token_parity_under_faults": parity,
        "worker_faults_fired": fired["disagg.prefill_worker"],
        "migrate_faults_fired": fired["disagg.migrate"],
        "requeues": requeues,
        "leaked_pages": leaks,
        "lockguard_violations": len(guard.violations()),
    }
    assert parity, f"seed {seed}: migrated tokens diverged under injection"
    total_fired = fired["disagg.prefill_worker"] + fired["disagg.migrate"]
    assert total_fired >= 1, result
    assert requeues >= total_fired, (
        f"seed {seed}: {total_fired} aborts but only {requeues} requeues "
        "— a killed migration was not requeued", result)
    assert leaks == {"prefill": (0, 0), "decode": (0, 0)}, (
        f"seed {seed}: leaked pages after chaos: {leaks}")
    assert not guard.violations(), guard.report()
    return result


def run_elastic(seed: int) -> dict:
    """Chaos leg for the elasticity tier (ISSUE 13): kill 1-3 chips out of
    the dp=8 mesh mid-run (``mesh.shrink``), sometimes hand them back later
    (``mesh.grow``), sometimes make the resharding restore itself fail once
    (``checkpoint.reshard``), and assert the supervised run COMPLETES on
    the surviving mesh with every recovered loss inside the documented
    elastic window (DESIGN.md §21: |loss - ref| <= 1e-5 across dp widths —
    psum association order changes with the width, so cross-width parity
    is a window, not bitwise) and a ``mesh_resize`` flight bundle emitted.
    """
    import pathlib
    import tempfile

    import jax
    import numpy as np

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.observability import FLIGHTREC, METRICS
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer, elastic_mesh
    from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
    from deeplearning4j_tpu.resilience import (
        FaultSpec, RetryPolicy, TrainingSupervisor, inject_faults)

    rng = random.Random(seed + 2)
    observability.enable()
    METRICS.reset()

    w_true = np.asarray([1.0, -2.0, 0.5], np.float32)
    xs = np.asarray(jax.random.normal(jax.random.key(3),
                                      (N_BATCHES * BATCH, 3)))
    ys = xs @ w_true

    class Batch:
        def __init__(self, x, y):
            self.features, self.labels = x, y

    data = [Batch(xs[i * BATCH:(i + 1) * BATCH],
                  ys[i * BATCH:(i + 1) * BATCH]) for i in range(N_BATCHES)]

    def loss_fn(p, xb, yb, key=None):
        return jax.numpy.mean(((xb @ p["w"]) - yb) ** 2)

    stage = rng.choice([0, 1, 2, 3])
    lost_chips = rng.randint(1, 3)
    shrink_at = rng.randint(2, N_BATCHES - 2)

    def factory(devices):
        devs = devices if devices is not None else jax.devices()[:8]
        return DataParallelTrainer(loss_fn, T.chain(T.momentum(0.9),
                                                    T.sgd_lr(5e-2)),
                                   mesh=elastic_mesh(devs), zero_stage=stage)

    params = {"w": np.zeros(3, np.float32)}
    from deeplearning4j_tpu.observability import GoodputTracker
    t_ref = factory(None)
    gp_ref = GoodputTracker()
    s_ref, ref_losses = t_ref.fit(t_ref.init_state(params), data, epochs=1,
                                  goodput=gp_ref)
    ref_goodput = gp_ref.finish()

    plan = [FaultSpec("mesh.shrink", at_step=shrink_at, kind=str(lost_chips))]
    grow = rng.random() < 0.5
    if grow:
        plan.append(FaultSpec("mesh.grow", at_step=rng.randint(
            shrink_at + 1, N_BATCHES - 1)))
    if rng.random() < 0.5:
        # the reshard itself dies once mid-flight; the supervisor's retry
        # budget must absorb it
        plan.append(FaultSpec("checkpoint.reshard", probability=1.0,
                              max_fires=1))
    with tempfile.TemporaryDirectory() as ckpt_dir, \
            tempfile.TemporaryDirectory() as rec_dir:
        old_dump_dir = FLIGHTREC.dump_dir
        FLIGHTREC.dump_dir = pathlib.Path(rec_dir)
        try:
            mgr = CheckpointManager(ckpt_dir, keep=10)
            with inject_faults(*plan, seed=seed):
                sup = TrainingSupervisor(
                    mgr, RetryPolicy(max_attempts=8, backoff_base_s=0.01),
                    install_signal_handlers=False)
                t_wall = time.monotonic()
                state, losses = sup.fit(factory, params, data, epochs=1,
                                        checkpoint_every=2)
                wall_s = time.monotonic() - t_wall
            bundles = sorted(p.name for p in
                             pathlib.Path(rec_dir).glob("flightrec-mesh_resize-*"))
        finally:
            FLIGHTREC.dump_dir = old_dump_dir

    final_mesh = int(sup.trainer.mesh.devices.size)
    by_step = sup.report.losses_by_step
    window = max((abs(v - ref_losses[s - 1]) for s, v in by_step.items()),
                 default=0.0)
    counters = METRICS.snapshot()["counters"]
    result = {
        "seed": seed,
        "zero_stage": stage,
        "plan": [f"{s.site}:at={s.at_step},kind={s.kind}" for s in plan],
        "final_step": int(state.step),
        "ref_step": int(s_ref.step),
        "final_mesh_size": final_mesh,
        "mesh_sizes": sup.report.mesh_sizes,
        "resizes": sup.report.resizes,
        "loss_window": float(window),
        "losses_recovered": len(by_step),
        "losses_finite": all(math.isfinite(v) for v in losses),
        "mesh_resize_bundles": bundles,
        "reshard_restores": int(counters.get("checkpoint.reshards", 0)),
        "faults_injected": {k: int(v) for k, v in counters.items()
                            if k.startswith("faults.injected.")},
        "goodput": _goodput_check(sup, ref_goodput, wall_s, seed),
    }
    assert result["final_step"] == result["ref_step"], \
        f"seed {seed}: elastic run stopped at step {result['final_step']}"
    expect_mesh = 8 if grow else 8 - lost_chips
    assert final_mesh == expect_mesh, \
        f"seed {seed}: final mesh {final_mesh}, expected {expect_mesh}"
    assert result["mesh_sizes"][0] == 8 - lost_chips, result["mesh_sizes"]
    # the documented elastic window (DESIGN.md §21): cross-width psum order
    # shifts float32 losses by O(1e-6); 1e-5 bounds it with margin
    assert window <= 1e-5, f"seed {seed}: loss window {window:.3e} > 1e-5"
    assert bundles, f"seed {seed}: no mesh_resize flight bundle emitted"
    assert result["faults_injected"].get("faults.injected.mesh.shrink", 0) \
        or result["faults_injected"], result
    return result


def run_online(seed: int) -> dict:
    """Chaos leg for the online learning loop (DESIGN.md §23): serve real
    traffic through a capture-hooked ``ModelServer``, then roll the dice
    across the loop's whole dataflow — ``capture.write`` damages the
    active segment mid-wave, ``capture.replay`` kills a round at replay,
    ``train.step`` (and sometimes ``preempt``) fail the fine-tune,
    ``online.publish kind="poison"`` rewrites the published params with
    NaNs under *recomputed* checksums, ``online.reload`` aborts a swap,
    and ``online.rollback`` fails inside the recovery path itself.

    Acceptance, per ISSUE 15: (a) every completed response's tokens match
    offline ``Transformer.sample`` under the checkpoint named by its OWN
    ``loaded_step`` stamp — no request ever decodes under a torn or mixed
    model, before, during, or after the chaos; (b) the faulted fine-tune's
    goodput timeline passes the shared §22 audit (exhaustive states, wall
    parity within 1%, fraction strictly below the fault-free reference);
    (c) the poisoned checkpoint ALWAYS rolls back — quarantined, an
    ``online_rollback`` flight bundle naming the bad step, serving back on
    the previous valid generation — and a later round republishes the
    same step cleanly and reloads it (the loop heals itself)."""
    import pathlib
    import tempfile

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, TransformerLM, lm_loss_local)
    from deeplearning4j_tpu.observability import (FLIGHTREC, GoodputTracker,
                                                  METRICS)
    from deeplearning4j_tpu.online import CaptureStore, OnlineConfig, OnlineLoop
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel import DataParallelTrainer
    from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
    from deeplearning4j_tpu.parallel.mesh import local_mesh
    from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
    from deeplearning4j_tpu.serving import (InferenceEngine, ModelServer,
                                            ServingClient, ServingConfig)

    rng = random.Random(seed + 3)
    observability.enable()
    METRICS.reset()

    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                            d_ff=32, max_len=32, dtype=jnp.float32,
                            remat=False)
    model = TransformerLM(cfg)
    params0 = model.init(jax.random.key(7))
    root = tempfile.mkdtemp(prefix="online-chaos-")
    # tiny segments so the damaged-medium fault lands on a rotating store
    store = CaptureStore(f"{root}/capture", segment_bytes=1024)
    mgr = CheckpointManager(f"{root}/ckpt", keep=64)
    # canary_factor=10: the poison is NaN (caught at ANY factor); a tight
    # regression factor would let fault-shifted replay streams flake the
    # scripted heal sequence with false-positive rollbacks
    ocfg = OnlineConfig(batch=2, seq=8, canary_factor=10.0)
    engine = InferenceEngine(model, params=params0, checkpoint=mgr,
                             cfg=ServingConfig(slots=2, idle_wait_s=0.01))
    loop = OnlineLoop(store, mgr, model, params0=params0, engine=engine,
                      cfg=ocfg)

    # time each supervised fit from outside, exactly as the other legs
    # wrap sup.fit — the goodput audit compares the tracker's own wall
    # against this independent clock
    fit_walls: list[float] = []
    orig_fit = loop.supervisor.fit

    def timed_fit(*a, **k):
        t0 = time.monotonic()
        try:
            return orig_fit(*a, **k)
        finally:
            fit_walls.append(time.monotonic() - t0)

    loop.supervisor.fit = timed_fit

    served: list[dict] = []

    def wave(client, n):
        for _ in range(n):
            req = dict(prompt=[rng.randrange(cfg.vocab_size)
                               for _ in range(rng.randint(2, 6))],
                       max_new_tokens=rng.randint(2, 8),
                       temperature=0.0, seed=rng.randrange(1 << 20))
            served.append({"req": req, "out": client.generate(**req)})

    reports: list[dict] = []
    goodput = None
    rec_dir = tempfile.mkdtemp(prefix="online-chaos-rec-")
    old_dump_dir = FLIGHTREC.dump_dir
    FLIGHTREC.dump_dir = pathlib.Path(rec_dir)
    try:
        with engine, ModelServer(engine=engine, capture=store) as server:
            client = ServingClient(port=server.port)
            # warm round, fault-free: captures wave 1, fine-tunes,
            # publishes, hot-reloads — and compiles every jit path so the
            # chaos round's goodput measures recovery, not compilation
            wave(client, 12)
            rep = loop.run_once().to_dict()
            reports.append(rep)
            assert rep["status"] == "ok", \
                f"seed {seed}: fault-free warm round failed: {rep}"
            base_step = mgr.latest_valid_step()

            # fault-free goodput reference: the same replayed stream
            # through the same trainer construction, no checkpointing
            batches0 = loop._pack(list(store.replay()))

            def loss_fn(p, xb, yb, key=None):
                return lm_loss_local(p, xb, yb, model.cfg)

            t_ref = DataParallelTrainer(loss_fn, T.sgd_lr(ocfg.learning_rate),
                                        mesh=local_mesh(1))
            gp_ref = GoodputTracker()
            t_ref.fit(t_ref.init_state(params0), batches0, epochs=1,
                      goodput=gp_ref)
            ref_goodput = gp_ref.finish()

            plan = [
                # fails the fine-tune 1-3 steps past the warm checkpoint
                FaultSpec("train.step",
                          at_step=base_step + rng.randint(1, 3)),
                # damages the active capture segment under a wave-2 append
                FaultSpec("capture.write", at_step=rng.randint(1, 12),
                          kind=rng.choice(["truncate", "bitflip"])),
                # the first publish after the warm round is poisoned
                FaultSpec("online.publish", at_step=1, kind="poison"),
                # ...and after its rollback, the republish's reload aborts
                FaultSpec("online.reload", at_step=2),
                # rollback's own seam fails once inside recovery
                FaultSpec("online.rollback", at_step=1),
            ]
            if rng.random() < 0.5:
                plan.append(FaultSpec("capture.replay", at_step=1))
            if rng.random() < 0.5:
                plan.append(FaultSpec("preempt",
                                      at_step=base_step + rng.randint(1, 3)))
            with inject_faults(*plan, seed=seed):
                wave(client, 16)
                walls_before = len(fit_walls)
                for _ in range(8):
                    rep = loop.run_once().to_dict()
                    reports.append(rep)
                    counters = METRICS.snapshot()["counters"]
                    if (goodput is None and len(fit_walls) > walls_before
                            and counters.get("faults.injected.train.step")):
                        # this round ran the fit the step fault hit; audit
                        # its report before a later round's fit replaces it
                        goodput = _goodput_check(loop.supervisor, ref_goodput,
                                                 fit_walls[-1], seed)
                    if rep["status"] == "ok":
                        break
            # post-chaos traffic decodes under the healed generation
            wave(client, 6)
        bundles = sorted(p.name for p in
                         pathlib.Path(rec_dir).glob("*online_rollback*"))
    finally:
        FLIGHTREC.dump_dir = old_dump_dir
    store.close()

    # generation-consistency audit: every response across ALL waves must
    # match offline sampling under the checkpoint its OWN stamp names
    restored_cache: dict = {None: params0}

    def params_at(step):
        if step not in restored_cache:
            restored_cache[step] = mgr.restore(params0, step=step)["params"]
        return restored_cache[step]

    parity_failures = []
    for rec in served:
        req, out = rec["req"], rec["out"]
        exp = model.sample(params_at(out.get("loaded_step")), req["prompt"],
                           len(out["tokens"]), temperature=0.0,
                           key=jax.random.key(req["seed"]),
                           kv_cache=True)[len(req["prompt"]):]
        if out["tokens"] != exp:
            parity_failures.append(
                f"step {out.get('loaded_step')} gen {out.get('generation')}: "
                f"{out['tokens']} != {exp}")

    rolled = [r for r in reports if r["rolled_back"]]
    counters = METRICS.snapshot()["counters"]
    result = {
        "seed": seed,
        "plan": [f"{s.site}:at={s.at_step},kind={s.kind}" for s in plan],
        "base_step": base_step,
        "requests": len(served),
        "rounds": [r["status"] for r in reports],
        "generation": loop.generation,
        "loaded_step": engine.stats()["loaded_step"],
        "token_parity_at_stamped_generation": not parity_failures,
        "parity_failures": parity_failures,
        "rollbacks": [{"reason": r["rollback_reason"],
                       "quarantined": r["quarantined"]} for r in rolled],
        "rollback_bundles": bundles,
        "captured_records": int(counters.get("online.captured_records", 0)),
        "corrupt_records": int(counters.get("capture.corrupt_records", 0)),
        "faults_injected": {k: int(v) for k, v in counters.items()
                            if k.startswith("faults.injected.")},
        "goodput": goodput,
    }
    assert not parity_failures, \
        f"seed {seed}: stamped-generation parity broke: {parity_failures}"
    assert rolled and all(r["rollback_reason"] == "canary_nonfinite"
                          and r["quarantined"] for r in rolled), \
        f"seed {seed}: poisoned publish did not roll back: {reports}"
    assert bundles, f"seed {seed}: rollback emitted no flight bundle"
    assert reports[-1]["status"] == "ok", \
        f"seed {seed}: loop never healed after the chaos: {reports}"
    assert engine.stats()["loaded_step"] == \
        reports[-1]["reloaded"].get("engine"), \
        f"seed {seed}: engine not on the healed generation: {reports[-1]}"
    assert goodput is not None, \
        f"seed {seed}: train.step never hit a fine-tune round: {reports}"
    assert result["faults_injected"].get("faults.injected.online.publish"), \
        result
    return result


def run_overload(seed: int) -> dict:
    """Chaos leg for the control plane (DESIGN.md §26), in three phases.

    **Brownout ladder**: a speculative engine is walked up the full
    ladder (healthy -> spec off -> ``max_new`` clamped -> background
    shed) by a burn-rate feed and back down one rung at a time.  At
    EVERY level each served greedy completion must be token-identical
    to the fault-free ``Transformer.sample`` reference under that
    level's effective budget (the level-2 clamp serves the exact
    offline prefix) — brownout trades throughput and length for
    capacity, never token content.  At level 3 background submissions
    must 429 while interactive ones keep parity; after descent the
    engine must be speculative again with full-length parity.

    **Fair share**: a tight per-tenant token bucket is installed; the
    noisy tenant exhausts its OWN bucket (429 + a
    ``tenant.noisy.throttled`` row) while the quiet tenant's next
    request is admitted untouched.

    **Autoscaler kill**: an :class:`Autoscaler` over a REAL router
    scales 1 -> 2 through the warmed-admission seam, then the
    ``control.autoscaler`` fault kills the loop mid-run.  The pool must
    freeze at its current size (static capacity), further pressure
    windows must take no action, and routing must keep serving with
    greedy parity — never a half-drained replica or a wrong route.
    """
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.control import (Autoscaler, AutoscalerConfig,
                                            BrownoutConfig,
                                            BrownoutController, ControlSignals,
                                            OverloadGate, Throttled,
                                            TokenBucketAdmission)
    from deeplearning4j_tpu.control.autoscaler import router_actuators
    from deeplearning4j_tpu.control.overload import BucketConfig
    from deeplearning4j_tpu.models import zoo
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
    from deeplearning4j_tpu.serving import (EngineReplica, InferenceEngine,
                                            PrefixRouter, RouterConfig,
                                            ServingConfig)

    rng = random.Random(seed + 3)
    observability.enable()
    METRICS.reset()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=32, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(11))
    draft, dparams = zoo.draft_lm(cfg, seed=99)
    engine = InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=3, resolve_every=2, speculative=True,
                          spec_k=2),
        draft_model=draft, draft_params=dparams).start()

    clamp = 4
    clock = [1000.0]
    brownout = BrownoutController(
        engine, BrownoutConfig(enter_burn=(1.0, 2.0, 4.0), exit_fraction=0.5,
                               dwell_s=5.0, clamp_max_new=clamp),
        clock=lambda: clock[0])
    gate = OverloadGate(bucket=TokenBucketAdmission(clock=lambda: clock[0]),
                        brownout=brownout).install(engine)

    def serve(n: int, priority: int = 0, tenant: str = "quiet"):
        """Submit n greedy requests; returns (plans, outputs, rejects)."""
        plans = [dict(prompt=[rng.randrange(cfg.vocab_size)
                              for _ in range(rng.randint(1, 8))],
                      max_new_tokens=rng.randint(2, 8), temperature=0.0,
                      seed=rng.randrange(1 << 16))
                 for _ in range(n)]
        handles, rejects = [], 0
        for p in plans:
            try:
                handles.append((p, engine.submit(**p, tenant=tenant,
                                                 priority=priority)))
            except Throttled:
                rejects += 1
        outs = [(p, h.result(60.0)) for p, h in handles]
        return plans, outs, rejects

    def parity(outs, effective_cap=None) -> list[str]:
        bad = []
        for p, out in outs:
            n = p["max_new_tokens"] if effective_cap is None \
                else min(p["max_new_tokens"], effective_cap)
            exp = model.sample(params, p["prompt"], n, temperature=0.0,
                               key=jax.random.key(p["seed"]),
                               kv_cache=True)[len(p["prompt"]):]
            if out.tokens != exp:
                bad.append(f"{p}: {out.tokens} != {exp}")
        return bad

    # ---- phase 1: walk the ladder up, serving with parity at every level
    ladder: list[dict] = []
    parity_failures: list[str] = []
    for burn, want_level in [(0.0, 0), (1.2, 1), (2.5, 2), (5.0, 3)]:
        clock[0] += brownout.cfg.dwell_s + 1.0   # clears dwell AND refills
        level = brownout.update(burn)
        assert level == want_level, (
            f"seed {seed}: burn {burn} drove level {level}, "
            f"wanted {want_level}")
        stats = engine.stats()
        assert stats["speculative_enabled"] == (level < 1), (level, stats)
        assert stats["max_new_cap"] == (clamp if level >= 2 else None), \
            (level, stats)
        _, outs, _ = serve(3)
        parity_failures += parity(
            outs, effective_cap=clamp if level >= 2 else None)
        shed_rejects = 0
        if level >= 3:
            _, bg_outs, shed_rejects = serve(3, priority=1, tenant="batch")
            assert shed_rejects == 3 and not bg_outs, (
                f"seed {seed}: level 3 served background work "
                f"({shed_rejects}/3 shed)")
        ladder.append({"burn": burn, "level": level, "served": len(outs),
                       "background_shed": shed_rejects})

    # ---- descend one rung at a time; full quality restored at the bottom
    for want_level in (2, 1, 0):
        clock[0] += brownout.cfg.dwell_s + 1.0
        level = brownout.update(0.1)
        assert level == want_level, (
            f"seed {seed}: descent reached {level}, wanted {want_level} "
            "(must step one rung at a time)")
    assert engine.stats()["speculative_enabled"] is True
    clock[0] += brownout.cfg.dwell_s + 1.0
    _, outs, _ = serve(3)
    parity_failures += parity(outs)

    # ---- phase 2: tight fair-share bucket — noisy tenant starves itself
    OverloadGate(bucket=TokenBucketAdmission(
        BucketConfig(rate_tokens_s=0.0, burst_tokens=20.0),
        clock=lambda: clock[0]), brownout=brownout).install(engine)
    noisy_plans = [dict(prompt=[1, 2, 3], max_new_tokens=8, temperature=0.0,
                        seed=rng.randrange(1 << 16)) for _ in range(5)]
    noisy_served, noisy_throttled = [], 0
    for p in noisy_plans:
        try:
            noisy_served.append((p, engine.submit(**p, tenant="noisy")))
        except Throttled:
            noisy_throttled += 1
    quiet_plan = dict(prompt=[4, 5, 6], max_new_tokens=8, temperature=0.0,
                      seed=rng.randrange(1 << 16))
    quiet_handle = engine.submit(**quiet_plan, tenant="quiet")
    parity_failures += parity([(p, h.result(60.0)) for p, h in noisy_served])
    parity_failures += parity([(quiet_plan, quiet_handle.result(60.0))])
    counters = METRICS.snapshot()["counters"]
    assert noisy_throttled == 3 and len(noisy_served) == 2, (
        f"seed {seed}: 20-token bucket admitted {len(noisy_served)}/5 "
        f"8-token requests ({noisy_throttled} throttled)")
    assert counters.get("tenant.noisy.throttled", 0) >= 3, counters
    assert "tenant.quiet.throttled" not in counters, (
        "quiet tenant was throttled for the noisy tenant's burst")
    engine.set_admission_hook(None)
    engine.stop()

    # ---- phase 3: chaos-kill the autoscaler mid-run over a real router
    def replica(name: str) -> EngineReplica:
        eng = InferenceEngine(model, params=params,
                              cfg=ServingConfig(slots=2,
                                                resolve_every=2)).start()
        return EngineReplica(name, eng, own_engine=True)

    serial = [0]

    def factory() -> EngineReplica:
        serial[0] += 1
        return replica(f"k{serial[0]}")

    router = PrefixRouter([replica("k0")], RouterConfig(
        page_size=4, probe_interval_s=0.5))
    acfg = AutoscalerConfig(min_replicas=1, max_replicas=4, cooldown_s=10.0)
    up, down, size = router_actuators(router, factory, acfg)
    sim_t, feed = [0.0], []
    scaler = Autoscaler(lambda: feed.pop(0), up, down, size, acfg,
                        clock=lambda: sim_t[0])

    def play(sig):
        sim_t[0] += acfg.cooldown_s + 1.0
        feed.append(sig)
        return scaler.step()

    pressure = ControlSignals(burn=3.0, queue_depth=64)
    took = play(pressure)
    assert took == "up" and len(router.pool.names()) == 2, (
        took, router.pool.names())
    with inject_faults(FaultSpec("control.autoscaler", probability=1.0),
                       seed=seed):
        killed_take = play(pressure)
    frozen = len(router.pool.names())
    post_kill = [play(pressure) for _ in range(3)]
    probe = dict(prompt=[3, 1, 4], max_new_tokens=6, temperature=0.0, seed=0)
    routed = [router.generate(**probe) for _ in range(4)]
    exp = model.sample(params, probe["prompt"], probe["max_new_tokens"],
                       temperature=0.0, key=jax.random.key(0),
                       kv_cache=True)[len(probe["prompt"]):]
    snap = METRICS.snapshot()
    router.close()   # pool.close() closes every replica's engine

    result = {
        "seed": seed,
        "ladder": ladder,
        "parity_failures": parity_failures[:5],
        "brownout_transitions":
            int(snap["counters"].get("control.brownout_transitions", 0)),
        "noisy_throttled": noisy_throttled,
        "shed": int(snap["counters"].get("control.shed", 0)),
        "autoscaler_killed":
            int(snap["counters"].get("control.autoscaler_killed", 0)),
        "pool_after_kill": frozen,
        "actions_after_kill": [a for a in post_kill if a],
        "routed_after_kill": len(routed),
    }
    assert not parity_failures, (
        f"seed {seed}: brownout broke token parity: {parity_failures[:3]}")
    # 3 up + 3 down rungs walked exactly once each
    assert result["brownout_transitions"] == 6, result
    assert scaler.dead and killed_take is None, (killed_take, result)
    assert frozen == 2 and not result["actions_after_kill"], (
        f"seed {seed}: killed autoscaler kept acting: {result}")
    assert snap["gauges"].get("control.autoscaler_alive") == 0.0, (
        "autoscaler death is invisible on the alive gauge")
    assert all(r["tokens"] == exp for r in routed), (
        f"seed {seed}: routing broke after the autoscaler died: "
        f"{[r['tokens'] for r in routed]} != {exp}")
    assert scaler.start() is False, "a dead autoscaler must not restart"
    return result


def main(argv: list[str]) -> int:
    seed = int(argv[argv.index("--seed") + 1]) if "--seed" in argv else None
    shardguard = None
    if "--shardguard" in argv:
        # run every leg with runtime sharding-drift detection: injected
        # faults drive recovery paths (mesh shrink/grow, reload) that are
        # exactly where a step can start dispatching onto stale placements
        from deeplearning4j_tpu.analysis.shardguard import SHARDGUARD \
            as shardguard
        shardguard.reset()
        shardguard.enable()
    try:
        return _dispatch_legs(argv, seed, shardguard)
    finally:
        if shardguard is not None:
            shardguard.disable()


def _dispatch_legs(argv: list[str], seed, shardguard) -> int:
    def finish(result: dict) -> int:
        if shardguard is not None:
            result["shardguard_violations"] = len(shardguard.violations())
            assert not shardguard.violations(), shardguard.report()
        print(json.dumps(result))
        return 0

    if "--elastic" in argv:
        # replay a single failing elastic draw
        return finish(run_elastic(seed if seed is not None
                                  else random.SystemRandom().randrange(2 ** 31)))
    if "--online" in argv:
        # replay a single failing online-loop draw
        return finish(run_online(seed if seed is not None
                                 else random.SystemRandom().randrange(2 ** 31)))
    if "--overload" in argv:
        # replay a single failing overload/brownout draw
        return finish(run_overload(seed if seed is not None
                                   else random.SystemRandom().randrange(2 ** 31)))
    if "--disagg" in argv:
        # replay a single failing disagg-migration draw
        return finish(run_disagg(seed if seed is not None
                                 else random.SystemRandom().randrange(2 ** 31)))
    if "--stage" in argv:
        # replay a single failing (seed, stage) draw
        stage = int(argv[argv.index("--stage") + 1])
        return finish(run(seed, zero_stage=stage))
    # one random plan per ZeRO stage: recovery must restore BITWISE params
    # whether optimizer state (and, at stage 3, params) live sharded or
    # replicated — a corrupted/per-shard-mismatched restore would show up
    # as parity failure here
    result = run(seed)
    base = result["seed"]
    result["zero_stages"] = {
        stage: run(base + stage, zero_stage=stage) for stage in (1, 2, 3)}
    result["serving"] = run_serving(base)
    result["serving_kv_int8"] = run_serving(base, kv_quant="int8")
    result["disagg"] = run_disagg(base)
    result["elastic"] = run_elastic(base)
    result["online"] = run_online(base)
    result["overload"] = run_overload(base)
    return finish(result)


if __name__ == "__main__":
    import os
    import pathlib
    import warnings

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "--xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    warnings.simplefilter("ignore", UserWarning)   # checkpoint-fallback noise
    sys.exit(main(sys.argv[1:]))
