"""Prefix-affinity consistent-hash routing (DESIGN.md §19).

The routing key is the request's content-addressed prefix chain — the
SAME chained blake2b the :class:`~..paging.PagePool` uses
(:func:`~..paging.prefix_chain_keys`), over full pages only — truncated
to the first ``affinity_pages`` pages.  Truncation is the affinity/skew
trade: hashing the *last* chain key would scatter one tenant's requests
(every user turn extends the chain), while the first few pages are
exactly the shared system prompt whose KV pages are worth landing on.
Prompts too short for one full page fall back to a whole-prompt hash —
no cached pages exist for them anyway, so any stable spread is fine.

Dispatch walks the ring clockwise from the key, skipping quarantined
nodes (the pool's breaker), and degrades in order:

- 429 (``QueueFull`` / ``PagePoolExhausted`` / an HTTP 429 answer):
  the affinity replica is shedding — count ``router.spillover``, note it
  in the flight recorder (burst trigger), try the next node.  Spillover
  trades prefix locality for availability, which is why it is a counter
  and not silent.
- :class:`ReplicaUnavailable` / timeout / 5xx transport death: feed the
  pool's breaker (may trip quarantine) and try the next node.
- 400 / 404 / 504: the request itself is the problem — propagate, a
  different replica would answer the same.

Every attempt runs inside a ``router.route`` span nested under one
``router.request`` span, so a request that spilled twice shows three
route spans under one trace id and ``tools/trace_report.py`` renders the
router hop on the same critical path as the engine's queue/prefill/
decode/emit spans (cross-process via the ``traceparent`` header the
:class:`~..client.ServingClient` already sends).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

from ...observability import METRICS, trace
from ...observability.flightrec import FLIGHTREC
from ...resilience.faults import FAULTS
from ..batcher import ServingRejected
from ..client import ServingError
from ..paging import prefix_chain_keys
from .replicas import (AllReplicasUnavailable, Replica, ReplicaPool,
                       ReplicaUnavailable)
from .ring import HashRing


@dataclass(frozen=True)
class RouterConfig:
    """Knobs for ring construction, affinity, spillover and the breaker."""

    page_size: int = 16          # MUST match the replicas' PagePool
    affinity_pages: int = 4      # chain prefix length the key hashes
    vnodes: int = 64             # ring points per replica
    request_timeout_s: float = 60.0
    max_spill: int | None = None  # extra nodes tried after the owner (None: all)
    probe_interval_s: float = 0.5
    probe_timeout_s: float = 2.0
    fail_threshold: int = 2      # consecutive failures -> quarantine
    recover_threshold: int = 2   # consecutive probe successes -> re-admit


class PrefixRouter:
    """Consistent-hash front tier over a :class:`ReplicaPool`."""

    def __init__(self, replicas: list[Replica],
                 cfg: RouterConfig = RouterConfig()):
        self.cfg = cfg
        self.pool = ReplicaPool(
            replicas,
            probe_interval_s=cfg.probe_interval_s,
            probe_timeout_s=cfg.probe_timeout_s,
            fail_threshold=cfg.fail_threshold,
            recover_threshold=cfg.recover_threshold)
        self.ring = HashRing(self.pool.names(), vnodes=cfg.vnodes)

    # ------------------------------------------------------------ routing
    def routing_key(self, prompt) -> str:
        """Content-addressed key for ``prompt``: the chain hash of its
        first ``affinity_pages`` FULL pages (identical to the pool's
        page addressing), else a whole-prompt fallback hash."""
        tokens = [int(t) for t in prompt]
        usable = len(tokens) - 1  # the last token is the first decode query
        keys = prefix_chain_keys(tokens, usable, self.cfg.page_size)
        if keys:
            return keys[min(len(keys), self.cfg.affinity_pages) - 1]
        return "short:" + hashlib.blake2b(
            (",".join(map(str, tokens))).encode(), digest_size=16).hexdigest()

    def route_order(self, key: str) -> list[str]:
        """Active replicas in dispatch order: the owner first, then its
        clockwise successors (the spillover / quarantine-drain order)."""
        return [n for n in self.ring.walk(key) if self.pool.is_active(n)]

    # ------------------------------------------------------------ dispatch
    def generate(self, prompt, max_new_tokens: int = 16,
                 temperature: float = 0.0, seed: int = 0,
                 eos_id: int | None = None,
                 deadline_ms: float | None = None,
                 tenant: str | None = None,
                 timeout_s: float | None = None) -> dict:
        """Route one generation; returns the replica's completion dict
        plus ``replica`` (who served it) and ``spills`` (how many nodes
        were tried before it).  ``tenant`` rides the payload opaquely —
        the serving replica folds it into bounded per-tenant metrics."""
        FAULTS.maybe_fire("router.route")
        payload = {"prompt": list(prompt), "max_new_tokens": max_new_tokens,
                   "temperature": temperature, "seed": seed}
        if eos_id is not None:
            payload["eos_id"] = eos_id
        if deadline_ms is not None:
            payload["deadline_ms"] = deadline_ms
        if tenant:
            payload["tenant"] = str(tenant)
        timeout = timeout_s if timeout_s is not None \
            else self.cfg.request_timeout_s
        key = self.routing_key(prompt)
        with trace.span("router.request", key=key[:12]):
            order = self.route_order(key)
            if not order:
                METRICS.increment("router.unroutable")
                raise AllReplicasUnavailable(
                    "no active replicas on the ring")
            if self.cfg.max_spill is not None:
                order = order[: self.cfg.max_spill + 1]
            last_rejection: ServingRejected | None = None
            for spills, name in enumerate(order):
                rep = self.pool.replica(name)
                self.pool.begin_request(name)
                try:
                    with trace.span("router.route", replica=name,
                                    spills=spills):
                        out = rep.generate(payload, timeout)
                except (ReplicaUnavailable, TimeoutError) as e:
                    # transport-level death: feed the breaker, drain to
                    # the next ring node
                    METRICS.increment("router.replica_errors")
                    self.pool.record_failure(name, f"dispatch: {e}")
                    last_rejection = e if isinstance(e, ServingRejected) \
                        else ReplicaUnavailable(str(e))
                    continue
                except ServingRejected as e:
                    if e.status == 429:
                        # the owner is shedding load: spill clockwise,
                        # trading prefix locality for availability
                        METRICS.increment("router.spillover")
                        FLIGHTREC.note_spillover(name)
                        last_rejection = e
                        continue
                    raise  # 504 deadline etc.: the request's problem
                except ServingError as e:
                    if e.status == 429:
                        METRICS.increment("router.spillover")
                        FLIGHTREC.note_spillover(name)
                        last_rejection = _as_rejection(e)
                        continue
                    if e.status >= 500:
                        METRICS.increment("router.replica_errors")
                        self.pool.record_failure(name, f"dispatch: {e}")
                        last_rejection = _as_rejection(e)
                        continue
                    raise  # 400/404/409: a different replica answers the same
                finally:
                    self.pool.end_request(name)
                self.pool.record_success(name)
                METRICS.increment("router.requests")
                if spills == 0:
                    # landed on the first active ring node for its key —
                    # the replica whose PagePool holds this prefix
                    METRICS.increment("router.prefix_affinity_hit")
                out["replica"] = name
                out["spills"] = spills
                return out
            raise last_rejection if last_rejection is not None else \
                AllReplicasUnavailable("all replicas failed")

    # ------------------------------------------------------------ admin
    def reload(self, step: int | None = None) -> dict[str, int]:
        """Hot-reload every ACTIVE replica (to ``step`` when given — the
        online loop's fan-out and rollback path); name -> loaded step."""
        return {name: self.pool.replica(name).reload(step)
                for name in self.pool.active_names()}

    def stats(self) -> dict:
        """Router-level view: per-replica breaker state + load."""
        out = {}
        for name in self.pool.names():
            out[name] = {"active": self.pool.is_active(name),
                         "last_probe": self.pool.last_probe(name)}
        return out

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "PrefixRouter":
        self.pool.start()
        return self

    def close(self) -> None:
        self.pool.close()

    def __enter__(self) -> "PrefixRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def _as_rejection(e: ServingError) -> ServingRejected:
    """Carry a downstream HTTP rejection's status through the router."""
    rej = ServingRejected(str(e))
    rej.status = e.status
    return rej
