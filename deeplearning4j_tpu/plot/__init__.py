"""L4 — visualization (reference: ``plot/``)."""

from .tsne import BarnesHutTsne, Tsne
from .renderers import FilterRenderer, NeuralNetPlotter, draw_mnist_grid

__all__ = ["BarnesHutTsne", "Tsne", "FilterRenderer", "NeuralNetPlotter",
           "draw_mnist_grid"]
