"""MultiLayerNetwork end-to-end tests — the minimum vertical slice.

Mirror of the reference's ``nn/multilayer/MultiLayerTest.java:33-70`` (DBN on
Iris with F1 assertion) and ``models/layers/OutputLayerTest.java``, exercising
config -> init -> fit -> optimize -> eval.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet, IrisDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import (
    LayerKind,
    NeuralNetConfiguration,
    OptimizationAlgorithm,
    list_builder,
)


def iris_data():
    it = IrisDataSetIterator(batch=150)
    ds = it.next().normalize_zero_mean_unit_variance().shuffle(seed=42)
    return ds


def mlp_conf(n_iter=200, algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT):
    base = NeuralNetConfiguration(
        n_in=4, n_out=3, lr=0.1, momentum=0.9, use_adagrad=True,
        num_iterations=n_iter, optimization_algo=algo, activation="tanh",
    )
    return (list_builder(base, 2)
            .hidden_layer_sizes(10)
            .override(1, kind="output", activation="softmax", loss="mcxent")
            .pretrain(False)
            .build())


def test_mlp_iris_convergence():
    """2-layer MLP reaches F1 >= 0.9 on Iris (the reference test asserts
    f1 > 0.9 for its 3-layer DBN)."""
    ds = iris_data()
    net = MultiLayerNetwork(mlp_conf())
    net.init(jax.random.key(0))
    net.fit(ds)
    ev = net.evaluate(ds)
    assert ev.f1() >= 0.9, ev.stats()


def test_dbn_pretrain_then_finetune_iris():
    """RBM pretraining + supervised finetune (greedy layerwise, mirror of
    MultiLayerTest's DBN)."""
    ds = iris_data().scale_minmax()
    base = NeuralNetConfiguration(
        n_in=4, n_out=3, lr=0.05, use_adagrad=True, num_iterations=60,
        kind=LayerKind.RBM, visible_unit="gaussian", hidden_unit="binary",
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        activation="sigmoid", k=1,
    )
    conf = (list_builder(base, 3)
            .hidden_layer_sizes(12, 8)
            .override(2, kind="output", activation="softmax", loss="mcxent",
                      num_iterations=300, lr=0.1)
            .pretrain(True)
            .build())
    net = MultiLayerNetwork(conf)
    net.init(jax.random.key(1))
    net.fit(ds)
    ev = net.evaluate(ds)
    assert ev.f1() >= 0.8, ev.stats()


def test_hessian_free_finetune_iris():
    """HF fine-tune through the network path: the Gauss-Newton split (net up
    to final pre-activation + convex loss-of-logits) trains a NON-convex
    tanh-hidden MLP on Iris (VERDICT r3 #7 — the full-Hessian version was
    only safe on convex-ish objectives)."""
    ds = iris_data()
    net = MultiLayerNetwork(mlp_conf(
        n_iter=60, algo=OptimizationAlgorithm.HESSIAN_FREE))
    net.init(jax.random.key(0))
    net.fit(ds)
    ev = net.evaluate(ds)
    assert ev.f1() >= 0.9, ev.stats()


def test_output_layer_alone_iris():
    """Softmax regression on Iris via CG (OutputLayerTest mirror)."""
    ds = iris_data()
    base = NeuralNetConfiguration(
        n_in=4, n_out=3, num_iterations=100, use_adagrad=False, lr=0.5,
        optimization_algo=OptimizationAlgorithm.CONJUGATE_GRADIENT,
        kind=LayerKind.OUTPUT, activation="softmax", loss="mcxent",
    )
    conf = list_builder(base, 1).pretrain(False).build()
    net = MultiLayerNetwork(conf)
    net.init(jax.random.key(2))
    net.fit(ds)
    assert net.evaluate(ds).f1() >= 0.9


def test_predict_and_probabilities():
    ds = iris_data()
    net = MultiLayerNetwork(mlp_conf(n_iter=50))
    net.init(jax.random.key(0))
    net.fit(ds)
    preds = net.predict(ds.features[:10])
    assert preds.shape == (10,)
    probs = np.asarray(net.label_probabilities(ds.features[:10]))
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, rtol=1e-4)


def test_params_flatten_roundtrip_network():
    net = MultiLayerNetwork(mlp_conf())
    net.init(jax.random.key(0))
    flat = net.params_flat()
    assert flat.shape == (net.num_params(),)
    out0 = np.asarray(net.output(np.ones((2, 4), np.float32)))
    net.set_params_flat(np.asarray(flat))
    out1 = np.asarray(net.output(np.ones((2, 4), np.float32)))
    np.testing.assert_allclose(out0, out1, rtol=1e-6)


def test_merge_parameter_averaging():
    n1 = MultiLayerNetwork(mlp_conf())
    n2 = MultiLayerNetwork(mlp_conf())
    n1.init(jax.random.key(0))
    n2.init(jax.random.key(1))
    w1 = np.asarray(n1.params[0]["W"])
    w2 = np.asarray(n2.params[0]["W"])
    n1.merge(n2)
    np.testing.assert_allclose(np.asarray(n1.params[0]["W"]), (w1 + w2) / 2, rtol=1e-6)


def test_save_load_roundtrip(tmp_path):
    ds = iris_data()
    net = MultiLayerNetwork(mlp_conf(n_iter=30))
    net.init(jax.random.key(0))
    net.fit(ds)
    p = tmp_path / "model.bin"
    net.save(p)
    loaded = MultiLayerNetwork.load(p)
    np.testing.assert_allclose(np.asarray(loaded.output(ds.features[:5])),
                               np.asarray(net.output(ds.features[:5])), rtol=1e-6)


def test_score_decreases_during_training():
    ds = iris_data()
    net = MultiLayerNetwork(mlp_conf(n_iter=5))
    net.init(jax.random.key(0))
    s0 = net.score(ds)
    net.fit(ds)
    assert net.score(ds) < s0


def test_hessian_free_finetune_minibatched_no_merged_array(monkeypatch):
    """Second-order finetune at 10x the Iris corpus, fed as mini-batches:
    the solver cycles one batch at a time (grad + CG curvature share the
    iteration's batch, the stochastic-HF contract) and never materializes
    the merged corpus — DataSet.merge is booby-trapped to prove it."""
    base = iris_data()
    reps = 10
    feats = np.tile(np.asarray(base.features), (reps, 1))
    labels = np.tile(np.asarray(base.labels), (reps, 1))
    rng = np.random.default_rng(0)
    feats = feats + rng.normal(0, 0.05, feats.shape).astype(np.float32)
    big = DataSet(feats, labels).shuffle(seed=1)

    monkeypatch.setattr(
        DataSet, "merge",
        staticmethod(lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("solver path must not merge batches"))))

    net = MultiLayerNetwork(mlp_conf(
        n_iter=60, algo=OptimizationAlgorithm.HESSIAN_FREE))
    net.init(jax.random.key(0))
    net.fit(big.batch_by(150))
    ev = net.evaluate(base)
    assert ev.f1() >= 0.9, ev.stats()
