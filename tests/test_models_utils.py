"""Model zoo + utility tests (mirror of LSTMTest beam search, MNIST conv
test, MathUtils/serialization/moving-window util tests)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.datasets import DataSet, DigitsDataSetIterator
from deeplearning4j_tpu.models import (
    LSTMSequenceModel,
    ResNet,
    ResNetConfig,
    dbn,
    lenet,
    mlp,
    stacked_denoising_autoencoder,
)
from deeplearning4j_tpu.utils import (
    Counter,
    CounterMap,
    DiskBasedQueue,
    Index,
    SummaryStatistics,
    Viterbi,
    viterbi_decode,
)
from deeplearning4j_tpu.utils.misc import (
    entropy,
    moving_window_matrix,
    read_object,
    save_object,
)


def digits_ds(n=500):
    it = DigitsDataSetIterator(batch=n)
    return it.next().shuffle(seed=0)


def test_mlp_on_digits():
    ds = digits_ds()
    net = mlp(64, 10, hidden=(48,), num_iterations=150)
    net.init(jax.random.key(0))
    net.fit(ds)
    assert net.evaluate(ds).f1() > 0.85


def test_lenet_trains_on_digit_images():
    it = DigitsDataSetIterator(batch=300, flatten=False)
    ds = it.next()
    net = lenet(n_classes=10, input_side=8, num_filters=4, filter_size=(3, 3),
                pool=(2, 2), num_iterations=200, lr=0.1)
    net.init(jax.random.key(0))
    net.fit(ds)
    ev = net.evaluate(ds)
    assert ev.accuracy() > 0.7, ev.stats()


def test_sda_pretrains_and_finetunes():
    ds = digits_ds(300).scale_minmax()
    net = stacked_denoising_autoencoder(
        64, 10, hidden=(32,), pretrain_iterations=40, finetune_iterations=150)
    net.init(jax.random.key(0))
    net.fit(ds)
    assert net.evaluate(ds).f1() > 0.8


def test_lstm_model_learns_and_beam_search():
    seq = np.array(([0, 1, 2, 3] * 10), np.int32)
    model = LSTMSequenceModel(vocab_size=4, hidden_size=24, lr=0.3)
    model.init()
    losses = model.fit_sequence(seq, epochs=120)
    assert losses[-1] < losses[0] * 0.4
    assert model.predict_next([0, 1, 2]) == 3
    decoded, score = model.beam_search([0, 1], length=4, beam_width=3)
    assert decoded[2:] == [2, 3, 0, 1]
    sampled = model.sample([0], length=5, temperature=0.3, seed=1)
    assert len(sampled) == 6


def test_resnet18_forward_and_grad():
    cfg = ResNetConfig.resnet18(num_classes=5, width=8, dtype=jnp.float32)
    model = ResNet(cfg)
    model.init(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    logits = model.predict_logits(x)
    assert logits.shape == (2, 5)
    y = jax.nn.one_hot(jnp.array([0, 3]), 5)
    g = jax.grad(lambda p: model.loss_fn()(p, x, y))(model.params)
    flat = jax.tree_util.tree_leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in flat)
    assert any(float(jnp.max(jnp.abs(l))) > 0 for l in flat)


def test_viterbi_recovers_smooth_path():
    # emissions strongly favor [0,0,1,1] with one noisy step
    em = np.array([[0.9, 0.1], [0.8, 0.2], [0.45, 0.55], [0.1, 0.9]])
    v = Viterbi([0, 1], transition_prob=0.8)
    assert v.decode(em) == [0, 0, 1, 1]
    path, score = viterbi_decode(np.log(em), np.log(np.array([[0.8, 0.2], [0.2, 0.8]])))
    assert path.tolist() == [0, 0, 1, 1]


def test_counters():
    c = Counter(["a", "b", "a"])
    assert c.get_count("a") == 2
    assert c.argmax() == "a"
    c.normalize()
    assert c.total_count() == pytest.approx(1.0)
    cm = CounterMap()
    cm.increment("x", "y", 2.0)
    assert cm.get_count("x", "y") == 2.0
    idx = Index(["w1", "w2"])
    assert idx.index_of("w2") == 1
    assert idx.get(0) == "w1"
    assert idx.add("w1") == 0 and len(idx) == 2


def test_summary_statistics_and_entropy():
    s = SummaryStatistics()
    s.add_all([1.0, 2.0, 3.0, 4.0])
    assert s.mean == pytest.approx(2.5)
    assert s.std == pytest.approx(np.std([1, 2, 3, 4], ddof=1))
    assert entropy([0.5, 0.5]) == pytest.approx(np.log(2))


def test_disk_based_queue(tmp_path):
    q = DiskBasedQueue(tmp_path, memory_window=3)
    for i in range(10):
        q.add(i)
    assert len(q) == 10
    assert [q.poll() for _ in range(10)] == list(range(10))
    assert q.is_empty()


def test_moving_window_and_serialization(tmp_path):
    m = np.arange(16).reshape(4, 4)
    wins = moving_window_matrix(m, 2, 2)
    assert wins.shape == (4, 4)
    wins_rot = moving_window_matrix(m, 2, 2, add_rotations=True)
    assert wins_rot.shape == (16, 4)
    save_object({"a": 1}, tmp_path / "o.pkl")
    assert read_object(tmp_path / "o.pkl") == {"a": 1}


def test_preprocessor_serde_roundtrip():
    net = lenet(n_classes=10, input_side=8, num_filters=2, filter_size=(3, 3))
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    back = MultiLayerConfiguration.from_json(net.conf.to_json())
    assert back.preprocessors == {0: "flatten"}


def test_resnet_s2d_stem_matches_plain_stem():
    """The space-to-depth stem (4x4/1 conv on a 2x2-s2d input) is an exact
    rearrangement of the 7x7/2 conv — logits must match the plain stem to
    summation-order noise."""
    import dataclasses

    from deeplearning4j_tpu.models.resnet import forward, init_params

    cfg = ResNetConfig.resnet18(num_classes=5, width=8, dtype=jnp.float32)
    assert cfg.stem_space_to_depth
    params = init_params(jax.random.key(0), cfg)
    x = jax.random.normal(jax.random.key(1), (2, 32, 32, 3))
    with jax.default_matmul_precision("highest"):
        y_s2d = forward(params, x, cfg)
        y_plain = forward(params, x,
                          dataclasses.replace(cfg, stem_space_to_depth=False))
    # stem outputs agree to ~1e-6; batch-norm rsqrt amplifies that
    # summation-order noise through the stack, hence the loose logit atol
    np.testing.assert_allclose(np.asarray(y_s2d), np.asarray(y_plain),
                               atol=2e-3)
