"""Benchmark: flagship (BERT-base-class) training-step throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.md); the driver's north star is
BERT-base fine-tune at >=35% MFU, so ``vs_baseline`` = achieved_MFU / 0.35
(1.0 == the target; higher is better).

Robustness: the tunneled TPU can wedge (held grant). Device discovery runs
in a watchdog thread; on timeout or absence of a TPU the bench falls back to
CPU and says so in the metric name, still emitting exactly one JSON line.
"""

from __future__ import annotations

import json
import sys
import threading
import time

PEAK_FLOPS = {
    "tpu v5 lite": 197e12,   # v5e bf16 peak per chip
    "tpu v5": 197e12,
    "tpu": 197e12,
    "cpu": 5e10,             # nominal; cpu fallback is a smoke signal only
}


def _discover_devices(timeout_s: float = 120.0):
    """Probe the TPU backend in a SUBPROCESS (an in-thread probe that hangs
    would wedge jax's backend lock and deadlock the CPU fallback too); only
    touch the TPU platform in-process once the probe proves it healthy."""
    import subprocess
    import jax

    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; print(d.platform, d.device_kind)"],
            capture_output=True, text=True, timeout=timeout_s)
        ok = proc.returncode == 0 and proc.stdout.strip()
        reason = None if ok else f"probe rc={proc.returncode}: {proc.stderr[-200:]}"
    except subprocess.TimeoutExpired:
        ok, reason = False, f"device discovery hung >{timeout_s:.0f}s"
    if ok:
        return jax.devices(), None
    jax.config.update("jax_platforms", "cpu")
    return jax.devices("cpu"), reason


def main():
    t_start = time.time()
    devices, fallback_reason = _discover_devices()
    dev = devices[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    on_tpu = "tpu" in kind or dev.platform == "tpu"

    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, TransformerLM)

    if on_tpu:
        batch, seq, iters = 32, 512, 20
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12,
                                n_layers=12, d_ff=3072, max_len=seq,
                                causal=False, dtype=jnp.bfloat16, remat=True)
    else:
        batch, seq, iters = 4, 128, 3
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_heads=4,
                                n_layers=2, d_ff=256, max_len=seq,
                                causal=False, dtype=jnp.float32, remat=False)

    from deeplearning4j_tpu.optimize import transforms as T

    model = TransformerLM(cfg)
    with jax.default_device(dev):
        tx = T.adamw(T.warmup_cosine(1e-4, 10, 1000), weight_decay=0.01)
        params = model.init(jax.random.key(0))
        opt = model.init_opt(params, tx)
        tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                                    cfg.vocab_size)
        targets = jnp.roll(tokens, -1, axis=1)
        step = model.build_train_step(tx)

        # compile + warmup
        params, opt, loss = step(params, opt, tokens, targets)
        jax.block_until_ready(loss)
        t0 = time.time()
        for _ in range(iters):
            params, opt, loss = step(params, opt, tokens, targets)
        jax.block_until_ready(loss)
        dt = time.time() - t0

    tokens_per_sec = batch * seq * iters / dt
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind), PEAK_FLOPS["cpu"])
    mfu = cfg.flops_per_token() * tokens_per_sec / peak
    metric = ("bert_base_train_tokens_per_sec" if on_tpu
              else "bert_base_train_tokens_per_sec_CPU_FALLBACK")
    out = {
        "metric": metric,
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec/chip",
        # CPU fallback numbers are a smoke signal, not a claim: report 0.
        "vs_baseline": round(mfu / 0.35, 4) if on_tpu else 0.0,
        "extra": {
            "device": str(dev),
            "mfu": round(mfu, 4),
            "loss": round(float(loss), 4),
            "wall_s": round(time.time() - t_start, 1),
            **({"fallback": fallback_reason} if fallback_reason else {}),
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
