"""Control-plane tests (DESIGN.md §26): autoscaler decisions against a
scripted metric feed, brownout ladder hysteresis, per-tenant fair share,
priority tiers with aging, the queue-depth-at-expiry fix, and the
router's warmed-gated ring admission.

Every decision test drives the controller with an injected clock and
hand-written :class:`ControlSignals` — no threads, no sleeps, no real
engine — which is exactly what the four-callable wiring exists for.
"""

import threading
import time

import pytest

from deeplearning4j_tpu.control import (Autoscaler, AutoscalerConfig,
                                        BrownoutConfig, BrownoutController,
                                        ControlSignals, OverloadGate,
                                        Throttled, TokenBucketAdmission)
from deeplearning4j_tpu.control.overload import BucketConfig
from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.resilience.faults import FaultSpec, inject_faults
from deeplearning4j_tpu.serving import RequestQueue
from deeplearning4j_tpu.serving.batcher import (DeadlineExceeded,
                                                GenerateRequest)
from deeplearning4j_tpu.serving.router.replicas import Replica
from deeplearning4j_tpu.serving.router.router import (PrefixRouter,
                                                      RouterConfig)


# ------------------------------------------------------------------ harness
class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


class _Harness:
    """Scripted-feed autoscaler: a list of ControlSignals plays back one
    per step(); actuators mutate a fake pool size and log calls."""

    def __init__(self, size=1, **cfg_kw):
        cfg_kw.setdefault("interval_s", 0.01)
        cfg_kw.setdefault("cooldown_s", 5.0)
        cfg_kw.setdefault("down_consecutive", 3)
        self.cfg = AutoscalerConfig(**cfg_kw)
        self.clock = _Clock()
        self.size = size
        self.feed = []
        self.actions = []
        self.fail_next = None
        self.scaler = Autoscaler(
            self._read, self._up, self._down, lambda: self.size,
            cfg=self.cfg, clock=self.clock)

    def _read(self):
        return self.feed.pop(0) if self.feed else ControlSignals()

    def _up(self):
        if self.fail_next == "up":
            self.fail_next = None
            raise RuntimeError("actuator broke")
        self.size += 1
        self.actions.append("up")

    def _down(self):
        if self.fail_next == "down":
            self.fail_next = None
            raise RuntimeError("actuator broke")
        self.size -= 1
        self.actions.append("down")

    def play(self, sig, dt=1.0):
        self.clock.tick(dt)
        self.feed.append(sig)
        return self.scaler.step()


BURNING = ControlSignals(burn=2.0)
QUIET = ControlSignals(burn=0.1, queue_depth=0)


# ------------------------------------------------------------- decisions
def test_scale_up_on_burn():
    h = _Harness()
    assert h.play(BURNING) == "up"
    assert h.size == 2
    assert METRICS.snapshot()["counters"]["control.scale_up"] == 1


def test_scale_up_on_queue_depth_and_forecast():
    h = _Harness(queue_high=10)
    assert h.play(ControlSignals(burn=0.0, queue_depth=50)) == "up"
    h2 = _Harness(ttb_horizon_s=60.0)
    assert h2.play(ControlSignals(burn=0.0, ttb_s=30.0)) == "up"
    # a receding forecast is not pressure
    h3 = _Harness(ttb_horizon_s=60.0)
    assert h3.play(ControlSignals(burn=0.0, ttb_s=10_000.0)) is None


def test_cooldown_one_action_per_window():
    h = _Harness()
    assert h.play(BURNING) == "up"
    # still burning, but inside the cooldown: no second action
    assert h.play(BURNING, dt=1.0) is None
    assert h.play(BURNING, dt=1.0) is None
    # cooldown over -> the next burning window acts again
    assert h.play(BURNING, dt=10.0) == "up"
    assert h.actions == ["up", "up"]


def test_scale_down_needs_consecutive_quiet_windows():
    h = _Harness(size=3)
    assert h.play(QUIET, dt=10.0) is None     # quiet #1
    assert h.play(QUIET) is None              # quiet #2
    assert h.play(QUIET) == "down"            # quiet #3 -> act
    assert h.size == 2
    assert METRICS.snapshot()["counters"]["control.scale_down"] == 1


def test_hysteresis_blocks_flapping():
    h = _Harness(size=2)
    # a pressure window resets the quiet vote even while cooldown
    # blocks acting on it — alternating load must produce NO actions
    for _ in range(6):
        assert h.play(QUIET, dt=10.0) is None
        assert h.play(BURNING, dt=10.0) == "up" or True
    # the ups are legitimate (each after a full cooldown); the point is
    # zero downs ever happened between them
    assert "down" not in h.actions


def test_bounds_clamp():
    h = _Harness(size=4, max_replicas=4, min_replicas=1)
    assert h.play(BURNING) is None            # at max: no up
    h2 = _Harness(size=1, min_replicas=1)
    for _ in range(5):
        assert h2.play(QUIET, dt=10.0) is None   # at min: no down
    assert h2.actions == []


def test_failed_actuator_burns_the_cooldown_window():
    h = _Harness()
    h.fail_next = "up"
    assert h.play(BURNING) == "up"            # decision made...
    assert h.size == 1                        # ...but the actuator failed
    assert METRICS.snapshot()["counters"]["control.scale_errors"] == 1
    # the failed attempt still holds the cooldown — no retry storm
    assert h.play(BURNING, dt=1.0) is None
    assert h.play(BURNING, dt=10.0) == "up"
    assert h.size == 2


def test_killed_autoscaler_degrades_to_static_capacity():
    h = _Harness()
    assert h.play(BURNING) == "up"
    with inject_faults(FaultSpec("control.autoscaler", probability=1.0)):
        assert h.play(BURNING, dt=10.0) is None
    assert h.scaler.dead
    snap = METRICS.snapshot()
    assert snap["counters"]["control.autoscaler_killed"] == 1
    assert snap["gauges"]["control.autoscaler_alive"] == 0.0
    # dead means STATIC: burning signals no longer reach the actuators
    for _ in range(3):
        assert h.play(BURNING, dt=10.0) is None
    assert h.size == 2 and h.actions == ["up"]
    # and a dead controller refuses to restart into a zombie loop
    assert h.scaler.start() is False


def test_daemon_lifecycle():
    h = _Harness()
    assert h.scaler.start() is True
    assert h.scaler.start() is False          # no-op while alive
    assert h.scaler.running
    h.scaler.stop()
    assert not h.scaler.running


# -------------------------------------------------------------- brownout
class _FakeEngine:
    def __init__(self):
        self.spec = True
        self.cap = None

    def set_speculative(self, enabled):
        self.spec = bool(enabled)
        return self.spec

    def set_max_new_cap(self, cap):
        self.cap = cap


def test_brownout_ladder_and_hysteresis():
    clock = _Clock()
    eng = _FakeEngine()
    bc = BrownoutController(eng, BrownoutConfig(
        enter_burn=(1.0, 2.0, 4.0), exit_fraction=0.5, dwell_s=1.0,
        clamp_max_new=8), clock=clock)
    clock.tick(10)
    assert bc.update(0.5) == 0 and eng.spec and eng.cap is None
    assert bc.update(1.2) == 1                # level 1: spec off
    assert not eng.spec and eng.cap is None
    clock.tick(2)
    assert bc.update(2.5) == 2 and eng.cap == 8
    clock.tick(2)
    assert bc.update(9.0) == 3
    assert bc.shed_background
    # exit hysteresis: burn must drop BELOW exit_fraction * enter rung,
    # and only one rung per dwell — no cliff exits
    clock.tick(2)
    assert bc.update(3.0) == 3                # 3.0 >= 4.0*0.5: hold
    clock.tick(2)
    assert bc.update(1.5) == 2
    clock.tick(2)
    assert bc.update(0.4) == 1
    clock.tick(2)
    assert bc.update(0.4) == 0
    assert eng.spec and eng.cap is None       # fully restored
    snap = METRICS.snapshot()
    assert snap["gauges"]["control.brownout_level"] == 0.0
    assert snap["counters"]["control.brownout_transitions"] == 6


def test_brownout_dwell_and_missing_signal_hold_level():
    clock = _Clock()
    bc = BrownoutController(None, BrownoutConfig(dwell_s=5.0), clock=clock)
    clock.tick(10)
    assert bc.update(2.5) == 2
    clock.tick(1)
    assert bc.update(0.0) == 2                # inside dwell: hold
    assert bc.update(None) == 2               # no data must never relax
    clock.tick(10)
    assert bc.update(0.0) == 1


# ------------------------------------------------------------- fair share
def _req(tenant="", max_new=10, priority=0):
    return GenerateRequest(prompt=[1], max_new_tokens=max_new,
                           tenant=tenant, priority=priority)


def test_token_bucket_fair_share_isolates_tenants():
    clock = _Clock()
    bucket = TokenBucketAdmission(
        BucketConfig(rate_tokens_s=10.0, burst_tokens=20.0), clock=clock)
    bucket.charge(_req(tenant="a", max_new=15))
    with pytest.raises(Throttled) as ei:
        bucket.charge(_req(tenant="a", max_new=15))
    assert ei.value.status == 429
    # tenant b is untouched by a's exhaustion — that is the fair share
    bucket.charge(_req(tenant="b", max_new=15))
    # refill at the configured rate restores a's budget
    clock.tick(2.0)
    bucket.charge(_req(tenant="a", max_new=15))
    snap = METRICS.snapshot()["counters"]
    assert snap["control.throttled"] == 1
    assert snap["tenant.a.throttled"] == 1
    assert "tenant.b.throttled" not in snap


def test_overload_gate_sheds_background_only_at_level_3():
    clock = _Clock()
    bc = BrownoutController(None, BrownoutConfig(dwell_s=0.0), clock=clock)
    gate = OverloadGate(brownout=bc)
    clock.tick(10)
    bc.update(9.0)
    assert bc.level == 3
    with pytest.raises(Throttled):
        gate(_req(tenant="bg", priority=1))
    gate(_req(tenant="fg", priority=0))       # interactive still served
    clock.tick(10)
    bc.update(0.0)
    gate(_req(tenant="bg", priority=1))       # below level 3: admitted


# ------------------------------------------------- priority tiers + aging
def test_interactive_claimed_ahead_of_background():
    q = RequestQueue(max_depth=8, max_batch_delay_ms=0.0)
    bg = q.submit(_req(priority=1))
    fg = q.submit(_req(priority=0))
    assert q.take(8) == [fg, bg]              # interactive first


def test_claim_preempts_unaged_background():
    q = RequestQueue(max_depth=8, max_batch_delay_ms=0.0, aging_s=60.0)
    bg = q.submit(_req(priority=1))
    [p] = q.take(1)
    assert p is bg
    fg = q.submit(_req(priority=0))
    # claim-time arbitration: an interactive arrival bounces the
    # background claim; False means "skip, not fail" — bg stays pending
    assert q.claim(bg) is False
    assert not bg.request or not bg.done()
    assert METRICS.snapshot()["counters"]["serving.preempted"] == 1
    assert q.take(8) == [fg, bg]              # bg re-taken after fg
    assert q.claim(fg) and q.claim(bg)        # no rival now: both admit


def test_aged_background_cannot_starve():
    q = RequestQueue(max_depth=8, max_batch_delay_ms=0.0, aging_s=0.05)
    bg = q.submit(_req(priority=1))
    time.sleep(0.06)
    fg = q.submit(_req(priority=0))
    assert q.take(8) == [bg, fg]              # aged bg beats interactive
    q2 = RequestQueue(max_depth=8, max_batch_delay_ms=0.0, aging_s=0.05)
    bg2 = q2.submit(_req(priority=1))
    [p] = q2.take(1)
    time.sleep(0.06)
    q2.submit(_req(priority=0))
    assert q2.claim(bg2) is True              # aged: preemption-exempt


# ----------------------------------------- queue depth at expiry (bugfix)
def test_expiry_decrements_depth_gauge_without_a_take():
    q = RequestQueue(max_depth=8, max_batch_delay_ms=0.0)
    past = time.monotonic() - 1.0
    pends = [q.submit(_req()) for _ in range(3)]
    for p in pends:
        p.request.deadline_s = past
    assert METRICS.snapshot()["gauges"]["serving.queue.depth"] == 3
    # the autoscaler's read path sweeps: dead work leaves the gauge NOW,
    # not at whatever future take() would have popped it
    assert q.depth() == 0
    snap = METRICS.snapshot()
    assert snap["gauges"]["serving.queue.depth"] == 0
    assert snap["counters"]["serving.deadline_dropped"] == 3
    for p in pends:
        with pytest.raises(DeadlineExceeded):
            p.result(0)


def test_expiry_sweep_frees_room_for_live_submits():
    q = RequestQueue(max_depth=2, max_batch_delay_ms=0.0)
    a = q.submit(_req())
    b = q.submit(_req())
    a.request.deadline_s = b.request.deadline_s = time.monotonic() - 1.0
    # full queue of dead work must NOT 429 a live request
    live = q.submit(_req())
    assert q.take(8) == [live]


@pytest.mark.lockguard
def test_queue_expiry_contention():
    """Submitters, takers and depth-pollers hammer one queue while
    deadlines expire mid-flight; every request resolves exactly once
    and the depth gauge lands on the true (empty) depth."""
    q = RequestQueue(max_depth=256, max_batch_delay_ms=0.0, aging_s=0.01)
    done = threading.Event()
    taken, lock = [], threading.Lock()

    def submitter(seed):
        for i in range(40):
            try:
                p = q.submit(GenerateRequest(
                    prompt=[1], max_new_tokens=1,
                    priority=(seed + i) % 2,
                    deadline_s=time.monotonic()
                    + (0.0005 if i % 3 == 0 else 5.0)))
            except Exception:
                continue
            with lock:
                taken.append(p)

    def taker():
        while not done.is_set():
            for p in q.take(4, block_s=0.001):
                if q.claim(p):
                    p._complete("served")

    def poller():
        while not done.is_set():
            q.depth()

    threads = [threading.Thread(target=submitter, args=(s,))
               for s in range(4)]
    threads += [threading.Thread(target=taker) for _ in range(2)]
    threads.append(threading.Thread(target=poller))
    for t in threads:
        t.start()
    for t in threads[:4]:
        t.join()
    deadline = time.monotonic() + 5.0
    while q.depth() > 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    done.set()
    for t in threads[4:]:
        t.join()
    for p in q.drain():                       # nothing should remain
        p._fail(DeadlineExceeded("leftover"))
    # single-shot resolution survived the contention: served XOR failed
    assert len(taken) == 160
    served = sum(1 for p in taken if p.done() and p._exc is None)
    failed = sum(1 for p in taken if p.done() and p._exc is not None)
    assert served + failed == len(taken)
    assert METRICS.snapshot()["gauges"]["serving.queue.depth"] == 0


# ------------------------------------------- warmed-gated ring admission
class _WarmableReplica(Replica):
    """Stub whose healthz mirrors the engine warmed flag."""

    def __init__(self, name, warmed=True):
        super().__init__(name)
        self.warmed = warmed
        self.closed = False
        self.served = 0

    def generate(self, payload, timeout_s):
        self.served += 1
        return {"tokens": [1], "finish_reason": "length",
                "latency_s": 0.0, "ttft_s": 0.0}

    def healthz(self, timeout_s):
        return {"ok": True, "engine": {"warmed": self.warmed}}

    def close(self):
        self.closed = True


def test_scale_up_gates_ring_admission_on_warmed(monkeypatch):
    router = PrefixRouter([_WarmableReplica("r0")],
                          RouterConfig(page_size=4, affinity_pages=2))
    cold = _WarmableReplica("r1", warmed=False)
    admitted_at = []

    def admit():
        router.scale_up(cold, warm_timeout_s=5.0, poll_s=0.005)
        admitted_at.append(time.monotonic())

    t = threading.Thread(target=admit)
    t0 = time.monotonic()
    t.start()
    time.sleep(0.08)
    # still cold: the ring MUST NOT know it — requests keep landing on
    # the old capacity with no compile-storm node in the walk
    assert router.pool.names() == ["r0"]
    cold.warmed = True
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert admitted_at and admitted_at[0] - t0 >= 0.08
    assert set(router.pool.names()) == {"r0", "r1"}
    assert router.pool.is_active("r1")
    assert "r1" in set(router.ring.walk("any-key"))
    assert METRICS.snapshot()["gauges"]["router.pool_size"] == 2.0


def test_scale_up_warm_timeout_fails_safe():
    router = PrefixRouter([_WarmableReplica("r0")],
                          RouterConfig(page_size=4, affinity_pages=2))
    cold = _WarmableReplica("r1", warmed=False)
    with pytest.raises(TimeoutError, match="refusing ring admission"):
        router.scale_up(cold, warm_timeout_s=0.05, poll_s=0.005)
    assert cold.closed                        # not admitted, not leaked
    assert router.pool.names() == ["r0"]


def test_scale_down_drains_then_removes():
    reps = [_WarmableReplica(f"r{i}") for i in range(2)]
    router = PrefixRouter(reps, RouterConfig(page_size=4, affinity_pages=2))
    router.pool.begin_request("r1")           # simulate in-flight work
    with pytest.raises(TimeoutError, match="reactivated"):
        router.scale_down("r1", drain_timeout_s=0.05, poll_s=0.005)
    # fail safe: the drain timed out, so the replica is BACK (active),
    # never half-removed
    assert router.pool.is_active("r1")
    assert set(router.pool.names()) == {"r0", "r1"}
    router.pool.end_request("r1")
    rep = router.scale_down("r1", drain_timeout_s=1.0, poll_s=0.005)
    assert rep is reps[1]
    assert router.pool.names() == ["r0"]
    assert "r1" not in set(router.ring.walk("any-key"))
    snap = METRICS.snapshot()["counters"]
    assert snap["router.drain_aborts"] == 1
    assert snap["router.scale_down"] == 1


def test_scale_down_refuses_last_replica():
    router = PrefixRouter([_WarmableReplica("r0")],
                          RouterConfig(page_size=4, affinity_pages=2))
    with pytest.raises(RuntimeError, match="last replica"):
        router.scale_down("r0")


def test_autoscaler_over_real_router_seams():
    """End-to-end over the real seams: burn scales the router up (warmed
    replica), quiet windows drain one back down, and the chaos kill
    freezes membership."""
    from deeplearning4j_tpu.control.autoscaler import router_actuators

    seq = [1]
    router = PrefixRouter([_WarmableReplica("r0")],
                          RouterConfig(page_size=4, affinity_pages=2))

    def factory():
        name = f"r{seq[0]}"
        seq[0] += 1
        return _WarmableReplica(name)

    cfg = AutoscalerConfig(cooldown_s=0.0, down_consecutive=1,
                           max_replicas=3, warm_timeout_s=1.0,
                           drain_timeout_s=1.0)
    up, down, size = router_actuators(router, factory, cfg)
    clock = _Clock()
    feed = []
    scaler = Autoscaler(lambda: feed.pop(0), up, down, size,
                        cfg=cfg, clock=clock)
    feed.append(BURNING)
    clock.tick(1)
    assert scaler.step() == "up" and size() == 2
    feed.append(QUIET)
    clock.tick(1)
    assert scaler.step() == "down" and size() == 1
    with inject_faults(FaultSpec("control.autoscaler", probability=1.0)):
        feed.append(BURNING)
        clock.tick(1)
        assert scaler.step() is None
    assert scaler.dead and size() == 1        # static capacity, intact ring
    assert router.pool.is_active("r0")


def test_router_signals_reads_real_evaluators():
    """`router_signals` wires the live SLOEvaluator / RequestQueue /
    ForecastEvaluator stack into ControlSignals — burn from the worst
    full window, depth post-expiry-sweep, TTB by objective NAME (the
    name-based `ttb_seconds` accessor, +inf when nothing is ramping)."""
    from deeplearning4j_tpu.control.autoscaler import router_signals
    from deeplearning4j_tpu.observability import (ForecastEvaluator,
                                                  MetricsRegistry,
                                                  SLOEvaluator, SLObjective,
                                                  TimeSeriesStore)

    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg)
    obj = SLObjective("ttft", "upper", "serving.ttft.p99", 0.5,
                      budget=0.05, windows=(8.0, 16.0))
    slo = SLOEvaluator([obj], store, registry=reg, breach_cooldown_s=1e9)
    fore = ForecastEvaluator([obj], store, registry=reg, horizon_s=30.0,
                             window_s=8.0, min_samples=4,
                             breach_cooldown_s=1e9)
    queue = RequestQueue(max_depth=8)
    read = router_signals(slo, queue, "ttft", forecast=fore)

    sig = read()                    # before any samples: all-healthy
    assert sig.burn is None and sig.queue_depth == 0 and sig.ttb_s is None

    t = 0.0
    while t <= 20.0:                # ramp crosses the 0.5 objective
        reg.gauge("serving.ttft.p99", 0.1 + 0.04 * t)
        store.sample_once(t=t)
        t += 0.5
    queue.submit(GenerateRequest(prompt=[1], max_new_tokens=1))
    sig = read()
    assert sig.burn is not None and sig.burn > 0
    assert sig.queue_depth == 1
    assert sig.ttb_s is not None and sig.ttb_s < 30.0
    assert fore.ttb_seconds("no-such-objective") is None
