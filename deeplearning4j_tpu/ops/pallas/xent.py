"""Blocked cross-entropy: stream (N, V) logits tile-by-tile, never
materializing them.

The LM loss is the single biggest HBM tensor in big-vocab training (the
full f32 logits are 4.3 GB at batch 64 / seq 512 / 32k vocab).  The
chunked-scan path in ``models/transformer.lm_head_loss`` already streams
token chunks, but its chunk size must DIVIDE the token count — a
near-prime count used to force a zero-weight padding workaround (PR 5).
This kernel replaces that fallback with a shape-independent schedule:

- grid = (token tiles, vocab tiles); the vocab axis is the inner
  (sequential) dimension, so each token tile keeps a running softmax
  (max, denominator) and its gold-logit gather in VMEM while (D, BV)
  head tiles stream through the MXU;
- any token count works (rows pad internally with zero-weight tokens),
  any vocab works (the tail tile is masked in-kernel by the real V, so
  odd vocabularies never pad the head matrix);
- per-token ``w * (logsumexp - gold)`` and the lse come out; the sum is
  the same quantity ``token_xent`` computes today.

Backward is a jnp ``lax.scan`` over token tiles under a custom_vjp: it
recomputes each tile's logits from the saved lse (flash-attention-style
recompute — O(tile × V) transient, nothing stored), emits dh/dhead/dw,
and a ``float0`` cotangent for the integer targets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from ..flash_attention import _VMEM
from . import registry

_NEG_INF = -1e30


def reference_xent_sum(h, head, targets, weights=None):
    """Naive ground truth: full (N, V) logits, f32, weighted sum of
    per-token (lse - gold)."""
    logits = jnp.dot(h, head,
                     preferred_element_type=jnp.float32).astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[:, None], axis=-1)[:, 0]
    w = jnp.ones_like(lse) if weights is None else weights.astype(jnp.float32)
    return ((lse - gold) * w).sum()


def _kernel(h_ref, hd_ref, t_ref, wt_ref,
            m_ref, l_ref, g_ref, loss_ref, lse_ref, *,
            block_v: int, v_real: int):
    j = pl.program_id(1)
    n_v = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full(m_ref.shape, _NEG_INF, jnp.float32)
        l_ref[...] = jnp.zeros(l_ref.shape, jnp.float32)
        g_ref[...] = jnp.zeros(g_ref.shape, jnp.float32)

    logits = jnp.dot(h_ref[...], hd_ref[...],
                     preferred_element_type=jnp.float32)   # (BT, BV)
    cols = j * block_v + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    # the tail vocab tile may run past the real V (uneven split — the
    # whole point is never padding the head): mask phantom columns so
    # they contribute exp(-inf)=0 to the denominator and 0 to gold
    logits = jnp.where(cols < v_real, logits, _NEG_INF)
    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, logits.max(-1, keepdims=True))
    l_new = (l_prev * jnp.exp(m_prev - m_new)
             + jnp.exp(logits - m_new).sum(-1, keepdims=True))
    m_ref[...] = m_new
    l_ref[...] = l_new
    hit = cols == t_ref[...]                              # (BT, BV)
    g_ref[...] = g_ref[...] + jnp.where(hit, logits, 0.0).sum(
        -1, keepdims=True)

    @pl.when(j == n_v - 1)
    def _finish():
        lse = m_ref[...] + jnp.log(jnp.maximum(l_ref[...], 1e-30))
        lse_ref[...] = lse
        loss_ref[...] = wt_ref[...] * (lse - g_ref[...])


def _xent_fwd(h2, head, t2, w2, block_t, block_v, interpret):
    """h2 (N, D) with N % block_t == 0; returns (wloss (N,), lse (N,))."""
    n, d = h2.shape
    v = head.shape[1]
    n_v = -(-v // block_v)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    kernel = functools.partial(_kernel, block_v=block_v, v_real=v)
    outs = pl.pallas_call(
        kernel,
        grid=(n // block_t, n_v),
        in_specs=[
            pl.BlockSpec((block_t, d), lambda i, j: (i, 0), **mem),
            pl.BlockSpec((d, block_v), lambda i, j: (0, j), **mem),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0), **mem),
            pl.BlockSpec((block_t, 1), lambda i, j: (i, 0), **mem),
        ],
        # running (max, denom, gold) live in revisited output blocks —
        # the same accumulate-across-the-inner-grid-axis pattern as a
        # blocked matmul; loss/lse are written on the final vocab tile
        out_specs=[pl.BlockSpec((block_t, 1), lambda i, j: (i, 0), **mem)
                   for _ in range(5)],
        out_shape=[jax.ShapeDtypeStruct((n, 1), jnp.float32)
                   for _ in range(5)],
        interpret=interpret,
    )(h2, head, t2.reshape(n, 1), w2.reshape(n, 1))
    _, _, _, wloss, lse = outs
    return wloss[:, 0], lse[:, 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _blocked(h2, head, t2, w2, block_t, block_v, interpret):
    wloss, _ = _xent_fwd(h2, head, t2, w2, block_t, block_v, interpret)
    return wloss.sum()


def _blocked_fwd(h2, head, t2, w2, block_t, block_v, interpret):
    wloss, lse = _xent_fwd(h2, head, t2, w2, block_t, block_v, interpret)
    return wloss.sum(), (h2, head, t2, w2, lse)


def _blocked_bwd(block_t, block_v, interpret, res, gct):
    h2, head, t2, w2, lse = res
    n, d = h2.shape
    head32 = head.astype(jnp.float32)
    n_t = n // block_t
    tiles = (h2.reshape(n_t, block_t, d), t2.reshape(n_t, block_t),
             w2.reshape(n_t, block_t), lse.reshape(n_t, block_t))

    def tile(dhead, xs):
        h_t, t_t, w_t, lse_t = xs
        logits = jnp.dot(h_t, head,
                         preferred_element_type=jnp.float32)  # (BT, V)
        p = jnp.exp(logits.astype(jnp.float32) - lse_t[:, None])
        gw = (gct * w_t)[:, None]                             # (BT, 1) f32
        # dL/dlogits = g*w * (softmax - onehot), applied without ever
        # building the onehot: matmul with p, then scatter the gold term
        dh_t = (jnp.dot(gw * p, head32.T)
                - gw * head32.T[t_t]).astype(h2.dtype)
        h32 = h_t.astype(jnp.float32)
        dhead = dhead + jnp.dot(h32.T, gw * p)
        dhead = dhead.at[:, t_t].add(-(gw * h32).T)
        gold = jnp.take_along_axis(logits, t_t[:, None], axis=1)[:, 0]
        dw_t = gct * (lse_t - gold.astype(jnp.float32))
        return dhead, (dh_t, dw_t)

    dhead, (dhs, dws) = lax.scan(
        tile, jnp.zeros(head.shape, jnp.float32), tiles)
    dh = dhs.reshape(n, d)
    dw = dws.reshape(n).astype(w2.dtype)
    # integer targets take a float0 cotangent (JAX's convention for
    # non-differentiable integer primal inputs)
    dt = np.zeros(t2.shape, jax.dtypes.float0)
    return dh, dhead.astype(head.dtype), dt, dw


_blocked.defvjp(_blocked_fwd, _blocked_bwd)


def blocked_cross_entropy(h, head, targets, weights=None, *,
                          block_t: int = 256, block_v: int = 512,
                          interpret: bool | None = None):
    """Weighted token cross-entropy SUM of (N, D) hiddens against a
    (D, V) head, streamed so (N, V) logits never exist.

    Mirrors ``lm_head_loss``'s ``token_xent`` contract (the caller
    divides by the real token count).  Any N and V work: N pads
    internally with zero-weight rows, the tail V tile is masked
    in-kernel.  ``interpret=None`` auto-selects interpret mode off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n, d = h.shape
    block_t = min(block_t, n)
    block_v = min(block_v, head.shape[1])
    t2 = targets.astype(jnp.int32)
    w2 = (jnp.ones((n,), jnp.float32) if weights is None
          else weights.astype(jnp.float32))
    pad = -n % block_t
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        t2 = jnp.concatenate([t2, jnp.zeros((pad,), t2.dtype)])
        w2 = jnp.concatenate([w2, jnp.zeros((pad,), w2.dtype)])
    return _blocked(h, head, t2, w2, block_t, block_v, interpret)


def _scan_xent_sum(h, head, targets, weights=None, *, block_t: int = 256,
                   **_):
    """The XLA incumbent (and pallas-unavailable fallback): a remat'd
    ``lax.scan`` over zero-weight-padded token tiles — the PR-5 schedule,
    generalized to any N.  Still O(tile × V) transient memory, but each
    tile's logits DO materialize."""
    n, d = h.shape
    block_t = min(block_t, n)
    w = (jnp.ones((n,), jnp.float32) if weights is None
         else weights.astype(jnp.float32))
    t = targets
    pad = -n % block_t
    if pad:
        h = jnp.concatenate([h, jnp.zeros((pad, d), h.dtype)])
        t = jnp.concatenate([t, jnp.zeros((pad,), t.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])

    @jax.checkpoint
    def tile(h_t, t_t, w_t):
        return reference_xent_sum(h_t, head, t_t, w_t)

    def body(tot, xs):
        return tot + tile(*xs), None

    total, _ = lax.scan(
        body, jnp.zeros((), jnp.float32),
        (h.reshape(-1, block_t, d), t.reshape(-1, block_t),
         w.reshape(-1, block_t)))
    return total


registry.register(registry.KernelCandidate(
    kind="xent", name="blocked", fn=blocked_cross_entropy,
    reference=reference_xent_sum,
    blocks=({"block_t": 128, "block_v": 512},
            {"block_t": 256, "block_v": 512},
            {"block_t": 256, "block_v": 1024},
            {"block_t": 512, "block_v": 1024}),
    # fwd relative loss error + bwd max grad error vs reference (f32)
    tolerances={"max_err": 1e-3},
))

registry.register(registry.KernelCandidate(
    kind="xent", name="scan", fn=_scan_xent_sum,
    reference=reference_xent_sum, source="xla",
))
