"""Inverted index with optional on-disk persistence.

Capability match of ``text/invertedindex/InvertedIndex.java:17`` +
``LuceneInvertedIndex.java`` (912 LoC): document ingestion, posting lists,
term/document lookups, batch iteration for embedding training, simple
TF-IDF ranked search, and — like Lucene's on-disk segments — a compact
save/load so a large corpus index survives process restarts, all without
the Lucene dependency (the reference embeds Lucene purely as a corpus
store for Word2Vec batching).
"""

from __future__ import annotations

import gzip
import json
import math
from collections import defaultdict
from pathlib import Path
from typing import Callable, Iterable, Iterator, Sequence

from .tokenization import CommonPreprocessor, DefaultTokenizerFactory


class InvertedIndex:
    def __init__(self, tokenizer_factory=None):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory(
            CommonPreprocessor())
        self._docs: list[list[str]] = []
        self._labels: list[str | None] = []
        self._postings: dict[str, list[int]] = defaultdict(list)

    # ------------------------------------------------------------------ ingest
    def add_doc(self, text_or_tokens: str | Sequence[str],
                label: str | None = None) -> int:
        tokens = (self.tokenizer_factory.create(text_or_tokens).get_tokens()
                  if isinstance(text_or_tokens, str) else list(text_or_tokens))
        doc_id = len(self._docs)
        self._docs.append(tokens)
        self._labels.append(label)
        for t in set(tokens):
            self._postings[t].append(doc_id)
        return doc_id

    def add_all(self, texts: Iterable[str]) -> None:
        for t in texts:
            self.add_doc(t)

    # ------------------------------------------------------------------ lookup
    def document(self, doc_id: int) -> list[str]:
        return self._docs[doc_id]

    def label(self, doc_id: int) -> str | None:
        return self._labels[doc_id]

    def documents_for(self, word: str) -> list[int]:
        return list(self._postings.get(word, ()))

    def doc_frequency(self, word: str) -> int:
        return len(self._postings.get(word, ()))

    def num_documents(self) -> int:
        return len(self._docs)

    def terms(self) -> list[str]:
        return sorted(self._postings)

    # ------------------------------------------------------------------ iterate
    def batch_iter(self, batch_size: int) -> Iterator[list[list[str]]]:
        """Token-list batches (the reference's Word2Vec minibatch source)."""
        for off in range(0, len(self._docs), batch_size):
            yield self._docs[off:off + batch_size]

    def all_docs(self) -> list[list[str]]:
        return list(self._docs)

    # ------------------------------------------------------------------ persist
    def save(self, path: str | Path) -> None:
        """Persist docs+labels as gzipped JSON lines (the Lucene-directory
        role); postings are rebuilt on load, so the file stays one
        source of truth."""
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(path.suffix + ".tmp")
        with gzip.open(tmp, "wt", encoding="utf-8") as f:
            for tokens, label in zip(self._docs, self._labels):
                f.write(json.dumps({"t": tokens, "l": label}) + "\n")
        tmp.replace(path)

    @classmethod
    def load(cls, path: str | Path, tokenizer_factory=None) -> "InvertedIndex":
        idx = cls(tokenizer_factory)
        with gzip.open(Path(path), "rt", encoding="utf-8") as f:
            for line in f:
                if line.strip():
                    rec = json.loads(line)
                    idx.add_doc(rec["t"], rec.get("l"))
        return idx

    # ------------------------------------------------------------------ search
    def search(self, query: str, n: int = 10) -> list[tuple[int, float]]:
        """TF-IDF ranked (doc_id, score)."""
        q_tokens = self.tokenizer_factory.create(query).get_tokens()
        n_docs = max(1, len(self._docs))
        scores: dict[int, float] = defaultdict(float)
        for t in q_tokens:
            df = self.doc_frequency(t)
            if df == 0:
                continue
            idf = math.log((1 + n_docs) / (1 + df)) + 1.0
            for d in self._postings[t]:
                tf = self._docs[d].count(t) / max(1, len(self._docs[d]))
                scores[d] += tf * idf
        return sorted(scores.items(), key=lambda kv: -kv[1])[:n]
