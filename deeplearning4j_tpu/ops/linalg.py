"""BLAS-contract parity ops.

The reference consumes ``Nd4j.getBlasWrapper()`` for ``dot``, ``axpy``,
``iamax`` (``MultiLayerNetwork.java:1062``,
``InMemoryLookupTable.java:192,208``) and matrix multiply via
``INDArray.mmul``.  On TPU these are jnp/lax compositions XLA lowers to MXU
dot-generals; they exist as named functions so higher layers read like the
contract they replace.  In-place BLAS semantics (axpy mutating y) become
functional returns.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .dtypes import get_policy


def gemm(a: jnp.ndarray, b: jnp.ndarray, precision=None) -> jnp.ndarray:
    """Matrix multiply with the active compute-dtype policy (bf16 on MXU when
    enabled), accumulating in float32.  ``precision=None`` takes the backend
    default (fast MXU passes); ``lax.Precision.HIGHEST`` forces full f32."""
    policy = get_policy()
    return jnp.matmul(policy.cast_compute(a), policy.cast_compute(b),
                      precision=precision,
                      preferred_element_type=jnp.float32).astype(policy.param_dtype)


mmul = gemm


def dot(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.vdot(x, y)


def axpy(alpha, x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """y + alpha*x (functional form of BLAS axpy)."""
    return y + alpha * x


def iamax(x: jnp.ndarray) -> jnp.ndarray:
    """Index of the max-|value| element (argmax over flattened input)."""
    return jnp.argmax(jnp.abs(jnp.ravel(x)))


def nrm2(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sqrt(jnp.sum(x * x))


def to_flattened(arrays) -> jnp.ndarray:
    """Concatenate raveled arrays — Nd4j.toFlattened, used for param vectors
    (``MultiLayerNetwork.java:744-788`` params()/setParams)."""
    return jnp.concatenate([jnp.ravel(a) for a in arrays])
