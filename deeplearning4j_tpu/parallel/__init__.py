"""L5-L7 — distributed training, TPU-native.

The reference's scaleout stack (Akka actors + Hazelcast blackboard + YARN
supersteps + ZooKeeper config, SURVEY.md §2.3) collapses on TPU into:

- **SPMD compute plane** (``mesh``, ``collectives``, ``trainer``): one jitted
  train step sharded over a `jax.sharding.Mesh`; parameter averaging ≡ the
  gradient `pmean` XLA inserts for sharded-batch/replicated-param layouts,
  riding ICI/DCN — replacing IterativeReduceWorkRouter + INDArrayAggregator +
  Hazelcast replication wholesale.
- **host control plane** (``scaleout``): Job/WorkerPerformer/StateTracker/
  WorkRouter capability parity for orchestration-level workloads (the
  reference's embedding trainers, grid jobs), including heartbeats,
  stale-worker eviction, and job re-routing — in-process threads instead of
  an actor cluster, with ``jax.distributed`` bootstrap for real multi-host.
- **checkpoint/resume** (``checkpoint``): params + optimizer state + data
  cursor (exceeds the reference, which only java-serializes params).
"""

from .compile_cache import setup_compile_cache
from .mesh import (MeshMismatchError, MeshSpec, elastic_mesh, grow_mesh,
                   local_mesh, make_mesh, shrink_mesh)
from .trainer import DataParallelTrainer, LazyLoss, TrainState
from .checkpoint import CheckpointManager
from .driver import Driver

__all__ = [
    "MeshSpec", "local_mesh", "make_mesh",
    "MeshMismatchError", "elastic_mesh", "shrink_mesh", "grow_mesh",
    "DataParallelTrainer", "LazyLoss", "TrainState",
    "CheckpointManager", "Driver", "setup_compile_cache",
]
