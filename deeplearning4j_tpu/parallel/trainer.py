"""SPMD data-parallel trainer.

TPU-native replacement for the reference's scaleout training loop
(master/worker actors + StateTracker + WorkRouter policy, SURVEY.md §3.3):
ONE jitted train step over a `jax.sharding.Mesh`, batch sharded on the
``dp`` axis.  Both of the reference's routing policies exist:

- **iterative-reduce** (``IterativeReduceWorkRouter.java:16,30``): replicated
  params + dp-sharded batch — XLA inserts the gradient all-reduce (the
  `pmean`) into the compiled step, so 'wait for all workers, average,
  rebroadcast' is a single fused collective per step on ICI.
- **hogwild** (``HogWildWorkRouter.java``, async always-send): TPUs are
  lockstep, so the idiomatic approximation is *local SGD / periodic
  averaging*: per-worker parameter replicas (leading dp-sharded axis) take
  K local steps with NO cross-device traffic, then average with one
  in-compiled `pmean` (``shard_map``).  K=1 degenerates to iterative-reduce.
  Deviation documented per SURVEY.md §7 hard-part #5.
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map

from ..datasets.dataset import DataSet
from ..observability import METRICS, NOOP_SPAN, enabled as _obs_enabled
from ..observability import sample_device_memory, trace
from ..optimize import transforms as tfm
from .mesh import DP, local_mesh

LossFn = Callable[..., jnp.ndarray]  # (params, x, y, key) -> scalar


@dataclasses.dataclass
class TrainState:
    params: Any
    tstate: Any
    step: int
    key: Any


class DataParallelTrainer:
    """Shard a supervised train step over the ``dp`` axis of a mesh."""

    def __init__(self, loss_fn: LossFn, transform: tfm.GradientTransform,
                 mesh: Mesh | None = None, router: str = "iterative_reduce",
                 average_every: int = 8):
        if router not in ("iterative_reduce", "hogwild"):
            raise ValueError(f"unknown router {router!r}")
        self.loss_fn = loss_fn
        self.transform = transform
        self.mesh = mesh if mesh is not None else local_mesh()
        self.router = router
        self.average_every = average_every
        self.n_dp = self.mesh.shape[DP]
        self._step_fn = None
        self._avg_fn = None

    # ------------------------------------------------------------------ state
    def init_state(self, params, key=None) -> TrainState:
        key = key if key is not None else jax.random.key(0)
        # Copy before placement: device_put may alias the caller's buffers as
        # mesh shards, and the jitted step donates its inputs — without this
        # copy the caller's params would be deleted by the first step.
        params = jax.tree_util.tree_map(jnp.array, params)
        if self.router == "hogwild":
            # per-worker replicas: stack along a leading dp axis
            params = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (self.n_dp,) + x.shape), params)
            params = jax.device_put(
                params, NamedSharding(self.mesh, P(DP)))
        else:
            params = jax.device_put(params, NamedSharding(self.mesh, P()))
        tstate = self.transform.init(
            jax.tree_util.tree_map(lambda x: x[0], params)
            if self.router == "hogwild" else params)
        if self.router == "hogwild":
            tstate = jax.tree_util.tree_map(
                lambda x: (jnp.broadcast_to(x[None], (self.n_dp,) + x.shape)
                           if isinstance(x, jnp.ndarray) else x), tstate)
            tstate = jax.device_put(tstate, NamedSharding(self.mesh, P(DP)))
        return TrainState(params=params, tstate=tstate, step=0, key=key)

    # ------------------------------------------------------------------ steps
    def _build_sync_step(self):
        mesh = self.mesh
        batch_sh = NamedSharding(mesh, P(DP))
        rep = NamedSharding(mesh, P())

        def step(params, tstate, x, y, key, iteration):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, x, y, key)
            updates, tstate = self.transform.update(grads, tstate, params, iteration)
            params = tfm.apply_updates(params, updates)
            return params, tstate, loss

        return jax.jit(
            step,
            in_shardings=(rep, rep, batch_sh, batch_sh, rep, rep),
            out_shardings=(rep, rep, rep),
            donate_argnums=(0, 1),
        )

    def _build_local_step(self):
        """HogWild-approx local step: runs independently per dp shard."""
        mesh = self.mesh

        def local(params, tstate, x, y, key, iteration):
            # leading dp axis stripped by shard_map (shard size 1) -> squeeze
            params = jax.tree_util.tree_map(lambda a: a[0], params)
            tstate = jax.tree_util.tree_map(
                lambda a: a[0] if isinstance(a, jnp.ndarray) else a, tstate)
            loss, grads = jax.value_and_grad(self.loss_fn)(params, x, y, key[0])
            updates, tstate = self.transform.update(grads, tstate, params, iteration[0])
            params = tfm.apply_updates(params, updates)
            expand = lambda a: a[None] if isinstance(a, jnp.ndarray) else a
            return (jax.tree_util.tree_map(expand, params),
                    jax.tree_util.tree_map(expand, tstate), loss[None])

        smapped = shard_map(
            local, mesh=mesh,
            in_specs=(P(DP), P(DP), P(DP), P(DP), P(DP), P(DP)),
            out_specs=(P(DP), P(DP), P(DP)),
            check_vma=False,
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def _build_average(self):
        """Periodic parameter averaging: one pmean inside shard_map."""
        mesh = self.mesh

        def avg(params):
            local = jax.tree_util.tree_map(lambda a: a[0], params)
            meaned = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, DP), local)
            return jax.tree_util.tree_map(lambda a: a[None], meaned)

        return jax.jit(shard_map(
            avg, mesh=mesh, in_specs=(P(DP),), out_specs=P(DP),
            check_vma=False))

    # ------------------------------------------------------------------ api
    def step(self, state: TrainState, x, y) -> tuple[TrainState, float]:
        # Observability is gated on one flag check: when disabled, no span
        # object, no perf_counter read, no registry lock on this path.
        obs = _obs_enabled()
        first = self._step_fn is None  # first call pays trace+compile
        t0 = time.perf_counter() if obs else 0.0
        cm = trace.span("train_step.compile" if first else "train_step",
                        step=state.step, router=self.router) if obs else NOOP_SPAN
        with cm:
            x = jnp.asarray(x)
            y = jnp.asarray(y)
            n_samples = x.shape[0]
            if x.shape[0] % self.n_dp != 0:
                pad = self.n_dp - (x.shape[0] % self.n_dp)
                if obs:
                    METRICS.increment("train_step.pad_batch")
                    METRICS.increment("train_step.padded_samples", pad)
                idx = jnp.arange(pad) % x.shape[0]  # wrap: pad may exceed batch
                x = jnp.concatenate([x, x[idx]])
                y = jnp.concatenate([y, y[idx]])
            state.key, sub = jax.random.split(state.key)
            if self.router == "iterative_reduce":
                if first:
                    self._step_fn = self._build_sync_step()
                params, tstate, loss = self._step_fn(
                    state.params, state.tstate, x, y, sub, jnp.asarray(state.step))
                mean_loss = float(loss)
            else:
                if first:
                    self._step_fn = self._build_local_step()
                    self._avg_fn = self._build_average()
                keys = jax.random.split(sub, self.n_dp)
                iters = jnp.full((self.n_dp,), state.step, jnp.int32)
                params, tstate, losses = self._step_fn(
                    state.params, state.tstate, x, y, keys, iters)
                if (state.step + 1) % self.average_every == 0:
                    params = self._avg_fn(params)
                    if obs:
                        METRICS.increment("train_step.periodic_average")
                mean_loss = float(jnp.mean(losses))
        if obs:
            dt = time.perf_counter() - t0
            # compile-vs-execute split: the first call's wall time is
            # dominated by trace+lower+compile — keep it out of the steady
            # state histogram so p99 means what a dashboard thinks it means
            METRICS.observe_time("train_step.compile" if first else "train_step", dt)
            METRICS.increment("train_step.iterations")
            METRICS.gauge("train_step.loss", mean_loss)
            if dt > 0:
                METRICS.gauge("train_step.samples_per_sec", n_samples / dt)
        return TrainState(params, tstate, state.step + 1, state.key), mean_loss

    def fit(self, state: TrainState, data: Iterable[DataSet] | DataSet,
            epochs: int = 1, *, checkpoint_manager=None,
            checkpoint_every: int = 0, resume: bool = True,
            ) -> tuple[TrainState, list[float]]:
        """Run to ``epochs * n_batches`` total steps, counting from
        ``state.step`` — so a state restored from a checkpoint continues
        where it left off (the elastic-recovery resume path; the reference
        only ever re-loaded bare params, ``ModelSavingActor.java:75-79``).

        With ``checkpoint_manager`` set, auto-saves params + transform state
        + RNG key + data cursor every ``checkpoint_every`` steps (and at the
        end); with ``resume`` (default) restores the latest checkpoint
        before training."""
        batches = [data] if isinstance(data, DataSet) else list(data)
        with trace.span("trainer.fit", epochs=epochs, n_batches=len(batches),
                        router=self.router):
            if checkpoint_manager is not None and resume \
                    and checkpoint_manager.latest_step() is not None:
                state = self.restore(state, checkpoint_manager)
            losses = []
            total = epochs * len(batches)
            while state.step < total:
                b = batches[state.step % len(batches)]
                state, loss = self.step(state, b.features, b.labels)
                losses.append(loss)
                if (checkpoint_manager is not None and checkpoint_every > 0
                        and state.step % checkpoint_every == 0):
                    self.checkpoint(state, checkpoint_manager)
            if checkpoint_manager is not None and losses:
                self.checkpoint(state, checkpoint_manager)
        sample_device_memory()  # HBM gauges; no-op on CPU / when disabled
        return state, losses

    # ------------------------------------------------------------------ ckpt
    def checkpoint(self, state: TrainState, manager) -> None:
        manager.save(state.step, state.params, tstate=state.tstate,
                     key=state.key, data_cursor=state.step)

    def restore(self, template: TrainState, manager) -> TrainState:
        """Restore the latest checkpoint into a state shaped like
        ``template`` (fresh ``init_state`` output), re-placed on the mesh."""
        r = manager.restore(template.params, tstate_template=template.tstate)
        params = jax.tree_util.tree_map(
            lambda t, a: jax.device_put(jnp.asarray(a), t.sharding),
            template.params, r["params"])
        tstate = template.tstate
        if r["tstate"] is not None:
            tstate = jax.tree_util.tree_map(
                lambda t, a: (jax.device_put(jnp.asarray(a), t.sharding)
                              if isinstance(t, jnp.ndarray) else a),
                template.tstate, r["tstate"])
        key = r["key"] if r["key"] is not None else template.key
        return TrainState(params=params, tstate=tstate, step=r["step"], key=key)

    def final_params(self, state: TrainState):
        """Collapse to a single param set (average replicas for hogwild)."""
        if self.router == "hogwild":
            avgd = self._avg_fn(state.params) if self._avg_fn else state.params
            return jax.tree_util.tree_map(lambda a: a[0], avgd)
        return state.params
