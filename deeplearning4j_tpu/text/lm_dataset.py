"""Corpus -> (tokens, targets) batch pipeline for language-model training.

The connective tissue between the L8 text stack (tokenization/vocab,
reference ``text/**``) and the flagship ``TransformerLM``: the reference
era fed sequence models through ``MovingWindowBaseDataSetIterator``-style
fixed windows (``datasets/iterator/.../MovingWindowBaseDataSetIterator.java``,
``Windows.java:17``); a TPU LM wants the GPT-style alternative — tokenize
the whole corpus ONCE into one contiguous id array (documents joined by an
``<eos>`` separator), then slice dense ``(B, T)`` blocks with shifted
targets.  Dense packing keeps every MXU step full (no padding waste),
shapes are static for jit, and the block-order shuffle is stateless-keyed
so an epoch is reproducible and resumable from a cursor (composes with
``parallel.checkpoint`` and ``datasets.iterator.prefetch_to_device``).
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from .tokenization import DefaultTokenizerFactory
from .vocab import VocabCache

EOS = "<eos>"
UNK = "<unk>"


class LMCorpus:
    """Tokenized, packed corpus with a word <-> id vocabulary.

    ``vocab_size`` counts the two specials; ids: 0..n_words-1 are corpus
    words (frequency-sorted, the word2vec convention), then ``<eos>``,
    then ``<unk>``.
    """

    def __init__(self, sentences: Iterable[str], tokenizer_factory=None,
                 min_word_frequency: float = 1.0,
                 vocab: VocabCache | None = None):
        tf = tokenizer_factory or DefaultTokenizerFactory()
        sentences = [s for s in sentences if s and s.strip()]
        # tokenize ONCE; both the vocab count and the id pass read the same
        # token lists (tokenization dominates corpus-construction cost)
        tokenized = [tf.create(s).get_tokens() for s in sentences]
        if vocab is None:
            vocab = VocabCache()
            for toks in tokenized:
                for tok in toks:
                    vocab.add(tok)
            vocab.prune(min_word_frequency)
        self.vocab = vocab
        n = len(self.vocab)
        self.eos_id, self.unk_id = n, n + 1
        self.vocab_size = n + 2
        ids: list[int] = []
        for toks in tokenized:
            for tok in toks:
                i = self.vocab.index_of(tok)
                ids.append(i if i >= 0 else self.unk_id)
            ids.append(self.eos_id)
        self.ids = np.asarray(ids, np.int32)

    def decode(self, ids: Sequence[int]) -> list[str]:
        out = []
        for i in ids:
            i = int(i)
            if i == self.eos_id:
                out.append(EOS)
            elif i == self.unk_id or i < 0 or i >= len(self.vocab):
                out.append(UNK)
            else:
                out.append(self.vocab.word_at(i))
        return out


class LMTokenBatchIterator:
    """Epochs of dense ``(batch, seq)`` LM batches over an :class:`LMCorpus`.

    Each batch is ``(tokens, targets)`` with ``targets[i, t] ==
    tokens[i, t+1]`` (blocks are cut ``seq + 1`` wide so the shift never
    crosses a block edge).  Block order reshuffles per epoch from
    ``seed`` (stateless: epoch k's permutation is a pure function of
    ``seed + k``), and ``cursor``/``set_cursor`` expose resumable position
    in batches-since-epoch-0 for checkpoint integration.
    """

    def __init__(self, corpus: LMCorpus, batch: int, seq: int,
                 seed: int = 0, shuffle: bool = True):
        self.corpus, self.batch, self.seq = corpus, batch, seq
        self.seed, self.shuffle = seed, shuffle
        span = seq + 1
        n_blocks = len(corpus.ids) // span
        if n_blocks < batch:
            raise ValueError(
                f"corpus packs into {n_blocks} blocks of {span} tokens — "
                f"fewer than one batch of {batch}; shrink batch/seq or "
                "grow the corpus")
        self.blocks = corpus.ids[:n_blocks * span].reshape(n_blocks, span)
        self.batches_per_epoch = n_blocks // batch
        self._cursor = 0          # global batch index across epochs
        self._order_cache: tuple[int, np.ndarray] | None = None

    # -- resumable position ----------------------------------------------
    @property
    def cursor(self) -> int:
        return self._cursor

    def set_cursor(self, cursor: int) -> None:
        self._cursor = int(cursor)

    def _order(self, epoch: int) -> np.ndarray:
        if not self.shuffle:
            return np.arange(len(self.blocks))
        if self._order_cache is None or self._order_cache[0] != epoch:
            self._order_cache = (epoch, np.random.default_rng(
                self.seed + epoch).permutation(len(self.blocks)))
        return self._order_cache[1]

    def next(self) -> tuple[np.ndarray, np.ndarray]:
        epoch, k = divmod(self._cursor, self.batches_per_epoch)
        order = self._order(epoch)
        rows = order[k * self.batch:(k + 1) * self.batch]
        blk = self.blocks[rows]
        self._cursor += 1
        return blk[:, :-1], blk[:, 1:]

    def epoch_batches(self):
        """One epoch's worth of batches from the current cursor."""
        for _ in range(self.batches_per_epoch):
            yield self.next()

    def __iter__(self):
        while True:
            yield self.next()
