"""Benchmark: flagship training-step throughput on one chip, with guards.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "extra": {...}}

Headline metric: BERT-base-class train tokens/sec/chip (north star >=35% MFU
on v5e => ``vs_baseline`` = achieved_MFU / 0.35).  ``extra`` carries a
ResNet-50 leg (images/sec/chip + MFU) and a data-parallel machinery check
(dp8-vs-single loss parity on a virtual CPU mesh), per BASELINE.md.

Trust guards (round-3 hardening — the r2 number was physically impossible
because async dispatch on the tunneled platform returned before execution):

1. The timed loop pulls ``float(loss)`` to HOST every iteration — a device->
   host transfer cannot complete before the step that produces it.
2. The two half-run timing medians are compared; wild disagreement (>4x)
   flags overlapped/fake timing.
3. Physics floor: measured time below ``flops / peak_flops`` (i.e. MFU > 1)
   is impossible; the run hard-fails (exit 1) with an ``invalid`` marker and
   ``vs_baseline: 0`` instead of publishing a claim.  Guards are enforced
   only on TPU (the CPU ``peak`` is a nominal constant and the CPU fallback
   is a smoke signal, not a claim — there they demote to warnings).
4. Analytic FLOPs are cross-checked against XLA's own ``cost_analysis()``.
5. The BERT leg is timed THREE ways: end-to-end with ``device_put``
   serialized into each step (upper bound on input-pipeline cost, honest
   about the tunneled link), through the double-buffered
   ``prefetch_to_device`` pipeline (the production input path), and with
   the batch pool pre-staged on device (pure compute throughput).  The
   headline tokens/sec and MFU come from the staged run; the other two
   are reported alongside.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np

PEAK_FLOPS = {
    "tpu v5 lite": 197e12,   # v5e bf16 peak per chip
    "tpu v5": 197e12,
    "tpu": 197e12,
    "cpu": 5e10,             # nominal; cpu fallback is a smoke signal only
}
MFU_TARGET = 0.35


_PROBE_SRC = r"""
import sys, time
t0 = time.time()
import jax
try:
    devs = jax.devices("tpu")
except Exception as e:
    print(f"explicit tpu probe failed after {time.time()-t0:.0f}s: "
          f"{type(e).__name__}: {e}", file=sys.stderr)
    devs = jax.devices()
d = devs[0]
print(d.platform, d.device_kind, len(devs), f"{time.time()-t0:.1f}s")
"""


def _discover_devices(attempts: int = None, timeout_s: float = None,
                      backoff_s: float = 60.0):
    """Probe the TPU backend in a SUBPROCESS (an in-thread probe that hangs
    would wedge jax's backend lock and deadlock the CPU fallback too); only
    touch the TPU platform in-process once the probe proves it healthy.

    Round-4 hardening (r3 fell back after a single 120s probe attempt while
    r2 proved the chip *can* attach): retry ``attempts`` times with backoff,
    try ``jax.devices("tpu")`` explicitly, and capture each failed attempt's
    stderr tail into the artifact so a fallback is diagnosable.

    ``BENCH_PROBE_ATTEMPTS`` / ``BENCH_PROBE_TIMEOUT_S`` env vars override
    the schedule (defaults 4 x 180s, 60s backoff)."""
    import jax

    if attempts is None:
        attempts = int(os.environ.get("BENCH_PROBE_ATTEMPTS", "4"))
    if timeout_s is None:
        timeout_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "180"))

    def _relay_ports():
        """TCP-connect scan of the loopback relay's likely ports — pure
        diagnostic (the claim port lives inside the PJRT plugin): tells a
        reader of a fallback artifact whether the tunnel was even up."""
        import socket
        host = os.environ.get("PALLAS_AXON_POOL_IPS", "127.0.0.1").split(",")[0]
        state = {}
        for port in (8080, 8081, 8082, 8083):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.settimeout(1.0)
            try:
                state[port] = "open" if s.connect_ex((host, port)) == 0 else "closed"
            finally:
                s.close()
        return f"relay {host} ports: " + ", ".join(
            f"{p}={v}" for p, v in state.items())

    failures = []
    for i in range(attempts):
        if i:
            time.sleep(backoff_s)
        t0 = time.time()
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                capture_output=True, text=True, timeout=timeout_s)
            # success = the probe actually reached a TPU-class device; a
            # probe that fell back to CPU (TPU init RAISED instead of
            # hanging) is a failed attempt — retrying is the whole point
            if proc.returncode == 0 and "tpu" in proc.stdout.lower():
                return jax.devices(), None, failures
            failures.append(
                f"attempt {i+1}: rc={proc.returncode} after "
                f"{time.time()-t0:.0f}s; probe saw: "
                f"{proc.stdout.strip()[:100]!r}; {_relay_ports()}; "
                f"stderr: {proc.stderr[-300:]}")
        except subprocess.TimeoutExpired as e:
            stderr = (e.stderr or b"")
            if isinstance(stderr, bytes):
                stderr = stderr.decode("utf-8", "replace")
            failures.append(
                f"attempt {i+1}: hung >{timeout_s:.0f}s; {_relay_ports()}; "
                f"stderr so far: {stderr[-300:]}")
    jax.config.update("jax_platforms", "cpu")
    reason = f"device discovery failed after {attempts} attempts"
    return jax.devices("cpu"), reason, failures


def _host_float(x) -> float:
    """Pull one device scalar to host EXPLICITLY (jax.device_get), so the
    bench's per-iteration trust-guard sync stays legal under the hot-loop
    ``jax.transfer_guard("disallow")`` scopes."""
    import jax

    return float(np.asarray(jax.device_get(x)))


def _timed_loop(step, params, opt, batches, iters, stage_on_device=False,
                prefetch=False, async_losses=False, metric=None):
    """Run ``iters`` steps rotating batches, syncing to host EVERY
    iteration.  Returns (iter_times, last_loss, params, opt) — params/opt
    are threaded back out because train steps donate their input buffers.

    ``metric``: also record every step time into the observability
    registry's ``metric`` histogram (``observe_time``), so the BENCH_*
    numbers and a scrape of ``/metrics`` during the run agree on the same
    raw observations.

    ``float(np.asarray(loss))`` inside the loop is the synchronization an
    async/misbehaving platform cannot fake: the scalar cannot arrive on
    host before the step that produced it executed.

    ``stage_on_device``: pre-put the batch pool on device once (for image-
    sized batches the tunneled link's MBs-per-batch transfer would measure
    the tunnel, not the chip; a real input pipeline overlaps this).

    ``prefetch``: feed through ``prefetch_to_device`` (double-buffered
    async transfers) — the production input-pipeline number, between the
    serialized end-to-end upper bound and the staged pure-compute one.

    ``async_losses``: the trainer's production mode — NO per-step sync;
    losses stay on device and ONE ``jax.block_until_ready`` fence at the
    end covers the whole run (device programs execute in dispatch order,
    so the fenced losses cannot exist before every step executed — the
    measurement stays physically sound, but only the caller's total wall
    clock around the loop is meaningful; the per-iteration entries are
    dispatch times and MUST NOT feed the MFU/consistency guards).
    """
    import jax

    from deeplearning4j_tpu.analysis.runtime import hot_loop_guard
    from deeplearning4j_tpu.observability import METRICS

    def record(dt):
        iter_times.append(dt)
        if metric is not None:
            METRICS.observe_time(metric, dt)

    if stage_on_device:
        batches = [tuple(map(jax.device_put, b)) for b in batches]
    iter_times, loss = [], None
    # every timed leg runs under the transfer guard: batch staging is an
    # explicit device_put and the trust-guard sync an explicit device_get,
    # so anything ELSE crossing the PCIe/ICI link mid-loop raises instead
    # of silently polluting the measurement
    if prefetch:
        from deeplearning4j_tpu.datasets.iterator import prefetch_to_device
        feed = prefetch_to_device(
            (batches[k % len(batches)] for k in range(iters)), size=2)
        with hot_loop_guard():
            for a, b in feed:
                t0 = time.perf_counter()
                params, opt, loss = step(params, opt, a, b)
                loss = _host_float(loss)         # forced host sync
                record(time.perf_counter() - t0)
        return iter_times, loss, params, opt
    if async_losses:
        pending = []
        with hot_loop_guard():
            for k in range(iters):
                a, b = batches[k % len(batches)]
                t0 = time.perf_counter()
                if not stage_on_device:
                    a, b = jax.device_put(a), jax.device_put(b)
                params, opt, loss = step(params, opt, a, b)
                pending.append(loss)             # stays on device
                record(time.perf_counter() - t0)  # dispatch time only
            jax.block_until_ready(pending)       # the single end fence
        return iter_times, _host_float(pending[-1]), params, opt
    with hot_loop_guard():
        for k in range(iters):
            a, b = batches[k % len(batches)]
            t0 = time.perf_counter()
            if not stage_on_device:
                a, b = jax.device_put(a), jax.device_put(b)
            params, opt, loss = step(params, opt, a, b)
            loss = _host_float(loss)             # forced host sync
            record(time.perf_counter() - t0)
    return iter_times, loss, params, opt


def _stats(iter_times):
    ts = sorted(iter_times)
    n = len(ts)
    return {"median_s": ts[n // 2], "p10_s": ts[max(0, n // 10)],
            "p90_s": ts[min(n - 1, (9 * n) // 10)], "total_s": sum(ts)}


def _validity_checks(name, iter_times, flops_per_iter, peak):
    """Return (problems, mfu).  MFU is computed from the MEDIAN step time
    (robust to transient tunnel stalls); the guards reject any measurement
    a real chip could not produce."""
    problems = []
    st = _stats(iter_times)
    mfu = flops_per_iter / (st["median_s"] * peak)
    floor_s = flops_per_iter / peak
    if mfu > 1.0:
        problems.append(
            f"{name}: mfu={mfu:.3f} > 1 is physically impossible "
            f"(median step {st['median_s']:.4f}s < floor {floor_s:.4f}s "
            "at 100% MFU)")
    half = len(iter_times) // 2
    if half >= 2:
        m1 = statistics.median(iter_times[:half])
        m2 = statistics.median(iter_times[half:])
        ratio = max(m1, m2) / max(min(m1, m2), 1e-12)
        if ratio > 4.0:
            problems.append(
                f"{name}: half-run medians disagree {ratio:.1f}x "
                f"({m1*1e3:.2f}ms vs {m2*1e3:.2f}ms/step) — "
                "dispatch is not synchronizing")
    return problems, mfu


def _tune_rows(path=None):
    """Rows from the on-chip tuning battery (tools/run_tpu_battery.sh), if
    it has run; [] otherwise.  With no explicit path the newest
    ``TUNE_r*.jsonl`` next to this file wins (batteries are per-round
    artifacts — a fresh round's evidence supersedes the last)."""
    here = os.path.dirname(os.path.abspath(__file__))
    if path is None:
        import glob
        batteries = sorted(glob.glob(os.path.join(here, "TUNE_r*.jsonl")))
        if not batteries:
            return []
        full = batteries[-1]
    else:
        full = os.path.join(here, path)
    rows = []
    try:
        with open(full) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        pass
    except OSError:
        pass
    return rows


def _generic_kernel_rows(rows):
    """Adapt battery JSONL into the kernel registry's generic schema
    (``{"kernel", "candidate", metric}`` / ``{"kernel", "candidate",
    "check"}``).  Rows already carrying a "kernel" key pass through;
    the legacy per-kind shapes (r05's ``flash_check`` and
    ``attention``/``batch`` rows) are converted so old batteries keep
    feeding the same auto-pick."""
    out = []
    for r in rows:
        if not isinstance(r, dict):
            continue
        if "kernel" in r:
            out.append(r)
        elif isinstance(r.get("flash_check"), dict):
            out.append({"kernel": "attention", "candidate": "flash",
                        "check": r["flash_check"]})
        elif "attention" in r and r.get("batch") == 64:
            out.append({"kernel": "attention", "candidate": r["attention"],
                        **{k: v for k, v in r.items() if k != "attention"}})
    return out


def _pick_attention(rows):
    """The headline attention kernel via the registry's evidence-gated
    auto-pick: a Pallas candidate ("flash", "fused") replaces ring only
    with an on-chip correctness check inside its tolerances AND a >2%
    throughput win over ring.  Returns (choice, reason)."""
    from deeplearning4j_tpu.ops.pallas import registry as kernel_registry
    pick = kernel_registry.autopick(
        "attention", _generic_kernel_rows(rows), incumbent="ring")
    return pick.choice, pick.reason


def _pick_fused_ln(rows):
    """True iff the battery proved the fused residual+LayerNorm kernel
    correct and >2% faster than the unfused XLA seam.  (bool, reason)."""
    from deeplearning4j_tpu.ops.pallas import registry as kernel_registry
    pick = kernel_registry.autopick(
        "layernorm_residual", _generic_kernel_rows(rows),
        incumbent="unfused")
    return pick.choice == "fused", pick.reason


def _pick_xent(rows):
    """LM-loss implementation: "blocked" (Pallas streaming xent) iff the
    battery proved it correct and >2% faster than the remat'd scan.
    Returns (choice, reason)."""
    from deeplearning4j_tpu.ops.pallas import registry as kernel_registry
    pick = kernel_registry.autopick(
        "xent", _generic_kernel_rows(rows), incumbent="scan")
    return pick.choice, pick.reason


def _pick_bn_fold(rows):
    """True iff the battery showed the folded bf16 BN apply beating the f32
    normalize at the bench batch.  Returns (choice, reason)."""
    def best(fold):
        ms = [r["mfu"] for r in rows
              if r.get("bn_fold") is fold and r.get("batch") == 256
              and isinstance(r.get("mfu"), (int, float))]
        return max(ms) if ms else None
    off, on = best(False), best(True)
    # `is not None` + >2% margin, same rationale as _pick_attention: a
    # 0.0-MFU row must count as evidence and jitter must not flip defaults
    if off is not None and on is not None and on > off * 1.02:
        return True, (f"TUNE: bn_fold mfu {on:.3f} > {off:.3f} "
                      "(>2% margin) at batch 256")
    return False, "default (no on-chip evidence that bn_fold wins by >2%)"


def _bert_leg(dev, on_tpu, conserve_hbm=False):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, TransformerLM)
    from deeplearning4j_tpu.optimize import transforms as T

    # BENCH_ATTENTION=flash/ring overrides; otherwise the choice comes from
    # on-chip tuning evidence (_pick_attention) and defaults to the XLA
    # ring/block path when no battery has run.
    attention = os.environ.get("BENCH_ATTENTION")
    attention_reason = f"BENCH_ATTENTION={attention}" if attention else None
    if attention is None:
        attention, attention_reason = _pick_attention(_tune_rows())
    # same evidence chain for the two other trainable-path kernels:
    # BENCH_FUSED_LN=0/1 and BENCH_XENT=scan/blocked override; otherwise
    # the battery decides through the registry gate, defaults off/scan
    env_ln = os.environ.get("BENCH_FUSED_LN")
    if env_ln is not None:
        fused_ln, fused_ln_reason = env_ln == "1", f"BENCH_FUSED_LN={env_ln}"
    else:
        fused_ln, fused_ln_reason = _pick_fused_ln(_tune_rows())
    env_xe = os.environ.get("BENCH_XENT")
    if env_xe is not None:
        xent_impl, xent_reason = env_xe, f"BENCH_XENT={env_xe}"
    else:
        xent_impl, xent_reason = _pick_xent(_tune_rows())
    if not on_tpu:
        # the CPU smoke config always runs ring — say so rather than
        # reporting a TUNE-based choice the leg did not use
        attention, attention_reason = "ring", "cpu fallback (ring)"
        fused_ln, fused_ln_reason = False, "cpu fallback (unfused)"
        xent_impl, xent_reason = "scan", "cpu fallback (scan)"
    if on_tpu and conserve_hbm:
        # OOM retry path: remat + half batch (main() falls back here when
        # the full-size leg dies with RESOURCE_EXHAUSTED)
        batch, seq, iters = 32, 512, 16
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12,
                                n_layers=12, d_ff=3072, max_len=seq,
                                causal=False, dtype=jnp.bfloat16, remat=True,
                                attention=attention, fused_ln=fused_ln,
                                xent_impl=xent_impl)
    elif on_tpu:
        # remat off: BERT-base at this batch fits v5e HBM comfortably and
        # remat's recompute would burn ~1/3 more FLOPs for nothing.
        batch, seq, iters = 64, 512, 16
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12,
                                n_layers=12, d_ff=3072, max_len=seq,
                                causal=False, dtype=jnp.bfloat16, remat=False,
                                attention=attention, fused_ln=fused_ln,
                                xent_impl=xent_impl)
    else:
        batch, seq, iters = 4, 128, 4
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_heads=4,
                                n_layers=2, d_ff=256, max_len=seq,
                                causal=False, dtype=jnp.float32, remat=False)

    model = TransformerLM(cfg)
    with jax.default_device(dev):
        tx = T.adamw(T.warmup_cosine(1e-4, 10, 1000), weight_decay=0.01)
        params = model.init(jax.random.key(0))
        opt = model.init_opt(params, tx)
        rng = np.random.default_rng(0)
        batches = []
        for _ in range(4):                      # host-staged batch pool
            toks = rng.integers(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
            batches.append((toks, np.roll(toks, -1, axis=1)))
        step = model.build_train_step(tx)

        # compile + warmup (excluded from timing)
        a, b = map(jax.device_put, batches[0])
        params, opt, loss = step(params, opt, a, b)
        warm_loss = _host_float(loss)

        # XLA's own FLOPs estimate for one step (independent cross-check),
        # captured through the observability cost model so the artifact and
        # a live ``train.mfu`` scrape come from the SAME accounting
        from deeplearning4j_tpu.observability import COSTS
        cost_info = COSTS.capture(
            "bench.bert_base.step", step, params, opt, a, b,
            analytic_flops=cfg.flops_per_token() * batch * seq)
        xla_flops = (cost_info.flops
                     if cost_info is not None and cost_info.source == "xla"
                     else None)

        # end-to-end first (device_put serialized into each step), then the
        # double-buffered production pipeline, then the device-staged run
        # the headline is computed from (see module doc #5)
        e2e_times, _, params, opt = _timed_loop(
            step, params, opt, batches, iters,
            metric="bench.bert_base.step_e2e")
        # e2e again but the way the trainer actually runs it: no per-step
        # loss sync, one fence at the end — only total wall clock (fence
        # included) is a claim; dispatch times are recorded for diagnosis
        al_wall0 = time.perf_counter()
        al_times, _, params, opt = _timed_loop(
            step, params, opt, batches, iters, async_losses=True,
            metric="bench.bert_base.step_e2e_async_dispatch")
        al_wall_s = time.perf_counter() - al_wall0
        # the prefetched leg's per-step timer starts AFTER the generator
        # pull, so device_put issuance hides outside it — also record the
        # whole-loop wall clock (includes every pull) alongside
        pf_wall0 = time.perf_counter()
        pf_times, _, params, opt = _timed_loop(
            step, params, opt, batches, iters, prefetch=True,
            metric="bench.bert_base.step_prefetch")
        pf_wall_s = time.perf_counter() - pf_wall0
        iter_times, last_loss, params, opt = _timed_loop(
            step, params, opt, batches, iters, stage_on_device=True,
            metric="bench.bert_base.step")

    st = _stats(iter_times)
    e2e = _stats(e2e_times)
    pf = _stats(pf_times)
    return {
        "name": "bert_base", "iters": iters, "batch": batch, "seq": seq,
        "attention": cfg.attention,
        "attention_choice": attention_reason,
        "fused_ln": cfg.fused_ln, "fused_ln_choice": fused_ln_reason,
        "xent_impl": cfg.xent_impl, "xent_choice": xent_reason,
        "iter_times": iter_times, "stats": st,
        "e2e_stats": e2e, "prefetch_stats": pf,
        "async_dispatch_stats": _stats(al_times),
        "tokens_per_sec": batch * seq / st["median_s"],
        "tokens_per_sec_e2e": batch * seq / e2e["median_s"],
        "tokens_per_sec_prefetched": batch * seq / pf["median_s"],
        "prefetch_wall_s_total": pf_wall_s,
        "tokens_per_sec_prefetched_wall": batch * seq * iters / pf_wall_s,
        "async_wall_s_total": al_wall_s,
        "tokens_per_sec_e2e_async": batch * seq * iters / al_wall_s,
        "flops_per_iter": cfg.flops_per_token() * batch * seq,
        "flops_per_token_analytic": cfg.flops_per_token(),
        "xla_flops_per_step": xla_flops,
        "warm_loss": warm_loss, "last_loss": last_loss,
    }


def _resnet_leg(dev, on_tpu, batch_override=None):
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.resnet import (
        ResNetConfig, cross_entropy, init_params)
    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.optimize.transforms import apply_updates

    if on_tpu:
        # BENCH_BN_FOLD=0/1 overrides; otherwise the folded bf16 BN apply
        # (models/resnet.py bn_fold) turns on iff the on-chip tune battery
        # showed it winning (_pick_bn_fold); default off.
        env = os.environ.get("BENCH_BN_FOLD")
        if env is not None:
            bn_fold, fold_reason = env == "1", f"BENCH_BN_FOLD={env}"
        else:
            bn_fold, fold_reason = _pick_bn_fold(_tune_rows())
        cfg = ResNetConfig.resnet50(bn_fold=bn_fold)
        # batch 256 ≈ 2x the MFU of batch 64 on v5e (tools/tune_tpu.py sweep:
        # 16.4% vs 8.3%) — small batches leave the MXU idle on the deep
        # low-resolution stages
        batch, size, iters = batch_override or 256, 224, 12
    else:
        cfg = ResNetConfig.resnet18(num_classes=10, dtype=jnp.float32)
        batch, size, iters = 4, 64, 3
        fold_reason = "cpu fallback (bn_fold off)"

    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))

    def step(params, opt, images, labels):
        count, st = opt
        loss, g = jax.value_and_grad(cross_entropy)(params, images, labels, cfg)
        updates, st = tx.update(g, st, params, count)
        return apply_updates(params, updates), (count + 1, st), loss

    with jax.default_device(dev):
        params = init_params(jax.random.key(0), cfg)
        opt = (jnp.zeros((), jnp.int32), tx.init(params))
        rng = np.random.default_rng(1)
        batches = []
        for _ in range(3):
            imgs = rng.standard_normal((batch, size, size, 3), dtype=np.float32)
            onehot = np.eye(cfg.num_classes, dtype=np.float32)[
                rng.integers(0, cfg.num_classes, batch)]
            batches.append((imgs, onehot))
        jstep = jax.jit(step, donate_argnums=(0, 1))
        a, b = map(jax.device_put, batches[0])
        params, opt, loss = jstep(params, opt, a, b)
        _host_float(loss)
        iter_times, last_loss, params, opt = _timed_loop(
            jstep, params, opt, batches, iters, stage_on_device=True,
            metric="bench.resnet.step")

    st = _stats(iter_times)
    return {
        "name": "resnet", "iters": iters, "batch": batch, "image": size,
        "depth50": cfg.stage_sizes == (3, 4, 6, 3),
        "bn_fold": cfg.bn_fold, "bn_fold_choice": fold_reason,
        "iter_times": iter_times, "stats": st,
        "images_per_sec": batch / st["median_s"],
        "flops_per_iter": cfg.flops_per_image(size) * batch,
        "flops_per_image_analytic": cfg.flops_per_image(size),
        "last_loss": last_loss,
    }


def _decode_leg(dev, on_tpu):
    """Inference decode throughput: KV-cached greedy generation on a
    GPT-base-class causal model (the flagship's serving path; the
    reference has no generation story to compare against — this is a
    beats-reference metric).  Host sync is inherent: sample() returns the
    realized token list."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models.transformer import (
        TransformerConfig, TransformerLM)

    if on_tpu:
        cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12,
                                n_layers=12, d_ff=3072, max_len=512,
                                causal=True, dtype=jnp.bfloat16, remat=False)
        prime_len, gen = 32, 480
    else:
        cfg = TransformerConfig(vocab_size=1024, d_model=128, n_heads=4,
                                n_layers=2, d_ff=256, max_len=128,
                                causal=True, dtype=jnp.float32, remat=False)
        prime_len, gen = 8, 56
    model = TransformerLM(cfg)
    with jax.default_device(dev):
        params = model.init(jax.random.key(0))
        prime = list(range(1, prime_len + 1))
        model.sample(params, prime, gen, temperature=0.0,
                     kv_cache=True)                     # compile + warmup
        runs = []
        for _ in range(3):
            t0 = time.perf_counter()
            out = model.sample(params, prime, gen, temperature=0.0,
                               kv_cache=True)
            runs.append(time.perf_counter() - t0)
        assert len(out) == prime_len + gen
    med = statistics.median(runs)
    steps = prime_len + gen - 1       # prefill steps run in the same loop
    return {"mode": "kv_cached_greedy", "prime": prime_len,
            "generated": gen, "decode_steps": steps,
            "runs_s": [round(t, 3) for t in runs],
            "ms_per_step": round(med / steps * 1e3, 3),
            "generated_tokens_per_sec_incl_prefill": round(gen / med, 1)}


def _word2vec_leg(dev, on_tpu):
    """Embeddings-path throughput: the batched HS and NS skip-gram device
    kernels (text/word2vec.py — the hot loops the reference hand-optimized
    in InMemoryLookupTable.java:171-279) on a synthetic 50k vocab, with
    the same per-iteration host-sync guard as the headline leg.  Reports
    pairs/sec (one pair = one center-context token update); no MFU claim —
    these kernels are gather/scatter-bound, not MXU-bound."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.text.word2vec import _hs_step, _ns_step

    if on_tpu:
        V, D, B, K, L, iters = 50_000, 128, 16_384, 5, 18, 16
    else:
        V, D, B, K, L, iters = 2_000, 32, 512, 5, 11, 3
    rng = np.random.default_rng(7)
    alpha = jnp.float32(0.025)

    def batches(n):
        out = []
        for _ in range(n):
            centers = rng.integers(0, V, B).astype(np.int32)
            targets = rng.integers(0, V, (B, 1 + K)).astype(np.int32)
            labels = np.zeros((B, 1 + K), np.float32)
            labels[:, 0] = 1.0
            points = rng.integers(0, V, (B, L)).astype(np.int32)
            codes = rng.integers(0, 2, (B, L)).astype(np.float32)
            mask = (rng.random((B, L)) < 0.8).astype(np.float32)
            out.append((centers, targets, labels, points, codes, mask))
        return out

    def timed(step_fn, make_args, state):
        from deeplearning4j_tpu.analysis.runtime import hot_loop_guard

        ts = []
        pool = batches(4)
        args = make_args(pool[0])
        state = step_fn(*state, *args)                 # compile + warmup
        _host_float(state[0][0, 0])
        with hot_loop_guard():
            for k in range(iters):
                args = make_args(pool[k % len(pool)])
                t0 = time.perf_counter()
                state = step_fn(*state, *args)
                _host_float(state[0][0, 0])            # forced host sync
                ts.append(time.perf_counter() - t0)
        return ts

    with jax.default_device(dev):
        ns_times = timed(
            _ns_step,
            lambda b: (jax.device_put(b[0]), jax.device_put(b[1]),
                       jax.device_put(b[2]), alpha),
            (jnp.asarray(rng.normal(0, 1e-2, (V, D)), jnp.float32),
             jnp.zeros((V, D), jnp.float32)))
        hs_times = timed(
            _hs_step,
            lambda b: (jax.device_put(b[0]), jax.device_put(b[3]),
                       jax.device_put(b[4]), jax.device_put(b[5]), alpha),
            (jnp.asarray(rng.normal(0, 1e-2, (V, D)), jnp.float32),
             jnp.zeros((V, D), jnp.float32)))

    leg = {"vocab": V, "dim": D, "batch_pairs": B, "negatives": K,
           "path_len": L, "iters": iters}
    for name, ts in (("ns", ns_times), ("hs", hs_times)):
        st = _stats(ts)
        leg[name] = {"pairs_per_sec": round(B / st["median_s"], 1),
                     "step_ms_median": round(st["median_s"] * 1e3, 3)}
        half = len(ts) // 2
        if half >= 2:
            m1, m2 = statistics.median(ts[:half]), statistics.median(ts[half:])
            ratio = max(m1, m2) / max(min(m1, m2), 1e-12)
            if ratio > 4.0:
                leg[name]["warning"] = (
                    f"half-run medians disagree {ratio:.1f}x — "
                    "dispatch is not synchronizing")
    return leg


_SCALING_CHILD = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
dp, batch = int(sys.argv[1]), int(sys.argv[2])   # dp=0 -> single device, no mesh
from deeplearning4j_tpu.models.transformer import TransformerConfig, TransformerLM
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.optimize import transforms as T
cfg = TransformerConfig(vocab_size=512, d_model=128, n_heads=4, n_layers=2,
                        d_ff=512, max_len=128, causal=False,
                        dtype=jnp.float32, remat=False)
mesh = (make_mesh(MeshSpec(dp=dp, sp=1, tp=1), devices=jax.devices()[:dp])
        if dp else None)
model = TransformerLM(cfg, mesh=mesh)
tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-3))
params = model.place(model.init(jax.random.key(0)))
opt = model.init_opt(params, tx)
tokens = jax.random.randint(jax.random.key(1), (batch, 128), 0, cfg.vocab_size)
targets = jnp.roll(tokens, -1, axis=1)
step = model.build_train_step(tx)
# one compile: reuse the lowered executable for both the HLO inspection
# (mesh child only) and the loop, instead of compiling again via the jit
# cache; the dp=0 child never needs the HLO
if dp:
    step = step.lower(params, opt, tokens, targets).compile()
    all_reduce = "all-reduce" in step.as_text()
else:
    all_reduce = False
losses = []
for _ in range(4):
    params, opt, loss = step(params, opt, tokens, targets)
    losses.append(float(np.asarray(loss)))
print(json.dumps({"losses": losses, "all_reduce": all_reduce}))
"""


def _scaling_leg(timeout_s: float = 420.0):
    """Data-parallel MACHINERY check on the virtual 8-device CPU mesh
    (subprocess: the TPU-registered parent can't switch platforms).

    All virtual devices share one host CPU, so no timing from this mesh is
    a chip-scaling number (r4 shipped a relative_throughput ratio here and
    the verdict rightly called it a pseudo-number).  What IS checkable on a
    virtual mesh is correctness of the dp machinery: at equal total batch,
    the dp=8 sharded step (per-shard grads + pmean) must reproduce the
    unsharded single-device loss trajectory step for step, and the compiled
    dp=8 HLO must actually contain the gradient all-reduce.  This leg runs
    that check and publishes pass/fail — no throughput ratio.  Real 8->64
    chip efficiency must be measured on real chips (BASELINE.md '8 -> 64
    chips'; reference analog IterativeReduceWorkRouter.java:16,30)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()

    def run(dp, batch):
        proc = subprocess.run(
            [sys.executable, "-c", _SCALING_CHILD, str(dp), str(batch)],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0:
            raise RuntimeError(f"dp={dp} b={batch} rc={proc.returncode}: "
                               f"{proc.stderr[-300:]}")
        return json.loads(proc.stdout.strip().splitlines()[-1])

    try:
        single = run(0, 32)
        mesh = run(8, 32)
    except Exception as e:        # child died / bad stdout — never kill bench
        return {"error": str(e)[:300]}
    diffs = [abs(a - b) for a, b in zip(single["losses"], mesh["losses"])]
    ok = max(diffs) < 1e-3 and mesh["all_reduce"]
    verdict = "ok" if ok else (
        f"FAIL: max loss diff {max(diffs):.2e} over 4 steps, "
        f"all_reduce_in_hlo={mesh['all_reduce']}")
    return {
        "mode": "dp_machinery_check_virtual_cpu_mesh",
        "dp_machinery": verdict,
        "losses_single_dev": [round(x, 6) for x in single["losses"]],
        "losses_dp8_mesh": [round(x, 6) for x in mesh["losses"]],
        "max_abs_loss_diff": round(max(diffs), 8),
        "all_reduce_in_dp8_hlo": mesh["all_reduce"],
        "total_batch": 32,
        "note": ("pass/fail parity at equal total work on shared-host "
                 "virtual devices; timing on this mesh would measure host "
                 "thread scheduling, so none is published"),
    }


_ZERO_CHILD = r"""
import json, time
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu import observability
from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel import DataParallelTrainer

observability.enable()
D, BATCH, STEPS = 1024, 64, 12
rng = np.random.default_rng(0)
w_true = rng.normal(size=(D, 1))
x = rng.normal(size=(BATCH, D)).astype(np.float32)
y = (x @ w_true).astype(np.float32)

def loss_fn(p, xb, yb, key=None):
    return ((xb @ p["w"] - yb) ** 2).mean()

out = {}
for stage in (0, 1, 2):
    METRICS.reset()
    tr = DataParallelTrainer(loss_fn, T.adam(1e-3), zero_stage=stage)
    state = tr.init_state({"w": np.zeros((D, 1), np.float32)})
    state, lazy = tr.step(state, x, y)   # compile + settle placement
    lazy.block(); tr._resolve_pending()
    times, losses = [], []
    for _ in range(STEPS):
        t0 = time.perf_counter()
        state, lazy = tr.step(state, x, y)
        lazy.block()
        times.append(time.perf_counter() - t0)
        losses.append(float(lazy))
    tr._resolve_pending()
    g = METRICS.snapshot()["gauges"]
    out[str(stage)] = {
        "step_ms_median": round(sorted(times)[len(times) // 2] * 1e3, 3),
        "opt_state_bytes_per_device": max(
            v for k, v in g.items()
            if k.startswith("train.opt_state_bytes.device.")),
        "params_bytes_per_device": max(
            v for k, v in g.items()
            if k.startswith("train.params_bytes.device.")),
        "losses": losses,
    }
print(json.dumps(out))
"""


def _zero_leg(timeout_s: float = 420.0):
    """ZeRO stage comparison on the virtual 8-device CPU mesh (subprocess,
    like ``_scaling_leg``): stage 0 vs 1 vs 2 step time plus the per-device
    params/opt-state bytes the trainer gauges report.  Like the scaling
    leg, virtual-mesh TIMING is host scheduling, not a chip claim — the
    checkable facts are the 1/ndp opt-state shrink and loss parity across
    stages; step times are published as a relative smell test only."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _ZERO_CHILD],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0:
            raise RuntimeError(f"rc={proc.returncode}: {proc.stderr[-300:]}")
        r = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:        # child died / bad stdout — never kill bench
        return {"error": str(e)[:300]}
    parity = all(r[s]["losses"] == r["0"]["losses"] for s in ("1", "2"))
    shrink = (r["0"]["opt_state_bytes_per_device"]
              / max(r["2"]["opt_state_bytes_per_device"], 1.0))
    return {
        "mode": "zero_stage_comparison_virtual_cpu_mesh",
        "stages": {s: {k: v for k, v in r[s].items() if k != "losses"}
                   for s in r},
        "loss_parity_bitwise": parity,
        "opt_state_shrink_x": round(shrink, 2),
        "note": ("bytes/device + parity are the claims; virtual-mesh step "
                 "times measure host scheduling, not chips"),
    }


# first PR whose code carries the elastic tier (resharding restore + live
# resize); TPU artifacts stamped earlier have no reshard rows to compare
ELASTIC_TIER_PR = 13

_ELASTIC_CHILD = r"""
import json, tempfile, time
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu import observability
from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel import (CheckpointManager, DataParallelTrainer,
                                         MeshMismatchError, elastic_mesh)

observability.enable()
D, STEPS = 1024, 3
n = len(jax.devices())
rng = np.random.default_rng(0)
x = rng.normal(size=(n * 8, D)).astype(np.float32)
y = rng.normal(size=(n * 8, 1)).astype(np.float32)

def loss_fn(p, xb, yb, key=None):
    return ((xb @ p["w"] - yb) ** 2).mean()

def mk(width, stage):
    return DataParallelTrainer(loss_fn, T.adam(1e-3),
                               mesh=elastic_mesh(jax.devices()[:width]),
                               zero_stage=stage)

out = {}
for stage in (0, 1, 2, 3):
    with tempfile.TemporaryDirectory() as ckpt_dir:
        mgr = CheckpointManager(ckpt_dir)
        src = mk(n, stage)
        state = src.init_state({"w": np.zeros((D, 1), np.float32)})
        for _ in range(STEPS):
            state, lazy = src.step(state, x, y)
        src.checkpoint(state, mgr)
        state, lazy = src.step(state, x, y)   # uninterrupted reference step
        lazy.block()
        ref_loss = float(lazy)
        dst = mk(n // 2, stage)
        tmpl = dst.init_state({"w": np.zeros((D, 1), np.float32)})
        refused = False
        try:
            dst.restore(tmpl, mgr, reshard=False)
        except MeshMismatchError:
            refused = True
        METRICS.reset()
        t0 = time.perf_counter()
        restored = dst.restore(tmpl, mgr)
        jax.block_until_ready((restored.params, restored.tstate))
        dt = time.perf_counter() - t0
        _, lazy2 = dst.step(restored, x, y)
        lazy2.block()
        snap = METRICS.snapshot()
        out[str(stage)] = {
            "restore_ms": round(dt * 1e3, 3),
            "mismatch_refused_without_flag": refused,
            "first_step_abs_loss_delta": abs(float(lazy2) - ref_loss),
            "reshard_counted": snap["counters"].get("checkpoint.reshards", 0) >= 1,
        }
print(json.dumps(out))
"""


def _elastic_leg(timeout_s: float = 420.0):
    """Elastic resharding restore on the virtual 8-device CPU mesh
    (subprocess, like ``_zero_leg``): per zero stage, save a checkpoint at
    dp=8 and restore it at dp=4 through the resharding path.  Checkable
    facts: the cross-width restore REFUSES without ``reshard=True``
    (``MeshMismatchError`` contract, never a shape error) and the first
    post-restore step stays inside the documented 1e-5 elastic window vs
    the uninterrupted run; virtual-mesh restore timing is host work only,
    published as a smell test like the other virtual legs."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _ELASTIC_CHILD],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0:
            raise RuntimeError(f"rc={proc.returncode}: {proc.stderr[-300:]}")
        r = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:        # child died / bad stdout — never kill bench
        return {"error": str(e)[:300]}
    window = max(r[s]["first_step_abs_loss_delta"] for s in r)
    contract = all(r[s]["mismatch_refused_without_flag"]
                   and r[s]["reshard_counted"] for s in r)
    return {
        "mode": "elastic_reshard_virtual_cpu_mesh",
        "stages": {s: {"restore_ms": r[s]["restore_ms"],
                       "first_step_abs_loss_delta":
                           round(r[s]["first_step_abs_loss_delta"], 9)}
                   for s in r},
        "mismatch_contract_all_stages": contract,
        "max_first_step_loss_delta": round(window, 9),
        "within_documented_window": window <= 1e-5,
        "note": ("contract + loss window are the claims; virtual-mesh "
                 "restore times measure host slicing, not chips"),
    }


_REAL_CONFIG_CHILD = r"""
import json, sys
import numpy as np
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
out = {}

from deeplearning4j_tpu.models.transformer import TransformerConfig, TransformerLM
from deeplearning4j_tpu.optimize import transforms as T
cfg = TransformerConfig(vocab_size=32768, d_model=768, n_heads=12, n_layers=12,
                        d_ff=3072, max_len=512, causal=False,
                        dtype=jnp.bfloat16, remat=False)
model = TransformerLM(cfg)
tx = T.adamw(T.warmup_cosine(1e-4, 10, 1000), weight_decay=0.01)
params = model.init(jax.random.key(0))
opt = model.init_opt(params, tx)
step = model.build_train_step(tx)
toks = jnp.zeros((8, 512), jnp.int32)
compiled = step.lower(params, opt, toks, toks).compile()
cost = compiled.cost_analysis()
c = cost[0] if isinstance(cost, (list, tuple)) else (cost or {})
n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
out["bert_base"] = {"compiled": True, "batch": 8, "seq": 512,
                    "n_params": n_params,
                    "xla_flops": float(c.get("flops", -1.0)) if c else None}

from deeplearning4j_tpu.models.resnet import (ResNetConfig, cross_entropy,
                                              init_params)
rcfg = ResNetConfig.resnet50()
rparams = init_params(jax.random.key(1), rcfg)
fwd = jax.jit(jax.value_and_grad(cross_entropy))
imgs = jnp.zeros((8, 224, 224, 3), rcfg.dtype)
lbls = jnp.zeros((8, rcfg.num_classes), jnp.float32)
rcompiled = fwd.lower(rparams, imgs, lbls, rcfg).compile()
rcost = rcompiled.cost_analysis()
rc = rcost[0] if isinstance(rcost, (list, tuple)) else (rcost or {})
rn_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(rparams))
out["resnet50"] = {"compiled": True, "batch": 8, "image": 224,
                   "n_params": rn_params,
                   "xla_flops": float(rc.get("flops", -1.0)) if rc else None}
print(json.dumps(out))
"""


def _real_config_compile_check(timeout_s: float = 540.0):
    """CPU-fallback rounds used to carry zero signal about the real
    benchmark configs (r3 weak #2): lower + compile BERT-base (768/12L/512)
    and ResNet-50 (224px) on host CPU — no timing claim, but it proves the
    real graphs build, and records XLA's FLOPs for them."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _REAL_CONFIG_CHILD],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        if proc.returncode != 0:
            return {"error": f"rc={proc.returncode}: {proc.stderr[-300:]}"}
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"error": str(e)[:300]}


def _registry_timers():
    """Timer summaries from the observability registry, rounded for the
    artifact (BENCH_* and /metrics agree because both read these)."""
    from deeplearning4j_tpu.observability import METRICS

    return {name: {k: (round(v, 6) if isinstance(v, float) else v)
                   for k, v in summary.items()}
            for name, summary in METRICS.snapshot()["timers"].items()}


def _stale_guard(last_valid, allow_stale):
    """Refuse to surface a stale TPU artifact as comparison evidence.

    ``LAST_VALID_TPU_BENCH.json`` carries ``stale: true`` when its
    numbers predate code changes that invalidate them (``asof_pr`` says
    how far back).  A CPU fallback run must not quote those as the
    most-recent evidence unless the operator explicitly passes
    ``--allow-stale``."""
    if not isinstance(last_valid, dict) or not last_valid.get("stale"):
        return last_valid
    if allow_stale:
        return dict(last_valid, stale_comparison_allowed_by_flag=True)
    return {
        "refused_stale_comparison": last_valid.get("metric"),
        "asof_pr": last_valid.get("asof_pr"),
        "note": ("artifact is marked stale (predates current code) — "
                 "rerun the TPU battery to refresh it, or pass "
                 "--allow-stale to quote it anyway"),
    }


def _kernel_picks():
    """The full auto-pick table for the artifact: one decision per kernel
    kind, with every dropped candidate and its reason (no silent caps)."""
    from deeplearning4j_tpu.ops.pallas import registry as kernel_registry
    rows = _generic_kernel_rows(_tune_rows())
    table = {}
    for kind, incumbent in (("attention", "ring"),
                            ("layernorm_residual", "unfused"),
                            ("xent", "scan"),
                            ("int8_matmul", "f32"),
                            ("paged_attention", "gather"),
                            ("paged_attention_int8", "gather_int8")):
        try:
            table[kind] = kernel_registry.autopick(
                kind, rows, incumbent=incumbent).as_dict()
        except Exception as e:                  # table is telemetry, not a leg
            table[kind] = {"error": repr(e)[:200]}
    return table


def main():
    t_start = time.time()
    allow_stale = "--allow-stale" in sys.argv
    # Persistent XLA compilation cache (repo-local, gitignored): the BERT
    # leg's compile dominates bench wall time on reruns; cache hits skip it.
    from deeplearning4j_tpu.parallel.compile_cache import setup_compile_cache
    setup_compile_cache(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), ".cache", "xla"))
    devices, fallback_reason, probe_failures = _discover_devices()
    dev = devices[0]
    kind = getattr(dev, "device_kind", "cpu").lower()
    on_tpu = "tpu" in kind or dev.platform == "tpu"
    peak = next((v for k, v in PEAK_FLOPS.items() if k in kind),
                PEAK_FLOPS["cpu"])

    problems = []

    try:
        try:
            bert = _bert_leg(dev, on_tpu)
        except Exception as oom:
            if on_tpu and "RESOURCE_EXHAUSTED" in repr(oom):
                bert = _bert_leg(dev, on_tpu, conserve_hbm=True)
                bert["hbm_fallback"] = "remat+batch32 after RESOURCE_EXHAUSTED"
            else:
                raise
    except Exception as e:
        # Headline leg failed (OOM, tunnel death mid-run, compile error):
        # still honor the one-JSON-line contract, publish no claim, fail.
        out = {"metric": "bert_base_train_tokens_per_sec_ERROR", "value": 0.0,
               "unit": "tokens/sec/chip", "vs_baseline": 0.0,
               "extra": {"device": str(dev), "error": repr(e)[:400],
                         "wall_s": round(time.time() - t_start, 1),
                         **({"probe_failures": probe_failures}
                            if probe_failures else {})}}
        print(json.dumps(out))
        print(f"BENCH ERROR: {e!r}", file=sys.stderr)
        sys.exit(1)
    bert_problems, bert_mfu = _validity_checks(
        "bert", bert["iter_times"], bert["flops_per_iter"], peak)
    problems += bert_problems
    # live gauges from the same cost_analysis-derived FLOPs the artifact
    # cross-checks against (PR-10): a /metrics scrape during a bench run
    # sees train.mfu computed exactly as the JSON line reports it
    from deeplearning4j_tpu.observability import COSTS
    bert_mfu_xla = COSTS.publish_utilization(
        COSTS.get("bench.bert_base.step"), bert["stats"]["median_s"],
        "train.mfu", "train.mbu")
    # the e2e leg serializes a device_put into every step, so it should be
    # an upper bound on the staged step time; e2e beating staged by more
    # than noise (r4 saw a 5% inversion) means the timing model is off for
    # this run — surface it as a warning on the artifact, not a hard fail
    timing_warnings = []
    if bert["e2e_stats"]["median_s"] < bert["stats"]["median_s"] * 0.95:
        timing_warnings.append(
            f"e2e median {bert['e2e_stats']['median_s']*1e3:.1f}ms beat "
            f"staged {bert['stats']['median_s']*1e3:.1f}ms by >5% — "
            "e2e should upper-bound staged; treat the gap between legs "
            "as noise for this run")
    # analytic-vs-XLA FLOPs cross-check (>2.5x disagreement = bad accounting)
    if bert.get("xla_flops_per_step"):
        ratio = bert["flops_per_iter"] / bert["xla_flops_per_step"]
        bert["flops_analytic_over_xla"] = round(ratio, 3)
        if not (1 / 2.5 < ratio < 2.5):
            problems.append(
                f"bert: analytic FLOPs {ratio:.2f}x XLA cost_analysis")

    try:
        try:
            resnet = _resnet_leg(dev, on_tpu)
        except Exception as oom:
            if on_tpu and "RESOURCE_EXHAUSTED" in repr(oom):
                try:
                    resnet = _resnet_leg(dev, on_tpu, batch_override=64)
                except Exception as oom2:
                    if "RESOURCE_EXHAUSTED" in repr(oom2):
                        resnet = _resnet_leg(dev, on_tpu, batch_override=24)
                    else:
                        raise
            else:
                raise
        rn_problems, rn_mfu = _validity_checks(
            "resnet", resnet["iter_times"], resnet["flops_per_iter"], peak)
        problems += rn_problems
    except Exception as e:                      # resnet leg must not kill bench
        resnet, rn_mfu = {"error": repr(e)[:300]}, None

    try:
        w2v = _word2vec_leg(dev, on_tpu)
    except Exception as e:                      # embeddings leg must not kill bench
        w2v = {"error": repr(e)[:300]}

    try:
        decode = _decode_leg(dev, on_tpu)
    except Exception as e:                      # decode leg must not kill bench
        decode = {"error": repr(e)[:300]}

    scaling = _scaling_leg()
    zero = _zero_leg()
    elastic = _elastic_leg()
    # when we could not reach the chip, at least prove the REAL configs
    # compile and record XLA's FLOPs for them (no timing claim)
    real_compile = None if on_tpu else _real_config_compile_check()
    # ... and surface the most recent guard-passing TPU run (written by a
    # prior successful invocation below) so a transient relay outage does
    # not erase the round's evidence; clearly labeled as NOT this run.
    last_valid = None
    if not on_tpu:
        try:
            with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "LAST_VALID_TPU_BENCH.json")) as f:
                last_valid = json.load(f)
        except Exception:
            pass
        last_valid = _stale_guard(last_valid, allow_stale)

    # the elastic rows only became measurable in the elastic-tier PR: an
    # artifact stamped before it has no reshard numbers, so comparing this
    # run's elastic leg against it would be a cross-tier apples/oranges —
    # refuse explicitly rather than silently omitting the comparison.
    if isinstance(elastic, dict) and "error" not in elastic:
        asof = (last_valid.get("asof_pr") or 0) if isinstance(last_valid, dict) \
            else 0
        if asof < ELASTIC_TIER_PR:
            elastic["artifact_comparison"] = {
                "refused_pre_elastic_artifact": True,
                "artifact_asof_pr": asof,
                "note": (f"TPU artifact predates the elastic tier (PR "
                         f"{ELASTIC_TIER_PR}) and carries no reshard rows — "
                         "rerun the TPU battery to get a comparable "
                         "artifact"),
            }
        else:
            elastic["artifact_comparison"] = {"artifact_asof_pr": asof}

    bst = bert["stats"]
    metric = ("bert_base_train_tokens_per_sec" if on_tpu
              else "bert_base_train_tokens_per_sec_CPU_FALLBACK")
    extra = {
        "device": str(dev),
        "mfu": round(bert_mfu, 4),
        "step_ms": {"median": round(bst["median_s"] * 1e3, 2),
                    "p10": round(bst["p10_s"] * 1e3, 2),
                    "p90": round(bst["p90_s"] * 1e3, 2),
                    "iters": bert["iters"]},
        "e2e_with_transfers": {
            "tokens_per_sec": round(bert["tokens_per_sec_e2e"], 1),
            "step_ms_median": round(bert["e2e_stats"]["median_s"] * 1e3, 2)},
        "e2e_prefetched": {
            "tokens_per_sec": round(bert["tokens_per_sec_prefetched"], 1),
            "step_ms_median": round(bert["prefetch_stats"]["median_s"] * 1e3, 2),
            "tokens_per_sec_wall": round(
                bert["tokens_per_sec_prefetched_wall"], 1),
            "wall_ms_per_step": round(
                bert["prefetch_wall_s_total"] / bert["iters"] * 1e3, 2)},
        "e2e_async_losses": {
            # wall-clock throughput incl. the single end fence — the
            # lazy-loss win over e2e_with_transfers' per-step syncs
            "tokens_per_sec_wall": round(bert["tokens_per_sec_e2e_async"], 1),
            "wall_ms_per_step": round(
                bert["async_wall_s_total"] / bert["iters"] * 1e3, 2),
            "dispatch_ms_median": round(
                bert["async_dispatch_stats"]["median_s"] * 1e3, 2)},
        "loss": round(bert["last_loss"], 4),
        **({"hbm_fallback": bert["hbm_fallback"]}
           if "hbm_fallback" in bert else {}),
        "batch_seq": [bert["batch"], bert["seq"]],
        "attention": bert["attention"],
        "attention_choice": bert.get("attention_choice"),
        "flops_per_token": round(bert["flops_per_token_analytic"]),
        **({"mfu_xla": round(bert_mfu_xla, 6)}
           if bert_mfu_xla is not None else {}),
        **({"flops_analytic_over_xla": bert["flops_analytic_over_xla"]}
           if "flops_analytic_over_xla" in bert else {}),
        "resnet": ({"images_per_sec_per_chip": round(resnet["images_per_sec"], 2),
                    "mfu": round(rn_mfu, 4) if rn_mfu is not None else None,
                    "step_ms_median": round(resnet["stats"]["median_s"] * 1e3, 2),
                    "batch": resnet["batch"], "image": resnet["image"],
                    "resnet50": resnet["depth50"],
                    "bn_fold": resnet["bn_fold"],
                    "bn_fold_choice": resnet["bn_fold_choice"],
                    "loss": round(resnet["last_loss"], 4)}
                   if "error" not in resnet else resnet),
        "word2vec": w2v,
        "decode": decode,
        "dp_machinery_check": scaling,
        "zero_sharding": zero,
        "elastic_reshard": elastic,
        # which implementation each kernel kind would run in production
        # and why, with every dropped candidate's reason on record
        "kernel_picks": _kernel_picks(),
        **({"real_config_compile_check": real_compile} if real_compile else {}),
        "wall_s": round(time.time() - t_start, 1),
        # same raw observations the /metrics endpoint would serve during
        # the run (bench._timed_loop records through the registry)
        "observability_timers": _registry_timers(),
        **({"timing_warnings": "; ".join(timing_warnings)}
           if timing_warnings else {}),
        **({"fallback": fallback_reason} if fallback_reason else {}),
        **({"probe_failures": probe_failures} if probe_failures else {}),
        **({"last_valid_tpu_run_NOT_this_run": last_valid} if last_valid else {}),
    }

    if problems and not on_tpu:
        # CPU fallback publishes no claim (vs_baseline 0) and its "peak" is
        # a nominal constant — surface guard trips as warnings, don't fail.
        extra["warnings"] = "; ".join(problems)
        problems = []
    if problems:
        extra["invalid"] = "; ".join(problems)
        out = {"metric": metric + "_INVALID", "value": 0.0,
               "unit": "tokens/sec/chip", "vs_baseline": 0.0, "extra": extra}
        print(json.dumps(out))
        print("BENCH INVALID: " + extra["invalid"], file=sys.stderr)
        sys.exit(1)

    out = {
        "metric": metric,
        "value": round(bert["tokens_per_sec"], 1),
        "unit": "tokens/sec/chip",
        # CPU fallback numbers are a smoke signal, not a claim: report 0.
        "vs_baseline": round(bert_mfu / MFU_TARGET, 4) if on_tpu else 0.0,
        "extra": extra,
    }
    print(json.dumps(out))
    if on_tpu:                        # persist guard-passing evidence
        try:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "LAST_VALID_TPU_BENCH.json")
            with open(path, "w") as f:
                # fresh on-chip evidence: not stale, stamped with the PR
                # it measured so future stale-marking has a reference
                json.dump(dict(out, stale=False, asof_pr=ELASTIC_TIER_PR), f)
                f.write("\n")
        except OSError:
            pass


if __name__ == "__main__":
    main()
