"""Word-vector serialization — Google word2vec text & binary formats.

Capability match of ``models/embeddings/loader/WordVectorSerializer.java:
27,40,269,303,337``: round-trip to the original word2vec C formats so vectors
interchange with the wider ecosystem.
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np


def save_txt(words: list[str], vectors: np.ndarray, path: str | Path) -> None:
    """One 'word v1 v2 ...' line per word (writeWordVectors)."""
    vectors = np.asarray(vectors)
    with open(path, "w", encoding="utf-8") as f:
        for w, vec in zip(words, vectors):
            f.write(w + " " + " ".join(f"{x:.6g}" for x in vec) + "\n")


def load_txt(path: str | Path) -> tuple[list[str], np.ndarray]:
    words, rows = [], []
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip("\n").split(" ")
            if len(parts) < 2:
                continue
            if len(words) == 0 and len(parts) == 2 and all(
                    p.isdigit() for p in parts):
                continue  # optional "count dim" header
            words.append(parts[0])
            rows.append(np.array(parts[1:], dtype=np.float32))
    return words, np.stack(rows)


def save_google_binary(words: list[str], vectors: np.ndarray,
                       path: str | Path) -> None:
    """word2vec C binary: header 'count dim\\n', then per word
    'word ' + dim float32s (loadGoogleModel's inverse)."""
    vectors = np.asarray(vectors, dtype=np.float32)
    n, d = vectors.shape
    with open(path, "wb") as f:
        f.write(f"{n} {d}\n".encode())
        for w, vec in zip(words, vectors):
            f.write(w.encode("utf-8") + b" ")
            f.write(vec.tobytes())
            f.write(b"\n")


def load_google_binary(path: str | Path) -> tuple[list[str], np.ndarray]:
    words, rows = [], []
    with open(path, "rb") as f:
        header = f.readline().decode()
        n, d = (int(x) for x in header.split())
        for _ in range(n):
            w = bytearray()
            while True:
                c = f.read(1)
                if c == b" " or c == b"":
                    break
                if c != b"\n":
                    w.extend(c)
            vec = np.frombuffer(f.read(4 * d), dtype=np.float32)
            rows.append(vec)
            words.append(w.decode("utf-8"))
            f.read(1)  # trailing newline
    return words, np.stack(rows)


def save_word2vec(model, path: str | Path, binary: bool = False) -> None:
    words = model.vocab.words()
    # embeddings (not syn0): trims shard padding on ShardedWord2Vec so the
    # header row count matches the records written
    vectors = (model.embeddings if hasattr(model, "embeddings")
               else np.asarray(model.syn0))
    (save_google_binary if binary else save_txt)(words, np.asarray(vectors), path)


def load_into_word2vec(path: str | Path, binary: bool = False):
    """Rebuild a queryable Word2Vec from a serialized file."""
    from .vocab import VocabCache
    from .word2vec import Word2Vec
    import jax.numpy as jnp

    words, vectors = (load_google_binary if binary else load_txt)(path)
    model = Word2Vec(layer_size=vectors.shape[1])
    cache = VocabCache()
    for i, w in enumerate(words):
        cache.add(w, by=float(len(words) - i))  # preserve order on finalize
    cache.finalize_indices()
    model.vocab = cache
    model.syn0 = jnp.asarray(vectors)
    return model
