"""Service-level objectives with multi-window error-budget burn rates.

An :class:`SLObjective` declares what "good" means for one series over
rolling windows; an :class:`SLOEvaluator` rides the
:class:`~.timeseries.TimeSeriesStore` sampler (scrape-free — it is called
after every sample, no HTTP involved) and computes, per objective and per
window, the **burn rate**: how fast the error budget is being spent, where
1.0 means "exactly on budget" and N means "the budget will be gone in
1/N of the budget period".

Three objective kinds:

- ``upper`` — the series must stay at or below ``objective`` (TTFT p99,
  inter-token p99, step-time p99).  Bad fraction = share of window samples
  above the objective; burn = bad fraction / ``budget``.
- ``lower`` — the series must stay at or above ``objective`` (goodput
  floor).  Bad fraction mirrors ``upper``.
- ``rate`` — two cumulative counters: burn = (Δ``series`` / Δ``denominator``)
  / ``objective`` over the window (error+429 rate, where the objective
  *is* the budgeted bad-request fraction).

Multi-window semantics follow the SRE burn-rate alert shape: a **breach**
fires only when every window with data burns at or above
``burn_threshold`` *and* at least one window is full (the store has
history covering its whole span) — the short window gives fast detection,
the long window keeps a transient spike from paging.  A breach dumps a
flight-recorder bundle naming the objective, the windows and their burns,
and the offending series tail, then cools down so a sustained breach
yields one bundle, not one per sample.  Every evaluation publishes
``slo.burn_rate.<name>`` (worst full-window burn) so the router prober
and the training supervisor can read the live number back off the
registry without knowing any of this.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any

from . import core
from .flightrec import FLIGHTREC, FlightRecorder
from .metrics import METRICS, MetricsRegistry
from .timeseries import TimeSeriesStore

# How many trailing points of the offending series a breach bundle keeps.
BUNDLE_TAIL = 32


@dataclass(frozen=True)
class SLObjective:
    """One declarative rolling-window objective."""

    name: str                 # gauge suffix: slo.burn_rate.<name>
    kind: str                 # "upper" | "lower" | "rate"
    series: str               # sampled series (numerator counter for rate)
    objective: float          # threshold (or budgeted bad fraction for rate)
    denominator: str | None = None     # rate only: total-events counter
    budget: float = 0.05      # upper/lower: allowed bad-sample fraction
    windows: tuple[float, ...] = (30.0, 120.0)   # seconds, short → long
    burn_threshold: float = 1.0

    def __post_init__(self):
        if self.kind not in ("upper", "lower", "rate"):
            raise ValueError(f"unknown SLO kind {self.kind!r}")
        if self.kind == "rate" and not self.denominator:
            raise ValueError(f"rate objective {self.name!r} needs a denominator")
        if not self.windows:
            raise ValueError(f"objective {self.name!r} declares no windows")


@dataclass
class WindowBurn:
    """Burn for one (objective, window) pair at one evaluation instant."""

    window_s: float
    burn: float | None        # None: no data in the window yet
    full: bool                # store history covers the whole window
    samples: int


def default_serving_objectives(ttft_p99_s: float = 0.5,
                               inter_token_p99_s: float = 0.25,
                               error_rate: float = 0.05,
                               windows: tuple[float, ...] = (30.0, 120.0),
                               ) -> list[SLObjective]:
    """The serving trio: TTFT p99, inter-token p99, error+429 rate."""
    return [
        SLObjective("serving_ttft_p99", "upper", "serving.ttft.p99",
                    ttft_p99_s, windows=windows),
        SLObjective("serving_inter_token_p99", "upper",
                    "serving.decode_step.p99", inter_token_p99_s,
                    windows=windows),
        SLObjective("serving_error_rate", "rate", "serving.rejected",
                    error_rate, denominator="serving.requests",
                    windows=windows),
    ]


def default_training_objectives(step_p99_s: float = 5.0,
                                goodput_floor: float = 0.5,
                                windows: tuple[float, ...] = (60.0, 300.0),
                                ) -> list[SLObjective]:
    """The training pair: step-time p99 ceiling and goodput floor."""
    return [
        SLObjective("train_step_p99", "upper", "train_step.p99",
                    step_p99_s, windows=windows),
        SLObjective("train_goodput", "lower", "goodput.fraction",
                    goodput_floor, windows=windows),
    ]


class SLOEvaluator:
    """Evaluates objectives against a store's rings on every sample."""

    def __init__(self, objectives: list[SLObjective], store: TimeSeriesStore,
                 registry: MetricsRegistry = METRICS,
                 flightrec: FlightRecorder = FLIGHTREC,
                 breach_cooldown_s: float = 60.0,
                 attach: bool = True):
        self.objectives = list(objectives)
        self.store = store
        self.registry = registry
        self.flightrec = flightrec
        self.breach_cooldown_s = float(breach_cooldown_s)
        self.evaluations = 0
        self.breaches: list[str] = []          # bundle paths (or "" if inhibited)
        # First breach instant per objective (sample-clock time) — the
        # forecast tier's ground truth: a useful forecast published its
        # warning strictly before the time recorded here.
        self.breach_times: dict[str, float] = {}
        self.last: dict[str, list[WindowBurn]] = {}
        self._last_breach_t: dict[str, float] = {}
        if attach:
            store.add_evaluator(self.evaluate)

    # ------------------------------------------------------------ windows
    def _window_burn(self, obj: SLObjective, window_s: float,
                     now: float) -> WindowBurn:
        if obj.kind == "rate":
            num = self.store.window(obj.series, window_s, now)
            den = self.store.window(obj.denominator or "", window_s, now)
            full = self._covers(obj.denominator or "", window_s, now)
            if len(den) < 2:
                return WindowBurn(window_s, None, full, len(den))
            d_den = den[-1][1] - den[0][1]
            d_num = (num[-1][1] - num[0][1]) if len(num) >= 2 else 0.0
            if d_den <= 0:
                return WindowBurn(window_s, None, full, len(den))
            rate = max(0.0, d_num) / d_den
            return WindowBurn(window_s, rate / obj.objective, full, len(den))
        pts = self.store.window(obj.series, window_s, now)
        full = self._covers(obj.series, window_s, now)
        if not pts:
            return WindowBurn(window_s, None, full, 0)
        if obj.kind == "upper":
            bad = sum(1 for _, v in pts if v > obj.objective)
        else:
            bad = sum(1 for _, v in pts if v < obj.objective)
        burn = (bad / len(pts)) / obj.budget if obj.budget > 0 else float("inf")
        return WindowBurn(window_s, burn, full, len(pts))

    def _covers(self, series: str, window_s: float, now: float) -> bool:
        pts = self.store.series(series)
        return bool(pts) and pts[0][0] <= now - window_s

    # ---------------------------------------------------------- evaluation
    def evaluate(self, store: TimeSeriesStore | None = None,
                 now: float | None = None) -> dict[str, list[WindowBurn]]:
        """One pass over every objective.  Signature matches the store's
        evaluator hook ``fn(store, t)``."""
        if not core.enabled():
            return {}
        if now is None:
            now = time.time()
        self.evaluations += 1
        out: dict[str, list[WindowBurn]] = {}
        for obj in self.objectives:
            burns = [self._window_burn(obj, w, now) for w in obj.windows]
            out[obj.name] = burns
            computed = [b for b in burns if b.burn is not None]
            full = [b for b in computed if b.full]
            worst = max((b.burn for b in full), default=None)
            if worst is None and computed:
                worst = max(b.burn for b in computed)
            if worst is not None:
                self.registry.gauge(f"slo.burn_rate.{obj.name}", worst)
            breach = (bool(full)
                      and len(computed) == len(burns)
                      and all(b.burn >= obj.burn_threshold for b in computed))
            if breach:
                self._breach(obj, burns, now)
        self.last = out
        return out

    def _breach(self, obj: SLObjective, burns: list[WindowBurn],
                now: float) -> None:
        last = self._last_breach_t.get(obj.name)
        if last is not None and now - last < self.breach_cooldown_s:
            return
        self._last_breach_t[obj.name] = now
        self.breach_times.setdefault(obj.name, now)
        self.registry.increment("slo.breaches")
        tail = self.store.series(obj.series)[-BUNDLE_TAIL:]
        path = self.flightrec.dump("slo_breach", extra={
            "objective": obj.name,
            "kind": obj.kind,
            "series": obj.series,
            "threshold": obj.objective,
            "burn_threshold": obj.burn_threshold,
            "windows": [{
                "window_s": b.window_s, "burn": b.burn,
                "full": b.full, "samples": b.samples} for b in burns],
            "series_tail": [[t, v] for t, v in tail],
        })
        self.breaches.append(str(path) if path else "")

    # ------------------------------------------------------------- report
    def status(self) -> dict[str, Any]:
        return {
            "evaluations": self.evaluations,
            "breaches": len(self.breaches),
            "objectives": {
                name: [{"window_s": b.window_s, "burn": b.burn,
                        "full": b.full, "samples": b.samples}
                       for b in burns]
                for name, burns in self.last.items()
            },
        }

    def burn_rate(self, name: str) -> float | None:
        """Latest worst-window burn for one objective (None before data)."""
        burns = self.last.get(name)
        if not burns:
            return None
        vals = [b.burn for b in burns if b.burn is not None]
        return max(vals) if vals else None
