"""Tier-1 wiring for ``tools/perf_smoke.py`` — the bounded-recompile guard.

Fast by design (30 tiny CPU steps, a handful of bucket compiles): NOT
marked slow, so the bucketing regression is caught on every tier-1 run.
"""

import importlib.util
import pathlib

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_perf_smoke():
    spec = importlib.util.spec_from_file_location(
        "perf_smoke", _TOOLS / "perf_smoke.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_perf_smoke_bounded_recompiles():
    ps = _load_perf_smoke()
    result = ps.run(steps=30)
    # run() asserts the invariants internally; pin the headline ones here
    # too so a refactor of run() cannot silently drop them
    assert result["steps"] == 30
    assert result["recompiles"] == result["expected_buckets"]
    # ragged sizes collapse onto a small ladder: strictly fewer compiles
    # than distinct raw batch sizes (the whole point of bucketing)
    assert result["recompiles"] < len(set(ps.RAGGED_SIZES))
    assert result["losses_finite"]


def test_perf_smoke_zero_leg():
    """ZeRO leg: opt-state bytes/device ~ replicated/ndp AND the bucket
    ladder survives the sharded step (recompiles == buckets)."""
    ps = _load_perf_smoke()
    result = ps.run_zero(steps=30)
    assert result["recompiles"] == result["expected_buckets"]
    rep, shard = result["opt_bytes_replicated"], result["opt_bytes_zero2"]
    n_dp = result["n_dp"]
    assert rep / n_dp <= shard <= rep / n_dp + result["pad_slack_bytes"]
    assert result["losses_finite"]


def test_expected_buckets_ladder():
    ps = _load_perf_smoke()
    # nominal 32 (first size), dp 8: pow2 ladder rounded to 8s, capped
    assert ps.expected_buckets([32, 31, 17, 9, 23, 13, 32, 5, 29, 11], 8) \
        == {8, 16, 32}
    # oversized batches round to the dp width, uncapped
    assert ps.expected_buckets([16, 40], 8) == {16, 40}
