"""Interactive embedding render web app.

Capability match of the reference's dropwizard render application
(``deeplearning4j-nlp/.../plot/dropwizard/RenderApplication.java:21`` with
``ApiResource``/``RenderResource``: a small web app that serves 2-D word
coordinates as JSON and a page that draws them).  Zero-dependency
equivalent: a stdlib HTTP server with one embedded HTML/canvas page —
pan/zoom scatter, hover tooltips, substring search — reading
``/api/coords``.  Feed it t-SNE output (``plot/tsne.py``) over a vocab, or
any (words, (N, 2) coords) pair; ``update()`` republishes live during
training.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Sequence

import numpy as np

__all__ = ["EmbeddingRenderServer", "render_word_vectors"]

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>Embedding render</title>
<style>
 body { margin:0; font:13px system-ui, sans-serif; }
 #bar { padding:6px 10px; background:#222; color:#eee; display:flex; gap:10px;
        align-items:center; }
 #bar input { padding:3px 6px; border-radius:3px; border:none; }
 #tip { position:fixed; pointer-events:none; background:#222; color:#fff;
        padding:2px 7px; border-radius:3px; display:none; }
 canvas { display:block; cursor:grab; }
</style></head><body>
<div id="bar"><b>embedding render</b>
 <input id="q" placeholder="search word..."/>
 <span id="n"></span>
 <span style="opacity:.6">drag to pan &middot; wheel to zoom</span></div>
<div id="tip"></div><canvas id="c"></canvas>
<script>
const cv = document.getElementById('c'), tip = document.getElementById('tip');
const ctx = cv.getContext('2d');
let pts = [], view = {x:0, y:0, s:1}, drag = null, query = '';
function resize(){ cv.width = innerWidth; cv.height = innerHeight - 34; draw(); }
addEventListener('resize', resize);
function fit(){
  if (!pts.length) return;
  const xs = pts.map(p=>p.x), ys = pts.map(p=>p.y);
  const x0 = Math.min(...xs), x1 = Math.max(...xs);
  const y0 = Math.min(...ys), y1 = Math.max(...ys);
  const s = 0.9 * Math.min(cv.width/(x1-x0+1e-9), cv.height/(y1-y0+1e-9));
  view = {s, x: cv.width/2 - s*(x0+x1)/2, y: cv.height/2 - s*(y0+y1)/2};
}
function toScreen(p){ return [view.x + view.s*p.x, view.y + view.s*p.y]; }
function draw(){
  ctx.clearRect(0,0,cv.width,cv.height);
  for (const p of pts){
    const [sx, sy] = toScreen(p);
    if (sx < -20 || sy < -20 || sx > cv.width+20 || sy > cv.height+20) continue;
    const hit = query && p.word.includes(query);
    ctx.fillStyle = hit ? '#d33' : '#4477cc';
    ctx.beginPath(); ctx.arc(sx, sy, hit ? 5 : 3, 0, 7); ctx.fill();
    if (view.s > 40 || hit){
      ctx.fillStyle = '#333'; ctx.fillText(p.word, sx+6, sy+3);
    }
  }
}
cv.onmousedown = e => drag = {x:e.clientX, y:e.clientY};
addEventListener('mouseup', () => drag = null);
cv.onmousemove = e => {
  if (drag){
    view.x += e.clientX - drag.x; view.y += e.clientY - drag.y;
    drag = {x:e.clientX, y:e.clientY}; draw(); return;
  }
  let best = null, bd = 144;
  for (const p of pts){
    const [sx, sy] = toScreen(p);
    const d = (sx-e.clientX)**2 + (sy-(e.clientY-34))**2;
    if (d < bd){ bd = d; best = p; }
  }
  if (best){
    tip.style.display = 'block';
    tip.style.left = (e.clientX+12)+'px'; tip.style.top = (e.clientY+12)+'px';
    tip.textContent = best.word;
  } else tip.style.display = 'none';
};
cv.onwheel = e => {
  e.preventDefault();
  const k = Math.exp(-e.deltaY * 0.001);
  view.x = e.clientX - k*(e.clientX - view.x);
  view.y = (e.clientY-34) - k*((e.clientY-34) - view.y);
  view.s *= k; draw();
};
document.getElementById('q').oninput = e => { query = e.target.value; draw(); };
async function load(){
  const r = await fetch('api/coords');
  pts = await r.json();
  document.getElementById('n').textContent = pts.length + ' words';
  resize(); fit(); draw();
}
load(); setInterval(load, 5000);
</script></body></html>
"""


class EmbeddingRenderServer:
    """Serve an interactive 2-D embedding scatter.

    ``/`` — the render page; ``/api/coords`` — ``[{word, x, y}, ...]``;
    ``update(words, coords)`` republishes (the page polls every 5 s, so a
    training loop can stream its t-SNE snapshots like the reference's
    ``plotVocab`` + render app pair).
    """

    def __init__(self, words: Sequence[str], coords: np.ndarray,
                 host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        self._payload = b"[]"
        self.update(words, coords)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path in ("/", "/index.html"):
                    body, ctype = _PAGE.encode(), "text/html; charset=utf-8"
                elif self.path == "/api/coords":
                    with outer._lock:
                        body = outer._payload
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def update(self, words: Sequence[str], coords: np.ndarray) -> None:
        coords = np.asarray(coords, np.float64)
        if coords.shape != (len(words), 2):
            raise ValueError(f"coords must be ({len(words)}, 2), "
                             f"got {coords.shape}")
        payload = [{"word": w, "x": float(x), "y": float(y)}
                   for w, (x, y) in zip(words, coords)]
        with self._lock:
            self._payload = json.dumps(payload).encode()

    def start(self) -> "EmbeddingRenderServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def render_word_vectors(model, *, perplexity: float = 15.0,
                        max_words: int = 500, seed: int = 0,
                        host: str = "127.0.0.1", port: int = 0,
                        n_iter: int = 300) -> EmbeddingRenderServer:
    """One-call path from a trained embedding model (``Word2Vec``/``Glove``
    — anything with ``.vocab.words()`` and ``.get_word_vector``) to a live
    render server (the ``InMemoryLookupTable.plotVocab`` -> dropwizard
    flow): t-SNE the top ``max_words`` vectors to 2-D and serve them."""
    from .tsne import Tsne

    words = list(model.vocab.words())[:max_words]
    vecs = np.stack([np.asarray(model.get_word_vector(w)) for w in words])
    coords = Tsne(n_iter=n_iter, perplexity=min(perplexity,
                                                max(2.0, len(words) / 4)),
                  seed=seed).fit_transform(vecs)
    return EmbeddingRenderServer(words, coords, host=host, port=port).start()
