"""LSTM sequence model: training, sampling, and beam-search decoding.

Capability match of ``models/classifiers/lstm/LSTM.java`` (char-rnn style):
train x->next-token with the concatenated-gate LSTM from ``nn.layers``
(autodiff BPTT under ``lax.scan`` replaces the manual backward ``:63-140``),
then decode with temperature sampling or beam search (``:241-340``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.conf import LayerKind, NeuralNetConfiguration
from ..nn.layers import LSTM as LSTMLayer


class LSTMSequenceModel:
    def __init__(self, vocab_size: int, hidden_size: int = 128, *,
                 lr: float = 0.1, seed: int = 0):
        self.conf = NeuralNetConfiguration(
            kind=LayerKind.LSTM, n_in=vocab_size, n_out=vocab_size,
            hidden_size=hidden_size, activation="softmax", lr=lr, seed=seed)
        self.layer = LSTMLayer(self.conf)
        self.params = None
        self._step = None

    def init(self, key=None):
        self.params = self.layer.init(key if key is not None else
                                      jax.random.key(self.conf.seed))
        return self.params

    # ------------------------------------------------------------------ train
    def fit_sequence(self, tokens: np.ndarray, epochs: int = 100) -> list[float]:
        """Next-token training on one index sequence (char-rnn style)."""
        if self.params is None:
            self.init()
        v = self.conf.n_in
        x = jax.nn.one_hot(jnp.asarray(tokens[:-1]), v)
        y = jax.nn.one_hot(jnp.asarray(tokens[1:]), v)
        if self._step is None:
            lr = self.conf.lr

            @jax.jit
            def step(params, x, y):
                loss, g = jax.value_and_grad(self.layer.loss)(params, x, y)
                params = jax.tree_util.tree_map(lambda p, gg: p - lr * gg, params, g)
                return params, loss

            self._step = step
        losses = []
        for _ in range(epochs):
            self.params, loss = self._step(self.params, x, y)
            losses.append(float(loss))
        return losses

    # ------------------------------------------------------------------ decode
    def _step_logits(self, carry, token_id: int):
        v = self.conf.n_in
        x_t = jax.nn.one_hot(jnp.asarray(token_id), v)
        carry, h = self.layer._step(self.params, carry, x_t)
        logits = h @ self.params["decoderweights"] + self.params["decoderbias"]
        return carry, np.asarray(jax.nn.log_softmax(logits))

    def _init_carry(self):
        d = self.conf.hidden_size or self.conf.n_out
        return (jnp.zeros((d,)), jnp.zeros((d,)))

    def _prime(self, prime: list[int]):
        """Carry + log-probs after consuming ``prime`` (possibly empty: the
        zero hidden state's decoder distribution seeds generation)."""
        carry = self._init_carry()
        h0 = carry[0]
        logits = h0 @ self.params["decoderweights"] + self.params["decoderbias"]
        logp = np.asarray(jax.nn.log_softmax(logits))
        for t in prime:
            carry, logp = self._step_logits(carry, t)
        return carry, logp

    def sample(self, prime: list[int], length: int, temperature: float = 1.0,
               seed: int = 0) -> list[int]:
        """Temperature sampling continuation of ``prime``."""
        rng = np.random.default_rng(seed)
        carry, logp = self._prime(prime)
        out = list(prime)
        for _ in range(length):
            p = np.exp(logp / temperature)
            p /= p.sum()
            t = int(rng.choice(len(p), p=p))
            out.append(t)
            carry, logp = self._step_logits(carry, t)
        return out

    def beam_search(self, prime: list[int], length: int, beam_width: int = 5
                    ) -> tuple[list[int], float]:
        """Highest-log-likelihood continuation (``LSTM.java BeamSearch``).

        Returns (token sequence, total log prob)."""
        carry, logp = self._prime(prime)
        beams = [(0.0, list(prime), carry, logp)]
        for _ in range(length):
            candidates = []
            for score, seq, c, lp in beams:
                top = np.argsort(-lp)[:beam_width]
                for t in top:
                    candidates.append((score + float(lp[t]), seq + [int(t)], c, int(t)))
            candidates.sort(key=lambda s: -s[0])
            new_beams = []
            for score, seq, c, t in candidates[:beam_width]:
                c2, lp2 = self._step_logits(c, t)
                new_beams.append((score, seq, c2, lp2))
            beams = new_beams
        best = max(beams, key=lambda b: b[0])
        return best[1], best[0]

    def predict_next(self, prime: list[int]) -> int:
        carry, logp = self._prime(prime)
        return int(np.argmax(logp))
