#!/usr/bin/env python
"""Merge per-process trace exports into one Chrome trace + TTFT report.

Usage:
    python tools/trace_report.py trace1.json worker2.jsonl ...
                                 [--out merged.json] [--limit 20]

Inputs may be Chrome trace JSON objects (``{"traceEvents": [...]}`` as
written by ``Tracer.save_chrome_trace``) or JSONL event streams (one event
per line, as written by ``Tracer.export_jsonl`` / ``stream_jsonl``).  All
events share one ``time.perf_counter()``-anchored µs clock per host, so
merging exports from co-located processes (trainer + server + workers)
yields a single Perfetto-loadable flame; ``--out`` writes that merged
trace.

The report groups serving spans by ``args.trace_id`` (the W3C trace id
minted at admission or propagated via ``traceparent``) and prints, per
request, the critical-path breakdown the engine records:

    queue_wait | route | prefill | decode (sum of segments) | emit | TTFT | total

``route`` is the router hop (``router.route`` spans from the
multi-replica tier, PR 11) — for a request that spilled over, every
attempted replica's span counts, so the column is the full routing cost,
and ``hops`` shows how many replicas were tried.  Single-replica runs
show ``-``.  Spans from a router process and a replica process share the
trace id via the ``traceparent`` header; their ``ts`` anchors differ per
process (perf_counter epochs), so columns are durations, never
cross-process timestamp differences.

The ``tenant`` column is the bounded tenant label the engine stamped on
the ``serving.request`` root span (``-`` for untenanted traffic), so a
trace slices per tenant the same way the registry's ``tenant.*``
counters do.

TTFT here is time from submission to the end of prefill — the first
token exists when prefill's last dispatch resolves.  Requests missing a
``serving.request`` root (still in flight at export time) are skipped.

Each row is also annotated against the serving TTFT SLO (the same
objective ``observability.slo.default_serving_objectives`` watches live,
0.5 s p99 by default — override with ``--slo-ttft-ms``): requests over
the objective show ``MISS`` in the ``slo`` column, and the summary line
compares the miss fraction to the error budget (``--slo-budget``, 5%
default) — the offline twin of the burn-rate gauges.

Exits nonzero when no input file yields any events.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def load_events(path: str | Path) -> tuple[list[dict], int]:
    """Events + dropped-count from one export (Chrome JSON or JSONL)."""
    text = Path(path).read_text()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None                   # multiple lines -> JSONL
    if isinstance(obj, dict) and "traceEvents" in obj:
        dropped = int((obj.get("metadata") or {}).get("dropped", 0))
        return obj["traceEvents"], dropped
    if isinstance(obj, dict):
        return [obj], 0              # a single-event JSONL file
    events = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            events.append(json.loads(line))
        except ValueError:
            continue  # torn tail line from a crashed streamer
    return events, 0


def merge(paths: list[str]) -> dict:
    """One Chrome trace object from many exports; ``dropped`` summed."""
    all_events: list[dict] = []
    dropped = 0
    for p in paths:
        evs, d = load_events(p)
        all_events.extend(evs)
        dropped += d
    all_events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": all_events,
            "displayTimeUnit": "ms",
            "metadata": {"dropped": dropped, "sources": list(paths)}}


def _by_request(events: list[dict]) -> dict[str, dict[str, list[dict]]]:
    """trace_id -> span name -> events, for serving.* and router.* spans."""
    out: dict[str, dict[str, list[dict]]] = {}
    for ev in events:
        name = str(ev.get("name", ""))
        if ev.get("ph") != "X" or not (name.startswith("serving.")
                                       or name.startswith("router.")):
            continue
        tid = (ev.get("args") or {}).get("trace_id")
        if not tid:
            continue
        out.setdefault(tid, {}).setdefault(ev["name"], []).append(ev)
    return out


def request_breakdowns(events: list[dict]) -> list[dict]:
    """Per-request phase durations (ms), newest first, roots required."""
    rows = []
    for trace_id, spans in _by_request(events).items():
        roots = spans.get("serving.request")
        if not roots:
            continue  # request still in flight when the trace was cut
        root = roots[0]

        def total_ms(name: str) -> float:
            return sum(e.get("dur", 0.0) for e in spans.get(name, ())) / 1e3

        prefills = spans.get("serving.prefill", ())
        ttft_ms = None
        if prefills:
            p = prefills[0]
            ttft_ms = (p["ts"] + p.get("dur", 0.0) - root["ts"]) / 1e3
        route_hops = len(spans.get("router.route", ()))
        rows.append({
            "trace_id": trace_id,
            "start_ts_us": root["ts"],
            "queue_wait_ms": total_ms("serving.queue_wait"),
            "route_ms": total_ms("router.route") if route_hops else None,
            "route_hops": route_hops,
            "prefill_ms": total_ms("serving.prefill"),
            "decode_ms": total_ms("serving.decode.segment"),
            "decode_segments": len(spans.get("serving.decode.segment", ())),
            "emit_ms": total_ms("serving.emit"),
            "ttft_ms": ttft_ms,
            "total_ms": root.get("dur", 0.0) / 1e3,
            "tokens": (root.get("args") or {}).get("tokens"),
            "tenant": (root.get("args") or {}).get("tenant"),
        })
    rows.sort(key=lambda r: r["start_ts_us"])
    return rows


def render(rows: list[dict], limit: int, slo_ttft_ms: float = 500.0,
           slo_budget: float = 0.05) -> str:
    if not rows:
        return "no completed serving requests in the trace"
    shown = rows[-limit:] if limit else rows

    def ms(v):
        return "-" if v is None else f"{v:.2f}"

    def slo(r):
        if r["ttft_ms"] is None:
            return "-"
        return "MISS" if r["ttft_ms"] > slo_ttft_ms else "ok"

    headers = ("trace_id", "tenant", "queue", "route", "hops", "prefill",
               "decode", "segs", "emit", "ttft", "slo", "total", "tokens")
    cells = [(r["trace_id"][:12], str(r.get("tenant") or "-"),
              ms(r["queue_wait_ms"]), ms(r["route_ms"]),
              str(r["route_hops"] or "-"), ms(r["prefill_ms"]),
              ms(r["decode_ms"]), str(r["decode_segments"]), ms(r["emit_ms"]),
              ms(r["ttft_ms"]), slo(r), ms(r["total_ms"]),
              str(r["tokens"] or "-"))
             for r in shown]
    widths = [max(len(h), *(len(c[i]) for c in cells))
              for i, h in enumerate(headers)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [f"per-request critical path (ms; {len(rows)} completed, "
             f"showing {len(shown)})",
             fmt.format(*headers),
             "-" * (sum(widths) + 2 * (len(widths) - 1))]
    lines.extend(fmt.format(*c) for c in cells)
    ttfts = sorted(r["ttft_ms"] for r in rows if r["ttft_ms"] is not None)
    if ttfts:
        lines.append(
            f"TTFT p50={ttfts[len(ttfts) // 2]:.2f}ms "
            f"p99={ttfts[min(len(ttfts) - 1, (99 * len(ttfts)) // 100)]:.2f}ms "
            f"over {len(ttfts)} requests")
        misses = sum(1 for t in ttfts if t > slo_ttft_ms)
        frac = misses / len(ttfts)
        verdict = ("BREACH" if frac > slo_budget else "within budget")
        lines.append(
            f"SLO serving_ttft (objective {slo_ttft_ms:.0f}ms): "
            f"{misses}/{len(ttfts)} over ({frac:.1%} vs "
            f"{slo_budget:.0%} budget) — {verdict}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("traces", nargs="+",
                    help="Chrome-trace JSON or JSONL export files")
    ap.add_argument("--out", help="write the merged Chrome trace here")
    ap.add_argument("--limit", type=int, default=20,
                    help="max requests to print (0 = all)")
    ap.add_argument("--slo-ttft-ms", type=float, default=500.0,
                    help="TTFT objective for the slo column (ms)")
    ap.add_argument("--slo-budget", type=float, default=0.05,
                    help="error budget: tolerated fraction over objective")
    args = ap.parse_args(argv)

    merged = merge(args.traces)
    if not merged["traceEvents"]:
        print("no events found in any input", file=sys.stderr)
        return 1
    if merged["metadata"]["dropped"]:
        print(f"warning: {merged['metadata']['dropped']} events were dropped "
              "by bounded ring buffers before export", file=sys.stderr)
    if args.out:
        Path(args.out).write_text(json.dumps(merged))
        print(f"merged {len(merged['traceEvents'])} events from "
              f"{len(args.traces)} file(s) -> {args.out}")
    print(render(request_breakdowns(merged["traceEvents"]), args.limit,
                 slo_ttft_ms=args.slo_ttft_ms, slo_budget=args.slo_budget))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
