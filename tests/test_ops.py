"""L0 ops parity tests vs NumPy (SURVEY.md §7 build order step 1)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.ops import activations, convolution, linalg, losses, sampling
from deeplearning4j_tpu.ops.rng import RngStream


class TestActivations:
    def test_sigmoid_matches_numpy(self, rng_np):
        x = rng_np.standard_normal((4, 5)).astype(np.float32)
        got = activations.apply("sigmoid", jnp.asarray(x))
        np.testing.assert_allclose(got, 1 / (1 + np.exp(-x)), rtol=1e-5)

    def test_softmax_rows_sum_to_one(self, rng_np):
        x = jnp.asarray(rng_np.standard_normal((3, 7)).astype(np.float32))
        y = activations.apply("softmax", x)
        np.testing.assert_allclose(np.sum(np.asarray(y), axis=-1), np.ones(3), rtol=1e-5)

    @pytest.mark.parametrize("name", ["sigmoid", "tanh", "relu", "softplus",
                                      "hardtanh", "leakyrelu", "linear", "softsign"])
    def test_derivative_matches_autodiff(self, name, rng_np):
        x = jnp.asarray(rng_np.standard_normal((11,)).astype(np.float32)) * 2
        fn = activations.get(name)
        want = jax.vmap(jax.grad(lambda v: fn(v[None])[0]))(x)
        got = activations.apply_derivative(name, x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            activations.get("nope")


class TestLosses:
    def test_mcxent_known_value(self):
        labels = jnp.array([[1.0, 0.0], [0.0, 1.0]])
        out = jnp.array([[0.8, 0.2], [0.4, 0.6]])
        want = -(np.log(0.8) + np.log(0.6)) / 2
        np.testing.assert_allclose(losses.score("mcxent", labels, out), want, rtol=1e-5)

    def test_mse_known_value(self):
        labels = jnp.array([[1.0, 0.0]])
        out = jnp.array([[0.0, 0.0]])
        np.testing.assert_allclose(losses.score("mse", labels, out), 0.5, rtol=1e-6)

    @pytest.mark.parametrize("name", [lf.value for lf in losses.LossFunction])
    def test_all_losses_finite_and_differentiable(self, name, rng_np):
        labels = jnp.asarray(np.eye(4, dtype=np.float32)[rng_np.integers(0, 4, 6)])
        logits = jnp.asarray(rng_np.standard_normal((6, 4)).astype(np.float32))
        out = jax.nn.softmax(logits)
        val = losses.score(name, labels, out)
        assert np.isfinite(float(val))
        g = jax.grad(lambda o: losses.score(name, labels, jax.nn.softmax(o)))(logits)
        assert np.all(np.isfinite(np.asarray(g)))

    def test_xent_penalizes_wrong_more(self):
        labels = jnp.array([[1.0, 0.0]])
        good = losses.score("xent", labels, jnp.array([[0.9, 0.1]]))
        bad = losses.score("xent", labels, jnp.array([[0.1, 0.9]]))
        assert float(bad) > float(good)


class TestConvolution:
    def test_conv2d_matches_naive(self, rng_np):
        x = rng_np.standard_normal((2, 3, 6, 6)).astype(np.float32)
        w = rng_np.standard_normal((4, 3, 3, 3)).astype(np.float32)
        got = np.asarray(convolution.conv2d(jnp.asarray(x), jnp.asarray(w),
                                            precision=jax.lax.Precision.HIGHEST))
        want = np.zeros((2, 4, 4, 4), np.float32)
        for n in range(2):
            for o in range(4):
                for i in range(4):
                    for j in range(4):
                        want[n, o, i, j] = np.sum(x[n, :, i:i + 3, j:j + 3] * w[o])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_max_pool(self):
        x = jnp.arange(16, dtype=jnp.float32).reshape(1, 1, 4, 4)
        y = convolution.max_pool(x, (2, 2))
        np.testing.assert_allclose(np.asarray(y)[0, 0], [[5, 7], [13, 15]])

    def test_conv_is_differentiable(self, rng_np):
        # The reference's conv backward is a stub; ours must be real.
        x = jnp.asarray(rng_np.standard_normal((1, 2, 5, 5)).astype(np.float32))
        w = jnp.asarray(rng_np.standard_normal((3, 2, 2, 2)).astype(np.float32))
        g = jax.grad(lambda w_: jnp.sum(convolution.conv2d(x, w_) ** 2))(w)
        assert g.shape == w.shape and np.all(np.isfinite(np.asarray(g)))

    def test_im2col_shape(self, rng_np):
        x = jnp.asarray(rng_np.standard_normal((2, 3, 5, 5)).astype(np.float32))
        cols = convolution.im2col(x, 2, 2)
        assert cols.shape == (2, 3 * 2 * 2, 16)


class TestLinalg:
    def test_gemm_vs_numpy(self, rng_np):
        a = rng_np.standard_normal((3, 4)).astype(np.float32)
        b = rng_np.standard_normal((4, 5)).astype(np.float32)
        np.testing.assert_allclose(
            linalg.gemm(jnp.asarray(a), jnp.asarray(b), precision=jax.lax.Precision.HIGHEST),
            a @ b, rtol=1e-5)

    def test_axpy_iamax_dot(self):
        x = jnp.array([1.0, -5.0, 2.0])
        y = jnp.array([1.0, 1.0, 1.0])
        np.testing.assert_allclose(linalg.axpy(2.0, x, y), [3.0, -9.0, 5.0])
        assert int(linalg.iamax(x)) == 1
        np.testing.assert_allclose(linalg.dot(x, y), -2.0)

    def test_to_flattened(self):
        v = linalg.to_flattened([jnp.ones((2, 2)), jnp.zeros((3,))])
        assert v.shape == (7,)


class TestSampling:
    def test_binomial_mean(self):
        key = jax.random.key(0)
        p = jnp.full((10000,), 0.3)
        s = sampling.binomial(key, p)
        assert abs(float(jnp.mean(s)) - 0.3) < 0.02
        assert set(np.unique(np.asarray(s))) <= {0.0, 1.0}

    def test_dropout_mask_preserves_expectation(self):
        key = jax.random.key(1)
        m = sampling.dropout_mask(key, (100000,), 0.5)
        assert abs(float(jnp.mean(m)) - 1.0) < 0.02

    def test_dropout_zero_rate_is_ones(self):
        m = sampling.dropout_mask(jax.random.key(2), (5,), 0.0)
        np.testing.assert_allclose(m, np.ones(5))

    def test_rng_stream_reproducible(self):
        a = RngStream(7).normal((3,))
        b = RngStream(7).normal((3,))
        np.testing.assert_allclose(a, b)
