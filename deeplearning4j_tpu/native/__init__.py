"""Native C++ host runtime (optional acceleration).

Where the reference leans on JVM-external native code (ND4J's JNI/BLAS) for
host-side heavy lifting, this package holds C++ implementations of the
host-bound hot paths — IDX/CSV parsing, tokenize+count vocab building,
prefetch buffering — built as a shared library (``build.py``) and bound via
ctypes.  Everything has a pure-Python fallback; import of this package never
fails just because the library isn't built.
"""

from . import runtime

__all__ = ["runtime"]
