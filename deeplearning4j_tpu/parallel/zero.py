"""ZeRO layout: the flattened+padded view of a param tree that the sharded
weight update (DESIGN.md §15, arXiv:2004.13336) trains in.

Every leaf of the natural param tree maps to a 1-D vector zero-padded to a
multiple of the dp width, so a ``NamedSharding(mesh, P('dp'))`` over the
(only) axis gives each chip one contiguous, equal-size chunk per leaf.
Optimizer-state leaves mirror the flat tree (the ``state_spec`` contract in
``optimize/transforms``), which is what makes the shard-local
``transform.update`` exact: every transform in this repo is elementwise
over its leaves, so updating 1/ndp of the elements on each chip computes
the same numbers the replicated update would — padding rows carry zero
gradients and are sliced off before the natural view is rebuilt.

The layout is pure metadata (``ShapeDtypeStruct`` trees + cached
shardings): flatten/unflatten are trace-safe and appear both inside the
jitted step (grads, param chunks) and on the host checkpoint path
(``to_natural_host`` gathers shard-local leaves and restores natural
shapes, so the on-disk format is identical across stages and dp widths —
the portable-restore requirement of arXiv:2112.01075).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..optimize import transforms as tfm
from .mesh import DP

tree_map = jax.tree_util.tree_map


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _size(shape) -> int:
    return int(np.prod(shape, dtype=np.int64)) if shape else 1


def flat_padded_size(size: int, n_dp: int) -> int:
    """Length of a natural leaf of ``size`` elements in the flat padded
    ``P('dp')`` layout at width ``n_dp`` — dp-divisible, never empty.
    Module-level twin of ``ZeroLayout.padded_size`` for host code that has
    only the checkpoint metadata, not a live mesh."""
    return max(_round_up(size, n_dp), n_dp)


def host_flat_to_natural(arr: np.ndarray, shape, saved_dp: int) -> np.ndarray:
    """Exact host-side re-split of one flat padded leaf back to its natural
    shape (arXiv:2112.01075 portable redistribution, degenerate host case:
    the padding is zeros by construction, so slicing it off loses nothing
    and no renormalization happens).  Raises ValueError when the length is
    not the padded length of ``shape`` at ``saved_dp``."""
    arr = np.asarray(arr)
    size = _size(shape)
    want = flat_padded_size(size, saved_dp)
    if arr.ndim != 1 or arr.shape[0] != want:
        raise ValueError(
            f"flat leaf has shape {arr.shape}, expected ({want},) for "
            f"natural shape {tuple(shape)} at saved dp={saved_dp}")
    return arr[:size].reshape(shape)


def host_natural_to_flat(arr: np.ndarray, n_dp: int) -> np.ndarray:
    """Exact host-side flatten+pad of one natural leaf for width ``n_dp``."""
    flat = np.asarray(arr).reshape(-1)
    pad = flat_padded_size(flat.shape[0], n_dp) - flat.shape[0]
    if pad:
        flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
    return flat


class ZeroLayout:
    """Static flatten/pad/shard metadata for one (mesh, transform, params).

    Built once at ``init_state`` from abstract shapes only — nothing here
    touches device memory, so constructing a layout is transfer-guard safe.
    """

    def __init__(self, mesh, transform: tfm.GradientTransform, params):
        self.mesh = mesh
        self.n_dp = int(mesh.shape[DP])
        self.transform = transform
        self.natural_params = jax.eval_shape(lambda t: t, params)
        self.natural_tstate = jax.eval_shape(transform.init,
                                             self.natural_params)
        self.flat_sharding = NamedSharding(mesh, P(DP))
        flat_params = jax.eval_shape(self.flatten_tree, self.natural_params)
        self.state_shardings = tfm.state_shardings(
            transform, flat_params, P(DP), mesh)
        # weight-decay classification comes from the NATURAL layout: the
        # ndim >= 2 heuristic is meaningless on 1-D chunks, so the sharded
        # step pushes this mask through decay_mask_override
        self.decay_mask = tree_map(lambda a: a.ndim >= 2, self.natural_params)

    # ---------------------------------------------------------- per-leaf ops
    def padded_size(self, size: int) -> int:
        """Leaf length after zero-padding: dp-divisible, never empty."""
        return max(_round_up(size, self.n_dp), self.n_dp)

    def chunk_size(self, size: int) -> int:
        return self.padded_size(size) // self.n_dp

    def _flatten_leaf(self, x):
        flat = jnp.reshape(x, (-1,))
        pad = self.padded_size(flat.shape[0]) - flat.shape[0]
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        return flat

    # ---------------------------------------------------------- tree ops
    def flatten_tree(self, tree):
        """Natural -> flat padded, leaf by leaf (trace-safe).  Works on any
        tree whose array leaves carry natural shapes — params and the
        optimizer state both, since state leaves mirror param shapes."""
        return tree_map(self._flatten_leaf, tree)

    def unflatten_like(self, flat_tree, natural_template):
        """Flat padded -> natural shapes (trace-safe): slice the pad off,
        reshape to the template leaf's shape."""
        return tree_map(
            lambda v, t: jnp.reshape(v[:_size(t.shape)], t.shape),
            flat_tree, natural_template)

    def chunk_tree(self, flat_tree, idx, natural_template):
        """This chip's contiguous chunk of every flat leaf (inside
        shard_map: ``idx = lax.axis_index(dp)``)."""
        return tree_map(
            lambda v, t: lax.dynamic_slice(
                v, (idx * self.chunk_size(_size(t.shape)),),
                (self.chunk_size(_size(t.shape)),)),
            flat_tree, natural_template)

    # ---------------------------------------------------------- host ops
    def to_natural_host(self, flat_tree, natural_template):
        """Gather shard-local flat leaves to host numpy and rebuild the
        natural layout — the mesh-agnostic checkpoint payload (a zero-N
        checkpoint is byte-compatible with a replicated one, and restores
        onto any dp width)."""
        return tree_map(
            lambda v, t: (np.asarray(v)[:_size(t.shape)].reshape(t.shape)
                          if isinstance(v, (jnp.ndarray, np.ndarray)) else v),
            flat_tree, natural_template)

    def place_flat(self, natural_tree, out_shardings):
        """Natural-layout host/device arrays -> flat padded leaves placed
        per ``out_shardings`` (restore path: reshard onto the CURRENT
        mesh, whatever dp width wrote the checkpoint)."""
        return jax.jit(self.flatten_tree,
                       out_shardings=out_shardings)(natural_tree)
