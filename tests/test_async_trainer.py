"""Async trainer hot loop: lazy losses, bucketed padding, pipelined input.

Pins the PR's acceptance criteria:
- dp-parity: async + bucketed ``fit`` produces the same losses as the
  synchronous per-step path on the same data/seed,
- exactness: the padding mask keeps the loss average exact (a padded
  step's loss equals the loss_fn evaluated on just the real rows),
- bounded compilation: a ragged fit compiles once per BUCKET, not once
  per distinct shape (``train_step.recompile``),
- fences: checkpoints resolve the pending-loss ring first; the ring
  self-fences at ``max_pending``,
- streaming: ``fit`` consumes one-shot generators without ``list(data)``,
- ``prefetch_to_device``: empty/size-1/sharded/threaded lifecycles.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterator import prefetch_to_device
from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel import DataParallelTrainer, LazyLoss
from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
from deeplearning4j_tpu.parallel.mesh import DP, local_mesh


def _toy(seed=0, d=6):
    rng = np.random.default_rng(seed)
    w = rng.normal(size=(d, 1))

    def loss_fn(p, x, y, key=None):
        return ((x @ p["w"] - y) ** 2).mean()

    params = {"w": np.zeros((d, 1), np.float32)}
    return params, loss_fn, w, rng


def _ragged_batches(rng, w, sizes, d=6):
    out = []
    for n in sizes:
        x = rng.normal(size=(n, d)).astype(np.float32)
        out.append(DataSet(x, (x @ w).astype(np.float32)))
    return out


RAGGED = [32, 17, 9, 23, 32, 5, 29, 13]


# --------------------------------------------------------------- parity
@pytest.mark.no_implicit_transfers
def test_async_fit_matches_sync_fit_on_ragged_batches():
    """Acceptance: async + bucketed == synchronous per-step, same data/seed."""
    params, loss_fn, w, rng = _toy()
    data = _ragged_batches(rng, w, RAGGED)

    def make():
        return DataParallelTrainer(loss_fn, T.chain(T.momentum(0.9),
                                                    T.sgd_lr(0.01)))

    t_async = make()
    s_a, l_async = t_async.fit(t_async.init_state(params), data, epochs=2,
                               async_dispatch=True, resolve_every=3)
    t_sync = make()
    s_s, l_sync = t_sync.fit(t_sync.init_state(params), data, epochs=2,
                             async_dispatch=False)
    assert len(l_async) == len(l_sync) == 2 * len(RAGGED)
    np.testing.assert_allclose(l_async, l_sync, atol=1e-6)
    for a, b in zip(jax.tree.leaves(s_a.params), jax.tree.leaves(s_s.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_padded_step_loss_is_exact():
    """The validity mask makes the padded bucket's loss equal the loss of
    the REAL rows alone — padding must not dilute the average."""
    params, loss_fn, w, rng = _toy()
    x = rng.normal(size=(13, 6)).astype(np.float32)
    y = (x @ w).astype(np.float32)
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.0))  # lr 0: params fixed
    state = trainer.init_state(params)
    direct = float(loss_fn({"w": jnp.zeros((6, 1))}, x, y))
    _, lazy = trainer.step(state, x, y)  # 13 -> bucket 16, 3 padded rows
    assert float(lazy) == pytest.approx(direct, abs=1e-5)


# --------------------------------------------------------------- buckets
def test_ragged_fit_compiles_once_per_bucket():
    """Acceptance: #compilations == #buckets, not #distinct shapes."""
    params, loss_fn, w, rng = _toy()
    data = _ragged_batches(rng, w, RAGGED)
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    trainer.fit(trainer.init_state(params), data, epochs=2)
    counters = METRICS.snapshot()["counters"]
    # sizes 32,29,23,17 -> 32; 13,9 -> 16; 5 -> 8: three buckets
    assert counters["train_step.recompile"] == 3
    assert len(trainer._step_cache) == 3
    assert counters["train_step.iterations"] == 2 * len(RAGGED)


def test_oversized_batch_gets_own_bucket():
    params, loss_fn, w, rng = _toy()
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    state = trainer.init_state(params)
    state, _ = trainer.step(state, *_xy(rng, w, 16))   # nominal 16
    state, _ = trainer.step(state, *_xy(rng, w, 40))   # > nominal: 40
    state, _ = trainer.step(state, *_xy(rng, w, 7))    # pow2 -> 8
    assert sorted(trainer._step_cache) == [8, 16, 40]


def _xy(rng, w, n, d=6):
    x = rng.normal(size=(n, d)).astype(np.float32)
    return x, (x @ w).astype(np.float32)


# --------------------------------------------------------------- lazy ring
def test_lazy_loss_handle():
    params, loss_fn, w, rng = _toy()
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    state = trainer.init_state(params)
    _, lazy = trainer.step(state, *_xy(rng, w, 16))
    assert isinstance(lazy, LazyLoss)
    assert not lazy.resolved and "pending" in repr(lazy)
    v = float(lazy)
    assert np.isfinite(v) and lazy.resolved
    assert f"{lazy:.3f}" == f"{v:.3f}"
    assert lazy.value() == v  # idempotent after resolution


def test_pending_ring_self_fences_at_max_pending():
    params, loss_fn, w, rng = _toy()
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05), max_pending=4)
    state = trainer.init_state(params)
    for _ in range(10):
        state, _ = trainer.step(state, *_xy(rng, w, 16))
    # 10 = 4 + 4 + 2: two auto-resolves fired, two entries still pending
    assert len(trainer._pending) == 2
    assert METRICS.snapshot()["counters"]["train_step.losses_resolved"] == 8


def test_resolution_point_owns_the_gauges():
    """Loss/throughput gauges appear at resolution, not at dispatch."""
    params, loss_fn, w, rng = _toy()
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    state = trainer.init_state(params)
    losses = []
    for _ in range(3):
        state, lazy = trainer.step(state, *_xy(rng, w, 16))
        losses.append(lazy)
    assert "train_step.loss" not in METRICS.snapshot()["gauges"]
    vals = trainer._resolve_pending()
    snap = METRICS.snapshot()
    assert snap["gauges"]["train_step.loss"] == pytest.approx(vals[-1])
    assert snap["gauges"]["train_step.samples_per_sec"] > 0
    assert snap["timers"]["train_step.execute"]["count"] == 3
    assert [float(l) for l in losses] == vals


# --------------------------------------------------------------- fences
def test_checkpoint_fences_pending_ring(tmp_path):
    params, loss_fn, w, rng = _toy()
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    state = trainer.init_state(params)
    for _ in range(3):
        state, _ = trainer.step(state, *_xy(rng, w, 16))
    assert trainer._pending  # ring is hot
    mgr = CheckpointManager(tmp_path)
    trainer.checkpoint(state, mgr)
    assert not trainer._pending  # fenced before the save read params
    assert METRICS.snapshot()["counters"]["checkpoint.fences"] == 1
    assert mgr.latest_step() == state.step


# --------------------------------------------------------------- streaming
def test_fit_streams_one_shot_generator():
    """fit must not call list(data): a one-shot generator of (x, y) tuples
    with no __len__ streams through, and every loss comes back resolved."""
    params, loss_fn, w, rng = _toy()

    def gen():
        for n in (16, 9, 16, 5):
            yield _xy(rng, w, n)

    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    state, losses = trainer.fit(trainer.init_state(params), gen())
    assert state.step == 4 and len(losses) == 4
    assert all(isinstance(l, float) and np.isfinite(l) for l in losses)
    assert not trainer._pending  # fit's final resolve drained the ring


def test_fit_without_prefetch_matches_prefetched():
    params, loss_fn, w, rng = _toy()
    data = _ragged_batches(rng, w, [16, 9, 12, 16])
    t1 = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    _, l1 = t1.fit(t1.init_state(params), data, prefetch_size=2)
    t2 = DataParallelTrainer(loss_fn, T.sgd_lr(0.05))
    _, l2 = t2.fit(t2.init_state(params), data, prefetch_size=0)
    np.testing.assert_allclose(l1, l2, atol=1e-6)


@pytest.mark.no_implicit_transfers
def test_hogwild_ragged_fit_smoke():
    params, loss_fn, w, rng = _toy()
    data = _ragged_batches(rng, w, [32, 17, 32, 9])
    trainer = DataParallelTrainer(loss_fn, T.sgd_lr(0.05), router="hogwild",
                                  average_every=2)
    state, losses = trainer.fit(trainer.init_state(params), data, epochs=2)
    assert len(losses) == 8 and all(np.isfinite(l) for l in losses)
    final = trainer.final_params(state)
    assert all(np.isfinite(np.asarray(a)).all()
               for a in jax.tree.leaves(final))


# --------------------------------------------------------- prefetch_to_device
def test_prefetch_empty_iterable():
    assert list(prefetch_to_device([])) == []
    assert list(prefetch_to_device(iter([]), size=1)) == []


def test_prefetch_buffer_size_one_preserves_order():
    batches = [(np.full((4, 2), i), np.full((4, 1), -i)) for i in range(5)]
    out = list(prefetch_to_device(iter(batches), size=1))
    assert len(out) == 5
    for i, (a, b) in enumerate(out):
        np.testing.assert_array_equal(np.asarray(a), batches[i][0])
        np.testing.assert_array_equal(np.asarray(b), batches[i][1])


def test_prefetch_explicit_sharding_places_leaves():
    mesh = local_mesh()
    sh = NamedSharding(mesh, P(DP))
    batches = [(np.zeros((16, 4), np.float32), np.zeros((16, 1), np.float32))]
    (a, b), = prefetch_to_device(batches, sharding=sh)
    assert a.sharding == sh and b.sharding == sh
    # non-array leaves (the trainer's python-int sample counts) pass through
    (x, n), = prefetch_to_device([(np.zeros((16, 2), np.float32), 13)],
                                 sharding=sh)
    assert isinstance(n, int) and n == 13


def test_prefetch_host_thread_exits_on_exhaustion():
    batches = ((np.full((4, 2), i), np.full((4, 1), i)) for i in range(6))
    pf = prefetch_to_device(batches, size=2, host_thread=True)
    out = list(pf)
    assert len(out) == 6
    pf.thread.join(timeout=5.0)
    assert not pf.thread.is_alive()  # no leaked worker after exhaustion


def test_prefetch_host_thread_close_mid_stream():
    """Abandoning iteration with a full queue must not leak the worker."""

    def gen():
        for i in range(1000):
            yield (np.full((4, 2), i),)

    pf = prefetch_to_device(gen(), size=2, host_thread=True)
    first = next(pf)
    np.testing.assert_array_equal(np.asarray(first[0]), np.full((4, 2), 0))
    pf.close()
    assert not pf.thread.is_alive()
    pf.close()  # idempotent


@pytest.mark.lockguard
def test_prefetch_host_thread_propagates_source_error():
    def gen():
        yield (np.zeros((4, 2)),)
        raise RuntimeError("boom in the input pipeline")

    pf = prefetch_to_device(gen(), size=2, host_thread=True)
    # the worker may surface the error before or after handing over the
    # staged batch — either way it must raise, and must not leak the thread
    with pytest.raises(RuntimeError, match="boom"):
        for _ in pf:
            pass
    pf.close()
    assert not pf.thread.is_alive()


# --------------------------------------------------------------- registry
def test_observe_many_batches_under_one_histogram():
    METRICS.observe_many("t.batch", [0.1, 0.2, 0.3])
    s = METRICS.snapshot()["timers"]["t.batch"]
    assert s["count"] == 3
    METRICS.observe_many("t.batch", [])
    assert METRICS.snapshot()["timers"]["t.batch"]["count"] == 3
