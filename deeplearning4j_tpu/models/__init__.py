"""Model zoo.

Reference-parity families (SURVEY.md §2.2): MLP/DBN and LeNet-style conv
nets are built from ``nn`` configs (see ``zoo.py``); LSTM classifier with
beam search in ``lstm.py``; the NLP embedding models live in ``..text``.

Beyond-v0 north-star families (BASELINE.json configs): ``transformer.py`` —
a BERT/GPT-class encoder with explicit SPMD sharding (dp/tp/sp with ring
attention) — and ``resnet.py``.
"""

from .lstm import LSTMSequenceModel
from .resnet import ResNet, ResNetConfig
from .transformer import TransformerConfig, TransformerLM
from .zoo import dbn, lenet, mlp, stacked_denoising_autoencoder

__all__ = ["LSTMSequenceModel", "ResNet", "ResNetConfig",
           "TransformerConfig", "TransformerLM",
           "dbn", "lenet", "mlp", "stacked_denoising_autoencoder"]
