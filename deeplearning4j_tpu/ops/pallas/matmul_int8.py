"""Weight-quantized int8 matmul for the serving decode path.

Decode is HBM-bandwidth-bound: each token streams every weight matrix
once for a handful of rows of activations.  Storing the big matrices
(FFN w1/w2 and the LM head — the bulk of the bytes) as int8 with
per-output-channel scales cuts that traffic 4x; the kernel dequantizes
tiles in VMEM on the way to the MXU, so full-precision weights never
exist on the wire.

Quantization is symmetric absmax per output channel: ``scale[n] =
max(|w[:, n]|) / 127``, ``q = round(w / scale)``.  The activation side
stays in the model's compute dtype (weight-only quantization — no
calibration data needed, and the error is a fixed, testable function of
the weights).

Opt-in behind ``ServingConfig(int8_decode=True)``; adoption on the
serving path is gated on token-level top-1 agreement with the f32
decode (``tolerances["min"]["top1_agree"]``) through the same auto-pick
chain as every kernel.  Differentiable wrt the activations only (the
quantized weights are frozen serving artifacts) — the custom_vjp hands
the int8 leaf a float0 cotangent.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..flash_attention import _VMEM
from . import registry


class QuantizedLinear(NamedTuple):
    """Per-output-channel int8 weight: ``w ≈ q * scale``.  NamedTuple =
    automatic pytree, so it rides inside param dicts through jit."""

    q: jax.Array       # (K, N) int8
    scale: jax.Array   # (N,) f32


def quantize(w) -> QuantizedLinear:
    """Symmetric absmax quantization of a (K, N) matrix, per column."""
    w32 = jnp.asarray(w, jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=0)
    scale = jnp.where(amax > 0, amax, 1.0) / 127.0
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantizedLinear(q=q, scale=scale)


def dequantize(qw: QuantizedLinear) -> jax.Array:
    return qw.q.astype(jnp.float32) * qw.scale


def reference_int8_matmul(x, qw: QuantizedLinear):
    """jnp ground truth: dequantize then matmul, f32 out."""
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1]).astype(jnp.float32)
    out = jnp.dot(x2, dequantize(qw), preferred_element_type=jnp.float32)
    return out.reshape(*lead, qw.q.shape[1])


def _kernel(x_ref, q_ref, s_ref, o_ref):
    x = x_ref[...].astype(jnp.float32)                    # (M, K)
    w = q_ref[...].astype(jnp.float32)                    # (K, BN) dequant
    o_ref[...] = jnp.dot(x, w,
                         preferred_element_type=jnp.float32) * s_ref[...]


def _largest_divisor(n: int, cap: int) -> int:
    d = min(cap, n)
    while n % d:
        d -= 1
    return d


def _int8_call(x2, q, s2, block_n, interpret):
    m, k = x2.shape
    n = q.shape[1]
    bn = _largest_divisor(n, block_n)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    return pl.pallas_call(
        _kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((m, k), lambda j: (0, 0), **mem),
            pl.BlockSpec((k, bn), lambda j: (0, j), **mem),
            pl.BlockSpec((1, bn), lambda j: (0, j), **mem),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda j: (0, j), **mem),
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        interpret=interpret,
    )(x2, q, s2)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _int8_mm(x2, q, s2, block_n, interpret):
    return _int8_call(x2, q, s2, block_n, interpret)


def _int8_mm_fwd(x2, q, s2, block_n, interpret):
    return _int8_call(x2, q, s2, block_n, interpret), (x2, q, s2)


def _int8_mm_bwd(block_n, interpret, res, g):
    x2, q, s2 = res
    g32 = g.astype(jnp.float32) * s2                      # fold scale in
    dx = jnp.dot(g32, q.astype(jnp.float32).T).astype(x2.dtype)
    dq = np.zeros(q.shape, jax.dtypes.float0)             # frozen weights
    ds = jnp.zeros_like(s2)
    return dx, dq, ds


_int8_mm.defvjp(_int8_mm_fwd, _int8_mm_bwd)


def int8_matmul(x, qw: QuantizedLinear, *, block_n: int = 512,
                interpret: bool | None = None):
    """``x @ (q * scale)`` on (..., K) activations, f32 out (callers cast
    — the decode head wants f32 logits, the FFN re-casts to the compute
    dtype).  ``interpret=None`` auto-selects interpret mode off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    x2 = x.reshape(-1, x.shape[-1])
    out = _int8_mm(x2, qw.q, qw.scale.reshape(1, -1), block_n, interpret)
    return out.reshape(*lead, qw.q.shape[1])


def quantize_params_for_decode(params: dict, cfg) -> dict:
    """Serving-side tree transform: add int8 copies of the decode path's
    bandwidth-heavy matrices (FFN w1/w2 per layer + the LM head), drop
    the f32 FFN originals from the copy so the decode step streams 4x
    fewer bytes.  ``decode_step``/``_ffn`` take the int8 path purely on
    key presence, so training trees (no ``*_q`` keys) are untouched."""
    layers = []
    for lp in params["layers"]:
        lp2 = {k: v for k, v in lp.items() if k not in ("w1", "w2")}
        lp2["w1_q"] = quantize(lp["w1"])
        lp2["w2_q"] = quantize(lp["w2"])
        layers.append(lp2)
    head = (params["tok_embed"].T if cfg.tie_embeddings
            else params["lm_head"])
    return dict(params, layers=layers, head_q=quantize(head))


def top1_agreement(logits_a, logits_b) -> jax.Array:
    """Fraction of rows whose argmax agrees — the serving int8 adoption
    gate's statistic (token-level greedy agreement)."""
    return jnp.mean((jnp.argmax(logits_a, axis=-1)
                     == jnp.argmax(logits_b, axis=-1)).astype(jnp.float32))


def _f32_matmul(x, qw: QuantizedLinear, **_):
    """The incumbent: plain matmul against the dequantized (i.e. full
    precision, as served today) weights."""
    return reference_int8_matmul(x, qw)


registry.register(registry.KernelCandidate(
    kind="int8_matmul", name="pallas_int8", fn=int8_matmul,
    reference=reference_int8_matmul,
    blocks=({"block_n": 256}, {"block_n": 512}, {"block_n": 1024}),
    # vs the int8 reference the kernel must be near-exact; adoption on
    # the serving path additionally needs token-level greedy agreement
    tolerances={"max_err": 1e-3, "min": {"top1_agree": 0.999}},
))

registry.register(registry.KernelCandidate(
    kind="int8_matmul", name="f32", fn=_f32_matmul,
    reference=reference_int8_matmul, source="xla",
))
