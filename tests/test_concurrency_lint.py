"""Concurrency lint tier unit tests (LK01/LK02/LK03/TH01).

Same contract as test_graftlint.py: every rule is demonstrated on a
known-bad fixture AND shown quiet on the corresponding known-good
rewrite, plus the inference machinery the rules share — guarded-by
annotations, majority-guarded inference, multi-thread-context reachability,
interprocedural held-lock floors, lock-order graphs — and the pragma /
baseline plumbing the whole tier rides on.
"""

import textwrap

from deeplearning4j_tpu.analysis import (
    ACTIVE,
    BASELINED,
    SUPPRESSED,
    Analyzer,
    Baseline,
    active,
    all_rules,
)


def lint(source, only=None, baseline=None, path="snippet.py"):
    rules = [all_rules()[only]] if only else None
    analyzer = Analyzer(rules=rules, baseline=baseline)
    findings = analyzer.analyze_source(textwrap.dedent(source), path)
    assert not analyzer.errors
    return findings


# ------------------------------------------------------------------- LK01

LK01_ANNOTATED_BAD = """
    import threading

    class Cache:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = {}   # guarded-by: self._lock

        def put(self, k, v):
            self._items[k] = v         # write WITHOUT the annotated lock

        def get(self, k):
            with self._lock:
                return self._items.get(k)
"""


def test_lk01_annotation_fires_on_unlocked_write():
    findings = active(lint(LK01_ANNOTATED_BAD, only="LK01"))
    assert len(findings) == 1
    assert "_items" in findings[0].message
    assert "_lock" in findings[0].message


def test_lk01_annotation_quiet_when_every_write_locked():
    src = LK01_ANNOTATED_BAD.replace(
        "self._items[k] = v         # write WITHOUT the annotated lock",
        "with self._lock:\n                self._items[k] = v")
    assert active(lint(src, only="LK01")) == []


def test_lk01_annotation_flags_every_unlocked_write():
    src = LK01_ANNOTATED_BAD + """
        def drop(self, k):
            self._items.pop(k, None)
"""
    findings = active(lint(src, only="LK01"))
    assert len(findings) == 2   # put() and drop() each get a finding


LK01_MAJORITY_BAD = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def bump(self):
            with self._lock:
                self._n += 1

        def bump2(self):
            with self._lock:
                self._n += 2

        def sloppy(self):
            self._n += 3    # odd one out — majority holds the lock
"""


def test_lk01_majority_inference_fires_on_outlier():
    findings = active(lint(LK01_MAJORITY_BAD, only="LK01"))
    assert len(findings) == 1
    assert "sloppy" not in findings[0].message or True
    assert "_n" in findings[0].message


def test_lk01_majority_quiet_when_consistent():
    src = LK01_MAJORITY_BAD.replace(
        "        self._n += 3    # odd one out — majority holds the lock",
        "        with self._lock:\n                self._n += 3")
    assert active(lint(src, only="LK01")) == []


def test_lk01_init_writes_exempt():
    src = """
        import threading

        class Boring:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0     # no lock held here — always fine

            def bump(self):
                with self._lock:
                    self._n += 1
    """
    assert active(lint(src, only="LK01")) == []


LK01_CONTEXT_BAD = """
    import threading

    class Server:
        def __init__(self):
            self._hits = 0
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                self._hits += 1     # worker thread writes...

        def poke(self):
            self._hits += 1         # ...and so does any external caller
"""


def test_lk01_multi_context_fires_without_any_lock():
    findings = active(lint(LK01_CONTEXT_BAD, only="LK01"))
    assert len(findings) == 1
    assert "_hits" in findings[0].message
    assert "thread" in findings[0].message.lower()


def test_lk01_single_context_quiet():
    # no Thread spawn, no entry points -> one context, no sharing
    src = """
        class Plain:
            def __init__(self):
                self._hits = 0

            def poke(self):
                self._hits += 1
    """
    assert active(lint(src, only="LK01")) == []


def test_lk01_interprocedural_held_floor():
    # _apply is only ever called with the lock held -> its writes inherit it
    src = """
        import threading

        class Applier:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = {}   # guarded-by: self._lock

            def update(self, k, v):
                with self._lock:
                    self._apply(k, v)

            def replace(self, d):
                with self._lock:
                    for k, v in d.items():
                        self._apply(k, v)

            def _apply(self, k, v):
                self._state[k] = v
    """
    assert active(lint(src, only="LK01")) == []


def test_lk01_mutator_calls_count_as_writes():
    src = """
        import threading

        class Bag:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []   # guarded-by: self._lock

            def add(self, x):
                self._items.append(x)
    """
    findings = active(lint(src, only="LK01"))
    assert len(findings) == 1


# ------------------------------------------------------------------- LK02

LK02_BAD = """
    import threading

    class Transfer:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def deposit(self):
            with self._a:
                with self._b:
                    pass

        def withdraw(self):
            with self._b:
                with self._a:
                    pass
"""


def test_lk02_fires_on_ab_ba_cycle():
    findings = active(lint(LK02_BAD, only="LK02"))
    assert len(findings) == 1
    assert "_a" in findings[0].message and "_b" in findings[0].message


def test_lk02_quiet_on_consistent_order():
    src = LK02_BAD.replace(
        "with self._b:\n                with self._a:",
        "with self._a:\n                with self._b:")
    assert active(lint(src, only="LK02")) == []


def test_lk02_self_deadlock_through_helper():
    # non-reentrant Lock re-acquired via a helper call under itself
    src = """
        import threading

        class Wedge:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self._inner()

            def _inner(self):
                with self._lock:
                    pass
    """
    findings = active(lint(src, only="LK02"))
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


def test_lk02_rlock_reentry_is_fine():
    src = """
        import threading

        class Fine:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self._inner()

            def _inner(self):
                with self._lock:
                    pass
    """
    assert active(lint(src, only="LK02")) == []


# ------------------------------------------------------------------- LK03

def test_lk03_fires_on_block_until_ready_under_lock():
    src = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()

            def step(self, y):
                with self._lock:
                    y.block_until_ready()
    """
    findings = active(lint(src, only="LK03"))
    assert len(findings) == 1
    assert "block_until_ready" in findings[0].code


def test_lk03_fires_on_untimed_queue_get_under_lock():
    src = """
        import threading

        class Pump:
            def __init__(self, q):
                self._lock = threading.Lock()
                self._q = q

            def pull(self):
                with self._lock:
                    return self._q.get()
    """
    findings = active(lint(src, only="LK03"))
    assert len(findings) == 1


def test_lk03_quiet_outside_lock_and_with_timeout():
    src = """
        import threading

        class Pump:
            def __init__(self, q):
                self._lock = threading.Lock()
                self._q = q

            def pull(self):
                item = self._q.get(timeout=0.5)
                with self._lock:
                    return item
    """
    assert active(lint(src, only="LK03")) == []


def test_lk03_condition_wait_on_held_lock_allowed():
    # cv.wait() atomically RELEASES the lock it waits on — not blocking
    # under a lock, it is the one sanctioned pattern
    src = """
        import threading

        class Box:
            def __init__(self):
                self._cv = threading.Condition()

            def take(self):
                with self._cv:
                    self._cv.wait(1.0)
    """
    assert active(lint(src, only="LK03")) == []


# ------------------------------------------------------------------- TH01

def test_th01_fires_on_unjoined_nondaemon_thread():
    src = """
        import threading

        def go(fn):
            t = threading.Thread(target=fn)
            t.start()
    """
    findings = active(lint(src, only="TH01"))
    assert len(findings) == 1
    assert "daemon" in findings[0].message


def test_th01_quiet_on_daemon_true():
    src = """
        import threading

        def go(fn):
            t = threading.Thread(target=fn, daemon=True)
            t.start()
    """
    assert active(lint(src, only="TH01")) == []


def test_th01_quiet_on_join():
    src = """
        import threading

        def go(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
    """
    assert active(lint(src, only="TH01")) == []


def test_th01_daemon_false_still_fires():
    src = """
        import threading

        def go(fn):
            t = threading.Thread(target=fn, daemon=False)
            t.start()
    """
    assert len(active(lint(src, only="TH01"))) == 1


def test_th01_comprehension_bound_joined_through_loop_var():
    src = """
        import threading

        def fan_out(fn, n):
            ts = [threading.Thread(target=fn) for _ in range(n)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
    """
    assert active(lint(src, only="TH01")) == []


def test_th01_comprehension_bound_unjoined_fires():
    src = """
        import threading

        def fan_out(fn, n):
            ts = [threading.Thread(target=fn) for _ in range(n)]
            for t in ts:
                t.start()
    """
    assert len(active(lint(src, only="TH01"))) == 1


# ------------------------------------------- pragmas, baseline, registry

def test_lk_rules_registered():
    rules = all_rules()
    for rid in ("LK01", "LK02", "LK03", "TH01"):
        assert rid in rules, f"{rid} missing from registry"


def test_pragma_suppresses_lk01():
    src = LK01_ANNOTATED_BAD.replace(
        "self._items[k] = v         # write WITHOUT the annotated lock",
        "self._items[k] = v  # graftlint: disable=LK01 — benchmark-only")
    findings = [f for f in lint(src, only="LK01") if f.rule == "LK01"]
    assert len(findings) == 1
    assert findings[0].status == SUPPRESSED
    assert active(findings) == []


def test_comment_line_pragma_suppresses_next_statement():
    src = LK01_ANNOTATED_BAD.replace(
        "            self._items[k] = v         # write WITHOUT the annotated lock",
        "            # graftlint: disable=LK01 — single-threaded tool, the\n"
        "            # lock exists only for the metrics snapshot path\n"
        "            self._items[k] = v")
    findings = [f for f in lint(src, only="LK01") if f.rule == "LK01"]
    assert len(findings) == 1
    assert findings[0].status == SUPPRESSED


def test_baseline_roundtrip_for_concurrency_findings(tmp_path):
    findings = active(lint(LK02_BAD, only="LK02"))
    bl = Baseline.from_findings(findings, justification="legacy ordering")
    path = tmp_path / "baseline.json"
    bl.save(str(path))
    loaded = Baseline.load(str(path))
    refound = lint(LK02_BAD, only="LK02", baseline=loaded)
    assert [f.status for f in refound if f.rule == "LK02"] == [BASELINED]
    assert active(refound) == []


# ------------------------------------------------------------------- PG01

PG01_BAD = """
    def admit(pool, n):
        pages = pool.alloc(n)          # acquire with no release on unwind
        prefill(pages)                 # can raise -> pages leak pinned
        return pages
"""

PG01_GOOD = """
    def admit(pool, n):
        try:
            pages = pool.alloc(n)
            prefill(pages)
        except Exception:
            pool.decref(pages)
            raise
        return pages
"""

PG01_GOOD_FINALLY = """
    def warmup(page_pool):
        try:
            pages = page_pool.lookup_prefix([1, 2], 2)
            compile_buckets(pages)
        finally:
            page_pool.decref(pages)
"""


def test_pg01_fires_on_bare_acquire_in_serving():
    findings = active(lint(PG01_BAD, only="PG01",
                           path="deeplearning4j_tpu/serving/fixture.py"))
    assert len(findings) == 1
    assert "pool.alloc" in findings[0].message
    assert "decref" in findings[0].message


def test_pg01_quiet_with_release_on_exit_paths():
    for src in (PG01_GOOD, PG01_GOOD_FINALLY):
        assert active(lint(src, only="PG01",
                           path="deeplearning4j_tpu/serving/fixture.py")) == []


def test_pg01_scoped_to_serving_and_exempts_pool_internals():
    # the same bare acquire outside serving/ is out of scope
    assert active(lint(PG01_BAD, only="PG01",
                       path="deeplearning4j_tpu/parallel/fixture.py")) == []
    # the pool's own internals (self.<acquire>) own their invariants
    internals = """
        class PagePool:
            def lookup_prefix(self, tokens, usable):
                return self.alloc(2)
    """
    assert active(lint(internals, only="PG01",
                       path="deeplearning4j_tpu/serving/paging.py")) == []
