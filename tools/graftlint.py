"""graftlint CLI — static JAX/TPU hazard analysis for this repo.

Usage (from the repo root):

    python -m tools.graftlint --check [PATHS...]     # CI gate: fail on NEW
    python -m tools.graftlint [PATHS...]             # report everything
    python -m tools.graftlint --json [PATHS...]      # machine-readable
    python -m tools.graftlint --write-baseline       # accept current state
    python -m tools.graftlint --rules                # list every rule

Defaults: PATHS = ``deeplearning4j_tpu``, baseline =
``graftlint.baseline.json`` at the repo root.  ``--check`` exits 1 when
any finding is neither suppressed inline (``# graftlint: disable=RULE``)
nor carried in the baseline; it also exits 1 on unparseable files.
``--stale`` lists baseline entries whose finding no longer fires (fixed
hazards whose ledger entry should be deleted).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/graftlint.py` direct runs
    sys.path.insert(0, _REPO_ROOT)

from deeplearning4j_tpu.analysis import (  # noqa: E402
    Analyzer,
    Baseline,
    active,
    all_rules,
    emit_metrics,
    summarize,
    to_json,
    to_text,
)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "graftlint.baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based JAX/TPU hazard analyzer (HS01 host syncs, "
                    "RC01 recompiles, RNG01 key reuse, DON01 use-after-"
                    "donate, TB01 traced branches, HOT02 uninstrumented "
                    "hot loops, LK01-LK03/TH01 concurrency; bare --rules "
                    "prints the full table)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to analyze (default: deeplearning4j_tpu)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 on any non-suppressed, non-baselined finding")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one machine-readable JSON report on stdout")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (default: %(default)s)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write all current active findings to the baseline "
                        "(with TODO justifications) and exit 0")
    p.add_argument("--stale", action="store_true",
                   help="also report baseline entries that no longer fire")
    p.add_argument("--all", action="store_true", dest="show_all",
                   help="text mode: show suppressed/baselined findings too")
    p.add_argument("--no-metrics", action="store_true",
                   help="skip publishing graftlint.violations.* gauges")
    p.add_argument("--rules", nargs="?", const="", default=None,
                   help="comma-separated rule ids to run (default: all); "
                        "bare --rules lists every registered rule and exits")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    paths = args.paths or [os.path.join(_REPO_ROOT, "deeplearning4j_tpu")]

    if args.rules == "":          # bare --rules: print the registry
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}  {rule.title}")
        return 0

    rules = None
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        registry = all_rules()
        unknown = wanted - set(registry)
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}; "
                  f"known: {', '.join(sorted(registry))}", file=sys.stderr)
            return 2
        rules = [registry[r] for r in sorted(wanted)]

    baseline = Baseline.load(args.baseline)
    analyzer = Analyzer(rules=rules, baseline=baseline, root=_REPO_ROOT)
    findings = analyzer.analyze_paths(paths)

    if args.write_baseline:
        Baseline.from_findings(active(findings)).save(args.baseline)
        print(f"graftlint: wrote {len(active(findings))} entries to "
              f"{args.baseline}")
        return 0

    if not args.no_metrics:
        try:
            emit_metrics(findings)
        except Exception:
            pass  # metrics are best-effort; the lint verdict is the product

    new = active(findings)
    if args.as_json:
        payload = to_json(findings, errors=analyzer.errors)
        if args.stale:
            payload["stale_baseline_entries"] = baseline.stale_entries(findings)
        print(json.dumps(payload, indent=2))
    else:
        text = to_text(findings, show_all=args.show_all)
        if text:
            print(text)
        for err in analyzer.errors:
            print(f"graftlint: parse error: {err}", file=sys.stderr)
        if args.stale:
            for e in baseline.stale_entries(findings):
                print(f"graftlint: stale baseline entry "
                      f"{e['rule']} {e['path']}: {e['code']!r}")
        s = summarize(findings)
        print(f"graftlint: {s['total']} finding(s) — {s['active']} active, "
              f"{s['suppressed']} suppressed, {s['baselined']} baselined")

    if args.check and (new or analyzer.errors):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
