"""Runtime race detection: instrumented locks + Eraser-style write checks.

The static concurrency rules (LK01-LK03, TH01) reason about the *code*;
this module watches the *execution*.  ``LockGuard.install()`` swaps
``threading.Lock``/``threading.RLock`` for wrappers that track, per
thread, the stack of locks currently held:

- every ``acquire`` while other locks are held adds edges to a runtime
  lock-order graph; an acquisition that closes a cycle (thread 1 takes
  A then B, thread 2 takes B then A) is reported as a **lock-order
  inversion** — the deadlock is detected even when the interleaving that
  would actually wedge never happens in this run;
- ``watch(obj)`` applies the Eraser lockset algorithm (Savage et al.,
  SOSP '97) to an object's attribute writes: after an attribute has been
  written by two or more threads, the intersection of the locksets held
  at each write must stay non-empty — an empty intersection means no
  single lock consistently guards the field and is reported as an
  **unguarded write**.

Opt-in only: nothing is patched at import.  Tests use the
``@pytest.mark.lockguard`` marker (conftest installs around the test and
asserts zero violations) or set ``DL4J_TPU_LOCKGUARD=1`` to run a whole
session instrumented.  ``tools/serving_smoke.py --lockguard`` and the
chaos harness serving leg use the same switch.

Known limits (inherited from Eraser): the initialization handoff —
object built on one thread, then published to a worker via
``Thread.start()``'s happens-before edge — looks like an unguarded
shared write to a pure lockset algorithm.  ``watch()`` is therefore
applied *after* the handoff point (e.g. after ``engine.start()``), which
makes the worker the exclusive first owner and keeps the signal clean.
Locks created before ``install()`` (or via ``from threading import
Lock``) are not instrumented; the serving/training stack constructs its
locks lazily enough that marker-scoped installs see them.
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import os
import sys
import threading

ENV_LOCKGUARD = "DL4J_TPU_LOCKGUARD"

_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_ON_VALUES = {"1", "on", "true", "yes", "enabled"}


def enabled_from_env() -> bool:
    """True when ``DL4J_TPU_LOCKGUARD`` asks for session-wide lockguard."""
    return os.environ.get(ENV_LOCKGUARD, "").strip().lower() in _ON_VALUES


@dataclasses.dataclass(frozen=True)
class Violation:
    """One runtime finding — a lock-order cycle or an unguarded write."""

    kind: str                    # "lock-order" | "unguarded-write"
    message: str
    thread: str                  # name of the thread that completed it
    details: tuple = ()          # cycle lock labels / (class, attr)

    def __str__(self) -> str:    # report/assert readability
        return f"[{self.kind}] {self.message} (thread={self.thread})"


def _creation_site() -> str:
    """file:line of the frame that called the lock factory (labels)."""
    f = sys._getframe(2)
    skip = (__file__, threading.__file__)
    while f is not None and f.f_code.co_filename in skip:
        f = f.f_back
    if f is None:
        return "<unknown>"
    fn = f.f_code.co_filename
    for marker in ("/deeplearning4j_tpu/", "/tools/", "/tests/"):
        i = fn.find(marker)
        if i >= 0:
            fn = fn[i + 1:]
            break
    return f"{fn}:{f.f_lineno}"


_LOCK_IDS = itertools.count(1)


class _GuardedLock:
    """Instrumented ``threading.Lock``.

    Deliberately does NOT expose ``_release_save``/``_acquire_restore``:
    ``threading.Condition`` then falls back to its plain-lock defaults,
    which route through :meth:`acquire`/:meth:`release` — condition
    waits stay visible to the hold tracker for free.
    """

    __slots__ = ("_inner", "_guard", "_id", "_label")

    _reentrant = False

    def __init__(self, guard: "LockGuard", inner, label: str):
        self._inner = inner
        self._guard = guard
        self._id = next(_LOCK_IDS)
        self._label = label

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:  # failed non-blocking probes hold nothing — not recorded
            self._guard._note_acquire(self)
        return ok

    def release(self) -> None:
        self._guard._note_release(self)
        self._inner.release()

    def locked(self) -> bool:
        probe = getattr(self._inner, "locked", None)
        return probe() if probe is not None else False

    def __enter__(self) -> "_GuardedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        kind = "RLock" if self._reentrant else "Lock"
        return f"<lockguard.{kind} #{self._id} from {self._label}>"


class _GuardedRLock(_GuardedLock):
    """Instrumented ``threading.RLock``.

    Unlike the plain wrapper it DOES delegate the Condition protocol
    (``_release_save`` fully releases, ``_acquire_restore`` re-acquires
    at the saved count) so ``Condition.wait`` on a reentrant lock keeps
    the hold stack truthful instead of corrupting the count.
    """

    __slots__ = ()

    _reentrant = True

    def _release_save(self):
        count = self._guard._note_release_all(self)
        state = self._inner._release_save()
        return (count, state)

    def _acquire_restore(self, saved) -> None:
        count, state = saved
        self._inner._acquire_restore(state)
        self._guard._note_acquire(self, restore_count=count)

    def _is_owned(self) -> bool:
        return self._inner._is_owned()


class LockGuard:
    """The detector: lock-order graph + Eraser lockset state.

    All internal metadata is protected by an ORIGINAL (pre-patch) lock so
    the guard never traffics through its own instrumentation.
    """

    def __init__(self) -> None:
        self._meta = _REAL_LOCK()
        self._tls = threading.local()
        self._installed = False
        self._edges: dict[int, dict[int, str]] = {}   # A -> {B: site}
        self._labels: dict[int, str] = {}
        self._violations: list[Violation] = []
        self._seen_cycles: set[frozenset] = set()
        # Eraser state per (object id, attr): [owner_ident, writers set,
        # candidate lock-id set | None while exclusive, reported flag]
        self._eraser: dict[tuple[int, str], list] = {}
        self._watched: dict[int, object] = {}   # id -> obj (keeps it alive)
        self._watch_classes: dict[type, type] = {}

    # ------------------------------------------------------------ install
    def install(self) -> "LockGuard":
        """Patch ``threading.Lock``/``RLock`` (idempotent).

        ``queue``, ``threading.Condition`` and ``threading.Event`` all
        resolve these names from the ``threading`` module at call time,
        so their internal locks come back instrumented too.
        """
        with self._meta:
            if self._installed:
                return self
            self._installed = True
        guard = self

        def make_lock():
            return _GuardedLock(guard, _REAL_LOCK(), _creation_site())

        def make_rlock():
            return _GuardedRLock(guard, _REAL_RLOCK(), _creation_site())

        threading.Lock = make_lock
        threading.RLock = make_rlock
        return self

    def uninstall(self) -> None:
        """Restore the real factories; live wrapped locks keep working."""
        with self._meta:
            if not self._installed:
                return
            self._installed = False
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        for obj in list(self._watched.values()):
            self.unwatch(obj)

    @property
    def installed(self) -> bool:
        return self._installed

    # ------------------------------------------------------- hold stacks
    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []   # entries: [lock, count]
        return st

    def _held_ids(self) -> frozenset:
        return frozenset(e[0]._id for e in self._stack())

    def _note_acquire(self, lock: _GuardedLock,
                      restore_count: int = 1) -> None:
        stack = self._stack()
        if lock._reentrant:
            for entry in stack:
                if entry[0] is lock:     # re-entry: no new edges
                    entry[1] += 1
                    return
        held = [e[0] for e in stack]
        stack.append([lock, restore_count])
        if not held:
            return
        site = _creation_site()
        with self._meta:
            self._labels.setdefault(lock._id, lock._label)
            for h in held:
                self._labels.setdefault(h._id, h._label)
                self._edges.setdefault(h._id, {}).setdefault(lock._id, site)
            self._check_cycle(lock._id)

    def _note_release(self, lock: _GuardedLock) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                stack[i][1] -= 1
                if stack[i][1] <= 0:
                    del stack[i]
                return
        # release of a lock acquired before install/on another thread —
        # nothing tracked, nothing to unwind

    def _note_release_all(self, lock: _GuardedLock) -> int:
        """Condition.wait on an RLock: drop every held count at once."""
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] is lock:
                count = stack[i][1]
                del stack[i]
                return count
        return 1

    # ------------------------------------------------------- order graph
    def _check_cycle(self, start: int) -> None:
        """DFS from ``start``; a path back to it is an inversion cycle.

        Caller holds ``self._meta``.
        """
        path: list[int] = [start]
        on_path = {start}

        def dfs(node: int) -> bool:
            for nxt in self._edges.get(node, ()):
                if nxt == start:
                    return True
                if nxt in on_path:
                    continue
                path.append(nxt)
                on_path.add(nxt)
                if dfs(nxt):
                    return True
                on_path.discard(path.pop())
            return False

        if not dfs(start):
            return
        key = frozenset(path)
        if key in self._seen_cycles:
            return
        self._seen_cycles.add(key)
        labels = tuple(self._labels.get(n, f"#{n}") for n in path)
        order = " -> ".join(labels + (labels[0],))
        self._violations.append(Violation(
            kind="lock-order",
            message=(f"lock-order inversion: {order} — two threads acquire "
                     f"these locks in opposite orders; under the wrong "
                     f"interleaving both block forever"),
            thread=threading.current_thread().name,
            details=labels))

    # ------------------------------------------------------------ eraser
    def watch(self, obj) -> object:
        """Track every attribute write on ``obj`` with the lockset rule.

        Swaps ``obj.__class__`` for a generated subclass whose
        ``__setattr__`` records (thread, lockset) before delegating, so
        there is zero cost for unwatched objects.  Apply AFTER any
        single-threaded initialization handoff (see module docstring).
        """
        cls = type(obj)
        if cls in self._watch_classes.values():
            return obj               # already watched
        sub = self._watch_classes.get(cls)
        if sub is None:
            guard = self

            def __setattr__(s, name, value):
                if not name.startswith("__"):   # class swap, machinery
                    guard._note_write(s, name)
                cls.__setattr__(s, name, value)

            sub = type(f"_Watched_{cls.__name__}", (cls,),
                       {"__setattr__": __setattr__, "__slots__": ()})
            self._watch_classes[cls] = sub
        obj.__class__ = sub
        self._watched[id(obj)] = obj
        return obj

    def unwatch(self, obj) -> None:
        if type(obj) in self._watch_classes.values():
            obj.__class__ = type(obj).__mro__[1]
        self._watched.pop(id(obj), None)

    def _note_write(self, obj, attr: str) -> None:
        ident = threading.get_ident()
        held = self._held_ids()
        with self._meta:
            key = (id(obj), attr)
            st = self._eraser.get(key)
            if st is None:
                # exclusive phase: first writer owns the field outright
                self._eraser[key] = [ident, {ident}, None, False]
                return
            owner, writers, candidates, reported = st
            if ident == owner and len(writers) == 1:
                return
            writers.add(ident)
            if candidates is None:
                # first genuinely shared write starts the lockset
                st[2] = candidates = set(held)
            else:
                candidates &= held
            if not candidates and not reported:
                st[3] = True
                self._violations.append(Violation(
                    kind="unguarded-write",
                    message=(f"{type(obj).__mro__[1].__name__}.{attr} "
                             f"written from {len(writers)} threads with an "
                             f"empty common lockset — no single lock "
                             f"consistently guards this field"),
                    thread=threading.current_thread().name,
                    details=(type(obj).__mro__[1].__name__, attr)))

    # ----------------------------------------------------------- results
    def violations(self) -> list[Violation]:
        with self._meta:
            return list(self._violations)

    def reset(self) -> None:
        """Clear findings and graphs; install/watch state is kept."""
        with self._meta:
            self._edges.clear()
            self._labels.clear()
            self._violations.clear()
            self._seen_cycles.clear()
            self._eraser.clear()

    def report(self) -> str:
        vs = self.violations()
        if not vs:
            return "lockguard: clean (0 violations)"
        lines = [f"lockguard: {len(vs)} violation(s)"]
        lines += [f"  {v}" for v in vs]
        return "\n".join(lines)

    def emit_metrics(self) -> None:
        """Publish counts on the PR 1 metrics registry (best effort)."""
        from ..observability import METRICS
        vs = self.violations()
        for kind in ("lock-order", "unguarded-write"):
            METRICS.gauge(
                "lockguard.violations." + kind.replace("-", "_"),
                sum(1 for v in vs if v.kind == kind))


LOCKGUARD = LockGuard()


@contextlib.contextmanager
def lockguard_active(guard: LockGuard | None = None):
    """Install around a block, uninstall after; yields the guard."""
    g = guard or LOCKGUARD
    g.install()
    try:
        yield g
    finally:
        g.uninstall()
