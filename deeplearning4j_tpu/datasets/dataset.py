"""The DataSet container: features + labels with pipeline helpers.

Capability match of the nd4j ``DataSet`` consumed throughout the reference
(``nn/multilayer/MultiLayerTest.java:57-60``: shuffle, splitTestAndTrain,
normalizeZeroMeanZeroUnitVariance) plus ``FeatureUtil.toOutcomeMatrix``
(``MultiLayerNetwork.java:1127``).  Host-side numpy container — device
placement happens at the jitted-step boundary, so the pipeline stays cheap
and XLA sees only the batched arrays.
"""

from __future__ import annotations

import dataclasses
from typing import Iterator, Sequence

import numpy as np


def to_outcome_matrix(labels: Sequence[int], num_classes: int) -> np.ndarray:
    """``FeatureUtil.toOutcomeMatrix`` — int labels to one-hot rows."""
    labels = np.asarray(labels, dtype=np.int64)
    out = np.zeros((labels.shape[0], num_classes), dtype=np.float32)
    out[np.arange(labels.shape[0]), labels] = 1.0
    return out


@dataclasses.dataclass
class DataSet:
    """Features (n, ...) + one-hot labels (n, c)."""

    features: np.ndarray
    labels: np.ndarray

    def __post_init__(self):
        self.features = np.asarray(self.features, dtype=np.float32)
        self.labels = np.asarray(self.labels, dtype=np.float32)

    # ------------------------------------------------------------------ basics
    def num_examples(self) -> int:
        return int(self.features.shape[0])

    def __len__(self) -> int:
        return self.num_examples()

    def num_inputs(self) -> int:
        return int(np.prod(self.features.shape[1:]))

    def num_outcomes(self) -> int:
        return int(self.labels.shape[-1])

    def get(self, i) -> "DataSet":
        idx = np.atleast_1d(i)
        return DataSet(self.features[idx], self.labels[idx])

    def copy(self) -> "DataSet":
        return DataSet(self.features.copy(), self.labels.copy())

    @staticmethod
    def merge(datasets: Sequence["DataSet"]) -> "DataSet":
        return DataSet(np.concatenate([d.features for d in datasets]),
                       np.concatenate([d.labels for d in datasets]))

    # ------------------------------------------------------------------ pipeline
    def shuffle(self, seed: int | None = None) -> "DataSet":
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.num_examples())
        return DataSet(self.features[perm], self.labels[perm])

    def split_test_and_train(self, num_train: int) -> tuple["DataSet", "DataSet"]:
        """``SplitTestAndTrain`` — first n as train, rest as test."""
        return (DataSet(self.features[:num_train], self.labels[:num_train]),
                DataSet(self.features[num_train:], self.labels[num_train:]))

    def normalize_zero_mean_unit_variance(self) -> "DataSet":
        mean = self.features.mean(axis=0, keepdims=True)
        std = self.features.std(axis=0, keepdims=True)
        std[std == 0] = 1.0
        return DataSet((self.features - mean) / std, self.labels)

    def scale_minmax(self, lo: float = 0.0, hi: float = 1.0) -> "DataSet":
        fmin = self.features.min(axis=0, keepdims=True)
        fmax = self.features.max(axis=0, keepdims=True)
        rng = np.where(fmax - fmin == 0, 1.0, fmax - fmin)
        return DataSet(lo + (self.features - fmin) / rng * (hi - lo), self.labels)

    def binarize(self, threshold: float = 0.5) -> "DataSet":
        return DataSet((self.features > threshold).astype(np.float32), self.labels)

    def round_to_zero_one(self) -> "DataSet":
        return self.binarize(0.5)

    def sample(self, num: int, seed: int | None = None,
               with_replacement: bool = True) -> "DataSet":
        rng = np.random.default_rng(seed)
        idx = rng.choice(self.num_examples(), size=num, replace=with_replacement)
        return DataSet(self.features[idx], self.labels[idx])

    def filter_by_outcome(self, outcomes: Sequence[int]) -> "DataSet":
        mask = np.isin(self.labels.argmax(axis=1), np.asarray(outcomes))
        return DataSet(self.features[mask], self.labels[mask])

    def sort_by_outcome(self) -> "DataSet":
        order = np.argsort(self.labels.argmax(axis=1), kind="stable")
        return DataSet(self.features[order], self.labels[order])

    def batch_by(self, batch_size: int) -> list["DataSet"]:
        n = self.num_examples()
        return [DataSet(self.features[i:i + batch_size], self.labels[i:i + batch_size])
                for i in range(0, n, batch_size)]

    def iterate_batches(self, batch_size: int) -> Iterator["DataSet"]:
        yield from self.batch_by(batch_size)

    def as_reconstruction(self) -> "DataSet":
        """labels := features (unsupervised view)."""
        return DataSet(self.features, self.features.reshape(self.num_examples(), -1))

    def outcome_counts(self) -> np.ndarray:
        return self.labels.sum(axis=0)
