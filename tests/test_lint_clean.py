"""Tier-1 gate: the shipped tree stays graftlint-clean.

This is the test form of ``python -m tools.graftlint --check`` — any new
hazard (host sync in a hot path, recompile trap, key reuse, use-after-
donate, traced branch, uninstrumented hot loop, lock-order hazard,
unbound collective axis, off-registry PartitionSpec axis, shard_map
arity mismatch, donation/placement conflict, unstable reduction) that is
neither suppressed inline with a reason nor carried in the committed
baseline fails CI here.  Companion invariants keep the baseline itself
honest: every entry must still fire (no stale ledger lines) and carry a
real justification (no TODOs shipped).

For the fast local pre-commit loop, run ``python -m tools.graftlint
--diff HEAD`` instead — it lints only the ``.py`` files you changed
(falling back to the full tree if git can't resolve the ref), then this
test re-checks the whole package in CI with identical rule semantics.
"""

import os

from deeplearning4j_tpu.analysis import Analyzer, Baseline, active

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(REPO, "graftlint.baseline.json")
PACKAGE = os.path.join(REPO, "deeplearning4j_tpu")


def _run():
    analyzer = Analyzer(baseline=Baseline.load(BASELINE), root=REPO)
    findings = analyzer.analyze_paths([PACKAGE])
    return analyzer, findings


def test_package_has_no_new_violations():
    analyzer, findings = _run()
    assert analyzer.errors == [], f"unparseable files: {analyzer.errors}"
    fresh = active(findings)
    listing = "\n".join(
        f"  {f.path}:{f.line}: {f.rule} {f.message}" for f in fresh)
    assert not fresh, (
        f"{len(fresh)} new graftlint violation(s) — fix them, suppress "
        f"inline with a reason, or (last resort) baseline with a "
        f"justification:\n{listing}")


def test_baseline_has_no_stale_entries():
    _, findings = _run()
    stale = Baseline.load(BASELINE).stale_entries(findings)
    listing = "\n".join(f"  {e['rule']} {e['path']}: {e['code']!r}"
                        for e in stale)
    assert not stale, (
        f"baseline entries that no longer fire (the hazard was fixed or "
        f"the line changed) — delete them:\n{listing}")


def test_baseline_entries_are_justified():
    for e in Baseline.load(BASELINE).entries:
        just = e.get("justification", "")
        assert just and "TODO" not in just, (
            f"baseline entry {e['rule']} {e['path']} lacks a real "
            f"justification: {just!r}")
