"""Provisioning analog (reference ``deeplearning4j-aws``: Ec2BoxCreator /
ClusterSetup / HostProvisioner) + the YARN Kill CLI analog."""

import os
import subprocess
import sys
import time
from pathlib import Path

from deeplearning4j_tpu.parallel.procstate import FileStateTracker
from deeplearning4j_tpu.parallel.provision import (
    PodSliceProvisioner, PodSliceSpec)

REPO = Path(__file__).resolve().parents[1]


def test_pod_slice_spec_geometry():
    s = PodSliceSpec(accelerator_type="v5litepod-64")
    assert s.n_chips == 64 and s.n_hosts == 16       # v5e: 4-chip hosts
    assert PodSliceSpec(accelerator_type="v5litepod-8").n_hosts == 2


def test_create_and_launch_commands():
    spec = PodSliceSpec(name="slice1", accelerator_type="v5litepod-16",
                        zone="us-west4-a", spot=True)
    prov = PodSliceProvisioner(spec)
    create = prov.create_command()
    assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "--accelerator-type=v5litepod-16" in create
    assert "--spot" in create

    env = prov.launch_env(3, "10.0.0.2")
    assert env == {"JAX_COORDINATOR_ADDRESS": "10.0.0.2:8476",
                   "JAX_NUM_PROCESSES": "4", "JAX_PROCESS_ID": "3"}

    launch = prov.launch_command("-m deeplearning4j_tpu train", "$COORD")
    assert "JAX_COORDINATOR_ADDRESS=$COORD:8476" in launch
    assert "JAX_NUM_PROCESSES=4" in launch
    assert "agent-worker-number" in launch           # per-host process id


def test_render_script_is_wellformed(tmp_path):
    prov = PodSliceProvisioner(PodSliceSpec(accelerator_type="v5litepod-8"))
    path = prov.write_script(tmp_path / "up.sh", "https://example.com/r.git",
                             "-m deeplearning4j_tpu train")
    text = path.read_text()
    assert text.startswith("#!/usr/bin/env bash")
    assert "set -euo pipefail" in text
    assert "tpu-vm create" in text and "--worker=all" in text
    # remote worker-index lookup must be escaped for the outer shell
    assert "\\$(curl" in text
    assert os.access(path, os.X_OK)
    # the script parses as shell
    subprocess.run(["bash", "-n", str(path)], check=True)


def test_cli_scaleout_kill(tmp_path):
    state = tmp_path / "state"
    FileStateTracker(state)          # create the layout
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu", "scaleout", "-t", "kill",
         "--state-dir", str(state)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-800:]
    assert FileStateTracker(state).is_done()


def test_kill_stops_running_master(tmp_path):
    """A kill issued while a master waits on an empty-but-unfinished job
    stream makes the whole run wind down (Kill.java behavior)."""
    from deeplearning4j_tpu.parallel.procrunner import ProcessDistributedRunner
    from deeplearning4j_tpu.parallel.scaleout import CollectionJobIterator

    state = tmp_path / "state"

    class NeverDone:
        """Iterator that claims more work is coming (streaming master)."""

        def next(self, worker_id=""):
            raise AssertionError("never dispenses")

        def has_next(self):
            return False

        def reset(self):
            pass

    runner = ProcessDistributedRunner(
        CollectionJobIterator(["a b", "c"]),
        "deeplearning4j_tpu.parallel.performers:WordCountPerformer",
        state_dir=state, n_workers=1,
        worker_env={"JAX_PLATFORMS": "cpu"})

    import threading
    killer = threading.Thread(
        target=lambda: (time.sleep(1.5), FileStateTracker(state).finish()),
        daemon=True)
    killer.start()
    t0 = time.time()
    runner.run(max_wall_s=60.0)
    # jobs drain quickly; kill (or natural finish) must not hang to the wall
    assert time.time() - t0 < 50.0
    assert FileStateTracker(state).is_done()


def test_core_counted_generations():
    """v4/v5p accelerator-type suffixes count TensorCores (2/chip), not
    chips; v5litepod suffixes count chips."""
    assert PodSliceSpec(accelerator_type="v4-8").n_chips == 4
    assert PodSliceSpec(accelerator_type="v4-8").n_hosts == 1
    assert PodSliceSpec(accelerator_type="v3-8").n_hosts == 1
    assert PodSliceSpec(accelerator_type="v5p-128").n_chips == 64
    assert PodSliceSpec(accelerator_type="v5litepod-64").n_chips == 64


def test_driver_wildcard_mesh_uses_all_devices():
    import jax

    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel.driver import Driver
    from deeplearning4j_tpu.parallel.mesh import MeshSpec

    import jax.numpy as jnp

    def loss_fn(p, xb, yb, key=None):
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    d = Driver(loss_fn, T.sgd_lr(1e-2), mesh_spec=MeshSpec(tp=2))
    assert d.mesh.devices.size == len(jax.devices())   # wildcard dp fills


def _stub_gcloud(tmp_path):
    """A fake gcloud on PATH that logs argv and answers `describe` with an
    IP — lets apply()/teardown() integration-test without a cloud."""
    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    log = tmp_path / "gcloud.log"
    stub = bindir / "gcloud"
    stub.write_text(
        "#!/usr/bin/env bash\n"
        f'echo "$@" >> {log}\n'
        'for a in "$@"; do\n'
        '  if [ "$a" = "describe" ]; then echo 10.1.2.3; fi\n'
        "done\n")
    stub.chmod(0o755)
    return bindir, log


def test_apply_dry_run_is_default_and_runs_nothing(tmp_path):
    prov = PodSliceProvisioner(PodSliceSpec(accelerator_type="v5litepod-8"))
    records = prov.apply("https://example.com/r.git", "-m deeplearning4j_tpu train")
    steps = [r["step"] for r in records]
    assert steps == ["create", "bootstrap", "resolve_coordinator", "launch"]
    assert all(r["rc"] is None for r in records)     # nothing executed


def test_apply_executes_against_stub_gcloud(tmp_path, monkeypatch):
    """--apply parity with ClusterSetup.java:24: the sequence actually
    executes (create -> bootstrap -> describe -> launch), the resolved
    coordinator IP feeds the launch env, and --kill tears down."""
    bindir, log = _stub_gcloud(tmp_path)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    prov = PodSliceProvisioner(PodSliceSpec(
        name="s8", accelerator_type="v5litepod-8", zone="us-west4-a"))
    records = prov.apply("https://example.com/r.git",
                         "-m deeplearning4j_tpu train", dry_run=False)
    assert [r["rc"] for r in records] == [0, 0, 0, 0]
    logged = log.read_text()
    assert "create s8" in logged and "delete" not in logged
    assert logged.count("--worker=all") == 2         # bootstrap + launch
    launch = records[-1]["cmd"][-1]
    assert "JAX_COORDINATOR_ADDRESS=10.1.2.3:8476" in launch

    rec = prov.teardown(dry_run=False)
    assert rec["rc"] == 0
    assert "delete s8" in log.read_text()


def test_cli_provision_apply_and_kill_with_stub(tmp_path, monkeypatch):
    bindir, log = _stub_gcloud(tmp_path)
    env = dict(os.environ)
    env["PATH"] = f"{bindir}:{env['PATH']}"
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu", "provision",
         "--name", "sX", "--accelerator-type", "v5litepod-8",
         "--repo-url", "https://example.com/r.git", "--apply"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-800:]
    assert "create sX" in log.read_text()

    p = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu", "provision",
         "--name", "sX", "--kill", "--apply"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-800:]
    assert "delete sX" in log.read_text()


def test_cli_provision_kill_dry_run_executes_nothing(tmp_path, monkeypatch):
    """--kill without --apply must only PRINT the delete command."""
    bindir, log = _stub_gcloud(tmp_path)
    env = dict(os.environ)
    env["PATH"] = f"{bindir}:{env['PATH']}"
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu", "provision",
         "--name", "sY", "--kill"],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-800:]
    assert "delete" in p.stdout and not log.exists()


def test_apply_timeout_names_step_and_keeps_audit_trail(tmp_path, monkeypatch):
    """A hung gcloud must surface as a RuntimeError naming the step, with
    the records-so-far attached — a half-created slice keeps its audit
    trail so the caller can tear down exactly what was attempted."""
    import pytest

    bindir = tmp_path / "bin"
    bindir.mkdir(exist_ok=True)
    stub = bindir / "gcloud"
    stub.write_text("#!/usr/bin/env bash\nsleep 5\n")
    stub.chmod(0o755)
    monkeypatch.setenv("PATH", f"{bindir}:{os.environ['PATH']}")

    prov = PodSliceProvisioner(PodSliceSpec(
        name="s8", accelerator_type="v5litepod-8", zone="us-west4-a"))
    with pytest.raises(RuntimeError, match="'create' timed out") as ei:
        prov.apply("https://example.com/r.git", "-m deeplearning4j_tpu train",
                   dry_run=False, timeout_s=0.3)
    err = ei.value
    assert isinstance(err.__cause__, subprocess.TimeoutExpired)
    assert [r["step"] for r in err.records] == ["create"]
    assert err.records[0]["rc"] is None          # never finished
    assert "teardown" in str(err)
