"""Mesh-sharded embedding tables — distributed Word2Vec/GloVe training.

TPU-native equivalent of the reference's distributed sparse-embedding
strategy (``scaleout/perform/models/word2vec/Word2VecWork.java`` +
``Word2VecPerformer.java:72-137``, GloVe mirror ``GlovePerformer.java``):
there, workers receive only the *rows* of syn0/syn1 their sentences touch
and return per-row deltas the master applies.  On a TPU mesh the same
semantics become SPMD primitives over the ``ep`` (embedding-parallel) axis:

- **tables row-sharded**: syn0/syn1/syn1neg live as ``P(ep, None)`` shards —
  each device owns ``rows/ep`` rows, so vocab size scales with the mesh.
- **row shipping = masked gather + psum**: every device materializes the
  batch's rows by gathering the ones it owns (others contribute zeros) and
  ``psum``-ing over ``ep`` — the collective IS the row shipment.
- **per-row deltas = masked scatter-add**: after the (identical, replicated)
  delta computation, each device applies only the rows it owns.  Duplicate
  indices within a batch accumulate exactly (XLA scatter-add), matching the
  reference's sequential per-pair ``axpy`` application order-independently.

The batch (center/context/path indices) is replicated across ``ep`` —
compute is tiny next to HBM for realistic tables, and replication keeps the
update equivalent to the single-device schedule bit-for-bit (tested).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from functools import wraps

    from jax.experimental.shard_map import shard_map as _sm_old

    @wraps(_sm_old)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # the experimental API spells the flag check_rep
        return _sm_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check_vma)

from ..parallel.mesh import EP
from .glove import Glove
from .word2vec import Word2Vec


def pad_rows(n: int, n_shards: int) -> int:
    """Rows padded up so each shard owns an equal slice."""
    return ((max(n, 1) + n_shards - 1) // n_shards) * n_shards


def gather_rows(table, idx, axis: str, rows_per: int):
    """Full rows for global indices from a row-sharded table: local masked
    gather + psum over the shard axis (the 'row shipping' collective)."""
    my = lax.axis_index(axis)
    loc = idx - my * rows_per
    valid = (loc >= 0) & (loc < rows_per)
    rows = table[jnp.clip(loc, 0, rows_per - 1)]
    rows = jnp.where(valid[..., None], rows, 0)
    return lax.psum(rows, axis)


def scatter_add_rows(table, idx, upd, axis: str, rows_per: int):
    """Apply per-row deltas to the locally-owned slice only."""
    my = lax.axis_index(axis)
    loc = idx - my * rows_per
    valid = (loc >= 0) & (loc < rows_per)
    upd = jnp.where(valid[..., None], upd, 0)
    return table.at[jnp.clip(loc, 0, rows_per - 1)].add(upd)


def _gather_vec(vec, idx, axis: str, rows_per: int):
    """gather_rows for 1-d tables (GloVe biases)."""
    my = lax.axis_index(axis)
    loc = idx - my * rows_per
    valid = (loc >= 0) & (loc < rows_per)
    vals = vec[jnp.clip(loc, 0, rows_per - 1)]
    return lax.psum(jnp.where(valid, vals, 0), axis)


def _scatter_add_vec(vec, idx, upd, axis: str, rows_per: int):
    my = lax.axis_index(axis)
    loc = idx - my * rows_per
    valid = (loc >= 0) & (loc < rows_per)
    return vec.at[jnp.clip(loc, 0, rows_per - 1)].add(jnp.where(valid, upd, 0))


# --------------------------------------------------------------------------- step builders

def build_hs_step(mesh: Mesh, rows0: int, rows1: int):
    """Sharded hierarchical-softmax skip-gram step (semantics of
    ``InMemoryLookupTable.java:182-222`` at batch granularity)."""
    n_ep = mesh.shape[EP]
    r0, r1 = rows0 // n_ep, rows1 // n_ep

    def local(syn0, syn1, centers, points, codes, mask, alpha):
        h = gather_rows(syn0, centers, EP, r0)             # (B, D)
        w = gather_rows(syn1, points, EP, r1)              # (B, L, D)
        u = jnp.einsum("bd,bld->bl", h, w)
        p = jax.nn.sigmoid(u)
        g = (1.0 - codes - p) * alpha * mask
        dh = jnp.einsum("bl,bld->bd", g, w)
        dw = g[:, :, None] * h[:, None, :]
        syn1 = scatter_add_rows(syn1, points, dw, EP, r1)
        syn0 = scatter_add_rows(syn0, centers, dh, EP, r0)
        return syn0, syn1

    t = P(EP, None)
    sm = shard_map(local, mesh=mesh,
                   in_specs=(t, t, P(), P(), P(), P(), P()),
                   out_specs=(t, t), check_vma=False)
    return jax.jit(sm, donate_argnums=(0, 1))


def build_ns_step(mesh: Mesh, rows0: int, rows1: int):
    """Sharded negative-sampling step (``InMemoryLookupTable.java:225-266``)."""
    n_ep = mesh.shape[EP]
    r0, r1 = rows0 // n_ep, rows1 // n_ep

    def local(syn0, syn1neg, centers, targets, labels, alpha):
        h = gather_rows(syn0, centers, EP, r0)
        w = gather_rows(syn1neg, targets, EP, r1)
        u = jnp.einsum("bd,bkd->bk", h, w)
        p = jax.nn.sigmoid(u)
        g = (labels - p) * alpha
        dh = jnp.einsum("bk,bkd->bd", g, w)
        dw = g[:, :, None] * h[:, None, :]
        syn1neg = scatter_add_rows(syn1neg, targets, dw, EP, r1)
        syn0 = scatter_add_rows(syn0, centers, dh, EP, r0)
        return syn0, syn1neg

    t = P(EP, None)
    sm = shard_map(local, mesh=mesh,
                   in_specs=(t, t, P(), P(), P(), P()),
                   out_specs=(t, t), check_vma=False)
    return jax.jit(sm, donate_argnums=(0, 1))


def build_glove_step(mesh: Mesh, rows: int, lr: float):
    """Sharded GloVe AdaGrad step (``GloveWeightLookupTable.java`` WLS)."""
    n_ep = mesh.shape[EP]
    r = rows // n_ep

    def local(w, wc, b, bc, hw, hwc, hb, hbc, rows_i, cols_i, logx, fx):
        wi = gather_rows(w, rows_i, EP, r)
        wj = gather_rows(wc, cols_i, EP, r)
        bi = _gather_vec(b, rows_i, EP, r)
        bj = _gather_vec(bc, cols_i, EP, r)
        diff = jnp.einsum("bd,bd->b", wi, wj) + bi + bj - logx
        wdiff = fx * diff
        gw = wdiff[:, None] * wj
        gwc = wdiff[:, None] * wi
        gb = wdiff
        hw = scatter_add_rows(hw, rows_i, gw * gw, EP, r)
        hwc = scatter_add_rows(hwc, cols_i, gwc * gwc, EP, r)
        hb = _scatter_add_vec(hb, rows_i, gb * gb, EP, r)
        hbc = _scatter_add_vec(hbc, cols_i, gb * gb, EP, r)
        hw_g = gather_rows(hw, rows_i, EP, r)
        hwc_g = gather_rows(hwc, cols_i, EP, r)
        hb_g = _gather_vec(hb, rows_i, EP, r)
        hbc_g = _gather_vec(hbc, cols_i, EP, r)
        w = scatter_add_rows(w, rows_i, -lr * gw * lax.rsqrt(hw_g + 1e-8), EP, r)
        wc = scatter_add_rows(wc, cols_i, -lr * gwc * lax.rsqrt(hwc_g + 1e-8), EP, r)
        b = _scatter_add_vec(b, rows_i, -lr * gb * lax.rsqrt(hb_g + 1e-8), EP, r)
        bc = _scatter_add_vec(bc, cols_i, -lr * gb * lax.rsqrt(hbc_g + 1e-8), EP, r)
        loss = 0.5 * jnp.mean(fx * diff * diff)
        return w, wc, b, bc, hw, hwc, hb, hbc, loss

    t, v = P(EP, None), P(EP)
    sm = shard_map(local, mesh=mesh,
                   in_specs=(t, t, v, v, t, t, v, v, P(), P(), P(), P()),
                   out_specs=(t, t, v, v, t, t, v, v, P()),
                   check_vma=False)
    return jax.jit(sm, donate_argnums=tuple(range(8)))


# --------------------------------------------------------------------------- models

class ShardedWord2Vec(Word2Vec):
    """Word2Vec with tables row-sharded over the mesh's ``ep`` axis.

    Same schedule, vocab, Huffman tree and hyperparameters as the
    single-device model — only the table placement and update kernels
    change, so results match ``Word2Vec`` exactly (tested)."""

    def __init__(self, sentences=None, *, mesh: Mesh, **kw):
        super().__init__(sentences, **kw)
        if EP not in mesh.shape or mesh.shape[EP] < 1:
            raise ValueError(f"mesh must carry an {EP!r} axis")
        self.mesh = mesh
        self._hs_fn = self._ns_fn = None

    def reset_weights(self) -> None:
        n_ep = self.mesh.shape[EP]
        n, d = len(self.vocab), self.layer_size
        n0, n1 = pad_rows(n, n_ep), pad_rows(max(n - 1, 1), n_ep)
        rng = np.random.default_rng(self.seed)
        syn0 = np.zeros((n0, d), np.float32)
        syn0[:n] = (rng.random((n, d), np.float32) - 0.5) / d
        sh = NamedSharding(self.mesh, P(EP, None))
        self.syn0 = jax.device_put(jnp.asarray(syn0), sh)
        self.syn1 = jax.device_put(jnp.zeros((n1, d), jnp.float32), sh)
        self._hs_fn = build_hs_step(self.mesh, n0, n1)
        if self.negative > 0:
            n1n = pad_rows(n, n_ep)
            self.syn1neg = jax.device_put(jnp.zeros((n1n, d), jnp.float32), sh)
            counts = self.vocab.counts_array() ** 0.75
            self._unigram_log = jnp.asarray(
                np.log(counts / counts.sum()), dtype=jnp.float32)
            self._ns_fn = build_ns_step(self.mesh, n0, n1n)

    def _apply_hs(self, cb, pts, cds, msk, alpha):
        self.syn0, self.syn1 = self._hs_fn(self.syn0, self.syn1, cb, pts,
                                           cds, msk, alpha)

    def _apply_ns(self, cb, targets, labels, alpha):
        self.syn0, self.syn1neg = self._ns_fn(self.syn0, self.syn1neg, cb,
                                              targets, labels, alpha)


class ShardedGlove(Glove):
    """GloVe with all six tables row-sharded over ``ep``."""

    def __init__(self, sentences=None, *, mesh: Mesh, **kw):
        super().__init__(sentences, **kw)
        if EP not in mesh.shape or mesh.shape[EP] < 1:
            raise ValueError(f"mesh must carry an {EP!r} axis")
        self.mesh = mesh
        self._step_fn = None
        self._n_pad = 0

    def _init_tables(self, n: int, d: int, rng) -> None:
        n_ep = self.mesh.shape[EP]
        self._n_pad = pad_rows(n, n_ep)
        w = np.zeros((self._n_pad, d), np.float32)
        wc = np.zeros((self._n_pad, d), np.float32)
        w[:n] = (rng.random((n, d), np.float32) - 0.5) / d
        wc[:n] = (rng.random((n, d), np.float32) - 0.5) / d
        t = NamedSharding(self.mesh, P(EP, None))
        v = NamedSharding(self.mesh, P(EP))
        zt = lambda: jax.device_put(
            jnp.zeros((self._n_pad, d), jnp.float32), t)
        zv = lambda: jax.device_put(jnp.zeros((self._n_pad,), jnp.float32), v)
        self._tables = [jax.device_put(jnp.asarray(w), t),
                        jax.device_put(jnp.asarray(wc), t),
                        zv(), zv(), zt(), zt(), zv(), zv()]
        self._step_fn = build_glove_step(self.mesh, self._n_pad,
                                         self.learning_rate)

    def _apply_step(self, rows, cols, logx, fx):
        """One sharded AdaGrad batch; returns the DEVICE loss so ``fit``
        resolves it at its own fence instead of draining the dispatch
        queue here (the mesh version pays a cross-device gather per sync,
        so the per-batch ``float(loss)`` this replaces was the single
        largest stall in the sharded GloVe hot loop)."""
        *self._tables, loss = self._step_fn(*self._tables, rows, cols, logx, fx)
        return loss
