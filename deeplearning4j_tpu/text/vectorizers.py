"""Text vectorizers: bag-of-words and TF-IDF.

Capability match of ``bagofwords/vectorizer/`` in the reference
(``BagOfWordsVectorizer``, ``TfidfVectorizer``): corpus -> (doc x vocab)
matrices, optionally with labels -> DataSet for the classifiers.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence

import numpy as np

from ..datasets.dataset import DataSet, to_outcome_matrix
from .tokenization import CommonPreprocessor, DefaultTokenizerFactory
from .vocab import VocabCache, build_vocab


class BagOfWordsVectorizer:
    def __init__(self, min_word_frequency: float = 1.0, tokenizer_factory=None,
                 binary: bool = False):
        self.min_word_frequency = min_word_frequency
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory(
            CommonPreprocessor())
        self.binary = binary
        self.vocab: VocabCache | None = None

    def fit(self, docs: Iterable[str]) -> "BagOfWordsVectorizer":
        self.vocab = build_vocab(docs, self.tokenizer_factory,
                                 self.min_word_frequency)
        return self

    def transform(self, docs: Iterable[str]) -> np.ndarray:
        docs = list(docs)
        out = np.zeros((len(docs), len(self.vocab)), np.float32)
        for r, doc in enumerate(docs):
            for tok in self.tokenizer_factory.create(doc).get_tokens():
                i = self.vocab.index_of(tok)
                if i >= 0:
                    out[r, i] = 1.0 if self.binary else out[r, i] + 1.0
        return out

    def fit_transform(self, docs: Sequence[str]) -> np.ndarray:
        return self.fit(docs).transform(docs)

    def vectorize(self, docs: Sequence[str], labels: Sequence[int],
                  num_classes: int | None = None) -> DataSet:
        x = self.fit_transform(docs)
        labels = np.asarray(labels)
        nc = num_classes or int(labels.max()) + 1
        return DataSet(x, to_outcome_matrix(labels, nc))


class TfidfVectorizer(BagOfWordsVectorizer):
    def __init__(self, **kw):
        super().__init__(**kw)
        self._idf: np.ndarray | None = None

    def fit(self, docs: Iterable[str]) -> "TfidfVectorizer":
        docs = list(docs)
        super().fit(docs)
        df = np.zeros(len(self.vocab), np.float64)
        for doc in docs:
            seen = {self.vocab.index_of(t)
                    for t in self.tokenizer_factory.create(doc).get_tokens()}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        n = len(docs)
        self._idf = np.log((1 + n) / (1 + df)) + 1.0  # smoothed idf
        return self

    def transform(self, docs: Iterable[str]) -> np.ndarray:
        counts = super().transform(docs)
        tf = counts / np.maximum(counts.sum(axis=1, keepdims=True), 1.0)
        return (tf * self._idf).astype(np.float32)
