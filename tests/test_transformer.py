"""Flagship transformer tests: single-device math, and the key sharding
correctness check — the explicit-SPMD (dp, sp, tp) step with ring attention
must produce the SAME loss/params as the single-device step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    forward_local,
    init_params,
    lm_loss_local,
    param_specs,
)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh


def tiny_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)  # exact math for comparisons
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


def data(cfg, batch=8, seq=16, seed=0):
    k1, k2 = jax.random.split(jax.random.key(seed))
    tokens = jax.random.randint(k1, (batch, seq), 0, cfg.vocab_size)
    targets = jax.random.randint(k2, (batch, seq), 0, cfg.vocab_size)
    return tokens, targets


def test_forward_shapes():
    cfg = tiny_cfg()
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    logits = forward_local(params, tokens, cfg)
    assert logits.shape == (8, 16, cfg.vocab_size)
    assert np.all(np.isfinite(np.asarray(logits)))


def test_causal_masking():
    """Changing future tokens must not change past logits (causal)."""
    cfg = tiny_cfg(causal=True)
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    logits1 = forward_local(params, tokens, cfg)
    tokens2 = tokens.at[:, 10:].set((tokens[:, 10:] + 7) % cfg.vocab_size)
    logits2 = forward_local(params, tokens2, cfg)
    np.testing.assert_allclose(np.asarray(logits1[:, :10]),
                               np.asarray(logits2[:, :10]), atol=1e-5)
    assert not np.allclose(np.asarray(logits1[:, 10:]), np.asarray(logits2[:, 10:]))


def test_bidirectional_mode():
    cfg = tiny_cfg(causal=False)
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    logits1 = forward_local(params, tokens, cfg)
    tokens2 = tokens.at[:, -1].set((tokens[:, -1] + 3) % cfg.vocab_size)
    logits2 = forward_local(params, tokens2, cfg)
    # bidirectional: even position 0 sees the change
    assert not np.allclose(np.asarray(logits1[:, 0]), np.asarray(logits2[:, 0]))


def test_single_device_training_reduces_loss():
    cfg = tiny_cfg()
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, lr=0.05)
    tokens, _ = data(cfg)
    targets = jnp.roll(tokens, -1, axis=1)
    step = model.build_train_step(lr=0.05)
    loss0 = None
    for i in range(30):
        params, opt, loss = step(params, opt, tokens, targets)
        if loss0 is None:
            loss0 = float(loss)
    assert float(loss) < loss0 * 0.7


def test_single_device_adamw_training_reduces_loss():
    """Flagship trains through the GradientTransform chain (VERDICT weak #2):
    AdamW + warmup-cosine schedule on the LM objective."""
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = tiny_cfg()
    model = TransformerLM(cfg)
    tx = T.adamw(T.warmup_cosine(5e-3, 5, 100), weight_decay=0.01)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    tokens, _ = data(cfg)
    targets = jnp.roll(tokens, -1, axis=1)
    step = model.build_train_step(tx)
    loss0 = None
    for i in range(30):
        params, opt, loss = step(params, opt, tokens, targets)
        if loss0 is None:
            loss0 = float(loss)
    assert int(opt[0]) == 30
    assert float(loss) < loss0 * 0.6


@pytest.mark.parametrize("meshspec", [
    MeshSpec(dp=8, sp=1, tp=1),
    MeshSpec(dp=2, sp=2, tp=2),
    MeshSpec(dp=1, sp=4, tp=2),
    MeshSpec(dp=1, sp=8, tp=1),
])
def test_sharded_step_matches_single_device(meshspec):
    """THE sharding correctness check: dp/sp/tp explicit-SPMD step (ring
    attention + Megatron tp psums + dp grad pmean) == single-device step."""
    cfg = tiny_cfg()
    tokens, _ = data(cfg, batch=8, seq=16)
    targets = jnp.roll(tokens, -1, axis=1)

    from deeplearning4j_tpu.optimize import transforms as T

    def make_tx():
        # Adam state rides the same specs as params (VERDICT next #2:
        # "re-run the sharded-vs-single parity test with Adam state").
        return T.adamw(T.warmup_cosine(0.1, 0, 100), weight_decay=0.01)

    # single-device ground truth
    solo = TransformerLM(cfg)
    p0 = solo.init(jax.random.key(1))
    o0 = solo.init_opt(p0, make_tx())
    step0 = solo.build_train_step(make_tx())
    p0b, o0b, loss0 = step0(jax.tree_util.tree_map(jnp.array, p0), o0,
                            tokens, targets)

    mesh = make_mesh(meshspec)
    model = TransformerLM(cfg, mesh=mesh)
    tx = make_tx()
    p1 = model.place(solo.init(jax.random.key(1)))
    o1 = model.init_opt(p1, tx)
    step1 = model.build_train_step(tx)
    p1b, o1b, loss1 = step1(p1, o1, tokens, targets)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(p1b["layers"][0]["w1"]),
                               np.asarray(p0b["layers"][0]["w1"]), atol=2e-4)
    np.testing.assert_allclose(np.asarray(p1b["tok_embed"]),
                               np.asarray(p0b["tok_embed"]), atol=2e-4)


def test_finetune_classifier_converges():
    """BERT-class fine-tune loop (VERDICT next #2): classifier head on the
    encoder, AdamW + warmup-linear, loss curve must drop and accuracy must
    beat chance on a synthetic token-signal task."""
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = tiny_cfg(causal=False)
    model = TransformerLM(cfg)
    n_classes = 2
    tree = model.init_finetune(jax.random.key(0), n_classes)

    # synthetic task: label = whether token id 7 appears in the sequence
    k = jax.random.key(3)
    tokens = jax.random.randint(k, (32, 16), 0, cfg.vocab_size)
    labels = jnp.any(tokens == 7, axis=1).astype(jnp.int32)

    tx = T.adamw(T.warmup_linear(3e-3, 5, 200), weight_decay=0.01)
    opt = model.init_opt(tree, tx)
    step = model.build_finetune_step(tx)
    losses = []
    for i in range(60):
        tree, opt, loss = step(tree, opt, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5

    from deeplearning4j_tpu.models.transformer import cls_loss_local, encode_local
    x = encode_local(tree["backbone"], tokens, cfg)
    pooled = x.astype(jnp.float32).mean(axis=1)
    logits = pooled @ tree["head"]["w_cls"] + tree["head"]["b_cls"]
    acc = float(jnp.mean((jnp.argmax(logits, -1) == labels)))
    assert acc >= 0.8


def test_finetune_sharded_matches_single():
    """Fine-tune step parity on a dp2-sp2-tp2 mesh with AdamW state."""
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = tiny_cfg(causal=False)
    tokens = jax.random.randint(jax.random.key(5), (8, 16), 0, cfg.vocab_size)
    labels = jnp.any(tokens == 7, axis=1).astype(jnp.int32)

    solo = TransformerLM(cfg)
    t0 = solo.init_finetune(jax.random.key(1), 2)
    o0 = solo.init_opt(t0, T.adamw(0.01))
    t0b, _, loss0 = solo.build_finetune_step(T.adamw(0.01))(t0, o0, tokens, labels)

    mesh = make_mesh(MeshSpec(dp=2, sp=2, tp=2))
    model = TransformerLM(cfg, mesh=mesh)
    t1 = model.place(solo.init_finetune(jax.random.key(1), 2),
                     model.finetune_specs())
    tx = T.adamw(0.01)
    o1 = model.init_opt(t1, tx)  # finetune-tree specs inferred
    t1b, _, loss1 = model.build_finetune_step(tx)(t1, o1, tokens, labels)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(t1b["head"]["w_cls"]),
                               np.asarray(t0b["head"]["w_cls"]), atol=2e-4)
    np.testing.assert_allclose(np.asarray(t1b["backbone"]["layers"][0]["w1"]),
                               np.asarray(t0b["backbone"]["layers"][0]["w1"]),
                               atol=2e-4)


def test_remat_matches_no_remat():
    cfg = tiny_cfg(remat=False)
    cfg_r = tiny_cfg(remat=True)
    params = init_params(jax.random.key(0), cfg)
    tokens, _ = data(cfg)
    targets = jnp.roll(tokens, -1, axis=1)
    g1 = jax.grad(lambda p: lm_loss_local(p, tokens, targets, cfg))(params)
    g2 = jax.grad(lambda p: lm_loss_local(p, tokens, targets, cfg_r))(params)
    np.testing.assert_allclose(np.asarray(g1["layers"][0]["w1"]),
                               np.asarray(g2["layers"][0]["w1"]), rtol=1e-4)


def test_long_context_ring_attention_sp8():
    """Long-context capability evidence: seq 1024 sharded over an sp=8 ring
    (128 tokens per device) matches the single-device step that materializes
    the full sequence — the blockwise running-softmax is exact, not an
    approximation, at sequence lengths far beyond the per-device block."""
    cfg = tiny_cfg(max_len=1024, n_layers=2)
    tokens, _ = data(cfg, batch=2, seq=1024)
    targets = jnp.roll(tokens, -1, axis=1)

    from deeplearning4j_tpu.optimize import transforms as T

    def make_tx():
        return T.sgd_lr(1e-2)

    solo = TransformerLM(cfg)
    p0 = solo.init(jax.random.key(1))
    o0 = solo.init_opt(p0, make_tx())
    step0 = solo.build_train_step(make_tx())
    p0b, _, loss0 = step0(jax.tree_util.tree_map(jnp.array, p0), o0,
                          tokens, targets)

    mesh = make_mesh(MeshSpec(dp=1, sp=8, tp=1))
    model = TransformerLM(cfg, mesh=mesh)
    tx = make_tx()
    p1 = model.place(solo.init(jax.random.key(1)))
    o1 = model.init_opt(p1, tx)
    step1 = model.build_train_step(tx)
    p1b, _, loss1 = step1(p1, o1, tokens, targets)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(p1b["layers"][0]["w1"]),
                               np.asarray(p0b["layers"][0]["w1"]), atol=2e-4)


def test_chunked_xent_matches_unchunked():
    """The streaming LM loss (xent_chunk scan + per-chunk remat) is an
    implementation detail: loss AND grads must match the full-logits path
    bit-for-bit-ish in f32."""
    import dataclasses

    from deeplearning4j_tpu.models.transformer import lm_head_loss

    cfg = tiny_cfg(vocab_size=128, xent_chunk=16)
    cfg0 = dataclasses.replace(cfg, xent_chunk=0)
    params = init_params(jax.random.key(0), cfg)
    h = jax.random.normal(jax.random.key(1), (4, 32, 32))
    targets = jax.random.randint(jax.random.key(2), (4, 32), 0, 128)

    l_chunk = lm_head_loss(params, h, targets, cfg)
    l_full = lm_head_loss(params, h, targets, cfg0)
    np.testing.assert_allclose(float(l_chunk), float(l_full), rtol=1e-6)

    g_chunk = jax.grad(lambda p: lm_head_loss(p, h, targets, cfg))(params)
    g_full = jax.grad(lambda p: lm_head_loss(p, h, targets, cfg0))(params)
    for a, b in zip(jax.tree.leaves(g_chunk), jax.tree.leaves(g_full)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_chunked_xent_pads_awkward_token_counts():
    """A near-prime token count must NOT collapse the divisor search into
    tiny chunks or fall back to full (B*T, V) logits: the stream is padded
    with zero-weight tokens to a multiple of the configured chunk, and
    loss AND grads still match the unchunked path (pad rows contribute
    exactly 0 to the sum and 0 cotangent to every param)."""
    import dataclasses

    from deeplearning4j_tpu.models.transformer import lm_head_loss

    cfg = tiny_cfg(vocab_size=128, max_len=64, xent_chunk=16)
    cfg0 = dataclasses.replace(cfg, xent_chunk=0)
    params = init_params(jax.random.key(0), cfg)
    # B*T = 61 (prime): largest divisor <= 16 is 1, so the pad path runs
    h = jax.random.normal(jax.random.key(1), (1, 61, 32))
    targets = jax.random.randint(jax.random.key(2), (1, 61), 0, 128)

    l_chunk = lm_head_loss(params, h, targets, cfg)
    l_full = lm_head_loss(params, h, targets, cfg0)
    np.testing.assert_allclose(float(l_chunk), float(l_full), rtol=1e-6)

    g_chunk = jax.grad(lambda p: lm_head_loss(p, h, targets, cfg))(params)
    g_full = jax.grad(lambda p: lm_head_loss(p, h, targets, cfg0))(params)
    for a, b in zip(jax.tree.leaves(g_chunk), jax.tree.leaves(g_full)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_zero1_step_matches_replicated_step():
    """ZeRO-1 weight-update sharding (reduce-scatter grads, dp-sharded
    optimizer state, all-gather params) computes the SAME training math as
    the replicated step — and its state really is 1/n_dp per rank."""
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = tiny_cfg(causal=False)
    tokens = jax.random.randint(jax.random.key(5), (8, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)

    mesh = make_mesh(MeshSpec(dp=2, sp=2, tp=2))
    model = TransformerLM(cfg, mesh=mesh)
    p_init = TransformerLM(cfg).init(jax.random.key(1))

    def tx():
        return T.adamw(0.01)

    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)

    # replicated baseline
    p0 = model.place(copy(p_init))
    o0 = model.init_opt(p0, tx())
    step0 = model.build_train_step(tx())
    for _ in range(2):
        p0, o0, loss0 = step0(p0, o0, tokens, targets)

    # zero1
    p1 = model.place(copy(p_init))
    o1 = model.init_opt_zero1(p1, tx())
    step1 = model.build_train_step(tx(), zero1=True)
    for _ in range(2):
        p1, o1, loss1 = step1(p1, o1, tokens, targets)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    # optimizer state memory: each adam moment leaf is sharded over dp —
    # the addressable shard on one device is (global leaves) / n_dp
    mu_leaves = jax.tree.leaves(o1[1])
    p_leaves = jax.tree.leaves(p1)
    n_state = sum(int(np.prod(x.shape)) for x in mu_leaves)
    n_params = sum(int(np.prod(x.shape)) for x in p_leaves)
    for x in mu_leaves:
        shard = next(iter(x.addressable_shards))
        assert shard.data.shape[1] * 2 == x.shape[1]  # dp=2 sharding
    assert n_state >= 2 * n_params  # mu+nu cover all params (plus padding)


def test_zero1_finetune_matches_replicated():
    """ZeRO-1 also composes with the {"backbone", "head"} fine-tune tree."""
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = tiny_cfg(causal=False)
    tokens = jax.random.randint(jax.random.key(5), (8, 16), 0, cfg.vocab_size)
    labels = jnp.any(tokens == 7, axis=1).astype(jnp.int32)

    mesh = make_mesh(MeshSpec(dp=2, sp=2, tp=2))
    model = TransformerLM(cfg, mesh=mesh)
    t_init = TransformerLM(cfg).init_finetune(jax.random.key(1), 2)
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)

    t0 = model.place(copy(t_init), model.finetune_specs())
    o0 = model.init_opt(t0, T.adamw(0.01))
    t0, o0, loss0 = model.build_finetune_step(T.adamw(0.01))(t0, o0, tokens, labels)

    t1 = model.place(copy(t_init), model.finetune_specs())
    o1 = model.init_opt_zero1(t1, T.adamw(0.01))
    t1, o1, loss1 = model.build_finetune_step(T.adamw(0.01), zero1=True)(
        t1, o1, tokens, labels)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    np.testing.assert_allclose(np.asarray(t1["head"]["w_cls"]),
                               np.asarray(t0["head"]["w_cls"]), atol=2e-4)
    np.testing.assert_allclose(np.asarray(t1["backbone"]["layers"][0]["w1"]),
                               np.asarray(t0["backbone"]["layers"][0]["w1"]),
                               atol=2e-4)


def test_zero1_applies_weight_decay_to_weight_leaves():
    """Regression: chunking flattens params to 1-D, which used to make the
    ndim >= 2 decay heuristic silently drop AdamW weight decay in the
    ZeRO-1 step.  Heavy decay (lr 0.3, wd 0.9) makes any drop blow far
    past tolerance against the replicated step."""
    from deeplearning4j_tpu.optimize import transforms as T

    cfg = tiny_cfg(causal=False)
    tokens = jax.random.randint(jax.random.key(5), (8, 16), 0, cfg.vocab_size)
    targets = jnp.roll(tokens, -1, axis=1)
    mesh = make_mesh(MeshSpec(dp=2, sp=1, tp=1), devices=jax.devices()[:2])
    model = TransformerLM(cfg, mesh=mesh)
    p_init = TransformerLM(cfg).init(jax.random.key(1))
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)

    def tx():
        return T.adamw(0.3, weight_decay=0.9)

    p0 = model.place(copy(p_init))
    o0 = model.init_opt(p0, tx())
    p0, _, _ = model.build_train_step(tx())(p0, o0, tokens, targets)

    p1 = model.place(copy(p_init))
    o1 = model.init_opt_zero1(p1, tx())
    p1, _, _ = model.build_train_step(tx(), zero1=True)(p1, o1, tokens, targets)

    # decay moved the embedding by ~lr*wd*|w| >> atol; a dropped decay
    # cannot pass this comparison
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_sample_continues_a_learned_cycle():
    """Generation parity with the LSTM's sampling seam: train the causal
    flagship on a strictly periodic token stream, then greedy sample must
    continue the cycle; temperature sampling is deterministic per key."""
    period = [3, 1, 4, 1, 5, 9, 2, 6]
    cfg = tiny_cfg(vocab_size=16, causal=True)
    stream = np.array(period * 32, np.int32)
    span = cfg.max_len + 1
    n = len(stream) // span
    blocks = stream[:n * span].reshape(n, span)
    tokens = jnp.asarray(blocks[:, :-1])
    targets = jnp.asarray(blocks[:, 1:])

    from deeplearning4j_tpu.optimize import transforms as T
    model = TransformerLM(cfg)
    tx = T.adamw(0.01)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    for _ in range(60):
        params, opt, loss = step(params, opt, tokens, targets)

    prime = period[:4]                     # 3 1 4 1 -> 5 9 2 6 3 ...
    out = model.sample(params, prime, length=8, temperature=0.0)
    want = (period * 3)[:len(out)]
    assert out == want, (out, want)

    # same key -> same continuation; different keys may differ
    a = model.sample(params, prime, 8, temperature=0.8, key=jax.random.key(1))
    b = model.sample(params, prime, 8, temperature=0.8, key=jax.random.key(1))
    assert a == b
    assert a[:4] == prime


def test_kv_cached_decode_matches_full_forward():
    """decode_step's incremental logits match the full forward position for
    position, and greedy kv-cached sampling reproduces the full-recompute
    path exactly."""
    from deeplearning4j_tpu.models.transformer import (decode_step,
                                                       forward_local,
                                                       init_decode_cache)

    cfg = tiny_cfg(vocab_size=32, causal=True)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    toks = jax.random.randint(jax.random.key(1), (1, cfg.max_len), 0, 32)

    full = forward_local(params, toks, cfg)                 # (1, T, V)
    cache = init_decode_cache(cfg, 1)
    for pos in range(cfg.max_len):
        step_logits, cache = decode_step(params, cache, toks[:, pos],
                                         jnp.int32(pos), cfg)
        np.testing.assert_allclose(np.asarray(step_logits[0]),
                                   np.asarray(full[0, pos]),
                                   rtol=1e-4, atol=1e-4)

    # end-to-end: greedy continuation identical through both paths (train
    # briefly so the argmax is confident, not a numerical coin flip)
    from deeplearning4j_tpu.optimize import transforms as T
    period = [3, 1, 4, 1, 5, 9, 2, 6]
    stream = np.array(period * 32, np.int32)
    span = cfg.max_len + 1
    n = len(stream) // span
    blocks = stream[:n * span].reshape(n, span)
    tx = T.adamw(0.01)
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    tr_t, tr_y = jnp.asarray(blocks[:, :-1]), jnp.asarray(blocks[:, 1:])
    for _ in range(40):
        params, opt, _ = step(params, opt, tr_t, tr_y)

    prime = period[:3]
    a = model.sample(params, prime, 9, temperature=0.0)
    b = model.sample(params, prime, 9, temperature=0.0, kv_cache=True)
    assert a == b, (a, b)
    # the cached path draws the SAME RNG stream (key advances only on
    # generation steps), so temperature sampling agrees across paths too
    c0 = model.sample(params, prime, 9, temperature=0.8,
                      key=jax.random.key(4))
    c1 = model.sample(params, prime, 9, temperature=0.8,
                      key=jax.random.key(4), kv_cache=True)
    assert c0 == c1, (c0, c1)


def test_beam_search_on_flagship():
    """Beam search (LSTM.java BeamSearch seam on the flagship): width-1
    equals greedy decode; wider beams never score worse; the trained cycle
    is recovered with a finite log prob."""
    from deeplearning4j_tpu.optimize import transforms as T

    period = [3, 1, 4, 1, 5, 9, 2, 6]
    cfg = tiny_cfg(vocab_size=16, causal=True)
    stream = np.array(period * 32, np.int32)
    span = cfg.max_len + 1
    n = len(stream) // span
    blocks = stream[:n * span].reshape(n, span)
    model = TransformerLM(cfg)
    tx = T.adamw(0.01)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    tr_t, tr_y = jnp.asarray(blocks[:, :-1]), jnp.asarray(blocks[:, 1:])
    for _ in range(50):
        params, opt, _ = step(params, opt, tr_t, tr_y)

    prime = period[:3]
    greedy = model.sample(params, prime, 9, temperature=0.0)
    b1, s1 = model.beam_search(params, prime, 9, beam_width=1)
    assert b1 == greedy, (b1, greedy)

    b5, s5 = model.beam_search(params, prime, 9, beam_width=5)
    assert np.isfinite(s5) and s5 <= 0.0
    assert s5 >= s1 - 1e-5          # wider beam can't score worse
    assert b5 == (period * 3)[:len(b5)], b5


def test_score_matches_train_step_loss():
    """score() (the reference Model.score seam) reports the same mean token
    cross entropy the train step computes at the current params."""
    cfg = tiny_cfg(causal=True)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    tokens, _ = data(cfg)
    targets = jnp.roll(tokens, -1, axis=1)
    s = model.score(params, tokens, targets)
    copy = jax.tree_util.tree_map(jnp.array, params)
    opt = model.init_opt(copy, lr=0.0)
    _, _, loss = model.build_train_step(lr=0.0)(copy, opt, tokens, targets)
    np.testing.assert_allclose(s, float(loss), rtol=1e-6)
    assert abs(s - np.log(cfg.vocab_size)) < 0.5     # untrained ~ uniform
