"""Record readers — the raw-record ingestion bridge.

Capability match of the reference's Canova bridge
(``datasets/canova/RecordReaderDataSetIterator.java:23,49-142`` wrapping the
external Canova ``RecordReader``): a RecordReader SPI producing per-example
value lists, concrete CSV / in-memory / file-per-example / image readers, and
the bridge iterator that converts records to DataSets (label column -> one
hot).
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator, Protocol, Sequence

import numpy as np

from .dataset import DataSet, to_outcome_matrix
from .iterator import ListDataSetIterator


class RecordReader(Protocol):
    def next_record(self) -> list: ...
    def has_next(self) -> bool: ...
    def reset(self) -> None: ...


class CollectionRecordReader:
    """Records from an in-memory collection of value lists."""

    def __init__(self, records: Sequence[Sequence]):
        self.records = [list(r) for r in records]
        self._i = 0

    def next_record(self) -> list:
        r = self.records[self._i]
        self._i += 1
        return r

    def has_next(self) -> bool:
        return self._i < len(self.records)

    def reset(self) -> None:
        self._i = 0


class CSVRecordReader(CollectionRecordReader):
    """CSV lines -> typed records (numbers parsed, strings kept)."""

    def __init__(self, path: str | Path, skip_lines: int = 0, delimiter: str = ","):
        lines = Path(path).read_text().strip().splitlines()[skip_lines:]
        records = []
        for line in lines:
            if not line.strip():
                continue
            rec = []
            for v in line.split(delimiter):
                v = v.strip()
                try:
                    rec.append(float(v))
                except ValueError:
                    rec.append(v)
            records.append(rec)
        super().__init__(records)


class LineRecordReader(CollectionRecordReader):
    """One record per line (whole line as a single value)."""

    def __init__(self, path: str | Path):
        super().__init__([[l] for l in Path(path).read_text().splitlines() if l])


class ImageRecordReader(CollectionRecordReader):
    """Image files under a directory; label = parent directory name
    (the reference's Canova image reader convention)."""

    def __init__(self, root: str | Path, size: tuple[int, int] = (28, 28)):
        from PIL import Image
        root = Path(root)
        records = []
        for p in sorted(root.rglob("*")):
            if p.suffix.lower() not in (".png", ".jpg", ".jpeg", ".bmp"):
                continue
            img = Image.open(p).convert("L").resize(size)
            arr = np.asarray(img, np.float32).reshape(-1) / 255.0
            records.append(arr.tolist() + [p.parent.name])
        super().__init__(records)


class RecordReaderDataSetIterator(ListDataSetIterator):
    """records -> DataSet batches (``RecordReaderDataSetIterator.java``):
    ``label_index`` column becomes a one-hot target (string labels are
    vocabulary-mapped); -1 = unsupervised (features only, labels=features)."""

    def __init__(self, reader: RecordReader, batch: int = 10,
                 label_index: int = -1, num_classes: int | None = None):
        reader.reset()
        rows = []
        while reader.has_next():
            rows.append(reader.next_record())
        if label_index is None or (label_index == -1 and not num_classes):
            feats = np.asarray(rows, np.float32)
            ds = DataSet(feats, feats)
        else:
            li = label_index % len(rows[0])
            raw = [r[li] for r in rows]
            feats = np.asarray(
                [[float(v) for j, v in enumerate(r) if j != li] for r in rows],
                np.float32)
            try:
                idx = np.asarray([int(float(v)) for v in raw])
            except (TypeError, ValueError):
                vocab = {v: i for i, v in enumerate(sorted({str(v) for v in raw}))}
                idx = np.asarray([vocab[str(v)] for v in raw])
            nc = num_classes or int(idx.max()) + 1
            ds = DataSet(feats, to_outcome_matrix(idx, nc))
        super().__init__(ds, batch)
