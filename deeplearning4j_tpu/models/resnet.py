"""ResNet — the second north-star model family (BASELINE.json: ResNet-50
ImageNet).

TPU-first: NHWC layout, bf16 MXU compute with f32 params, batch-norm with
batch statistics (training) folded next to convs for XLA fusion, and the
data-parallel path through ``parallel.trainer`` (batch sharded on dp,
XLA-inserted gradient all-reduce).  Functional init/apply like ``nn.layers``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    stem_space_to_depth: bool = True  # rewrite the 7x7/2 stem conv as an
    #                                   exactly-equivalent 4x4/1 conv on a
    #                                   2x2 space-to-depth input: C_in=3 is
    #                                   MXU-hostile (contraction 7*7*3=147,
    #                                   channels padded to the 128 lane);
    #                                   the s2d form contracts over 192 with
    #                                   12 input channels (standard TPU
    #                                   ResNet optimization)

    @classmethod
    def resnet18(cls, num_classes=1000, **kw):
        return cls(num_classes=num_classes, stage_sizes=(2, 2, 2, 2), **kw)

    @classmethod
    def resnet50(cls, num_classes=1000, **kw):
        return cls(num_classes=num_classes, stage_sizes=(3, 4, 6, 3), **kw)

    def flops_per_image(self, image_size: int = 224) -> float:
        """Analytic training FLOPs per image (2*MACs forward, ×3 for
        fwd+bwd), counting convs + the classifier matmul.  Used for MFU
        accounting in bench.py (same 2*MACs convention the transformer leg
        validates against XLA ``cost_analysis()`` there)."""
        def conv_flops(hw, k, cin, cout, stride):
            out_hw = hw // stride
            return 2.0 * out_hw * out_hw * k * k * cin * cout, out_hw

        total, hw = 0.0, image_size
        f, hw = conv_flops(hw, 7, 3, self.width, 2)          # stem
        total += f
        hw //= 2                                             # 3x3/2 max pool
        c_in = self.width
        for s, blocks in enumerate(self.stage_sizes):
            c_mid = self.width * (2 ** s)
            c_out = c_mid * 4
            for b in range(blocks):
                stride = 2 if (s > 0 and b == 0) else 1
                f1, _ = conv_flops(hw, 1, c_in, c_mid, 1)
                f2, hw2 = conv_flops(hw, 3, c_mid, c_mid, stride)
                f3, _ = conv_flops(hw2, 1, c_mid, c_out, 1)
                total += f1 + f2 + f3
                if c_in != c_out or stride != 1:
                    fp, _ = conv_flops(hw, 1, c_in, c_out, stride)
                    total += fp
                hw = hw2
                c_in = c_out
        total += 2.0 * c_in * self.num_classes               # head matmul
        return 3.0 * total                                   # fwd + bwd


def _conv_init(key, shape, pd):
    fan_in = shape[0] * shape[1] * shape[2]
    return (jax.random.normal(key, shape) * np.sqrt(2.0 / fan_in)).astype(pd)


def _bn_params(c, pd):
    return {"scale": jnp.ones((c,), pd), "bias": jnp.zeros((c,), pd)}


def init_params(key, cfg: ResNetConfig) -> dict:
    pd = cfg.param_dtype
    keys = iter(jax.random.split(key, 2048))
    params: dict = {
        "stem": {"conv": _conv_init(next(keys), (7, 7, 3, cfg.width), pd),
                 "bn": _bn_params(cfg.width, pd)},
        "stages": [],
    }
    c_in = cfg.width
    for s, blocks in enumerate(cfg.stage_sizes):
        c_mid = cfg.width * (2 ** s)
        c_out = c_mid * 4
        stage = []
        for b in range(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk = {
                "conv1": _conv_init(next(keys), (1, 1, c_in, c_mid), pd),
                "bn1": _bn_params(c_mid, pd),
                "conv2": _conv_init(next(keys), (3, 3, c_mid, c_mid), pd),
                "bn2": _bn_params(c_mid, pd),
                "conv3": _conv_init(next(keys), (1, 1, c_mid, c_out), pd),
                "bn3": _bn_params(c_out, pd),
            }
            if c_in != c_out or stride != 1:
                blk["proj"] = _conv_init(next(keys), (1, 1, c_in, c_out), pd)
                blk["proj_bn"] = _bn_params(c_out, pd)
            stage.append(blk)
            c_in = c_out
        params["stages"].append(stage)
    params["head"] = {
        "w": (jax.random.normal(next(keys), (c_in, cfg.num_classes)) *
              np.sqrt(1.0 / c_in)).astype(pd),
        "b": jnp.zeros((cfg.num_classes,), pd),
    }
    return params


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    return lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=(0, 1, 2), keepdims=True)
    var = x32.var(axis=(0, 1, 2), keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def _bottleneck(x, blk, stride, dtype):
    h = jax.nn.relu(_bn(_conv(x, blk["conv1"], 1, dtype), blk["bn1"]))
    h = jax.nn.relu(_bn(_conv(h, blk["conv2"], stride, dtype), blk["bn2"]))
    h = _bn(_conv(h, blk["conv3"], 1, dtype), blk["bn3"])
    if "proj" in blk:
        x = _bn(_conv(x, blk["proj"], stride, dtype), blk["proj_bn"])
    return jax.nn.relu(x + h)


def _space_to_depth(x):
    """(N, H, W, C) -> (N, H/2, W/2, 4C), channel-minor order (a, b, c)."""
    N, H, W, C = x.shape
    x = x.reshape(N, H // 2, 2, W // 2, 2, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(N, H // 2, W // 2, 4 * C)


def _stem_s2d_kernel(w):
    """Rearrange the (7, 7, C, O) stride-2 stem kernel into the (4, 4, 4C, O)
    stride-1 kernel that computes the identical map on a space-to-depth
    input: pad to 8x8 (the extra taps are zero), then space-to-depth the
    kernel itself with the same (a, b, c) channel order as the input."""
    _, _, C, O = w.shape
    wp = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    wp = wp.reshape(4, 2, 4, 2, C, O)
    return wp.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * C, O)


def forward(params, images, cfg: ResNetConfig) -> jnp.ndarray:
    """images: (N, H, W, 3) -> logits (N, num_classes)."""
    dt = cfg.dtype
    N, H, W, _ = images.shape
    if cfg.stem_space_to_depth and H % 2 == 0 and W % 2 == 0:
        # SAME on the s2d conv reproduces SAME on the original exactly:
        # k=7 s=2 pads (2, 3) on 2H -> k=4 s=1 pads (1, 2) on H
        w = _stem_s2d_kernel(params["stem"]["conv"]).astype(dt)
        x = lax.conv_general_dilated(
            _space_to_depth(images).astype(dt), w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    else:
        x = _conv(images, params["stem"]["conv"], 2, dt)
    x = jax.nn.relu(_bn(x, params["stem"]["bn"]))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for s, stage in enumerate(params["stages"]):
        for b, blk in enumerate(stage):
            stride = 2 if (s > 0 and b == 0) else 1
            x = _bottleneck(x, blk, stride, dt)
    x = x.mean(axis=(1, 2)).astype(jnp.float32)       # global average pool
    return x @ params["head"]["w"].astype(jnp.float32) + params["head"]["b"]


def cross_entropy(params, images, labels, cfg: ResNetConfig) -> jnp.ndarray:
    logits = forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


class ResNet:
    def __init__(self, cfg: ResNetConfig):
        self.cfg = cfg
        self.params = None
        self._fwd = None

    def init(self, key=None):
        self.params = init_params(key if key is not None else jax.random.key(0),
                                  self.cfg)
        return self.params

    def predict_logits(self, images):
        if self._fwd is None:
            self._fwd = jax.jit(partial(forward, cfg=self.cfg))
        return self._fwd(self.params, jnp.asarray(images))

    def loss_fn(self):
        """(params, x, y, key) -> scalar, pluggable into parallel.trainer."""
        cfg = self.cfg
        return lambda p, x, y, k=None: cross_entropy(p, x, y, cfg)
