"""Goodput accounting, time-series telemetry, SLO burn rates, perf gate.

The ISSUE-14 observability tier: deterministic interval accounting
(explicit timestamps, no sleeps), the store's bounded rings + torn-tail
JSONL reader, burn-rate math for all three objective kinds with a real
breach bundle on disk, the disabled-is-free contract (no thread, no
hot-path allocation), the histogram window cap, and the perf gate's
seed/idempotent/regression behavior.
"""

import json
import random
import threading
import tracemalloc

import pytest

from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.observability import (
    GoodputTracker,
    SLObjective,
    SLOEvaluator,
    TimeSeriesStore,
)
from deeplearning4j_tpu.observability.flightrec import FlightRecorder
from deeplearning4j_tpu.observability.goodput import STATES
from deeplearning4j_tpu.observability.metrics import Histogram, MetricsRegistry
from deeplearning4j_tpu.observability.timeseries import (
    read_back,
    read_back_series,
)


# ------------------------------------------------------------------ goodput


def test_goodput_exact_state_sequence_and_accounting():
    """A fixed transition plan with explicit timestamps yields an exact
    coalesced state sequence, and the per-state seconds sum to wall-clock
    with no drift at all (contiguous intervals by construction)."""
    gp = GoodputTracker(registry=MetricsRegistry())
    t = gp.started_at
    gp.transition("checkpoint", t + 1.0)
    gp.transition("productive", t + 1.5)
    gp.data_wait(t + 2.0, t + 2.3)           # >= threshold: carved as stall
    gp.transition("rollback", t + 3.0)
    gp.transition("restore", t + 3.5)
    gp.transition("productive", t + 4.0)
    gp.transition("drain", t + 5.0)
    rep = gp.finish(t + 5.5)

    assert rep["states"] == [
        "productive", "checkpoint", "productive", "stall", "productive",
        "rollback", "restore", "productive", "drain"]
    assert rep["wall_seconds"] == pytest.approx(5.5)
    assert rep["accounted_seconds"] == pytest.approx(rep["wall_seconds"],
                                                     abs=1e-9)
    assert rep["seconds"]["productive"] == pytest.approx(1.0 + 0.5 + 0.7 + 1.0)
    assert rep["seconds"]["stall"] == pytest.approx(0.3)
    assert rep["seconds"]["drain"] == pytest.approx(0.5)
    assert rep["fraction"] == pytest.approx(3.2 / 5.5)
    assert set(rep["seconds"]) == set(STATES)
    # finish() is idempotent: same report, clock does not move
    assert gp.finish() == rep


def test_goodput_subthreshold_wait_stays_productive():
    gp = GoodputTracker(registry=MetricsRegistry(), stall_threshold_s=0.5)
    t = gp.started_at
    gp.data_wait(t + 1.0, t + 1.2)           # under threshold: ignored
    rep = gp.finish(t + 2.0)
    assert rep["states"] == ["productive"]
    assert rep["seconds"]["stall"] == 0.0
    assert rep["fraction"] == pytest.approx(1.0)


def test_goodput_phase_restores_previous_state():
    gp = GoodputTracker(registry=MetricsRegistry())
    assert gp.state == "productive"
    with gp.phase("checkpoint"):
        assert gp.state == "checkpoint"
        with gp.phase("stall"):
            assert gp.state == "stall"
        assert gp.state == "checkpoint"
    assert gp.state == "productive"


def test_goodput_coalesces_repeated_state():
    gp = GoodputTracker(registry=MetricsRegistry())
    t = gp.started_at
    gp.transition("checkpoint", t + 1.0)
    gp.transition("checkpoint", t + 1.5)     # same state: merged
    gp.transition("productive", t + 2.0)
    rep = gp.finish(t + 3.0)
    assert rep["states"] == ["productive", "checkpoint", "productive"]
    assert rep["seconds"]["checkpoint"] == pytest.approx(1.0)


def test_goodput_timeline_cap_keeps_seconds_exact():
    gp = GoodputTracker(registry=MetricsRegistry(), timeline_cap=4)
    t = gp.started_at
    for i in range(20):
        gp.transition("stall" if i % 2 == 0 else "productive",
                      t + 0.1 * (i + 1))
    rep = gp.finish(t + 2.1)
    assert rep["timeline_dropped"] > 0
    assert len(rep["timeline"]) <= 4
    # the cap only bounds the *narrative*; the accounting stays exact
    assert rep["accounted_seconds"] == pytest.approx(rep["wall_seconds"],
                                                     abs=1e-9)


def test_goodput_publishes_gauges_on_finish():
    reg = MetricsRegistry()
    gp = GoodputTracker(registry=reg)
    t = gp.started_at
    gp.transition("checkpoint", t + 1.0)
    gp.transition("productive", t + 2.0)
    gp.finish(t + 4.0)
    gauges = reg.snapshot()["gauges"]
    assert gauges["goodput.fraction"] == pytest.approx(3.0 / 4.0)
    assert gauges["goodput.wall_seconds"] == pytest.approx(4.0)
    assert gauges["goodput.seconds.checkpoint"] == pytest.approx(1.0)
    for s in STATES:
        assert f"goodput.seconds.{s}" in gauges


def test_goodput_rejects_unknown_state():
    gp = GoodputTracker(registry=MetricsRegistry())
    with pytest.raises(ValueError):
        gp.transition("coffee_break")


# --------------------------------------------------------------- timeseries


def test_timeseries_ring_overflow_counts_dropped():
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg, ring=8)
    for i in range(20):
        reg.gauge("g", float(i))
        store.sample_once(t=100.0 + i)
    pts = store.series("g")
    assert len(pts) == 8                     # ring bound holds
    assert pts[-1] == (119.0, 19.0)
    assert pts[0] == (112.0, 12.0)           # oldest 12 evicted
    stats = store.stats()
    assert stats["dropped"]["g"] == 12
    assert stats["dropped_total"] == 12
    assert stats["samples"] == 20
    # window() trims by time, not count
    assert [v for _, v in store.window("g", 3.0, now=119.0)] == [
        16.0, 17.0, 18.0, 19.0]


def test_timeseries_samples_counters_gauges_and_quantiles():
    reg = MetricsRegistry()
    reg.increment("c", 3)
    reg.gauge("g", 2.5)
    for v in (0.1, 0.2, 0.3):
        reg.observe_time("op", v)
    store = TimeSeriesStore(registry=reg)
    n = store.sample_once(t=10.0)
    assert n == len(store.names())
    assert store.last("c") == 3.0
    assert store.last("g") == 2.5
    assert store.last("op.p50") == pytest.approx(0.2)
    assert "op.p99" in store.names()


def test_timeseries_jsonl_roundtrip_tolerates_torn_tail(tmp_path):
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg, out_dir=tmp_path)
    for i in range(5):
        reg.gauge("g", float(i))
        store.sample_once(t=50.0 + i)
    path = store.out_path
    assert path is not None and path.exists()
    with open(path, "a") as f:
        f.write('{"t": 55.0, "series": {"g": 5')     # killed mid-append
    rows = read_back(path)
    assert len(rows) == 5                            # torn tail skipped
    merged = read_back_series([path])
    assert merged["g"] == [(50.0 + i, float(i)) for i in range(5)]


def test_timeseries_background_thread_lifecycle():
    reg = MetricsRegistry()
    reg.gauge("g", 1.0)
    store = TimeSeriesStore(registry=reg, interval_s=0.01)
    assert store.start() is True
    assert store.start() is False            # second start refuses
    assert store.running
    deadline = threading.Event()
    for _ in range(200):                     # ~2 s worst case
        if store.stats()["samples"] >= 2:
            break
        deadline.wait(0.01)
    store.stop()
    assert not store.running
    assert store.stats()["samples"] >= 2
    assert store.last("g") == 1.0


def test_timeseries_evaluator_runs_after_sample():
    reg = MetricsRegistry()
    reg.gauge("g", 7.0)
    store = TimeSeriesStore(registry=reg)
    seen = []
    store.add_evaluator(lambda s, t: seen.append((s.last("g"), t)))
    store.sample_once(t=42.0)
    assert seen == [(7.0, 42.0)]


# ----------------------------------------------------------- disabled-free


def test_disabled_spawns_no_thread_and_allocates_nothing():
    """DL4J_TPU_OBS=0 contract: start() refuses to spawn, and the sample
    hot path performs zero allocations while disabled."""
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg, interval_s=0.01)
    obs.disable()
    try:
        before = threading.active_count()
        assert store.start() is False
        assert threading.active_count() == before
        assert not store.running
        assert store.sample_once() == 0
        assert store.stats()["samples"] == 0

        evaluator = SLOEvaluator(
            [SLObjective("x", "upper", "x", 1.0)], store, registry=reg,
            flightrec=FlightRecorder(), attach=False)
        assert evaluator.evaluate(store, now=1.0) == {}

        store.sample_once()                  # warm any lazy caches first
        tracemalloc.start()
        base = tracemalloc.get_traced_memory()[0]
        for _ in range(50):
            store.sample_once()
        grown = tracemalloc.get_traced_memory()[0] - base
        tracemalloc.stop()
        assert grown == 0, f"disabled sample path allocated {grown} bytes"
    finally:
        obs.enable()


# ---------------------------------------------------------------------- slo


def _fed_store(points, name="x", t0=100.0):
    """A store whose ``name`` ring holds ``points`` at 1 s spacing."""
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg)
    for i, v in enumerate(points):
        reg.gauge(name, v)
        store.sample_once(t=t0 + i)
    return reg, store


def test_slo_upper_burn_math_and_full_window():
    # 10 points, half above the 1.0 objective, budget 0.5
    # -> bad fraction 0.5, burn exactly 1.0
    reg, store = _fed_store([0.5, 2.0] * 5)
    obj = SLObjective("lat", "upper", "x", 1.0, budget=0.5, windows=(9.0,))
    ev = SLOEvaluator([obj], store, registry=reg,
                      flightrec=FlightRecorder(), attach=False)
    out = ev.evaluate(store, now=109.0)
    burn = out["lat"][0]
    assert burn.full                         # oldest point covers the window
    assert burn.samples == 10
    assert burn.burn == pytest.approx(1.0)
    assert reg.snapshot()["gauges"]["slo.burn_rate.lat"] == pytest.approx(1.0)


def test_slo_lower_and_rate_kinds():
    # lower: goodput floor 0.5; 4 of 5 points below -> burn (0.8)/0.2 = 4
    reg, store = _fed_store([0.4, 0.3, 0.6, 0.2, 0.1])
    low = SLObjective("gp", "lower", "x", 0.5, budget=0.2, windows=(4.0,))
    ev = SLOEvaluator([low], store, registry=reg,
                      flightrec=FlightRecorder(), attach=False,
                      breach_cooldown_s=0.0)
    out = ev.evaluate(store, now=104.0)
    assert out["gp"][0].burn == pytest.approx((4 / 5) / 0.2)

    # rate: 10 errors over 100 requests = 10%, objective 5% -> burn 2.0
    reg2 = MetricsRegistry()
    store2 = TimeSeriesStore(registry=reg2)
    for i in range(6):
        reg2.gauge("err", 2.0 * i)           # cumulative counters, sampled
        reg2.gauge("req", 20.0 * i)
        store2.sample_once(t=200.0 + i)
    rate = SLObjective("errs", "rate", "err", 0.05, denominator="req",
                       windows=(5.0,))
    ev2 = SLOEvaluator([rate], store2, registry=reg2,
                       flightrec=FlightRecorder(), attach=False)
    out2 = ev2.evaluate(store2, now=205.0)
    assert out2["errs"][0].burn == pytest.approx((10.0 / 100.0) / 0.05)


def test_slo_breach_dumps_bundle_with_series_tail(tmp_path):
    reg, store = _fed_store([5.0] * 12)      # everything bad: burn >> 1
    obj = SLObjective("lat", "upper", "x", 1.0, budget=0.5,
                      windows=(5.0, 10.0))
    ev = SLOEvaluator([obj], store, registry=reg,
                      flightrec=FlightRecorder(dump_dir=tmp_path),
                      attach=False, breach_cooldown_s=60.0)
    ev.evaluate(store, now=111.0)
    assert len(ev.breaches) == 1
    assert reg.snapshot()["counters"]["slo.breaches"] == 1

    bundle = json.loads(open(ev.breaches[0]).read())
    extra = bundle["extra"]
    assert extra["objective"] == "lat"
    assert extra["kind"] == "upper"
    assert extra["series"] == "x"
    assert len(extra["windows"]) == 2
    assert all(w["burn"] > 1.0 for w in extra["windows"])
    assert extra["series_tail"]              # the offending tail is included
    assert extra["series_tail"][-1] == [111.0, 5.0]

    # cooldown: an immediately-following evaluation does not double-dump
    ev.evaluate(store, now=112.0)
    assert len(ev.breaches) == 1


def test_slo_no_breach_without_a_full_window():
    # only 3 points over 2 s of history: the 30 s window is never covered,
    # so even an all-bad series must not page
    reg, store = _fed_store([5.0, 5.0, 5.0])
    obj = SLObjective("lat", "upper", "x", 1.0, windows=(30.0,))
    ev = SLOEvaluator([obj], store, registry=reg,
                      flightrec=FlightRecorder(), attach=False)
    out = ev.evaluate(store, now=102.0)
    assert not out["lat"][0].full
    assert ev.breaches == []


# ---------------------------------------------------------------- histogram


def test_histogram_window_cap_bounds_memory_and_keeps_quantiles():
    """At 10x the window cap the reservoir stays bounded, evictions are
    counted, and p50/p95/p99 of a stationary stream stay within
    tolerance of the true quantiles (the window IS the recent
    distribution)."""
    cap = 64
    h = Histogram(window=cap)
    rng = random.Random(7)
    for _ in range(10 * cap):
        h.observe(rng.random())              # uniform [0, 1)
    assert len(h.values) == cap
    s = h.summary()
    assert s["dropped"] == 10 * cap - cap
    assert s["count"] == 10 * cap            # cumulative count is unwindowed
    assert abs(s["p50_s"] - 0.50) < 0.15
    assert abs(s["p95_s"] - 0.95) < 0.10
    assert abs(s["p99_s"] - 0.99) < 0.10


def test_registry_surfaces_dropped_samples_counter():
    reg = MetricsRegistry()
    small = Histogram(window=16)
    with reg._lock:
        reg.timers["op"] = small
    for i in range(40):
        reg.observe_time("op", i * 0.001)
    snap = reg.snapshot()
    assert snap["counters"]["metrics.dropped_samples"] == 24.0
    assert "metrics_dropped_samples_total 24.0" in reg.to_prometheus()


# ---------------------------------------------------------------- perf gate


def _write(path, obj):
    path.write_text(json.dumps(obj))
    return path


def test_perf_gate_seeds_then_idempotent_then_fails(tmp_path):
    from tools.perf_gate import run

    traj = tmp_path / "traj.json"
    art = _write(tmp_path / "bench.json",
                 {"value": 1000.0, "extra": {"mfu": 0.4}})

    first = run(art, traj)                   # empty trajectory: self-seeds
    assert first["seeded"] and first["ok"] and first["recorded"]
    assert first["series"] == {"mfu": 0.4, "tokens_per_sec": 1000.0}

    second = run(art, traj)                  # same artifact: within any tol
    assert second["ok"] and not second["seeded"] and not second["recorded"]
    assert set(second["compared"]) == {"mfu", "tokens_per_sec"}

    bad = _write(tmp_path / "bad.json",
                 {"value": 900.0, "extra": {"mfu": 0.4}})   # -10% tokens/sec
    res = run(bad, traj)
    assert not res["ok"]
    assert len(res["failures"]) == 1
    assert "tokens_per_sec" in res["failures"][0]
    assert "5%" in res["failures"][0]        # names the tolerance


def test_perf_gate_direction_and_record(tmp_path):
    from tools.perf_gate import main, run

    traj = tmp_path / "traj.json"
    _write(traj, {"tolerance": 0.05, "series_tolerance": {},
                  "entries": [{"label": "seed", "source": "x",
                               "series": {"ttft_p99_s": 0.100}}]})
    # lower-is-better: a faster TTFT passes, a 10% slower one fails
    fast = _write(tmp_path / "fast.json", {"ttft_s": {"p99": 0.080}})
    slow = _write(tmp_path / "slow.json", {"ttft_s": {"p99": 0.110}})
    assert run(fast, traj)["ok"]
    res = run(slow, traj)
    assert not res["ok"] and "ttft_p99_s" in res["failures"][0]

    # --record appends a new baseline entry the next gate is held to
    rc = main([str(fast), "--trajectory", str(traj), "--record",
               "--label", "fast run"])
    assert rc == 0
    entries = json.loads(traj.read_text())["entries"]
    assert entries[-1]["label"] == "fast run"
    assert entries[-1]["series"] == {"ttft_p99_s": 0.080}
    # new baseline 0.080: the old 0.100 would now itself be a regression
    old = _write(tmp_path / "old.json", {"ttft_s": {"p99": 0.100}})
    assert not run(old, traj)["ok"]


def test_perf_gate_per_series_tolerance(tmp_path):
    from tools.perf_gate import run

    traj = tmp_path / "traj.json"
    _write(traj, {"tolerance": 0.05,
                  "series_tolerance": {"goodput_fraction": 0.5},
                  "entries": [{"label": "seed", "source": "x",
                               "series": {"goodput_fraction": 0.8}}]})
    # -40% goodput sits inside its widened 50% band...
    ok = _write(tmp_path / "ok.json", {"goodput": {"fraction": 0.48}})
    assert run(ok, traj)["ok"]
    # ...but -60% does not
    bad = _write(tmp_path / "bad.json", {"goodput": {"fraction": 0.3}})
    res = run(bad, traj)
    assert not res["ok"] and "goodput_fraction" in res["failures"][0]


def test_perf_gate_device_scoped_baselines(tmp_path):
    from tools.perf_gate import run

    traj = tmp_path / "traj.json"
    _write(traj, {"tolerance": 0.05, "series_tolerance": {},
                  "entries": [{"label": "tpu seed", "source": "x",
                               "device": "tpu",
                               "series": {"tokens_per_sec": 87000.0}},
                              {"label": "cpu seed", "source": "y",
                               "device": "cpu", "tolerance": 0.3,
                               "series": {"tokens_per_sec": 10000.0}}]})
    # a CPU-fallback artifact is held to the CPU entry's loose band,
    # never to the TPU baseline 8x above it
    cpu = _write(tmp_path / "cpu.json",
                 {"metric": "bert_CPU_FALLBACK", "value": 9000.0,
                  "extra": {"device": "TFRT_CPU_0"}})
    res = run(cpu, traj)
    assert res["device"] == "cpu" and res["ok"], res["failures"]
    # -30% busts even the loose CPU band
    slow = _write(tmp_path / "slow.json",
                  {"metric": "bert_CPU_FALLBACK", "value": 6900.0,
                   "extra": {"device": "TFRT_CPU_0"}})
    assert not run(slow, traj)["ok"]
    # a TPU artifact skips the CPU entry and fails against the TPU seed
    tpu = _write(tmp_path / "tpu.json",
                 {"metric": "bert_base_train_tokens_per_sec",
                  "value": 70000.0, "extra": {"device": "TPU v5 lite"}})
    res = run(tpu, traj)
    assert res["device"] == "tpu" and not res["ok"]
    assert "87000" in res["failures"][0]
