"""Analytic utilization accounting from XLA ``cost_analysis()``.

``COSTS.capture("train_step.b128", step_fn, *args)`` lowers+compiles the
jitted fn for the concrete arg shapes, pulls XLA's cost analysis (FLOPs and
bytes accessed), and caches the result per (key, arg-signature) — so the
second trace is paid once per compiled signature, amortized by the
persistent XLA compile cache.  Combined with a measured wall time, the
result publishes live utilization gauges:

    ``{prefix}.mfu``  = flops / (seconds * peak_flops)
    ``{prefix}.mbu``  = bytes_accessed / (seconds * peak_bytes_per_s)

the same accounting bench.py reports, so artifact and ``/metrics.prom``
agree.  Caveats (see DESIGN.md §18): some backends return no
``cost_analysis`` or report ``flops <= 0`` ("unknown"); ``capture`` then
falls back to a caller-supplied analytic FLOPs estimate, or returns None —
callers must treat None as "no utilization numbers", never an error.

Capturing is safe before a donating call: ``fn.lower(*args)`` reads only
shapes/dtypes and does not consume donated buffers.
"""

from __future__ import annotations

import math
import threading
from dataclasses import dataclass
from typing import Any

from . import core
from .metrics import METRICS

# Nominal peak numbers keyed by substring of ``device_kind.lower()``.
# The TPU rows mirror bench.py's PEAK_FLOPS table (v5e bf16); the CPU rows
# are nominal single-socket figures so CPU test runs produce finite, small
# MFU values rather than NaN.
PEAK_FLOPS: dict[str, float] = {
    "tpu v5 lite": 197e12,
    "tpu v5": 197e12,
    "tpu": 197e12,
    "cpu": 5e10,
}
PEAK_BYTES_PER_S: dict[str, float] = {
    "tpu v5 lite": 819e9,   # v5e HBM bandwidth
    "tpu v5": 819e9,
    "tpu": 819e9,
    "cpu": 2e10,
}


def _lookup(table: dict[str, float], kind: str) -> float | None:
    kind = kind.lower()
    for key in sorted(table, key=len, reverse=True):
        if key in kind:
            return table[key]
    return None


def _device_kind() -> str:
    try:
        import jax
        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


@dataclass(frozen=True)
class CostInfo:
    """Per-execution cost of one compiled fn (whole program, all devices)."""
    flops: float
    bytes_accessed: float
    source: str  # "xla" | "analytic"


def _signature(args: tuple) -> tuple:
    """Hashable (shape, dtype) signature of a concrete arg tree."""
    import jax
    sig = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append((type(leaf).__name__, repr(leaf)[:32]))
    return tuple(sig)


def _extract(analysis: Any) -> tuple[float, float]:
    """Pull (flops, bytes_accessed) out of ``cost_analysis()``'s return,
    which is a dict on some backends and a list of per-program dicts on
    others.  Missing/garbage values come back as 0.0."""
    if analysis is None:
        return 0.0, 0.0
    entries = analysis if isinstance(analysis, (list, tuple)) else [analysis]
    flops = 0.0
    nbytes = 0.0
    for entry in entries:
        if not isinstance(entry, dict):
            continue
        try:
            f = float(entry.get("flops", 0.0))
            if math.isfinite(f) and f > 0:
                flops += f
        except (TypeError, ValueError):
            pass
        try:
            b = float(entry.get("bytes accessed", 0.0))
            if math.isfinite(b) and b > 0:
                nbytes += b
        except (TypeError, ValueError):
            pass
    return flops, nbytes


class CostModel:
    """Caches per-compiled-signature cost; publishes utilization gauges."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache: dict[tuple, CostInfo | None] = {}
        self._by_key: dict[str, CostInfo] = {}

    # ------------------------------------------------------------- capture
    def capture(self, key: str, fn, *args,
                analytic_flops: float | None = None) -> CostInfo | None:
        """Cost of ``fn(*args)`` for these concrete arg shapes, from XLA's
        cost analysis (cached per signature).  ``fn`` must be jitted (have
        ``.lower``).  Never raises; returns None when no cost is knowable
        and no ``analytic_flops`` fallback was given."""
        if not core.enabled():
            return None
        try:
            sig = (key,) + _signature(args)
        except Exception:
            return None
        with self._lock:
            if sig in self._cache:
                info = self._cache[sig]
                if info is not None:
                    self._by_key[key] = info
                return info
        info = None
        try:
            compiled = fn.lower(*args).compile()
            flops, nbytes = _extract(compiled.cost_analysis())
            if flops > 0:
                info = CostInfo(flops, nbytes, "xla")
        except Exception:
            info = None
        if info is None and analytic_flops is not None and analytic_flops > 0:
            info = CostInfo(float(analytic_flops), 0.0, "analytic")
        with self._lock:
            self._cache[sig] = info
            if info is not None:
                self._by_key[key] = info
        return info

    def put(self, key: str, info: CostInfo) -> None:
        """Install an externally computed cost under ``key``."""
        with self._lock:
            self._by_key[key] = info

    def get(self, key: str) -> CostInfo | None:
        """Most recently captured cost for ``key`` (any signature)."""
        with self._lock:
            return self._by_key.get(key)

    # ------------------------------------------------------------- peaks
    def peak_flops(self) -> float | None:
        return _lookup(PEAK_FLOPS, _device_kind())

    def peak_bytes_per_s(self) -> float | None:
        return _lookup(PEAK_BYTES_PER_S, _device_kind())

    # ------------------------------------------------------------- publish
    def publish_utilization(self, info: CostInfo | None, seconds: float,
                            mfu_gauge: str, mbu_gauge: str | None = None,
                            registry=None) -> float | None:
        """Gauge ``mfu_gauge`` (and ``mbu_gauge`` when bytes are known)
        from one execution's cost and measured wall seconds.  Returns the
        MFU value, or None when nothing could be published."""
        if info is None or not (seconds > 0) or not core.enabled():
            return None
        reg = registry if registry is not None else METRICS
        mfu = None
        peak_f = self.peak_flops()
        if peak_f and info.flops > 0:
            mfu = info.flops / (seconds * peak_f)
            if math.isfinite(mfu):
                reg.gauge(mfu_gauge, mfu)
            else:
                mfu = None
        peak_b = self.peak_bytes_per_s()
        if mbu_gauge and peak_b and info.bytes_accessed > 0:
            mbu = info.bytes_accessed / (seconds * peak_b)
            if math.isfinite(mbu):
                reg.gauge(mbu_gauge, mbu)
        return mfu

    def clear(self) -> None:
        with self._lock:
            self._cache.clear()
            self._by_key.clear()


COSTS = CostModel()
