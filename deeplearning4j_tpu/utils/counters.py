"""Counter / CounterMap / Index — the vendored Berkeley-NLP util surface.

Capability match of ``berkeley/Counter.java`` (598 LoC), ``CounterMap.java``
(390), ``Index``/``Pair``/``Triple``: float-valued counters with
normalize/argmax/pruning, nested counters, and a bidirectional index.
Python's stdlib covers much of this; these classes keep the API the
reference's NLP code shapes itself around.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Generic, Hashable, Iterable, Iterator, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V", bound=Hashable)


class Counter(Generic[K]):
    def __init__(self, items: Iterable[K] | None = None):
        self._m: dict[K, float] = defaultdict(float)
        if items:
            for it in items:
                self.increment(it)

    def increment(self, key: K, by: float = 1.0) -> None:
        self._m[key] += by

    def set_count(self, key: K, value: float) -> None:
        self._m[key] = value

    def get_count(self, key: K) -> float:
        return self._m.get(key, 0.0)

    def remove(self, key: K) -> None:
        self._m.pop(key, None)

    def total_count(self) -> float:
        return sum(self._m.values())

    def normalize(self) -> None:
        total = self.total_count()
        if total:
            for k in self._m:
                self._m[k] /= total

    def argmax(self) -> K | None:
        return max(self._m, key=self._m.get) if self._m else None

    def max_count(self) -> float:
        return max(self._m.values()) if self._m else 0.0

    def keep_top_n(self, n: int) -> None:
        top = sorted(self._m.items(), key=lambda kv: -kv[1])[:n]
        self._m = defaultdict(float, top)

    def prune_below(self, threshold: float) -> None:
        self._m = defaultdict(
            float, {k: v for k, v in self._m.items() if v >= threshold})

    def sorted_keys(self) -> list[K]:
        return sorted(self._m, key=lambda k: -self._m[k])

    def __contains__(self, key: K) -> bool:
        return key in self._m

    def __len__(self) -> int:
        return len(self._m)

    def __iter__(self) -> Iterator[K]:
        return iter(self._m)

    def items(self):
        return self._m.items()


class CounterMap(Generic[K, V]):
    def __init__(self):
        self._m: dict[K, Counter[V]] = {}

    def increment(self, key: K, sub: V, by: float = 1.0) -> None:
        self._m.setdefault(key, Counter()).increment(sub, by)

    def get_count(self, key: K, sub: V) -> float:
        c = self._m.get(key)
        return c.get_count(sub) if c else 0.0

    def get_counter(self, key: K) -> Counter[V]:
        return self._m.setdefault(key, Counter())

    def total_count(self) -> float:
        return sum(c.total_count() for c in self._m.values())

    def normalize(self) -> None:
        for c in self._m.values():
            c.normalize()

    def keys(self):
        return self._m.keys()

    def __len__(self) -> int:
        return len(self._m)

    def __contains__(self, key: K) -> bool:
        return key in self._m


class Index(Generic[K]):
    """Bidirectional object<->int index (``util/Index.java``)."""

    def __init__(self, items: Iterable[K] | None = None):
        self._to_int: dict[K, int] = {}
        self._to_obj: list[K] = []
        if items:
            for it in items:
                self.add(it)

    def add(self, item: K) -> int:
        if item not in self._to_int:
            self._to_int[item] = len(self._to_obj)
            self._to_obj.append(item)
        return self._to_int[item]

    def index_of(self, item: K) -> int:
        return self._to_int.get(item, -1)

    def get(self, i: int) -> K:
        return self._to_obj[i]

    def __len__(self) -> int:
        return len(self._to_obj)

    def __contains__(self, item: K) -> bool:
        return item in self._to_int

    def __iter__(self) -> Iterator[K]:
        return iter(self._to_obj)
