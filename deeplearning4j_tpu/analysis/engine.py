"""graftlint engine: walk files, run rules, apply suppressions + baseline.

Suppression syntax (flake8-noqa flavored, but per-rule):

- ``code()  # graftlint: disable=HS01`` — silence HS01 on this line
- ``# graftlint: disable=HS01,RC01`` on a comment-only line — silence on
  the next non-comment line
- ``# graftlint: disable-file=HOT02`` anywhere — silence for the file
- ``disable`` with no ``=RULES`` silences every rule at that scope

A suppressed finding is kept (status ``suppressed``) so ``--json`` output
and the metrics gauges can count them; it never fails ``--check``.
"""

from __future__ import annotations

import os
import re
from typing import Iterable

from .baseline import Baseline
from .core import ACTIVE, BASELINED, SUPPRESSED, Finding, all_rules
from .jitinfo import ModuleInfo

_PRAGMA = re.compile(
    r"#\s*graftlint:\s*(disable(?:-file)?)\s*(?:=\s*([A-Z0-9_,\s]+))?")

#: sentinel rule-set meaning "every rule"
_ALL = frozenset({"*"})


def _parse_pragmas(source: str) -> tuple[dict[int, frozenset[str]],
                                         frozenset[str]]:
    """(line -> suppressed rule ids, file-wide suppressed rule ids)."""
    per_line: dict[int, frozenset[str]] = {}
    file_wide: set[str] = set()
    lines = source.splitlines()
    for i, line in enumerate(lines, start=1):
        m = _PRAGMA.search(line)
        if not m:
            continue
        kind = m.group(1)
        rules = (frozenset(r.strip() for r in m.group(2).split(",") if r.strip())
                 if m.group(2) else _ALL)
        if kind == "disable-file":
            file_wide |= set(rules)
            continue
        stripped = line.strip()
        if stripped.startswith("#"):
            # comment-only pragma: applies to the next non-comment line
            target = i + 1
            while target <= len(lines) and (
                    not lines[target - 1].strip()
                    or lines[target - 1].strip().startswith("#")):
                target += 1
            per_line[target] = per_line.get(target, frozenset()) | rules
        else:
            per_line[i] = per_line.get(i, frozenset()) | rules
    return per_line, frozenset(file_wide)


def _suppressed(rules: frozenset[str], rule_id: str) -> bool:
    return "*" in rules or rule_id in rules


class Analyzer:
    """Run the rule set over sources, then classify each finding as
    active / suppressed / baselined."""

    def __init__(self, rules=None, baseline: Baseline | None = None,
                 root: str | None = None):
        self.rules = rules if rules is not None else list(all_rules().values())
        self.baseline = baseline or Baseline()
        self.root = root  # paths in findings are made relative to this
        self.errors: list[str] = []   # unparseable files (reported, not fatal)
        self.visited_files = 0        # files actually analyzed (--diff proof)
        self.skipped_files = 0        # unreadable/unparseable files skipped

    # ------------------------------------------------------------------ files
    def _relpath(self, path: str) -> str:
        p = os.path.relpath(path, self.root) if self.root else path
        return p.replace("\\", "/")

    def iter_py_files(self, paths: Iterable[str]):
        for path in paths:
            if os.path.isfile(path):
                yield path
            elif os.path.isdir(path):
                for dirpath, dirnames, filenames in os.walk(path):
                    dirnames[:] = sorted(
                        d for d in dirnames
                        if d not in ("__pycache__", ".git", ".cache"))
                    for f in sorted(filenames):
                        if f.endswith(".py"):
                            yield os.path.join(dirpath, f)

    # ------------------------------------------------------------------ run
    def analyze_source(self, source: str, path: str) -> list[Finding]:
        self.visited_files += 1
        try:
            module = ModuleInfo(self._relpath(path), source)
        except (SyntaxError, ValueError) as e:
            # hostile input (syntax error, NUL byte): skip the file with a
            # counted, reported error instead of aborting the whole run
            self.errors.append(f"{path}: {e}")
            self.skipped_files += 1
            return []
        findings: list[Finding] = []
        for rule in self.rules:
            try:
                findings.extend(rule.check(module))
            except Exception as e:  # one brittle rule must not kill the run
                self.errors.append(
                    f"{path}: rule {rule.id} crashed: {e!r}")
        per_line, file_wide = _parse_pragmas(source)
        for f in findings:
            if _suppressed(file_wide, f.rule) or _suppressed(
                    per_line.get(f.line, frozenset()), f.rule):
                f.status = SUPPRESSED
            elif self.baseline.contains(f):
                f.status = BASELINED
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return findings

    def analyze_paths(self, paths: Iterable[str]) -> list[Finding]:
        findings: list[Finding] = []
        for path in self.iter_py_files(paths):
            try:
                with open(path, encoding="utf-8") as fh:
                    source = fh.read()
            except (OSError, UnicodeDecodeError) as e:
                self.errors.append(f"{path}: {e}")
                self.skipped_files += 1
                continue
            findings.extend(self.analyze_source(source, path))
        return findings


def active(findings: Iterable[Finding]) -> list[Finding]:
    return [f for f in findings if f.status == ACTIVE]
