"""Runtime sharding drift detection: the dynamic half of the SH rules.

The static sharding rules (SH01-SH04) reason about the *code*; this
module watches the *dispatches*.  ``ShardGuard.wrap(site, fn, ...)``
returns a call-compatible wrapper around a jitted step function that,
while the guard is enabled, diffs the shardings of the concrete arrays
crossing the call boundary against what the site *placed*:

- **explicit mode** (``in_shardings``/``out_shardings`` given): each
  positional argument's array leaves must carry a sharding equivalent to
  the declared ``NamedSharding`` — the exact placements the caller
  installed with ``device_put``.  A mismatch means XLA will silently
  reshard (an all-to-all per dispatch) before the program even runs:
  the classic "training still converges, 30% slower" bug.
- **baseline mode** (no expectations): the first enabled call captures
  each leaf's sharding as the site's baseline; later calls that arrive
  with a different sharding are flagged as drift.  This is the right
  mode for shard_map'd ZeRO steps and the serving decode dispatch, where
  the placement is an emergent property of the program rather than a
  declared contract.

Each mismatch is recorded once per (site, direction, leaf) as a
:class:`Violation` and counted per occurrence into the
``shardguard.violations.resharded_input`` / ``.resharded_output``
gauges.  Disabled, the wrapper costs one attribute check per dispatch —
it is always installed, never hot.

Opt-in only: tests use ``@pytest.mark.shardguard`` (conftest enables
around the test and asserts zero violations), ``DL4J_TPU_SHARDGUARD=1``
enables a whole session, and ``tools/chaos_smoke.py --shardguard`` /
``tools/perf_smoke.py --shardguard`` run the smokes instrumented.

Known limits: only positional arguments are checked (every wrapped site
in this repo dispatches positionally); shardings are compared with
``Sharding.is_equivalent_to`` so a replicated ``NamedSharding`` and a
``SingleDeviceSharding`` on a 1-device mesh compare equal, as they
should — the guard flags *placement* changes, not representation ones.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import os
import threading

ENV_SHARDGUARD = "DL4J_TPU_SHARDGUARD"

_ON_VALUES = {"1", "on", "true", "yes", "enabled"}


def enabled_from_env() -> bool:
    """True when ``DL4J_TPU_SHARDGUARD`` asks for session-wide guarding."""
    return os.environ.get(ENV_SHARDGUARD, "").strip().lower() in _ON_VALUES


@dataclasses.dataclass(frozen=True)
class Violation:
    """One runtime finding — a dispatch whose shardings drifted."""

    kind: str                    # "resharded-input" | "resharded-output"
    site: str                    # wrap() label, e.g. "train.sync_step"
    message: str
    details: tuple = ()          # (leaf path, expected, actual)

    def __str__(self) -> str:    # report/assert readability
        return f"[{self.kind}] {self.site}: {self.message}"


def _leaves_with_paths(tree):
    """(path string, leaf) pairs for array leaves (lazy jax import so the
    analysis package stays importable on a bare CI box running only the
    linter)."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if hasattr(leaf, "sharding") and hasattr(leaf, "ndim"):
            out.append((jax.tree_util.keystr(path), leaf))
    return out


def _equivalent(expected, actual, ndim: int) -> bool:
    """Placement equivalence; unknowns compare equal (never false-fire)."""
    if expected is None or actual is None:
        return True
    try:
        return expected.is_equivalent_to(actual, ndim)
    except Exception:
        try:
            return str(expected) == str(actual)
        except Exception:
            return True


class ShardGuard:
    """The detector: per-site sharding expectations + drift baselines.

    A process-wide singleton (:data:`SHARDGUARD`) so trainer, serving
    engine and tests all feed one findings list; per-wrapper baselines
    live on the wrapper closure, so two trainer instances (or two ZeRO
    stages) never cross-contaminate each other's captured placements.
    """

    def __init__(self) -> None:
        self._meta = threading.Lock()
        self._enabled = False
        self._violations: list[Violation] = []
        self._reported: set[tuple] = set()     # (site, kind, path) dedup
        self._counts = {"resharded-input": 0, "resharded-output": 0}

    # ------------------------------------------------------------- switch
    def enable(self) -> "ShardGuard":
        self._enabled = True
        return self

    def disable(self) -> None:
        self._enabled = False

    # conftest symmetry with lockguard's install/uninstall vocabulary
    install = enable
    uninstall = disable

    @property
    def enabled(self) -> bool:
        return self._enabled

    # --------------------------------------------------------------- wrap
    def wrap(self, site: str, fn, in_shardings=None, out_shardings=None):
        """Wrap a jitted step function for dispatch-time sharding diffs.

        ``in_shardings``/``out_shardings`` are per-position expectations
        (``None`` entries skip that position); omit both for baseline
        mode.  The wrapper forwards ``.lower`` so XLA cost capture keeps
        working, and checks inputs BEFORE the call — donated buffers are
        gone afterwards.
        """
        guard = self
        baseline: dict[tuple, tuple] = {}   # (io, path) -> (sharding, ndim)

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if guard._enabled:
                guard._check(site, "input", args, in_shardings, baseline)
            out = fn(*args, **kwargs)
            if guard._enabled:
                outs = out if isinstance(out, tuple) else (out,)
                guard._check(site, "output", outs, out_shardings, baseline)
            return out

        if hasattr(fn, "lower"):
            wrapper.lower = fn.lower
        wrapper.__wrapped__ = fn
        return wrapper

    # -------------------------------------------------------------- diffs
    def _check(self, site: str, io: str, values: tuple, expected,
               baseline: dict) -> None:
        kind = f"resharded-{io}"
        for pos, value in enumerate(values):
            exp = None
            if expected is not None:
                if pos >= len(expected):
                    continue
                exp = expected[pos]
                if exp is None:
                    continue
            for path, leaf in _leaves_with_paths(value):
                key = (io, f"[{pos}]{path}")
                actual = leaf.sharding
                if expected is None:
                    with self._meta:
                        stored = baseline.get(key)
                        if stored is None:
                            baseline[key] = (actual, leaf.ndim)
                            continue
                    want, _ = stored
                else:
                    want = exp
                if not _equivalent(want, actual, leaf.ndim):
                    self._record(site, kind, key[1], want, actual)

    def _record(self, site: str, kind: str, path: str,
                want, actual) -> None:
        with self._meta:
            self._counts[kind] = self._counts.get(kind, 0) + 1
            dedup = (site, kind, path)
            if dedup in self._reported:
                return
            self._reported.add(dedup)
            self._violations.append(Violation(
                kind=kind, site=site,
                message=(f"arg {path} arrived as {actual} but the site "
                         f"placed {want} — XLA reshards this array on "
                         f"every dispatch"),
                details=(path, str(want), str(actual))))

    # ----------------------------------------------------------- results
    def violations(self) -> list[Violation]:
        with self._meta:
            return list(self._violations)

    def counts(self) -> dict[str, int]:
        """Per-kind OCCURRENCE counts (violations() is deduped per leaf)."""
        with self._meta:
            return dict(self._counts)

    def reset(self) -> None:
        """Clear findings and occurrence counts; enable state is kept.
        Per-wrapper baselines are NOT cleared (they die with the step
        function they describe)."""
        with self._meta:
            self._violations.clear()
            self._reported.clear()
            self._counts = {"resharded-input": 0, "resharded-output": 0}

    def report(self) -> str:
        vs = self.violations()
        if not vs:
            return "shardguard: clean (0 violations)"
        lines = [f"shardguard: {len(vs)} violation(s)"]
        lines += [f"  {v}" for v in vs]
        return "\n".join(lines)

    def emit_metrics(self) -> None:
        """Publish occurrence counts on the PR 1 metrics registry."""
        from ..observability import METRICS
        counts = self.counts()
        for kind in ("resharded-input", "resharded-output"):
            METRICS.gauge(
                "shardguard.violations." + kind.replace("-", "_"),
                counts.get(kind, 0))


SHARDGUARD = ShardGuard()


@contextlib.contextmanager
def shardguard_active(guard: ShardGuard | None = None):
    """Enable around a block, disable after; yields the guard."""
    g = guard or SHARDGUARD
    g.enable()
    try:
        yield g
    finally:
        g.disable()
