"""RNTN — Recursive Neural Tensor Network over binary parse trees.

Capability match of ``models/rntn/RNTN.java:54,340,703`` (1,310 LoC): leaf
word vectors composed bottom-up with a bilinear tensor + affine layer,
per-node softmax classification (sentiment-style), trained over a tree
corpus.

TPU-first redesign: host recursion is replaced by a LINEARIZED tree — each
tree becomes fixed-size post-order arrays (child indices, word ids, labels,
mask) padded to a node budget; composition runs as ``lax.scan`` over node
slots writing a (max_nodes, d) vector buffer, and a batch of trees is
``vmap``-ed.  One compile per node-budget bucket instead of per tree shape;
autodiff replaces the reference's hand-written tensor backprop.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..text.tree import Tree
from ..text.vocab import VocabCache

UNK = "*UNK*"


@dataclasses.dataclass
class TreeArrays:
    """Post-order linearization; index -1 (masked) slots are padding."""

    is_leaf: np.ndarray      # (N,) int32 1/0
    word: np.ndarray         # (N,) int32 vocab id (leaves)
    left: np.ndarray         # (N,) int32 child slot (internal)
    right: np.ndarray        # (N,) int32
    label: np.ndarray        # (N,) int32 gold class (-1 = none)
    mask: np.ndarray         # (N,) float32 1 for real node

    @property
    def n_slots(self) -> int:
        return self.is_leaf.shape[0]


def linearize(tree: Tree, vocab: VocabCache, max_nodes: int) -> TreeArrays | None:
    """Post-order arrays; None if the tree exceeds the node budget."""
    nodes = []

    def visit(t: Tree) -> int:
        # collapse unary chains (incl. pre-terminals (tag (word))) downward
        while len(t.children) == 1:
            child = t.children[0]
            if child.gold_label < 0:
                child.gold_label = t.gold_label
            t = child
        if t.is_leaf():
            nodes.append(("leaf", t))
            return len(nodes) - 1
        assert len(t.children) == 2, "RNTN needs binary trees (use binarize())"
        l = visit(t.children[0])
        r = visit(t.children[1])
        nodes.append(("internal", t, l, r))
        return len(nodes) - 1

    visit(tree)
    n = len(nodes)
    if n > max_nodes:
        return None
    arrs = TreeArrays(
        is_leaf=np.zeros(max_nodes, np.int32),
        word=np.zeros(max_nodes, np.int32),
        left=np.zeros(max_nodes, np.int32),
        right=np.zeros(max_nodes, np.int32),
        label=np.full(max_nodes, -1, np.int32),
        mask=np.zeros(max_nodes, np.float32),
    )
    for i, rec in enumerate(nodes):
        arrs.mask[i] = 1.0
        node = rec[1]
        arrs.label[i] = node.gold_label
        if rec[0] == "leaf":
            arrs.is_leaf[i] = 1
            idx = vocab.index_of(node.word.lower() if node.word else "")
            arrs.word[i] = idx if idx >= 0 else vocab.index_of(UNK)
        else:
            arrs.left[i], arrs.right[i] = rec[2], rec[3]
    return arrs


def _forward_tree(params, t, d):
    """Vector buffer for one linearized tree: scan over post-order slots."""

    def step(buf, slot):
        is_leaf, word, left, right, i = slot
        leaf_vec = params["emb"][word]
        a = buf[left]
        b = buf[right]
        c = jnp.concatenate([a, b])
        bilinear = jnp.einsum("dij,i,j->d", params["V"], c, c)
        affine = params["W"] @ jnp.concatenate([c, jnp.ones(1)])
        internal_vec = jnp.tanh(affine + bilinear)
        vec = jnp.where(is_leaf == 1, leaf_vec, internal_vec)
        buf = buf.at[i].set(vec)
        return buf, vec

    n = t["is_leaf"].shape[0]
    buf0 = jnp.zeros((n, d), params["emb"].dtype)
    slots = (t["is_leaf"], t["word"], t["left"], t["right"], jnp.arange(n))
    buf, _ = jax.lax.scan(step, buf0, slots)
    return buf


def _tree_loss(params, t, d, n_classes):
    buf = _forward_tree(params, t, d)
    logits = buf @ params["Ws"].T + params["bs"]          # (N, C)
    logp = jax.nn.log_softmax(logits, axis=-1)
    labels = t["label"]
    has_label = (labels >= 0) & (t["mask"] > 0)
    safe = jnp.maximum(labels, 0)
    ll = jnp.take_along_axis(logp, safe[:, None], axis=1)[:, 0]
    return -jnp.sum(jnp.where(has_label, ll, 0.0)), jnp.sum(has_label)


class RNTN:
    def __init__(self, *, layer_size: int = 25, n_classes: int = 5,
                 max_nodes: int = 64, lr: float = 0.05, l2: float = 1e-4,
                 adagrad: bool = True, seed: int = 0):
        self.d = layer_size
        self.n_classes = n_classes
        self.max_nodes = max_nodes
        self.lr = lr
        self.l2 = l2
        self.adagrad = adagrad
        self.seed = seed
        self.vocab = VocabCache()
        self.params = None
        self._hist = None
        self._step = None

    # ------------------------------------------------------------------ setup
    def build_vocab(self, trees: Iterable[Tree]) -> None:
        for tree in trees:
            for w in tree.words():
                self.vocab.add(w.lower())
        self.vocab.add(UNK)
        self.vocab.finalize_indices()

    def init(self):
        d, v = self.d, len(self.vocab)
        k = jax.random.split(jax.random.key(self.seed), 4)
        self.params = {
            "emb": 0.1 * jax.random.normal(k[0], (v, d)),
            "W": 0.01 * jax.random.normal(k[1], (d, 2 * d + 1)),
            "V": 0.01 * jax.random.normal(k[2], (d, 2 * d, 2 * d)),
            "Ws": 0.01 * jax.random.normal(k[3], (self.n_classes, d)),
            "bs": jnp.zeros((self.n_classes,)),
        }
        self._hist = jax.tree_util.tree_map(jnp.zeros_like, self.params)
        return self.params

    def _batch_arrays(self, trees: Sequence[Tree]):
        arrs = [linearize(t, self.vocab, self.max_nodes) for t in trees]
        arrs = [a for a in arrs if a is not None]
        if not arrs:
            return None
        return {
            "is_leaf": jnp.asarray(np.stack([a.is_leaf for a in arrs])),
            "word": jnp.asarray(np.stack([a.word for a in arrs])),
            "left": jnp.asarray(np.stack([a.left for a in arrs])),
            "right": jnp.asarray(np.stack([a.right for a in arrs])),
            "label": jnp.asarray(np.stack([a.label for a in arrs])),
            "mask": jnp.asarray(np.stack([a.mask for a in arrs])),
        }

    # ------------------------------------------------------------------ train
    def _build_step(self):
        d, n_classes, l2, lr = self.d, self.n_classes, self.l2, self.lr
        adagrad = self.adagrad

        def batch_loss(params, batch):
            losses, counts = jax.vmap(
                lambda t: _tree_loss(params, t, d, n_classes))(batch)
            data = jnp.sum(losses) / jnp.maximum(jnp.sum(counts), 1.0)
            reg = sum(jnp.sum(p * p) for n, p in params.items() if n != "bs")
            return data + 0.5 * l2 * reg

        @jax.jit
        def step(params, hist, batch):
            loss, g = jax.value_and_grad(batch_loss)(params, batch)
            if adagrad:
                hist = jax.tree_util.tree_map(lambda h, gg: h + gg * gg, hist, g)
                params = jax.tree_util.tree_map(
                    lambda p, gg, h: p - lr * gg * jax.lax.rsqrt(h + 1e-8),
                    params, g, hist)
            else:
                params = jax.tree_util.tree_map(lambda p, gg: p - lr * gg,
                                                params, g)
            return params, hist, loss

        return step

    def fit(self, trees: Sequence[Tree], epochs: int = 20,
            batch_size: int = 32) -> list[float]:
        if len(self.vocab) == 0:
            self.build_vocab(trees)
        if self.params is None:
            self.init()
        if self._step is None:
            self._step = self._build_step()
        rng = np.random.default_rng(self.seed)
        losses = []
        for ep in range(epochs):
            order = rng.permutation(len(trees))
            ep_loss, nb = 0.0, 0
            for off in range(0, len(trees), batch_size):
                batch_trees = [trees[i] for i in order[off:off + batch_size]]
                batch = self._batch_arrays(batch_trees)
                if batch is None:
                    continue
                self.params, self._hist, loss = self._step(
                    self.params, self._hist, batch)
                ep_loss += float(loss)
                nb += 1
            losses.append(ep_loss / max(1, nb))
        return losses

    # ------------------------------------------------------------------ predict
    def predict_tree(self, tree: Tree) -> np.ndarray:
        """Per-node predicted classes in post-order (root last)."""
        arrs = linearize(tree, self.vocab, self.max_nodes)
        if arrs is None:
            raise ValueError(f"tree exceeds node budget {self.max_nodes}")
        t = {k: jnp.asarray(getattr(arrs, k))
             for k in ("is_leaf", "word", "left", "right", "label", "mask")}
        buf = _forward_tree(self.params, t, self.d)
        logits = buf @ self.params["Ws"].T + self.params["bs"]
        n_real = int(arrs.mask.sum())
        return np.asarray(jnp.argmax(logits, axis=-1))[:n_real]

    def predict_root(self, tree: Tree) -> int:
        return int(self.predict_tree(tree)[-1])

    def accuracy(self, trees: Sequence[Tree]) -> float:
        good = total = 0
        for t in trees:
            if t.gold_label >= 0:
                total += 1
                good += int(self.predict_root(t) == t.gold_label)
        return good / max(1, total)
