"""Quad tree with center-of-mass summaries (Barnes-Hut).

Capability match of ``clustering/quadtree/QuadTree.java:483``: 2-D spatial
subdivision with per-cell center of mass and cumulative size, plus the
Barnes-Hut force accumulation used by t-SNE's repulsive term.
"""

from __future__ import annotations

import numpy as np


MAX_DEPTH = 48  # duplicates/near-duplicates stop subdividing past this


class QuadTree:
    __slots__ = ("center", "half", "com", "size", "children", "point",
                 "index", "depth_")

    def __init__(self, center, half, depth: int = 0):
        self.center = np.asarray(center, np.float64)
        self.half = float(half)
        self.com = np.zeros(2)
        self.size = 0
        self.children: list[QuadTree] | None = None
        self.point = None
        self.index = -1
        self.depth_ = depth

    # ------------------------------------------------------------------ build
    @classmethod
    def build(cls, points) -> "QuadTree":
        pts = np.asarray(points, np.float64)
        lo, hi = pts.min(axis=0), pts.max(axis=0)
        center = (lo + hi) / 2
        half = float(max((hi - lo).max() / 2 * 1.001, 1e-9))
        tree = cls(center, half)
        for i, p in enumerate(pts):
            tree.insert(p, i)
        return tree

    def contains(self, p) -> bool:
        return bool(np.all(np.abs(p - self.center) <= self.half + 1e-12))

    def insert(self, p, index) -> None:
        p = np.asarray(p, np.float64)
        self.com = (self.com * self.size + p) / (self.size + 1)
        self.size += 1
        if self.size == 1:
            self.point, self.index = p, index
            return
        # duplicate/near-duplicate guard: past MAX_DEPTH the cell only
        # aggregates (com + size), which is all Barnes-Hut needs — without
        # this, two identical points recurse forever
        if self.depth_ >= MAX_DEPTH:
            return
        if self.children is None:
            self._subdivide()
            if self.point is not None:
                self._child_for(self.point).insert(self.point, self.index)
                self.point, self.index = None, -1
        self._child_for(p).insert(p, index)

    def _subdivide(self):
        h = self.half / 2
        cx, cy = self.center
        self.children = [QuadTree((cx + dx * h, cy + dy * h), h, self.depth_ + 1)
                         for dx in (-1, 1) for dy in (-1, 1)]

    def _child_for(self, p) -> "QuadTree":
        i = (2 if p[0] > self.center[0] else 0) + (1 if p[1] > self.center[1] else 0)
        return self.children[i]

    # ------------------------------------------------------------------ BH force
    def compute_non_edge_forces(self, point, theta: float, index: int):
        """Barnes-Hut negative-force accumulation for one query point.
        Returns (force_vec, sum_q) for the t-SNE repulsive term."""
        force = np.zeros(2)
        sum_q = 0.0
        stack = [self]
        while stack:
            node = stack.pop()
            if node.size == 0 or (node.size == 1 and node.index == index):
                continue
            diff = point - node.com
            d2 = float(diff @ diff) + 1e-12
            if node.children is None or (2.0 * node.half / np.sqrt(d2)) < theta:
                mult = node.size if not (node.size == 1 and node.index == index) else 0
                q = 1.0 / (1.0 + d2)
                sum_q += mult * q
                force += mult * q * q * diff
            else:
                stack.extend(node.children)
        return force, sum_q

    def depth(self) -> int:
        if self.children is None:
            return 1
        return 1 + max(c.depth() for c in self.children)
