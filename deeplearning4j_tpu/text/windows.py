"""Context windows over token streams.

Capability match of ``text/movingwindow/Windows.java:17`` + ``Window.java``:
fixed-size windows around each token (padded with edge markers), the input
representation for windowed sequence classifiers and Viterbi decoding
(``util/Viterbi.java``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

PAD = "<s>"
END = "</s>"


@dataclass
class Window:
    words: list[str]
    focus_index: int
    label: str | None = None

    @property
    def focus(self) -> str:
        return self.words[self.focus_index]

    def __iter__(self):
        return iter(self.words)


def windows(tokens: Sequence[str], window_size: int = 5,
            labels: Sequence[str] | None = None) -> list[Window]:
    """One window per token, padded at the edges (``Windows.windows``)."""
    assert window_size % 2 == 1, "window size must be odd"
    half = window_size // 2
    padded = [PAD] * half + list(tokens) + [END] * half
    out = []
    for i in range(len(tokens)):
        w = Window(words=padded[i:i + window_size], focus_index=half,
                   label=labels[i] if labels is not None else None)
        out.append(w)
    return out


def window_matrix(win: Window, lookup, dim: int) -> np.ndarray:
    """Concatenate word vectors of a window (zero for unknown/pad) — the
    classic windowed-input featurization (``WindowConverter`` role)."""
    vecs = []
    for w in win.words:
        v = lookup(w)
        vecs.append(np.zeros(dim, np.float32) if v is None else np.asarray(v))
    return np.concatenate(vecs)
