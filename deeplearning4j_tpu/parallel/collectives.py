"""The collectives layer — the reference's "NCCL"/transport, TPU-native.

The reference moves parameters/updates through Akka remote messages +
Hazelcast IMaps/ILists + Avro RPC (SURVEY.md §2.3 backend table).  On TPU the
entire data plane is XLA collectives compiled into the step function and
riding ICI (intra-slice) / DCN (inter-slice).  These wrappers name that
surface explicitly — use them inside ``shard_map``-ped functions; under plain
``pjit`` sharding propagation inserts the same collectives automatically.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def psum(x, axis: str):
    """All-reduce sum over a mesh axis (≡ parameter-averaging numerator,
    ``INDArrayAggregator.accumulate``)."""
    return lax.psum(x, axis)


def pmean(x, axis: str):
    """All-reduce mean (≡ ``IterativeReduceWorkRouter`` averaging in one op)."""
    return lax.pmean(x, axis)


def all_gather(x, axis: str, *, tiled: bool = False):
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension, tiled=True)


def ppermute(x, axis: str, perm):
    """Neighbor exchange — the ring primitive under ring attention /
    pipeline micro-batch handoff."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x, axis: str, axis_size: int, shift: int = 1):
    """Shift values around the ring by ``shift`` positions."""
    perm = [(i, (i + shift) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def barrier_sum(axis: str):
    """Cheap cross-device barrier: psum of a scalar 1 (control-plane sync;
    replaces the reference's 'wait for N worker updates' poll loop)."""
    return lax.psum(jnp.ones(()), axis)
