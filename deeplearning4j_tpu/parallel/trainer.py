"""SPMD data-parallel trainer with an asynchronous, pipelined hot loop.

TPU-native replacement for the reference's scaleout training loop
(master/worker actors + StateTracker + WorkRouter policy, SURVEY.md §3.3):
ONE jitted train step over a `jax.sharding.Mesh`, batch sharded on the
``dp`` axis.  Both of the reference's routing policies exist:

- **iterative-reduce** (``IterativeReduceWorkRouter.java:16,30``): replicated
  params + dp-sharded batch — XLA inserts the gradient all-reduce (the
  `pmean`) into the compiled step, so 'wait for all workers, average,
  rebroadcast' is a single fused collective per step on ICI.
- **hogwild** (``HogWildWorkRouter.java``, async always-send): TPUs are
  lockstep, so the idiomatic approximation is *local SGD / periodic
  averaging*: per-worker parameter replicas (leading dp-sharded axis) take
  K local steps with NO cross-device traffic, then average with one
  in-compiled `pmean` (``shard_map``).  K=1 degenerates to iterative-reduce.
  Deviation documented per SURVEY.md §7 hard-part #5.

Async execution model (DESIGN.md §10): JAX dispatch is asynchronous, so the
Python driver only stays ahead of the device if nothing on the hot loop
forces a device->host read.  Three rules enforce that here:

1. ``step`` never calls ``float(loss)`` — it returns a :class:`LazyLoss`
   handle and parks the device scalar on a bounded pending ring; ``fit``
   resolves the ring in batches (every ``resolve_every`` steps and at the
   end) behind one explicit ``block_until_ready`` fence.  Loss/throughput
   gauges move to the resolution point so metrics stay correct without
   re-introducing the per-step sync.
2. Ragged batches pad to a small powers-of-two bucket ladder (capped at
   the nominal batch) with one jitted step per bucket and a
   ``train_step.recompile`` counter — bounded compilation instead of one
   recompile per odd shape.  A validity mask keeps the loss/gradient
   average EXACT under padding (padded rows contribute zero).
3. ``fit`` streams any iterable (no ``list(data)`` materialization) and
   routes host batches through ``prefetch_to_device`` with the trainer's
   ``NamedSharding``, so H2D transfer overlaps device compute.

Checkpoints fence the ring before reading params (``checkpoint``), so a
snapshot never races in-flight steps.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        # the experimental API spells the flag check_rep
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)

from ..analysis.runtime import allow_transfers, hot_loop_guard
from ..analysis.shardguard import SHARDGUARD
from ..datasets.dataset import DataSet
from ..resilience.faults import FAULTS, DeviceLossError, DivergenceError
from ..observability import COSTS, METRICS, NOOP_SPAN, enabled as _obs_enabled
from ..observability import sample_device_memory, sample_state_bytes, trace
from ..optimize import transforms as tfm
from . import collectives as clv
from .compile_cache import setup_compile_cache
from .mesh import DP, local_mesh, mesh_devices
from .zero import ZeroLayout

LossFn = Callable[..., jnp.ndarray]  # (params, x, y, key) -> scalar


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


class LazyLoss:
    """Lazy handle to a device-resident loss.

    ``step`` returns one of these instead of a synced float: the scalar
    stays on device until ``float(handle)`` / ``handle.value()`` forces
    the device->host read, so the dispatch loop never blocks on it.
    ``block()`` waits for the device value without converting it (the
    fence primitive ``fit`` uses).  Hogwild steps carry a per-replica
    loss vector; ``value()`` reduces it to the replica mean.
    """

    __slots__ = ("_dev", "_value")

    def __init__(self, dev):
        self._dev = dev
        self._value: float | None = None

    @property
    def resolved(self) -> bool:
        return self._value is not None

    def block(self) -> "LazyLoss":
        if self._value is None:
            jax.block_until_ready(self._dev)
        return self

    def value(self) -> float:
        if self._value is None:
            self._value = float(np.mean(jax.device_get(self._dev)))
            self._dev = None
        return self._value

    __float__ = value

    def __format__(self, spec: str) -> str:
        return format(self.value(), spec)

    def __repr__(self) -> str:
        return (f"LazyLoss({self._value!r})" if self.resolved
                else "LazyLoss(<pending>)")


@dataclasses.dataclass
class TrainState:
    params: Any
    tstate: Any
    step: int
    key: Any


class DataParallelTrainer:
    """Shard a supervised train step over the ``dp`` axis of a mesh.

    ``max_pending`` bounds the ring of unresolved losses: when a caller
    drives ``step`` directly and never resolves, the trainer fences
    itself every ``max_pending`` dispatches so the device queue cannot
    grow without bound.
    """

    def __init__(self, loss_fn: LossFn, transform: tfm.GradientTransform,
                 mesh: Mesh | None = None, router: str = "iterative_reduce",
                 average_every: int = 8, max_pending: int = 64,
                 zero_stage: int = 0):
        if router not in ("iterative_reduce", "hogwild"):
            raise ValueError(f"unknown router {router!r}")
        if zero_stage not in (0, 1, 2, 3):
            raise ValueError(f"zero_stage must be 0..3, got {zero_stage!r}")
        if zero_stage and router != "iterative_reduce":
            raise ValueError(
                "zero_stage requires the iterative_reduce router — hogwild "
                "keeps independent per-replica optimizer state by design, "
                "so there is no shared state to shard")
        self.loss_fn = loss_fn
        self.transform = transform
        self.mesh = mesh if mesh is not None else local_mesh()
        self.router = router
        self.average_every = average_every
        self.max_pending = max(1, max_pending)
        self.n_dp = self.mesh.shape[DP]
        # ZeRO stage (DESIGN.md §15): 0 replicates grads + optimizer state
        # (the classic path); 1 shards optimizer state (all-reduce grads,
        # update this chip's chunk, all-gather params); 2 reduce-scatters
        # grads so full gradients never materialize; 3 additionally keeps
        # PARAMS sharded between steps, gathering per microbatch.
        self.zero_stage = int(zero_stage)
        self._zero: ZeroLayout | None = None  # built at init_state
        # canonical placements for step arguments: batches split over dp,
        # scalars replicated.  Dispatch device_puts EVERY argument against
        # these (a no-op for already-placed arrays), so nothing reaches the
        # jitted step via an implicit transfer/reshard — the invariant the
        # hot-loop transfer guard enforces.
        self._batch_sh = NamedSharding(self.mesh, P(DP))
        self._rep_sh = NamedSharding(self.mesh, P())
        self._avg_fn = None
        # bucketed jit cache: one compiled step per padded batch size
        self._step_cache: dict[int, Any] = {}
        self._nominal: int | None = None
        # pending-loss ring: (LazyLoss, n_real_samples, post-dispatch step)
        # awaiting resolution; the step rides along so the NaN guard can
        # report exactly which step diverged
        self._pending: list[tuple[LazyLoss, int, int]] = []
        self._window_t0: float | None = None
        self._nan_guard = False  # set per-fit; checked at resolution
        # XLA cost of the most recent bucket's dispatch (captured at first
        # compile) — feeds the live train.mfu gauge at resolution fences
        self._step_cost = None
        setup_compile_cache()  # persistent XLA cache (env-gated no-op)

    # ------------------------------------------------------------------ state
    def init_state(self, params, key=None) -> TrainState:
        if key is None:
            # seed from an explicitly-placed scalar: works under a caller's
            # transfer guard (jax.random.key(0) implicitly uploads the int)
            key = jax.random.key(jax.device_put(np.uint32(0)))
        key = jax.device_put(key, self._rep_sh)  # replicate once, up front
        # Copy before placement: device_put may alias the caller's buffers as
        # mesh shards, and the jitted step donates its inputs — without this
        # copy the caller's params would be deleted by the first step.  Host
        # leaves cross over via an EXPLICIT device_put (itself a fresh
        # buffer), so initializing from numpy works under a transfer guard.
        params = jax.tree_util.tree_map(
            lambda a: (jnp.array(a) if isinstance(a, jax.Array)
                       else jax.device_put(np.asarray(a))), params)
        if self.router == "hogwild":
            # per-worker replicas: stack along a leading dp axis
            params = jax.tree_util.tree_map(
                lambda x: jnp.broadcast_to(x[None], (self.n_dp,) + x.shape), params)
            params = jax.device_put(
                params, NamedSharding(self.mesh, P(DP)))
        else:
            params = jax.device_put(params, NamedSharding(self.mesh, P()))
        # transform.init (and the hogwild x[0] slice) build setup-time
        # constants — zero buffers, gather indices — that a surrounding
        # transfer guard would reject.  This is one-shot setup, not the hot
        # loop, and every leaf is explicitly re-placed below, so the
        # documented escape hatch applies here.
        with allow_transfers():
            if self.router == "hogwild":
                tstate = self.transform.init(
                    jax.tree_util.tree_map(lambda x: x[0], params))
                tstate = jax.tree_util.tree_map(
                    lambda x: (jnp.broadcast_to(x[None],
                                                (self.n_dp,) + x.shape)
                               if isinstance(x, jnp.ndarray) else x), tstate)
                tstate = jax.device_put(tstate, NamedSharding(self.mesh, P(DP)))
            elif self.zero_stage >= 1:
                # ZeRO: optimizer state is born shard-local — init runs
                # jitted over the flattened+padded param view with
                # out_shardings from state_spec, so each chip materializes
                # only its 1/ndp chunk of every state leaf
                z = self._zero_layout(params)
                flat_params = z.place_flat(params, z.flat_sharding)
                tstate = tfm.init_sharded(self.transform, flat_params,
                                          P(DP), self.mesh)
                if self.zero_stage >= 3:
                    params = flat_params  # params stay sharded between steps
            else:
                tstate = self.transform.init(params)
                # transform.init builds its buffers eagerly on one device;
                # replicate them NOW so the first step's call needs no
                # implicit reshard (the hot-loop transfer guard rejects it)
                tstate = jax.tree_util.tree_map(
                    lambda x: (jax.device_put(x, self._rep_sh)
                               if isinstance(x, jnp.ndarray) else x), tstate)
        state = TrainState(params=params, tstate=tstate, step=0, key=key)
        sample_state_bytes(state.params, state.tstate)  # ZeRO memory gauges
        return state

    def _zero_layout(self, params) -> ZeroLayout:
        """Build (once) the flatten/pad/shard metadata for zero_stage >= 1.
        Pure shape metadata — safe under a transfer guard."""
        if self._zero is None:
            self._zero = ZeroLayout(self.mesh, self.transform, params)
        return self._zero

    # ------------------------------------------------------------------ buckets
    def _bucket_size(self, n: int) -> int:
        """Padded size for a batch of ``n``: powers-of-two ladder rounded to
        the dp width, capped at the nominal (first-seen) batch size.  Bounds
        the number of compiled step variants at ~log2(nominal)."""
        if self._nominal is None:
            self._nominal = _round_up(n, self.n_dp)
        cap = self._nominal
        if n >= cap:
            return _round_up(n, self.n_dp)
        b = 1
        while b < n:
            b <<= 1
        return min(_round_up(b, self.n_dp), cap)

    def _pad_to_bucket(self, x, y):
        """Host-side pad to the bucket size.  Returns (x, y, n_valid, bucket).

        Wrap indices are built with ``np.arange`` — constructing padding
        indices must not launch a device computation.  The padded rows are
        masked out inside the jitted step, so the loss/gradient average
        stays exact regardless of what the pad rows contain.
        """
        n = int(np.shape(x)[0])
        bucket = self._bucket_size(n)
        pad = bucket - n
        if pad:
            if _obs_enabled():
                METRICS.increment("train_step.pad_batch")
                METRICS.increment("train_step.padded_samples", pad)
            idx = np.arange(pad) % n  # wrap: pad may exceed batch
            lib = jnp if isinstance(x, jnp.ndarray) else np
            if lib is jnp:
                # indexing a device array with a host index vector is an
                # implicit H2D transfer — spell it out (transfer-guard safe)
                idx = jax.device_put(idx)
            x = lib.concatenate([x, x[idx]])
            y = lib.concatenate([y, y[idx]])
        return x, y, n, bucket

    # ------------------------------------------------------------------ steps
    def _masked_mean_loss(self, key_select):
        """Wrap ``loss_fn`` (a per-sample mean) into an exact masked mean:
        per-example losses via a singleton-batch vmap, zero weight for
        padded rows, normalized by the REAL sample count.  Decomposable
        (per-row) losses — every loss in this repo — are exact under this
        rewrite; batch-coupled losses (cross-batch statistics) are not and
        should avoid ragged batches."""
        loss_fn = self.loss_fn

        def masked(params, x, y, key, mask, denom):
            per = jax.vmap(
                lambda xi, yi: loss_fn(params, xi[None], yi[None],
                                       key_select(key)))(x, y)
            per = per.reshape((x.shape[0],))
            return jnp.sum(per * mask.astype(per.dtype)) / denom.astype(per.dtype)

        return masked

    def _build_sync_step(self):
        mesh = self.mesh
        batch_sh = NamedSharding(mesh, P(DP))
        rep = NamedSharding(mesh, P())
        masked = self._masked_mean_loss(lambda k: k)

        def step(params, tstate, x, y, key, iteration, n_valid):
            mask = jnp.arange(x.shape[0]) < n_valid
            loss, grads = jax.value_and_grad(masked)(
                params, x, y, key, mask, n_valid)
            updates, tstate = self.transform.update(grads, tstate, params, iteration)
            params = tfm.apply_updates(params, updates)
            return params, tstate, loss

        # shardguard (off by default: one flag check per dispatch) diffs
        # the arrays crossing this boundary against the very shardings the
        # jit declares — a drifted device_put upstream means XLA reshards
        # on every step instead of failing loudly
        return SHARDGUARD.wrap(
            "train.sync_step",
            jax.jit(
                step,
                in_shardings=(rep, rep, batch_sh, batch_sh, rep, rep, rep),
                out_shardings=(rep, rep, rep),
                donate_argnums=(0, 1),
            ),
            in_shardings=(rep, rep, batch_sh, batch_sh, rep, rep, rep),
            out_shardings=(rep, rep, rep),
        )

    def _build_zero_step(self):
        """ZeRO sharded weight update (zero_stage >= 1), one shard_map'd
        program per bucket:

        local grads -> stage 1: all-reduce + slice this chip's chunk
                       stage >= 2: reduce-scatter (full grads never land)
        -> ``transform.update`` on this chip's flattened chunk only
        -> stage <= 2: all-gather updated params, rebuild natural shapes
           stage 3: params stay sharded; the NEXT step gathers them.

        Numerics match the replicated step bitwise on the CPU mesh: the
        per-row losses, the 1/n_valid cotangent, and the elementwise
        transform are the same programs, and the cross-chip sum reduces
        the same per-chip partials (psum / psum_scatter are the same
        reduction, differently placed).  Norm-coupled transforms
        (clip_unit_norm, clip_by_global_norm) would see shard-local norms
        and are NOT exact under zero_stage >= 1 — documented in §15.
        """
        mesh, n_dp, stage = self.mesh, self.n_dp, self.zero_stage
        z = self._zero
        if z is None:
            raise RuntimeError("zero step built before init_state — the "
                               "layout comes from the param shapes")
        loss_fn = self.loss_fn

        def local(params, tstate, x, y, key, iteration, n_valid):
            if stage >= 3:
                flat_full = jax.tree_util.tree_map(
                    lambda c: clv.all_gather_or_identity(c, DP, n_dp), params)
                nat = z.unflatten_like(flat_full, z.natural_params)
            else:
                nat = params
            idx = clv.axis_index(DP)
            rows = idx * x.shape[0] + jnp.arange(x.shape[0])
            mask = rows < n_valid

            def local_sum(p):
                per = jax.vmap(
                    lambda xi, yi: loss_fn(p, xi[None], yi[None], key))(x, y)
                per = per.reshape((x.shape[0],))
                return jnp.sum(per * mask.astype(per.dtype))

            # vjp with a 1/n_valid cotangent == grad of the GLOBAL masked
            # mean: the division folds into the backward seed exactly where
            # pjit's autodiff puts it, so per-chip partial grads are the
            # same floats as the replicated step's pre-psum partials
            lsum, vjp_fn = jax.vjp(local_sum, nat)
            denom = n_valid.astype(lsum.dtype)
            (grads,) = vjp_fn(jnp.ones((), lsum.dtype) / denom)
            loss = clv.psum(lsum, DP) / denom
            gflat = z.flatten_tree(grads)
            if stage == 1:
                gfull = jax.tree_util.tree_map(
                    lambda g: clv.psum(g, DP), gflat)
                gchunk = z.chunk_tree(gfull, idx, z.natural_params)
            else:
                gchunk = jax.tree_util.tree_map(
                    lambda g: clv.reduce_scatter_or_psum(g, DP, n_dp), gflat)
            if stage >= 3:
                pchunk = params  # already this chip's chunks
            else:
                pchunk = z.chunk_tree(z.flatten_tree(nat), idx,
                                      z.natural_params)
            # decay classification must come from the NATURAL shapes — on
            # 1-D chunks the ndim >= 2 heuristic would decay nothing
            with tfm.decay_mask_override(z.decay_mask):
                updates, tstate = self.transform.update(
                    gchunk, tstate, pchunk, iteration)
            pchunk = tfm.apply_updates(pchunk, updates)
            if stage >= 3:
                return pchunk, tstate, loss
            pfull = jax.tree_util.tree_map(
                lambda c: clv.all_gather_or_identity(c, DP, n_dp), pchunk)
            return z.unflatten_like(pfull, z.natural_params), tstate, loss

        param_spec = P(DP) if stage >= 3 else P()
        smapped = shard_map(
            local, mesh=mesh,
            in_specs=(param_spec, P(DP), P(DP), P(DP), P(), P(), P()),
            out_specs=(param_spec, P(DP), P()),
            check_vma=False,
        )
        # baseline mode: the ZeRO placements are emergent (stage-dependent
        # param spec), so the first dispatch captures them and later drift
        # — not the initial layout — is the violation
        return SHARDGUARD.wrap(
            "train.zero_step", jax.jit(smapped, donate_argnums=(0, 1)))

    def _build_local_step(self):
        """HogWild-approx local step: runs independently per dp shard."""
        mesh = self.mesh
        masked = self._masked_mean_loss(lambda k: k[0])

        def local(params, tstate, x, y, key, iteration, n_valid):
            # leading dp axis stripped by shard_map (shard size 1) -> squeeze
            params = jax.tree_util.tree_map(lambda a: a[0], params)
            tstate = jax.tree_util.tree_map(
                lambda a: a[0] if isinstance(a, jnp.ndarray) else a, tstate)
            # global row ids of this shard's slice -> local validity mask
            rows = jax.lax.axis_index(DP) * x.shape[0] + jnp.arange(x.shape[0])
            mask = rows < n_valid[0]
            denom = jnp.maximum(jnp.sum(mask), 1)  # all-pad shard guard
            loss, grads = jax.value_and_grad(masked)(
                params, x, y, key, mask, denom)
            updates, tstate = self.transform.update(grads, tstate, params, iteration[0])
            params = tfm.apply_updates(params, updates)
            expand = lambda a: a[None] if isinstance(a, jnp.ndarray) else a
            return (jax.tree_util.tree_map(expand, params),
                    jax.tree_util.tree_map(expand, tstate), loss[None])

        smapped = shard_map(
            local, mesh=mesh,
            in_specs=(P(DP), P(DP), P(DP), P(DP), P(DP), P(DP), P(DP)),
            out_specs=(P(DP), P(DP), P(DP)),
            check_vma=False,
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def _build_average(self):
        """Periodic parameter averaging: one pmean inside shard_map."""
        mesh = self.mesh

        def avg(params):
            local = jax.tree_util.tree_map(lambda a: a[0], params)
            meaned = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, DP), local)
            return jax.tree_util.tree_map(lambda a: a[None], meaned)

        return jax.jit(shard_map(
            avg, mesh=mesh, in_specs=(P(DP),), out_specs=P(DP),
            check_vma=False))

    def _step_for(self, bucket: int):
        fn = self._step_cache.get(bucket)
        if fn is None:
            # one compiled variant per bucket — the counter the perf smoke
            # asserts on: steady-state recompiles == buckets used
            METRICS.increment("train_step.recompile")
            if self.router == "iterative_reduce":
                fn = (self._build_zero_step() if self.zero_stage
                      else self._build_sync_step())
            else:
                fn = self._build_local_step()
                if self._avg_fn is None:
                    self._avg_fn = self._build_average()
            self._step_cache[bucket] = fn
        return fn

    # ------------------------------------------------------------------ api
    def step(self, state: TrainState, x, y) -> tuple[TrainState, LazyLoss]:
        """Dispatch one step; returns the new state and a :class:`LazyLoss`.

        The loss handle is float-compatible (``float(loss)`` forces the
        device->host sync) but the hot loop should leave resolution to
        ``fit``'s batched fences.
        """
        x, y, n_valid, bucket = self._pad_to_bucket(x, y)
        return self._dispatch(state, x, y, n_valid, bucket)

    def _dispatch(self, state: TrainState, x, y, n_valid: int,
                  bucket: int) -> tuple[TrainState, LazyLoss]:
        # chaos seam: transient step failure (disarmed cost: one attr test)
        FAULTS.maybe_fire("train.step", state.step + 1)
        # chaos seam: device loss — ``kind`` is the number of chips that
        # "die" (default 1, always leaving at least one survivor).  Raises
        # DeviceLossError so the supervisor can rebuild the mesh from the
        # survivors instead of retrying onto dead hardware.
        spec = FAULTS.check("mesh.shrink", state.step + 1)
        if spec is not None:
            devs = mesh_devices(self.mesh)
            k = int(spec.kind) if str(spec.kind or "").isdigit() else 1
            k = max(1, min(k, len(devs) - 1)) if len(devs) > 1 else 1
            raise DeviceLossError(state.step + 1, devs[-k:])
        # Observability is gated on one flag check: when disabled, no span
        # object, no perf_counter read, no registry lock on this path.
        obs = _obs_enabled()
        first = bucket not in self._step_cache  # first call pays trace+compile
        t0 = time.perf_counter() if obs else 0.0
        cm = trace.span("train_step.compile" if first else "train_step",
                        step=state.step, router=self.router) if obs else NOOP_SPAN
        with cm:
            step_fn = self._step_for(bucket)
            state.key, sub = jax.random.split(state.key)
            # every argument crosses to its device placement EXPLICITLY
            # (device_put, a no-op when already placed): under the hot-loop
            # transfer guard an implicit jnp.asarray(int) or a numpy batch
            # leaking into the jitted call would raise on every step
            x = jax.device_put(x, self._batch_sh)
            y = jax.device_put(y, self._batch_sh)
            if self.router == "iterative_reduce":
                args = (state.params, state.tstate, x, y,
                        jax.device_put(sub, self._rep_sh),
                        jax.device_put(np.int32(state.step), self._rep_sh),
                        jax.device_put(np.int32(n_valid), self._rep_sh))
            else:
                keys = jax.device_put(jax.random.split(sub, self.n_dp),
                                      self._batch_sh)
                iters = jax.device_put(
                    np.full((self.n_dp,), state.step, np.int32),
                    self._batch_sh)
                nv = jax.device_put(
                    np.full((self.n_dp,), n_valid, np.int32), self._batch_sh)
                args = (state.params, state.tstate, x, y, keys, iters, nv)
            if first and obs:
                # XLA cost per dispatch for this bucket (lower() reads
                # avals only — safe before the donating call); feeds the
                # live train.mfu gauge at every resolution fence
                self._step_cost = COSTS.capture(
                    f"train_step.b{bucket}", step_fn, *args)
            params, tstate, loss = step_fn(*args)
            if self.router != "iterative_reduce" \
                    and (state.step + 1) % self.average_every == 0:
                params = self._avg_fn(params)
                if obs:
                    METRICS.increment("train_step.periodic_average")
        lazy = LazyLoss(loss)
        if obs:
            dt = time.perf_counter() - t0
            # compile-vs-execute split: the first call's wall time is
            # dominated by trace+lower+compile — keep it out of the steady
            # state histogram so p99 means what a dashboard thinks it means.
            # Steady-state entries time DISPATCH only (the loop is async);
            # execution time lands in train_step.execute at resolution.
            METRICS.observe_time("train_step.compile" if first else "train_step", dt)
            METRICS.increment("train_step.iterations")
        if not self._pending:
            self._window_t0 = t0 if obs else time.perf_counter()
        self._pending.append((lazy, n_valid, state.step + 1))
        if len(self._pending) >= self.max_pending:
            self._resolve_pending()  # ring full: self-fence (bounded queue)
        return TrainState(params, tstate, state.step + 1, state.key), lazy

    def _resolve_pending(self) -> list[float]:
        """Fence: block until every pending loss is on host, then publish
        the window's metrics in one batch (gauges/histograms move HERE so
        the dispatch loop never syncs)."""
        if not self._pending:
            return []
        entries, self._pending = self._pending, []
        obs = _obs_enabled()
        wait0 = time.perf_counter() if obs else 0.0
        # one fence suffices: device programs execute in dispatch order, so
        # the last loss being ready implies the whole window has executed
        entries[-1][0].block()
        vals = [lazy.value() for lazy, _n, _s in entries]
        if obs:
            now = time.perf_counter()
            METRICS.observe_time("train_step.resolve_wait", now - wait0)
            METRICS.increment("train_step.losses_resolved", len(vals))
            METRICS.gauge("train_step.loss", vals[-1])
            t0 = self._window_t0
            if t0 is not None and now > t0:
                window = now - t0
                n_samples = sum(n for _, n, _s in entries)
                METRICS.gauge("train_step.samples_per_sec", n_samples / window)
                # amortized per-step execution time over the async window —
                # the steady-state throughput histogram (dispatch times in
                # `train_step` no longer measure execution)
                METRICS.observe_many(
                    "train_step.execute", [window / len(entries)] * len(entries))
                # live MFU/MBU from the same cost_analysis() accounting
                # bench reports: one dispatch's flops over the amortized
                # per-step execution time
                COSTS.publish_utilization(
                    self._step_cost, window / len(entries),
                    "train.mfu", "train.mbu")
        self._window_t0 = None
        if self._nan_guard:
            # divergence detection lives at the resolution point — the one
            # place losses are host floats anyway, so the guard adds no sync
            for (_lazy, _n, s), v in zip(entries, vals):
                if not np.isfinite(v):
                    METRICS.increment("resilience.nan_detected")
                    raise DivergenceError(s, v)
        return vals

    def abort(self) -> None:
        """Drop the pending-loss ring without resolving — the supervisor's
        retry path discards the in-flight window along with the state that
        produced it, then resumes from the last checkpoint."""
        self._pending.clear()
        self._window_t0 = None
        METRICS.increment("resilience.aborts")

    # ------------------------------------------------------------------ fit
    def _host_stream(self, data, epochs: int, skip: int, prefetch_size: int):
        """Stream (x, y, n_valid, bucket) tuples: host-side bucket padding,
        then double-buffered device transfer via ``prefetch_to_device``
        with this trainer's batch sharding — H2D overlaps compute on the
        production path, not just in bench.  Accepts a DataSet, a sequence,
        a DataSetIterator, or a one-shot generator (no ``list(data)``);
        re-iterable inputs replay for ``epochs``, one-shot generators
        stream a single pass."""
        if isinstance(data, DataSet):
            data = (data,)

        def batches():
            idx = 0
            for _ in range(max(1, int(epochs))):
                for b in iter(data):
                    if idx < skip:  # checkpoint-resume cursor
                        idx += 1
                        continue
                    idx += 1
                    # chaos seam: input-pipeline failure mid-stream
                    FAULTS.maybe_fire("data.next", idx)
                    x, y = ((b.features, b.labels)
                            if hasattr(b, "features") else (b[0], b[1]))
                    if not isinstance(x, jnp.ndarray):
                        x, y = np.asarray(x), np.asarray(y)
                    yield self._pad_to_bucket(x, y)

        if prefetch_size <= 0:
            return batches()
        from ..datasets.iterator import prefetch_to_device
        return prefetch_to_device(batches(), size=prefetch_size,
                                  sharding=NamedSharding(self.mesh, P(DP)))

    def fit(self, state: TrainState, data: Iterable[DataSet] | DataSet,
            epochs: int = 1, *, checkpoint_manager=None,
            checkpoint_every: int = 0, resume: bool = True,
            async_dispatch: bool = True, resolve_every: int = 32,
            prefetch_size: int = 2, nan_guard: bool = False,
            should_stop: Callable[[int], bool] | None = None,
            extra_skip: int = 0, goodput=None,
            ) -> tuple[TrainState, list[float]]:
        """Run ``epochs`` passes over ``data``, counting steps from
        ``state.step`` — so a state restored from a checkpoint continues
        where it left off (the elastic-recovery resume path; the reference
        only ever re-loaded bare params, ``ModelSavingActor.java:75-79``).

        ``data`` may be any iterable of batches and is never materialized;
        batches flow host-pad -> prefetch double-buffer -> jitted step.
        With ``async_dispatch`` (default) losses resolve in batches every
        ``resolve_every`` steps behind one ``block_until_ready`` fence;
        ``async_dispatch=False`` is the synchronous per-step reference
        path (same compiled steps, same numbers — used by the parity
        tests).  Returned losses are resolved floats either way.

        With ``checkpoint_manager`` set, auto-saves params + transform state
        + RNG key + data cursor every ``checkpoint_every`` steps (and at the
        end) — each save fences pending steps first; with ``resume``
        (default) restores the latest checkpoint before training.

        Supervisor hooks (all default-off; see ``resilience/``):
        ``nan_guard`` raises :class:`~..resilience.faults.DivergenceError`
        when a resolved loss is non-finite; ``should_stop(step)`` is polled
        after every dispatch — True drains the ring, writes an emergency
        checkpoint and returns (preemption handling); ``extra_skip`` drops
        that many additional stream batches past the resume cursor (the
        supervisor's divergence batch-window skip); ``goodput`` is an
        optional :class:`~..observability.goodput.GoodputTracker` the loop
        marks with restore/checkpoint/stall/drain intervals (``None`` —
        the default, and always the case when observability is off — adds
        zero per-step work: no clock reads, no allocations)."""
        n_known = len(data) if hasattr(data, "__len__") else -1
        self._nan_guard = nan_guard
        with trace.span("trainer.fit", epochs=epochs, n_batches=n_known,
                        router=self.router):
            if checkpoint_manager is not None and resume \
                    and checkpoint_manager.latest_step() is not None:
                if goodput is not None:
                    goodput.transition("restore")
                try:
                    state = self.restore(state, checkpoint_manager)
                except FileNotFoundError:
                    # every on-disk checkpoint failed verification — train
                    # from scratch rather than load corrupt state
                    METRICS.increment("checkpoint.no_valid_restore")
            if goodput is not None:
                # whatever the caller left us in (rollback backoff, resize
                # restore, drain), dispatching steps is productive time
                goodput.transition("productive")
            handles: list[LazyLoss] = []
            draining = False
            # steady state runs under the transfer guard: every host<->device
            # crossing in the loop must be an explicit device_put/device_get
            # (opt out via DL4J_TPU_TRANSFER_GUARD=0; see analysis.runtime)
            with hot_loop_guard():
                stream = iter(self._host_stream(
                    data, epochs, state.step + extra_skip, prefetch_size))
                while True:
                    if goodput is not None:
                        t_fetch = time.perf_counter()
                    try:
                        x, y, n_valid, bucket = next(stream)
                    except StopIteration:
                        break
                    if goodput is not None:
                        goodput.data_wait(t_fetch, time.perf_counter())
                    state, lazy = self._dispatch(state, x, y, n_valid, bucket)
                    handles.append(lazy)
                    if not async_dispatch:
                        self._resolve_pending()  # sync reference path
                    elif resolve_every and len(self._pending) >= resolve_every:
                        self._resolve_pending()
                    if should_stop is not None and should_stop(state.step):
                        # preemption: drain in-flight steps, snapshot, leave
                        # (goodput: everything from the stop signal to the
                        # return — including the emergency save — is drain)
                        if goodput is not None:
                            goodput.transition("drain")
                        draining = True
                        self._resolve_pending()
                        if checkpoint_manager is not None:
                            self.checkpoint(state, checkpoint_manager)
                        METRICS.increment("resilience.emergency_checkpoints")
                        break
                    if (checkpoint_manager is not None and checkpoint_every > 0
                            and state.step % checkpoint_every == 0):
                        if goodput is not None:
                            with goodput.phase("checkpoint"):
                                self.checkpoint(state, checkpoint_manager)
                        else:
                            self.checkpoint(state, checkpoint_manager)
                self._resolve_pending()
            losses = [h.value() for h in handles]
            if checkpoint_manager is not None and losses:
                if goodput is not None and not draining:
                    with goodput.phase("checkpoint"):
                        self.checkpoint(state, checkpoint_manager)
                else:
                    self.checkpoint(state, checkpoint_manager)
        sample_device_memory()  # HBM gauges; no-op on CPU / when disabled
        return state, losses

    # ------------------------------------------------------------------ ckpt
    def checkpoint(self, state: TrainState, manager,
                   layout: str = "natural") -> None:
        """Fence-then-save: resolve the pending-loss ring and block on the
        state itself so the snapshot cannot race in-flight steps.

        ``layout="natural"`` (default) gathers ZeRO state back to natural
        shapes — the width-agnostic on-disk format.  ``layout="flat"``
        writes the on-device flat padded ``P('dp')`` leaves as-is (skipping
        the unflatten); the manager stamps the save-side width so a restore
        at any other width re-splits host-side, exactly."""
        self._resolve_pending()
        jax.block_until_ready((state.params, state.tstate))
        METRICS.increment("checkpoint.fences")
        flat = layout == "flat" and self.zero_stage >= 1
        # the save pulls every leaf to host: a sanctioned sync point, so it
        # re-allows transfers even when called inside the guarded fit loop
        with allow_transfers():
            params, tstate, extra = state.params, state.tstate, None
            if self.zero_stage >= 1:
                # gather shard-local leaves and write the NATURAL layout:
                # the on-disk format is identical across stages and dp
                # widths, so restore can reshard onto any current mesh
                # (np.asarray on a dp-sharded leaf assembles the full
                # array from its chunks — single-host gather)
                z = self._zero
                if not flat:
                    tstate = z.to_natural_host(tstate, z.natural_tstate)
                    if self.zero_stage >= 3:
                        params = z.to_natural_host(params, z.natural_params)
                extra = {"zero_stage": self.zero_stage,
                         "saved_dp": int(self.n_dp)}
            manager.save(state.step, params, tstate=tstate,
                         key=state.key, data_cursor=state.step, extra=extra,
                         dp_width=int(self.n_dp), zero_stage=self.zero_stage,
                         layout="flat" if flat else "natural")

    def restore(self, template: TrainState, manager,
                reshard: bool = True) -> TrainState:
        """Restore the latest checkpoint into a state shaped like
        ``template`` (fresh ``init_state`` output), re-placed on the mesh.

        Under zero_stage >= 1 the checkpoint holds either the NATURAL
        layout (see :meth:`checkpoint`) or the flat save-side layout the
        manager re-splits; restoring re-flattens and re-shards onto THIS
        trainer's mesh — a checkpoint written at dp=2 restores onto dp=1
        (and vice versa) bit-for-bit.  The trainer's contract IS
        resharding, so ``reshard`` defaults to True; pass False to get the
        strict ``MeshMismatchError`` behavior across widths."""
        if self.zero_stage >= 1:
            state = self._restore_zero(template, manager, reshard=reshard)
        else:
            r = manager.restore(template.params,
                                tstate_template=template.tstate,
                                reshard=reshard, dp_width=int(self.n_dp))
            params = jax.tree_util.tree_map(
                lambda t, a: jax.device_put(jnp.asarray(a), t.sharding),
                template.params, r["params"])
            tstate = template.tstate
            if r["tstate"] is not None:
                tstate = jax.tree_util.tree_map(
                    lambda t, a: (jax.device_put(jnp.asarray(a), t.sharding)
                                  if isinstance(t, jnp.ndarray) else a),
                    template.tstate, r["tstate"])
            key = r["key"] if r["key"] is not None else template.key
            state = TrainState(params=params, tstate=tstate,
                               step=r["step"], key=key)
        sample_state_bytes(state.params, state.tstate)  # ZeRO memory gauges
        return state

    def _restore_zero(self, template: TrainState, manager,
                      reshard: bool = True) -> TrainState:
        """Reshard a natural-layout checkpoint onto the current mesh: load
        against abstract natural templates, then jit-flatten each tree
        straight into its cached dp sharding (no replicated intermediate)."""
        z = self._zero
        if z is None:
            # templates normally come from init_state (which builds the
            # layout); under stage 3 they MUST — template params are
            # already flat there, so natural shapes are unrecoverable
            if self.zero_stage >= 3:
                raise RuntimeError(
                    "zero_stage=3 restore needs a template from init_state")
            z = self._zero_layout(template.params)
        # restore is a sanctioned sync point like save: loading npz leaves
        # and re-placing them is setup, not the hot loop
        with allow_transfers():
            r = manager.restore(z.natural_params,
                                tstate_template=z.natural_tstate,
                                reshard=reshard, dp_width=int(self.n_dp))
            nat_params = jax.tree_util.tree_map(jnp.asarray, r["params"])
            if self.zero_stage >= 3:
                params = z.place_flat(nat_params, z.flat_sharding)
            else:
                params = jax.device_put(nat_params, self._rep_sh)
            tstate = template.tstate
            if r["tstate"] is not None:
                nat_t = jax.tree_util.tree_map(
                    lambda a: (jnp.asarray(a)
                               if isinstance(a, (jnp.ndarray, np.ndarray))
                               else a), r["tstate"])
                tstate = z.place_flat(nat_t, z.state_shardings)
        key = r["key"] if r["key"] is not None else template.key
        return TrainState(params=params, tstate=tstate,
                          step=r["step"], key=key)

    def final_params(self, state: TrainState):
        """Collapse to a single param set (average replicas for hogwild;
        gather + unflatten the sharded chunks for zero_stage 3)."""
        if self.zero_stage >= 3:
            z = self._zero
            return jax.jit(
                lambda t: z.unflatten_like(t, z.natural_params),
                out_shardings=self._rep_sh)(state.params)
        if self.router == "hogwild":
            # one-shot post-fit collapse; the x[0] gather index is a
            # setup-style constant a surrounding guard would reject
            with allow_transfers():
                avgd = (self._avg_fn(state.params) if self._avg_fn
                        else state.params)
                return jax.tree_util.tree_map(lambda a: a[0], avgd)
        return state.params
