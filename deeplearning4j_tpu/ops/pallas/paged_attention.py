"""Paged-attention decode kernel: K/V read through block tables.

The serving engine's paged decode (DESIGN.md §17) stores K/V as
fixed-size pages in a ``(num_pages, page_size, H, Dh)`` pool and
addresses each sequence through an ``(B, n_pages)`` block table.  The
exact-parity read path gathers a row's logical K/V into a dense
``(B, max_len, H, Dh)`` buffer and reuses the dense attention ops —
bitwise, but it materializes max_len per row per layer.  This module's
Pallas candidate streams the pages instead: one program per
(sequence, page), the block table SCALAR-PREFETCHED so each program's
K/V block is DMA'd straight from its physical page, a running softmax
in VMEM scratch across the page axis.  No (B, max_len) intermediate is
ever built.

Like every kernel in this tier it enters production only through the
bench auto-pick gate: :func:`reference_paged_attention` (pure jnp, the
same gather the engine's parity path uses) is both the incumbent
candidate ("gather", source="xla") and the correctness reference the
TUNE battery checks the Pallas candidate against.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..flash_attention import _VMEM, pltpu

from . import registry

_NEG_INF = -1e30


def reference_paged_attention(q, k_pages, v_pages, block_tables, lengths,
                              **_):
    """Ground truth: gather each row's pages to a dense (B, T, H, Dh)
    view and run the dense decode attention ops over it.

    ``q`` (B, H, Dh) single-position queries, ``k_pages``/``v_pages``
    (P, ps, H, Dh), ``block_tables`` (B, n_pages) physical page ids,
    ``lengths`` (B,) valid K/V prefix per row (>= 1).  Returns
    (B, H, Dh) in ``q``'s dtype.  These are byte-for-byte the engine's
    masked-gather attention ops, so this reference IS the parity path.
    """
    ps = k_pages.shape[1]
    B = q.shape[0]
    T = block_tables.shape[1] * ps
    scale = q.shape[-1] ** -0.5
    t = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    flat = jnp.take_along_axis(block_tables, t // ps, axis=1) * ps + t % ps
    k = k_pages.reshape((-1,) + k_pages.shape[2:])[flat]     # (B, T, H, Dh)
    v = v_pages.reshape((-1,) + v_pages.shape[2:])[flat]
    s = jnp.einsum("bhd,bthd->bht", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where((t < lengths[:, None])[:, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bht,bthd->bhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _paged_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, page_size: int, n_pages: int,
                  scale: float):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    q = q_ref[0].astype(jnp.float32) * scale                 # (H, Dh)
    k = k_ref[0].astype(jnp.float32)                         # (ps, H, Dh)
    v = v_ref[0].astype(jnp.float32)
    s = jnp.sum(q[None, :, :] * k, axis=-1).T                # (H, ps)
    pos = j * page_size + lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1)                        # (1, ps)
    mask = pos < len_ref[b]
    s = jnp.where(mask, s, _NEG_INF)
    m_prev = m_ref[:, 0]
    m_new = jnp.maximum(m_prev, s.max(axis=1))
    # a fully-masked page leaves m_new at -inf; zero its weights
    # explicitly so exp(-inf - -inf) == 1 cannot leak into the sum
    p = jnp.where(mask, jnp.exp(s - m_new[:, None]), 0.0)    # (H, ps)
    corr = jnp.exp(m_prev - m_new)
    l_ref[:, 0] = l_ref[:, 0] * corr + p.sum(axis=1)
    acc_ref[...] = (acc_ref[...] * corr[:, None]
                    + jnp.sum(p.T[:, :, None] * v, axis=0))  # (H, Dh)
    m_ref[:, 0] = m_new

    @pl.when(j == n_pages - 1)
    def _finalize():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def paged_attention(q, k_pages, v_pages, block_tables, lengths, *,
                    interpret: bool | None = None):
    """Pallas paged decode attention; same signature/result contract as
    :func:`reference_paged_attention` (within the registered tolerance —
    running softmax reassociates the reduction, so NOT bitwise).
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    B, H, Dh = q.shape
    ps = k_pages.shape[1]
    n_pages = block_tables.shape[1]
    scale = Dh ** -0.5
    kernel = functools.partial(_paged_kernel, page_size=ps, n_pages=n_pages,
                               scale=scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_pages),
        in_specs=[
            pl.BlockSpec((1, H, Dh), lambda b, j, bt, ln: (b, 0, 0), **mem),
            # the paged read itself: this program's K/V block is whatever
            # physical page the scalar-prefetched table names
            pl.BlockSpec((1, ps, H, Dh),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0, 0), **mem),
            pl.BlockSpec((1, ps, H, Dh),
                         lambda b, j, bt, ln: (bt[b, j], 0, 0, 0), **mem),
        ],
        out_specs=pl.BlockSpec((1, H, Dh), lambda b, j, bt, ln: (b, 0, 0),
                               **mem),
        scratch_shapes=[
            pltpu.VMEM((H, Dh), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Dh), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q, k_pages, v_pages)


registry.register(registry.KernelCandidate(
    kind="paged_attention", name="pallas", fn=paged_attention,
    reference=reference_paged_attention,
    blocks=({},),              # the page size IS the block: nothing to sweep
    tolerances={"max_err": 0.05},
))

registry.register(registry.KernelCandidate(
    kind="paged_attention", name="gather", fn=reference_paged_attention,
    reference=reference_paged_attention, source="xla",
))
