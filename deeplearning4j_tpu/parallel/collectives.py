"""The collectives layer — the reference's "NCCL"/transport, TPU-native.

The reference moves parameters/updates through Akka remote messages +
Hazelcast IMaps/ILists + Avro RPC (SURVEY.md §2.3 backend table).  On TPU the
entire data plane is XLA collectives compiled into the step function and
riding ICI (intra-slice) / DCN (inter-slice).  These wrappers name that
surface explicitly — use them inside ``shard_map``-ped functions; under plain
``pjit`` sharding propagation inserts the same collectives automatically.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def psum(x, axis: str):
    """All-reduce sum over a mesh axis (≡ parameter-averaging numerator,
    ``INDArrayAggregator.accumulate``)."""
    return lax.psum(x, axis)


def pmean(x, axis: str):
    """All-reduce mean (≡ ``IterativeReduceWorkRouter`` averaging in one op)."""
    return lax.pmean(x, axis)


def all_gather(x, axis: str, *, tiled: bool = False):
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension, tiled=True)


# --------------------------------------------------------------- ZeRO helpers
#
# The sharded weight update (trainer zero_stage >= 1) communicates flattened
# 1-D gradient/param chunks.  On a 1-member dp axis the tiled collectives
# degenerate — the "scatter" of one tile is the whole array and the "gather"
# of one shard is the input — so these wrappers take the axis size explicitly
# and fall back to a plain psum / identity, keeping the dp=1 step the same
# compiled program shape as the replicated path.

def reduce_scatter_or_psum(x, axis: str, axis_size: int):
    """Reduce-scatter ``x`` (1-D, length divisible by ``axis_size``) into
    per-member contiguous tiles; psum fallback when the axis has one member
    (sum of one shard = the shard, and the tile IS the array)."""
    if axis_size == 1:
        return lax.psum(x, axis)
    return lax.psum_scatter(x, axis, scatter_dimension=0, tiled=True)


def all_gather_or_identity(x, axis: str, axis_size: int):
    """Tiled all-gather of per-member chunks back to the full flattened
    vector; identity when the axis has one member."""
    if axis_size == 1:
        return x
    return lax.all_gather(x, axis, tiled=True)


def ppermute(x, axis: str, perm):
    """Neighbor exchange — the ring primitive under ring attention /
    pipeline micro-batch handoff."""
    return lax.ppermute(x, axis, perm)


def ring_shift(x, axis: str, axis_size: int, shift: int = 1):
    """Shift values around the ring by ``shift`` positions."""
    perm = [(i, (i + shift) % axis_size) for i in range(axis_size)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def barrier_sum(axis: str):
    """Cheap cross-device barrier: psum of a scalar 1 (control-plane sync;
    replaces the reference's 'wait for N worker updates' poll loop)."""
    return lax.psum(jnp.ones(()), axis)
