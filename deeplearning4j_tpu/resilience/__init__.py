"""Resilience: fault injection + self-healing supervision (DESIGN.md §12).

Three cooperating parts:

- :mod:`.faults` — a deterministic, seedable chaos layer: named injection
  sites across the training stack (worker death, slow worker, checkpoint
  corruption, data-pipeline failure, transient step failure, simulated
  preemption), armed via :func:`inject_faults` or ``DL4J_TPU_FAULTS``.
- :mod:`.supervisor` — :class:`TrainingSupervisor`: bounded retry with
  backoff + jitter, resume from the newest *valid* checkpoint, NaN/Inf
  divergence rollback, SIGTERM/SIGINT emergency checkpointing, and —
  given a trainer factory — elastic topology resize on device loss or
  re-admission (DESIGN.md §21).
- hardening in the layers underneath (``parallel/checkpoint.py`` checksum
  verification and restore fallback; ``parallel/scaleout.py`` job retry
  budgets, poison-job quarantine, execution timeouts) — see those modules.
"""

from .faults import (
    FAULTS,
    DataIteratorFault,
    DeviceLossError,
    DivergenceError,
    FaultInjector,
    FaultSpec,
    InjectedFault,
    PreemptionSignal,
    TrainingPreempted,
    TransientStepFault,
    WorkerKilled,
    corrupt_file,
    inject_faults,
    parse_fault_env,
)
from .supervisor import RetryPolicy, SupervisorReport, TrainingSupervisor

__all__ = [
    "FAULTS", "DataIteratorFault", "DeviceLossError", "DivergenceError",
    "FaultInjector",
    "FaultSpec", "InjectedFault", "PreemptionSignal", "RetryPolicy",
    "SupervisorReport", "TrainingPreempted", "TrainingSupervisor",
    "TransientStepFault", "WorkerKilled", "corrupt_file", "inject_faults",
    "parse_fault_env",
]
