"""Sentiment lexicon scoring.

Capability match of ``text/corpora/sentiwordnet/SWN3.java``: token-level
polarity lookup aggregated to a document judgment.  The reference ships the
SentiWordNet data file; redistribution isn't bundled here, so the loader
accepts the standard SWN tab-separated format from disk and falls back to a
small built-in seed lexicon for offline use.
"""

from __future__ import annotations

from pathlib import Path

_SEED = {
    "good": 0.6, "great": 0.8, "excellent": 0.9, "love": 0.8, "happy": 0.7,
    "wonderful": 0.8, "best": 0.7, "nice": 0.5, "amazing": 0.8, "like": 0.3,
    "bad": -0.6, "terrible": -0.8, "awful": -0.8, "hate": -0.8, "sad": -0.6,
    "horrible": -0.8, "worst": -0.9, "poor": -0.5, "disappointing": -0.6,
    "boring": -0.5, "not": -0.2, "never": -0.2,
}


class SentiWordNet:
    def __init__(self, path: str | Path | None = None):
        self.scores: dict[str, float] = dict(_SEED)
        if path is not None:
            self._load_swn(Path(path))

    def _load_swn(self, path: Path) -> None:
        """Parse the standard SentiWordNet 3.0 TSV (POS\\tID\\tPosScore\\t
        NegScore\\tSynsetTerms\\tGloss)."""
        agg: dict[str, list[float]] = {}
        for line in path.read_text().splitlines():
            if not line or line.startswith("#"):
                continue
            parts = line.split("\t")
            if len(parts) < 5:
                continue
            try:
                pos_s, neg_s = float(parts[2]), float(parts[3])
            except ValueError:
                continue
            for term in parts[4].split():
                word = term.rsplit("#", 1)[0].lower()
                agg.setdefault(word, []).append(pos_s - neg_s)
        for w, vals in agg.items():
            self.scores[w] = sum(vals) / len(vals)

    def score(self, word: str) -> float:
        return self.scores.get(word.lower(), 0.0)

    def classify(self, tokens) -> str:
        """strong_positive/positive/neutral/negative/strong_negative
        (SWN3's judgment buckets)."""
        total = sum(self.score(t) for t in tokens)
        n = max(1, sum(1 for t in tokens if t.lower() in self.scores))
        avg = total / n
        if avg >= 0.5:
            return "strong_positive"
        if avg > 0.05:
            return "positive"
        if avg <= -0.5:
            return "strong_negative"
        if avg < -0.05:
            return "negative"
        return "neutral"
