"""Serving-subsystem tests (DESIGN.md §13): continuous-batching parity
with the offline sampler, admission control, hot reload, slot lifecycle,
compile-cache discipline, and the HTTP surface.

The acceptance contract is token-level: a request served through the
slot-pool engine must produce EXACTLY the tokens
``Transformer.sample(..., key=jax.random.key(seed), kv_cache=True)``
produces for the same (prompt, max_new, temperature, seed) — continuous
batching is an implementation detail, not a semantics change.
"""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import TransformerConfig, TransformerLM
from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
from deeplearning4j_tpu.serving import (BatchScorer, InferenceEngine,
                                        ModelServer, QueueFull, RequestQueue,
                                        ServingClient, ServingConfig,
                                        ServingError)
from deeplearning4j_tpu.serving.batcher import DeadlineExceeded, GenerateRequest


def tiny_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 32)
    kw.setdefault("n_heads", 4)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)  # exact parity comparisons
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


@pytest.fixture(scope="module")
def lm():
    """Untrained tiny LM — parity only needs determinism, not quality."""
    cfg = tiny_cfg()
    model = TransformerLM(cfg)
    return model, model.init(jax.random.key(7))


@pytest.fixture(scope="module")
def cycle_lm():
    """The test_transformer.py trained-cycle idiom: a model that greedily
    continues a periodic stream, so EOS/reload tests can assert exact
    token content, not just shapes."""
    from deeplearning4j_tpu.optimize import transforms as T

    period = [3, 1, 4, 1, 5, 9, 2, 6]
    cfg = tiny_cfg(vocab_size=16, causal=True)
    stream = np.array(period * 32, np.int32)
    span = cfg.max_len + 1
    n = len(stream) // span
    blocks = stream[:n * span].reshape(n, span)
    tokens = jnp.asarray(blocks[:, :-1])
    targets = jnp.asarray(blocks[:, 1:])
    model = TransformerLM(cfg)
    tx = T.adamw(0.01)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    for _ in range(60):
        params, opt, _ = step(params, opt, tokens, targets)
    # fixture sanity: the offline sampler continues the cycle
    out = model.sample(params, period[:4], length=8, temperature=0.0)
    assert out == (period * 3)[:len(out)]
    return model, params, period


def _expected(model, params, prompt, n, temp, seed):
    return model.sample(params, prompt, n, temperature=temp,
                        key=jax.random.key(seed),
                        kv_cache=True)[len(prompt):]


# --------------------------------------------------------------- admission
def test_queue_backpressure_and_deadline():
    q = RequestQueue(max_depth=2, max_batch_delay_ms=0.0)

    def req(**kw):
        return GenerateRequest(prompt=[1], max_new_tokens=1, **kw)

    a, b = q.submit(req()), q.submit(req())
    with pytest.raises(QueueFull) as ei:
        q.submit(req())
    assert ei.value.status == 429
    assert q.take(8) == [a, b]      # FIFO, rejection freed no slot

    # a request whose deadline expired while queued never reaches a slot
    p = q.submit(req(deadline_s=time.monotonic() - 1.0))
    assert q.take(8) == []
    assert p.done()
    with pytest.raises(DeadlineExceeded) as ei:
        p.result(0)
    assert ei.value.status == 504

    counters = METRICS.snapshot()["counters"]
    assert counters["serving.rejected"] == 1
    assert counters["serving.deadline_dropped"] == 1


def test_submit_validation_and_engine_backpressure(lm):
    model, params = lm
    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=1, max_queue=2))
    with pytest.raises(ValueError, match="empty"):
        engine.submit([], 4)
    with pytest.raises(ValueError, match="out of range"):
        engine.submit([999], 4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.submit([1], 0)
    with pytest.raises(ValueError, match="max_len"):
        engine.submit([1] * 10, 30)
    engine.submit([1], 2)
    engine.submit([2], 2)
    with pytest.raises(QueueFull):   # engine not started: queue fills
        engine.submit([3], 2)
    engine.stop()                    # fails the two queued handles


# ----------------------------------------------------- continuous batching
@pytest.mark.lockguard
def test_continuous_batching_matches_offline_sample(lm):
    """The acceptance test: mixed greedy/temperature traffic through 3
    concurrent slots is token-identical to the sequential sampler — run
    with instrumented locks, so a lock-order inversion or unguarded
    shared write anywhere in the engine/queue path fails it too."""
    model, params = lm
    plans = [([5, 1, 4], 6, 0.0, 0),
             ([2, 8, 2, 8, 2, 8, 2, 8, 2], 4, 0.8, 123),
             ([7], 5, 0.0, 3),
             ([3, 2, 1, 0, 5], 6, 1.0, 9),
             ([11, 12], 3, 0.8, 77)]
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in plans]

    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=3, resolve_every=2))
    # submit everything BEFORE the loop starts, so the first device batch
    # is provably full (3/3 slots decoding concurrently)
    handles = [engine.submit(p, n, temperature=t, seed=s)
               for p, n, t, s in plans]
    # cold start on purpose: these plans touch only the 8/16 buckets, so
    # the warmup ladder would compile graphs this test never dispatches
    with engine.start(warmup=False):
        outs = [h.result(60.0) for h in handles]

    assert [o.tokens for o in outs] == want
    assert all(o.finish_reason == "length" for o in outs)
    assert all(o.ttft_s is not None and o.latency_s > 0 for o in outs)
    snap = METRICS.snapshot()
    assert snap["timers"]["serving.batch_fill_ratio"]["max_s"] == 1.0
    assert snap["counters"]["serving.completed"] == len(plans)
    assert snap["counters"]["serving.tokens"] == sum(len(w) for w in want)


def test_prefill_recompiles_bounded_by_bucket_count(lm):
    """PR-2 discipline: prompt lengths hash to a power-of-two bucket
    ladder, and ``warmup()`` precompiles EVERY rung up to ``max_len`` —
    so the recompile counter sits at the ladder size before the first
    request and NEVER moves under traffic, whatever the prompt length
    (first-request TTFT pays no compile stall)."""
    model, params = lm
    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2))
    ladder = [8, 16, 32]                 # min_prefill_bucket=8 .. max_len=32
    with engine:   # warmup compiled the whole ladder
        assert METRICS.snapshot()["counters"][
            "serving.prefill.recompile"] == len(ladder)
        assert engine.stats()["prefill_buckets"] == ladder
        for p_len in (3, 5, 8, 9, 12, 16, 17, 25):   # every rung hit
            engine.generate([1] * p_len, 2)
        assert METRICS.snapshot()["counters"][
            "serving.prefill.recompile"] == len(ladder)
        assert engine.stats()["prefill_buckets"] == ladder


def test_eos_evicts_slot_and_reuses_it(cycle_lm):
    """4 requests through 2 slots, each finishing on EOS long before its
    length budget — completion requires eviction AND slot reuse."""
    model, params, period = cycle_lm
    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2))
    with engine:
        handles = [engine.submit(period[:4], 8, eos_id=9, seed=i)
                   for i in range(4)]
        outs = [h.result(60.0) for h in handles]
    # greedy continuation is 5 9 2 6 ... -> stops at the injected EOS id 9
    assert all(o.tokens == [5, 9] for o in outs)
    assert all(o.finish_reason == "eos" for o in outs)
    st = engine.stats()
    assert st["admitted"] == 4 and st["completed"] == 4
    assert st["active"] == 0 and st["free"] == 2
    assert METRICS.snapshot()["counters"]["serving.completed"] == 4


# --------------------------------------------------------- int8 decode opt-in
def test_int8_decode_opt_in_matches_offline_quantized_sample(lm):
    """``int8_decode=True`` quantizes the SERVING copy of the params and
    must be token-identical to sampling offline with the same quantized
    tree (decode_step picks the int8 path on key presence).  The reload
    template stays float so checkpoint restore shapes are unchanged."""
    from deeplearning4j_tpu.ops.pallas.matmul_int8 import (
        quantize_params_for_decode)

    model, params = lm
    qp = quantize_params_for_decode(params, model.cfg)
    plans = [([5, 1, 4], 6, 0.0, 0),
             ([7], 5, 0.0, 3),
             ([2, 8, 2, 8], 4, 0.8, 123)]
    want = [_expected(model, qp, p, n, t, s) for p, n, t, s in plans]

    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2,
                                               int8_decode=True))
    assert "head_q" in engine._params            # serving copy: quantized
    assert "head_q" not in engine._raw_params    # reload template: float
    handles = [engine.submit(p, n, temperature=t, seed=s)
               for p, n, t, s in plans]
    # cold start on purpose: these plans touch only the 8/16 buckets, so
    # the warmup ladder would compile graphs this test never dispatches
    with engine.start(warmup=False):
        outs = [h.result(60.0) for h in handles]
    assert [o.tokens for o in outs] == want
    assert "serving.quantize" in METRICS.snapshot()["timers"]


def test_int8_decode_is_off_by_default(lm):
    """The default-config engine must serve the float params untouched —
    int8 is strictly opt-in (the acceptance contract's parity tests above
    all run through this default path)."""
    model, params = lm
    engine = InferenceEngine(model, params=params, cfg=ServingConfig())
    assert ServingConfig().int8_decode is False
    assert engine._params is engine._raw_params
    assert "head_q" not in engine._params
    engine.stop()


# -------------------------------------------------------------- hot reload
def test_hot_reload_mid_traffic(cycle_lm, tmp_path):
    """Swap to a newer checkpoint WITHOUT draining: the in-flight request
    still completes, and post-reload traffic decodes with the new params."""
    model, trained, period = cycle_lm
    rand = model.init(jax.random.key(99))
    ckdir = tmp_path / "ck"
    mgr = CheckpointManager(ckdir, keep=5)
    mgr.save(1, rand)

    engine = InferenceEngine(model, checkpoint=str(ckdir),
                             cfg=ServingConfig(slots=2, resolve_every=2))
    assert engine.stats()["loaded_step"] == 1
    with engine:
        inflight = engine.submit(period[:4], 24)      # long, likely mid-decode
        mgr.save(2, trained)
        assert engine.reload() == 2
        out = inflight.result(60.0)
        assert out.finish_reason == "length" and len(out.tokens) == 24
        post = engine.generate(period[:4], 8)
        assert post.tokens == (period * 2)[4:12]      # trained-cycle greedy
    snap = METRICS.snapshot()
    assert snap["counters"]["serving.reloads"] == 1
    assert snap["gauges"]["serving.loaded_step"] == 2
    assert engine.reload() == 2                        # same step: no-op
    assert METRICS.snapshot()["counters"]["serving.reloads"] == 1


def test_checkpoint_read_only_serving_path(lm, tmp_path):
    model, _ = lm
    with pytest.raises(FileNotFoundError):
        CheckpointManager.open_read_only(tmp_path / "missing")
    ckdir = tmp_path / "ck"
    mgr = CheckpointManager(ckdir, keep=2)
    with pytest.raises(FileNotFoundError, match="no verified checkpoint"):
        InferenceEngine(model, checkpoint=str(ckdir))  # dir exists, no ckpt
    mgr.save(1, {"w": np.zeros(3, np.float32)})
    ro = CheckpointManager.open_read_only(ckdir)
    assert ro.latest_valid_step() == 1
    with pytest.raises(RuntimeError, match="read-only"):
        ro.save(2, {"w": np.zeros(3, np.float32)})


# ------------------------------------------------------------ chaos sites
def test_chaos_sites_fixed_plan(lm):
    """Deterministic twin of tools/chaos_smoke.py's serving leg: a decode
    fault skips the dispatch (state untouched -> tokens unchanged), a
    submit fault raises to the caller and a retry wins."""
    from deeplearning4j_tpu.resilience import FaultSpec, inject_faults
    from deeplearning4j_tpu.resilience.faults import FAULTS, InjectedFault

    model, params = lm
    plans = [([4, 2], 5, 0.0, 0), ([1, 2, 3], 4, 0.8, 5), ([9], 3, 0.0, 1)]
    want = [_expected(model, params, p, n, t, s) for p, n, t, s in plans]
    specs = [FaultSpec("serving.decode", probability=1.0, max_fires=2),
             FaultSpec("serving.request", at_step=2)]
    retried = 0
    with inject_faults(*specs, seed=0):
        engine = InferenceEngine(
            model, params=params,
            cfg=ServingConfig(slots=2, resolve_every=2)).start()
        handles = []
        for p, n, t, s in plans:
            try:
                handles.append(engine.submit(p, n, temperature=t, seed=s))
            except InjectedFault:
                retried += 1
                handles.append(engine.submit(p, n, temperature=t, seed=s))
        outs = [h.result(60.0) for h in handles]
        engine.stop()
        assert FAULTS.fire_count("serving.decode") == 2
        assert FAULTS.fire_count("serving.request") == 1
    assert retried == 1
    assert [o.tokens for o in outs] == want
    assert METRICS.snapshot()["counters"]["serving.decode.faults"] == 2


# ------------------------------------------------------------ batch scorer
def test_batch_scorer_coalesces_and_matches_direct():
    calls = []
    w = np.arange(12, dtype=np.float32).reshape(4, 3)

    def fn(xs):
        calls.append(np.asarray(xs).shape[0])
        return np.asarray(xs) @ w

    xs = np.random.default_rng(0).normal(size=(6, 4)).astype(np.float32)
    with BatchScorer(fn, max_batch=8) as sc:
        np.testing.assert_allclose(sc.score_batch(xs), xs @ w, rtol=1e-6)
        np.testing.assert_allclose(sc.score(xs[0]), xs[0] @ w, rtol=1e-6)
        with pytest.raises(ValueError, match="row shape"):
            sc.submit(np.zeros((5,), np.float32))
    assert calls and all(c & (c - 1) == 0 for c in calls)  # pow2 buckets only
    counters = METRICS.snapshot()["counters"]
    assert counters["serving.score.rows"] == 7
    assert counters["serving.score.recompile"] == len(set(calls))


def test_batch_scorer_serves_multilayer_network():
    """The zoo/MultiLayerNetwork half of the serving story: ``net.output``
    drops into the scorer as-is."""
    from deeplearning4j_tpu.nn import MultiLayerNetwork
    from deeplearning4j_tpu.nn.conf import (NeuralNetConfiguration,
                                            OptimizationAlgorithm,
                                            list_builder)

    base = NeuralNetConfiguration(
        n_in=4, n_out=3, lr=0.1, momentum=0.9, use_adagrad=True,
        num_iterations=1,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        activation="tanh")
    conf = (list_builder(base, 2)
            .hidden_layer_sizes(8)
            .override(1, kind="output", activation="softmax", loss="mcxent")
            .pretrain(False)
            .build())
    net = MultiLayerNetwork(conf)
    net.init()
    x = np.random.default_rng(1).normal(size=(5, 4)).astype(np.float32)
    direct = np.asarray(net.output(x))
    with BatchScorer(net.output, max_batch=8) as sc:
        served = sc.score_batch(x)
    np.testing.assert_allclose(served, direct, rtol=1e-5, atol=1e-6)


# -------------------------------------------------------------- HTTP layer
def test_http_server_end_to_end(lm):
    model, params = lm
    w = np.arange(8, dtype=np.float32).reshape(4, 2)

    def score_fn(xs):
        return np.asarray(xs, np.float32) @ w

    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2))
    scorer = BatchScorer(score_fn, max_batch=8)
    with engine, scorer, ModelServer(engine=engine, scorer=scorer) as server:
        client = ServingClient(port=server.port)
        prompt, n, seed = [5, 1, 4], 6, 11
        want = _expected(model, params, prompt, n, 0.8, seed)
        out = client.generate(prompt, max_new_tokens=n, temperature=0.8,
                              seed=seed)
        assert out["tokens"] == want and out["finish_reason"] == "length"

        rows = [[1.0, 2.0, 3.0, 4.0], [0.0, -1.0, 0.5, 2.0]]
        np.testing.assert_allclose(np.asarray(client.score(rows)),
                                   np.asarray(rows, np.float32) @ w,
                                   rtol=1e-6)
        health = client.healthz()
        assert health["ok"] and health["engine"]["slots"] == 2
        prom = client.metrics_prom()
        assert "serving_request_latency_seconds" in prom
        assert "serving_tokens_total" in prom

        with pytest.raises(ServingError) as e400:
            client._json("/v1/generate", {"max_new_tokens": 2})  # no prompt
        assert e400.value.status == 400
        with pytest.raises(ServingError) as e409:
            client.reload()                      # no checkpoint attached
        assert e409.value.status == 409
        with pytest.raises(ServingError) as e404:
            client._json("/v1/nope", {})
        assert e404.value.status == 404


# ------------------------------------------------- concurrency regressions

def test_pending_result_completion_is_single_shot():
    """_complete/_fail race by design (expiry vs. resolution vs. shutdown);
    exactly one transition wins and the rest are no-ops."""
    p = RequestQueue().submit(GenerateRequest(prompt=[1], max_new_tokens=1))
    assert p._fail(DeadlineExceeded("expired")) is True
    assert p._complete("late value") is False       # rival lost
    assert p._fail(RuntimeError("also late")) is False
    with pytest.raises(DeadlineExceeded):           # first transition stuck
        p.result(0)


def test_claim_arbitrates_expiry_vs_admission_under_contention():
    """Regression for the check-then-act window between take() and slot
    occupancy: an engine-like thread claims each request at the moment it
    would take a slot, while deadlines straddle the claim point.  Every
    request must end in EXACTLY one of {claimed-and-completed, expired} —
    never both, never neither."""
    q = RequestQueue(max_depth=256, max_batch_delay_ms=0.0)
    completed, stop = [], threading.Event()

    def engine_like():
        while not stop.is_set():
            for p in q.take(4, block_s=0.01):
                time.sleep(0.002)        # widen the take->claim window
                if q.claim(p):
                    assert p._complete(f"ok-{p.request.id}")
                    completed.append(p)

    t = threading.Thread(target=engine_like)
    t.start()
    handles = []
    now = time.monotonic
    for i in range(60):
        # deadlines scattered tightly around the claim point, some already
        # dead, some comfortably alive
        dl = now() + (i % 3 - 1) * 0.004
        handles.append(q.submit(GenerateRequest(
            prompt=[1], max_new_tokens=1, deadline_s=dl)))
    deadline = time.monotonic() + 30.0
    while not all(h.done() for h in handles):
        assert time.monotonic() < deadline, "requests stranded"
        time.sleep(0.005)
    stop.set()
    t.join(10.0)
    assert not t.is_alive()

    outcomes = {"completed": 0, "expired": 0}
    for h in handles:
        try:
            val = h.result(0)
            assert val == f"ok-{h.request.id}"
            outcomes["completed"] += 1
        except DeadlineExceeded:
            outcomes["expired"] += 1
    assert sum(outcomes.values()) == len(handles)
    counters = METRICS.snapshot()["counters"]
    assert counters.get("serving.deadline_dropped", 0) == outcomes["expired"]
    assert len(completed) == outcomes["completed"]


def test_claim_refuses_already_failed_request():
    q = RequestQueue()
    p = q.submit(GenerateRequest(prompt=[1], max_new_tokens=1))
    p._fail(RuntimeError("shutdown"))
    assert q.claim(p) is False          # never resurrect a dead request


def test_stats_and_stop_race_free_during_traffic(lm):
    """Callers hammer stats() from several threads while requests flow and
    the engine shuts down mid-read — slot bookkeeping is lock-consistent:
    no snapshot ever shows more slots than exist (a slot may be in
    transit between free and active while its prefill runs, so the sum
    can briefly undershoot, never overshoot) and nothing throws."""
    model, params = lm
    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2))
    errors, stop = [], threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                s = engine.stats()
                assert 0 <= s["active"] + s["free"] <= s["slots"]
            except Exception as e:  # noqa: BLE001 - collected for the assert
                errors.append(e)

    ts = [threading.Thread(target=hammer) for _ in range(3)]
    for t in ts:
        t.start()
    try:
        # cold start: 3-token prompts touch only the 8 bucket, and the
        # race under test is stats()-vs-serve, not warmup
        with engine.start(warmup=False):
            outs = [engine.submit([1, 2, 3], 2, seed=i) for i in range(6)]
            for h in outs:
                h.result(60.0)
    finally:
        stop.set()
        for t in ts:
            t.join(10.0)
    assert errors == []
    assert engine.stats()["completed"] == 6


# ---------------------------------------------------------- request tracing

def test_request_trace_chain_over_http(lm):
    """PR 10 acceptance: a traced client call propagates its W3C
    traceparent over HTTP, and the engine's queue_wait -> prefill ->
    decode -> emit spans all share the CLIENT's trace id, parented under
    one serving.request root."""
    from deeplearning4j_tpu.observability import TRACER, trace

    model, params = lm
    TRACER.clear()
    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2))
    with engine, ModelServer(engine=engine) as server:
        client = ServingClient(port=server.port)
        with trace.span("client.generate") as sp:
            out = client.generate([5, 1, 4], max_new_tokens=6)
        client_trace = sp.trace_id
        client_span = sp.span_id
    assert len(out["tokens"]) == 6

    events = [e for e in TRACER.to_chrome_trace()["traceEvents"]
              if (e["args"].get("trace_id") == client_trace
                  and e["name"].startswith("serving."))]
    names = {e["name"] for e in events}
    assert {"serving.request", "serving.queue_wait", "serving.prefill",
            "serving.decode.segment", "serving.emit"} <= names

    by_name = {}
    for e in events:
        by_name.setdefault(e["name"], []).append(e)
    (root,) = by_name["serving.request"]
    # the server-side root is a CHILD of the client span (joined, not minted)
    assert root["args"]["parent_span_id"] == client_span
    for name in ("serving.queue_wait", "serving.prefill",
                 "serving.decode.segment", "serving.emit"):
        for e in by_name[name]:
            assert e["args"]["parent_span_id"] == root["args"]["span_id"]
    # phases sit inside the root on the timeline (small tolerance: span
    # ends are stamped on the serve thread after the phase boundary)
    t0, t1 = root["ts"], root["ts"] + root["dur"]
    for name in ("serving.queue_wait", "serving.prefill", "serving.emit"):
        for e in by_name[name]:
            assert e["ts"] >= t0 - 1e3
            assert e["ts"] + e["dur"] <= t1 + 1e3


def test_untraced_request_mints_trace_and_decode_mfu_lands(lm):
    """Without a caller span the engine mints a fresh trace id at
    admission; the decode loop publishes serving.decode_mfu either way."""
    from deeplearning4j_tpu.observability import METRICS, TRACER

    model, params = lm
    TRACER.clear()
    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=2, resolve_every=2))
    with engine:
        engine.generate([3, 1, 4], max_new_tokens=5)
    roots = [e for e in TRACER.to_chrome_trace()["traceEvents"]
             if e["name"] == "serving.request"]
    assert len(roots) == 1
    tid = roots[0]["args"]["trace_id"]
    assert tid and len(tid) == 32 and int(tid, 16) != 0
    gauges = METRICS.snapshot()["gauges"]
    assert gauges["serving.decode_mfu"] > 0
    assert np.isfinite(gauges["serving.decode_mfu"])


def test_disabled_observability_serves_without_spans(lm):
    """DL4J_TPU_OBS=0 contract: with the layer disabled the engine still
    serves, and records no spans, no cost capture, no MFU gauges."""
    from deeplearning4j_tpu import observability as obs
    from deeplearning4j_tpu.observability import METRICS, TRACER

    model, params = lm
    TRACER.clear()
    METRICS.reset()
    obs.disable()
    try:
        engine = InferenceEngine(model, params=params,
                                 cfg=ServingConfig(slots=2, resolve_every=2))
        with engine:
            out = engine.generate([5, 1, 4], max_new_tokens=4)
    finally:
        obs.enable()
    assert len(out.tokens) == 4
    assert TRACER.to_chrome_trace()["traceEvents"] == []
    assert "serving.decode_mfu" not in METRICS.snapshot()["gauges"]
    assert engine._decode_cost is None
